"""Threaded dataflow engine — the runtime replacing FastFlow's pipeline of
pinned threads + lock-free SPSC queues (SURVEY.md §2.8).

Host-side dataflow stays on CPU threads exactly like the reference; the
difference is that channel payloads are whole batches, so queue traffic is
O(stream/chunk) instead of O(stream), and the Python GIL is released inside
the numpy/XLA kernels doing the real work.  When the native C++ substrate is
built (native/), Inbox transparently switches to the native blocking MPSC
ring (mutex + condvar — the win over queue.Queue is GIL-released futex
waits instead of 50 ms polling, not lock-freedom).

Topology model: a directed graph of Nodes. Each node owns one Inbox; an edge
(a -> b) reserves a source-slot in b's inbox so b can count per-channel EOS
(the FastFlow multi-in protocol) and ordering nodes can tell channels apart.
"""

from __future__ import annotations

import os
import queue
import threading
from time import monotonic as _monotonic
from time import perf_counter_ns as _pc_ns
from time import sleep as _sleep

from .node import Node, RuntimeContext, SnapshotUnsupported, SourceNode
from .overload import DeadLetter, OverloadError, OverloadPolicy
from ..recovery.epoch import EpochMarker, Tagged, is_ctrl_payload

_EOS = object()


class _Cancelled(BaseException):
    """Raised inside a node thread when the dataflow failed elsewhere —
    unblocks producers stuck on a dead consumer's bounded queue."""


class Inbox:
    """MPSC channel carrying (src_slot, batch) pairs.  Blocking operations
    poll the dataflow's failure flag so a raised node cannot deadlock the
    graph (a full queue whose consumer died would block producers
    forever).

    An :class:`~windflow_tpu.runtime.overload.OverloadPolicy` reshapes the
    ``put`` side only (shed_oldest / shed_newest / deadline-bounded block);
    ``put_eos`` and ``get`` are policy-exempt — an EOS that is shed or
    timed out would corrupt the per-channel EOS counting.  Shed items are
    counted in ``self.shed`` (surfaced per node via tracing.NodeStats and
    ``Dataflow.shed_counts``)."""

    def __init__(self, capacity: int = 0, failed: threading.Event = None,
                 policy: OverloadPolicy = None):
        self._q = queue.Queue(maxsize=capacity)
        self.n_sources = 0
        self._failed = failed
        self._policy = policy if (policy is not None
                                  and policy.reshapes_put) else None
        self.shed = 0
        self._shed_lock = threading.Lock()
        #: occupancy high-water mark, maintained only when the dataflow
        #: is observed (metrics/sample_period): the put-side cost is a
        #: single predictable `_track` branch when off.  Updated without
        #: a lock — a lost race understates the mark by at most one
        #: concurrent put, a fine trade for a telemetry-only value.
        self.hwm = 0
        self._track = False

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _blocking(self, op):
        while True:
            try:
                return op()
            except (queue.Full, queue.Empty):
                if self._failed is not None and self._failed.is_set():
                    raise _Cancelled() from None

    def _record_shed(self):
        with self._shed_lock:
            self.shed += 1

    def _cancelled(self) -> bool:
        return self._failed is not None and self._failed.is_set()

    def put(self, src: int, item):
        pol = self._policy
        if pol is None:
            self._blocking(lambda: self._q.put((src, item), timeout=0.05))
        elif pol.shed == "shed_newest":
            lim = pol.soft_limit
            if lim is not None and self._q.qsize() >= lim:
                # adaptive soft limit (control plane, docs/CONTROL.md):
                # start dropping before the queue is hard-full
                if self._cancelled():
                    raise _Cancelled() from None
                self._record_shed()
            else:
                try:
                    self._q.put_nowait((src, item))
                except queue.Full:
                    if self._cancelled():
                        # shed_newest never blocks, so this is the only
                        # spot a producer can observe a failed graph —
                        # without it an unbounded source would generate
                        # forever
                        raise _Cancelled() from None
                    self._record_shed()
        elif pol.shed == "shed_oldest":
            self._put_shed_oldest(src, item)
        else:  # block with a deadline
            self._put_deadline(src, item, pol.put_deadline)
        if self._track:
            depth = self._q.qsize()
            if depth > self.hwm:
                self.hwm = depth

    def depth(self) -> int:
        """Current occupancy (items incl. queued EOS frames) — sampled
        by the observability layer, racy by design."""
        return self._q.qsize()

    def _put_shed_oldest(self, src: int, item):
        while True:
            lim = self._policy.soft_limit
            if lim is None or self._q.qsize() < lim:
                try:
                    return self._q.put_nowait((src, item))
                except queue.Full:
                    if self._cancelled():
                        raise _Cancelled() from None
            elif self._cancelled():
                # at/above the adaptive soft limit: evict before
                # admitting, exactly the full-queue path below
                raise _Cancelled() from None
            # evict the head to admit the new item.  EOS frames must
            # survive: re-queue them at the tail (safe — EOS is its
            # channel's LAST frame, so per-channel order is preserved)
            try:
                victim = self._q.get_nowait()
            except queue.Empty:
                continue    # consumer drained it meanwhile; retry the put
            if victim[1] is _EOS or is_ctrl_payload(victim[1]):
                # EOS and epoch-marker control frames survive eviction
                # (a shed marker would stall downstream barrier
                # alignment the way a shed EOS would corrupt the
                # per-channel EOS count)
                self._blocking(
                    lambda: self._q.put(victim, timeout=0.05))
                # shutdown skew: a full queue of only EOS frames would
                # otherwise hot-spin evict/re-queue until the (slow —
                # that's why shedding is on) consumer drains one
                _sleep(0.001)
            else:
                self._record_shed()

    def _put_deadline(self, src: int, item, deadline: float):
        t_end = _monotonic() + deadline
        while True:
            try:
                return self._q.put((src, item), timeout=0.05)
            except queue.Full:
                if self._cancelled():
                    raise _Cancelled() from None
                if _monotonic() >= t_end:
                    raise OverloadError(
                        f"inbox put blocked longer than the "
                        f"{deadline}s deadline (capacity "
                        f"{self._q.maxsize}): downstream stage is not "
                        f"keeping up") from None

    def put_eos(self, src: int):
        self._blocking(lambda: self._q.put((src, _EOS), timeout=0.05))

    def put_ctrl(self, src: int, item):
        """Policy-exempt blocking put for control frames (epoch barrier
        markers): like ``put_eos``, never shed and never deadlined."""
        self._blocking(lambda: self._q.put((src, item), timeout=0.05))

    def get(self):
        return self._blocking(lambda: self._q.get(timeout=0.05))

    def cancel(self):
        """Failure path: wake any blocked producer/consumer (the Python
        queue relies on the 50 ms poll; the native ring wakes instantly)."""


class NativeInbox:
    """Inbox over the C++ blocking ring (native/wf_native.cpp NativeQueue):
    blocking push/pop wait on a futex with the GIL released instead of the
    Python queue's 50 ms timeout polling.  Batch objects never cross the
    ABI — they sit in a side table keyed by the slot id the ring carries
    (the payload-pointer discipline of FastFlow's SPSC queues)."""

    def __init__(self, capacity: int, failed: threading.Event = None,
                 lib=None, policy: OverloadPolicy = None):
        self._lib = lib
        self._failed = failed
        self._h = lib.wf_queue_new(capacity)
        self._items = {}
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.n_sources = 0
        self._policy = policy if (policy is not None
                                  and policy.reshapes_put) else None
        self.shed = 0
        self._shed_lock = threading.Lock()
        self.hwm = 0         # see Inbox: observed-dataflow occupancy mark
        self._track = False

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            # wf_queue_free closes first and spins until the last blocked
            # thread has left push/pop before destroying the mutex
            self._lib.wf_queue_free(h)
            self._h = None

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _slot_for(self, item) -> int:
        with self._seq_lock:
            self._seq += 1
            slot = self._seq
        self._items[slot] = item
        return slot

    def _push(self, src: int, item):
        slot = self._slot_for(item)
        if self._lib.wf_queue_push(self._h, src, slot) != 0:
            self._items.pop(slot, None)
            raise _Cancelled()

    def _record_shed(self):
        with self._shed_lock:
            self.shed += 1

    def put(self, src: int, item):
        pol = self._policy
        if pol is None:
            self._push(src, item)
        elif pol.shed == "shed_newest":
            lim = pol.soft_limit
            if lim is not None and len(self._items) >= lim:
                # adaptive soft limit (see Inbox.put): drop before full.
                # This path never touches the ring, so it must observe a
                # failed graph itself or an unbounded source spins forever
                if self._failed is not None and self._failed.is_set():
                    raise _Cancelled()
                self._record_shed()
            else:
                slot = self._slot_for(item)
                rc = self._lib.wf_queue_try_push(self._h, src, slot)
                if rc != 0:
                    self._items.pop(slot, None)
                    if rc < 0:
                        raise _Cancelled()
                    self._record_shed()
        elif pol.shed == "shed_oldest":
            self._put_shed_oldest(src, self._slot_for(item))
        else:  # block with a deadline
            slot = self._slot_for(item)
            rc = self._lib.wf_queue_push_timed(
                self._h, src, slot, int(pol.put_deadline * 1000))
            if rc != 0:
                self._items.pop(slot, None)
                if rc < 0:
                    raise _Cancelled()
                raise OverloadError(
                    f"inbox put blocked longer than the "
                    f"{pol.put_deadline}s deadline (native ring): "
                    f"downstream stage is not keeping up")
        if self._track:
            depth = len(self._items)
            if depth > self.hwm:
                self.hwm = depth

    def depth(self) -> int:
        """Occupancy proxy: the payload side table holds exactly the
        items whose slot ids sit in the ring (plus any mid-handoff)."""
        return len(self._items)

    def _put_shed_oldest(self, src: int, slot: int):
        import ctypes
        lib = self._lib
        vsrc = ctypes.c_longlong()
        vslot = ctypes.c_longlong()
        while True:
            lim = self._policy.soft_limit
            if lim is None or len(self._items) < lim + 1:
                # +1: our own slot already sits in the side table
                rc = lib.wf_queue_try_push(self._h, src, slot)
                if rc == 0:
                    return
                if rc < 0:
                    self._items.pop(slot, None)
                    raise _Cancelled()
            # full: evict the head to admit the new item (EOS survives —
            # re-queued at the tail, see Inbox._put_shed_oldest)
            rc2 = lib.wf_queue_try_pop(self._h, ctypes.byref(vsrc),
                                       ctypes.byref(vslot))
            if rc2 < 0:
                self._items.pop(slot, None)
                raise _Cancelled()
            if rc2 == 1:
                continue    # consumer drained it meanwhile; retry the push
            victim = self._items.pop(vslot.value)
            if victim is _EOS or is_ctrl_payload(victim):
                # control frames survive eviction (see Inbox)
                self._push(vsrc.value, victim)
                _sleep(0.001)   # see Inbox._put_shed_oldest: no hot spin
            else:
                self._record_shed()

    def put_eos(self, src: int):
        self._push(src, _EOS)

    def put_ctrl(self, src: int, item):
        """Policy-exempt blocking push for control frames (see Inbox)."""
        self._push(src, item)

    def get(self):
        import ctypes
        src = ctypes.c_longlong()
        slot = ctypes.c_longlong()
        if self._lib.wf_queue_pop(self._h, ctypes.byref(src),
                                  ctypes.byref(slot)) != 0:
            raise _Cancelled()
        return src.value, self._items.pop(slot.value)

    def cancel(self):
        self._lib.wf_queue_close(self._h)


def _make_inbox(capacity: int, failed: threading.Event,
                policy: OverloadPolicy = None):
    if capacity > 0:  # capacity 0 = unbounded, which only the Python
        from ..native import enabled  # queue implements
        lib = enabled()
        if lib is not None and (
                policy is None or not policy.reshapes_put
                or getattr(lib, "wf_has_overload_queue", False)):
            # an old .so without the overload entry points still serves
            # every default path; only active shed/deadline knobs fall
            # back to the Python queue
            return NativeInbox(capacity, failed, lib=lib, policy=policy)
    return Inbox(capacity, failed, policy)


class Dataflow:
    """A graph of nodes executed by one thread per node
    (MultiPipe::run_and_wait_end spawns cardinality()-1 threads,
    multipipe.hpp:1010; same model here)."""

    #: valid ``check=`` modes (docs/CHECKS.md): None/'off' = seed
    #: behavior, the check package is never imported; 'warn' = run the
    #: static validator at run() and report diagnostics as warnings;
    #: 'error' = additionally raise CheckError (before any thread
    #: starts) when an error-severity diagnostic survives suppression
    CHECK_MODES = (None, "off", "warn", "error")

    def __init__(self, name: str = "dataflow", capacity: int = 16,
                 trace_dir: str = None, overload: OverloadPolicy = None,
                 metrics=None, sample_period: float = None,
                 recovery=None, check: str = None, control=None,
                 trace=None, federate=None):
        # bounded inboxes give natural backpressure (FastFlow's
        # FF_BOUNDED_BUFFER, the yahoo Makefile default): a source cannot
        # run unboundedly ahead of a slow consumer, keeping queue latency
        # proportional to capacity x batch size.  0 = unbounded.
        # `overload` (runtime/overload.py) opts the graph into shedding /
        # put deadlines / poison-tuple quarantine; None = seed behavior.
        # `metrics` (a MetricsRegistry, or truthy for a fresh one) and
        # `sample_period` (seconds; also the WF_SAMPLE_PERIOD env hook)
        # opt into the observability layer (docs/OBSERVABILITY.md):
        # a background sampler owned by this graph writes
        # <trace_dir>/metrics.jsonl and a structured event log writes
        # <trace_dir>/events.jsonl.  Both unset = no thread, no files,
        # and inbox hot paths keep a single disabled branch.
        from ..utils.tracing import default_sample_period, default_trace_dir
        if overload is not None and overload.reshapes_put and capacity <= 0:
            # an unbounded queue never fills: every shed/deadline knob
            # would be silently inert while memory grows without bound
            raise ValueError(
                f"OverloadPolicy with shed={overload.shed!r}/"
                f"put_deadline={overload.put_deadline} needs a bounded "
                f"inbox (capacity > 0, got {capacity}): an unbounded "
                f"queue never sheds and never times out")
        # `recovery` (recovery/policy.RecoveryPolicy) opts the graph into
        # epoch checkpoints + supervised node restart (docs/ROBUSTNESS.md
        # "Recovery"); None = seed behavior: no markers, no journals, no
        # supervisor thread, one dead branch on the emit hot path.
        if recovery is not None:
            from ..recovery.policy import RecoveryPolicy
            if not isinstance(recovery, RecoveryPolicy):
                raise TypeError(f"recovery= wants a RecoveryPolicy, got "
                                f"{type(recovery).__name__}")
        if check not in self.CHECK_MODES:
            raise ValueError(f"check= wants one of {self.CHECK_MODES}, "
                             f"got {check!r}")
        # `control` (control/policy.ControlPolicy) opts the graph into the
        # closed-loop control plane (docs/CONTROL.md): a controller fed by
        # the observability sampler drives elastic rescale, adaptive
        # shedding, and source admission.  None = seed behavior, and the
        # control package is never imported (same contract as check=).
        if control is not None:
            from ..control.policy import ControlPolicy
            if not isinstance(control, ControlPolicy):
                raise TypeError(f"control= wants a ControlPolicy, got "
                                f"{type(control).__name__}")
            if control.has_rescale and recovery is None:
                # a rescale seals at an epoch barrier; without recovery=
                # no source ever injects a marker, so the rule could
                # never fire — refuse the silently-inert pair outright
                # (check/ reports it as WF211 on a not-yet-built
                # MultiPipe, mirroring the WF208 split)
                raise ValueError(
                    f"[WF211] Dataflow {name!r}: control= has Rescale "
                    f"rules but recovery= is unset — live rescale seals "
                    f"at epoch barriers, which only a RecoveryPolicy's "
                    f"epoch triggers inject (docs/CONTROL.md)")
        self.control = control
        self._controller = None
        #: rescalable-farm registry stamped by runtime/farm.py at wiring
        #: time: {"pattern", "rule", "emitter", "workers", "width"} per
        #: farm a Rescale rule targets (inert metadata when control is
        #: unset — nothing reads it)
        self._farms: list[dict] = []
        self.name = name
        self.capacity = capacity
        self.trace_dir = trace_dir or default_trace_dir()
        self.overload = overload
        self.recovery = recovery
        #: pre-flight static-analysis mode (docs/CHECKS.md); run() defers
        #: to check/ lazily, so the unset default never imports it
        self.check = check
        self._supervisor = None
        #: callbacks fired (epoch:int) each time the supervisor seals a
        #: checkpoint epoch manifest — the hook the resumable row plane
        #: uses to ack sealed epochs back to remote senders so their
        #: journals trim (docs/ROBUSTNESS.md "Wire resume").  Read live
        #: by Supervisor._seal_ready, so registration after run() works.
        self._seal_listeners: list = []
        if sample_period is None:
            sample_period = default_sample_period()
        if sample_period is not None and float(sample_period) <= 0:
            raise ValueError(f"sample_period must be positive seconds, "
                             f"got {sample_period}")
        self.sample_period = sample_period
        self._sampler = None
        # truthiness, not `is not None`: metrics=False/0 must mean OFF
        # (docs/OBSERVABILITY.md — "any truthy value for a fresh one")
        if metrics or sample_period is not None:
            if not self.trace_dir:
                # the silent no-op (ISSUE 11 / WF207): the sampler and
                # event log run, but with no resolvable directory no
                # metrics.jsonl/events.jsonl is ever written.  Warn once
                # per graph, here at construction, naming the missing
                # knob — the string carries the WF id so the message and
                # the check/ diagnostic stay greppable as one, without
                # importing check/ on this path.
                import warnings
                warnings.warn(
                    f"[WF207] Dataflow {name!r}: metrics=/sample_period= "
                    f"is set but no trace_dir resolves (trace_dir= or "
                    f"WF_LOG_DIR) — the live registry works, but "
                    f"metrics.jsonl/events.jsonl will not be written",
                    stacklevel=2)
            from ..obs import EventLog, MetricsRegistry
            #: live metrics registry shared with channels/user functions
            self.metrics = (metrics if isinstance(metrics, MetricsRegistry)
                            else MetricsRegistry())
            #: structured runtime event log (file iff trace_dir is set;
            #: the file opens lazily, so a never-run preview graph
            #: creates nothing on disk)
            self.events = EventLog(
                os.path.join(self.trace_dir, "events.jsonl")
                if self.trace_dir else None)
        else:
            self.metrics = None
            self.events = None
        # `trace` (obs/trace.TracePolicy, or a sample-rate fraction; any
        # falsy value = OFF) opts the graph into end-to-end span tracing
        # (docs/OBSERVABILITY.md §tracing): a sampled fraction of source
        # batches carries a trace context, every traversed node records
        # queue-wait + service spans, device launches become child spans,
        # and <trace_dir>/trace.jsonl feeds scripts/wf_trace.py.  Unset
        # means the obs.trace module is never imported — the same
        # contract as check=/control=.
        if trace:
            from ..obs.trace import Tracer, as_policy
            self.trace = as_policy(trace)
            if not self.trace_dir:
                # the WF207 shape of silent no-op (docs/CHECKS.md
                # WF213): spans stay in the bounded in-memory ring and
                # trace.jsonl is never written.  The live percentile
                # sensors still work, so this is a warning, not an
                # error — but it is almost always a missing trace_dir.
                import warnings
                warnings.warn(
                    f"[WF213] Dataflow {name!r}: trace= is set but no "
                    f"trace_dir resolves (trace_dir= or WF_LOG_DIR) — "
                    f"sampled spans stay in the in-memory ring and "
                    f"trace.jsonl is never written", stacklevel=2)
            #: per-graph span tracer; file opens lazily, so a never-run
            #: preview graph still creates nothing on disk
            self.tracer = Tracer(self.name, self.trace,
                                 trace_dir=self.trace_dir,
                                 metrics=self.metrics, events=self.events)
            from ..obs.trace import Stamped as _StampedCls
            self._Stamped = _StampedCls
        else:
            self.trace = None
            self.tracer = None
            self._Stamped = None
        # `federate` (obs/federation.FederationPolicy, or True; any
        # falsy value = OFF) opts the process into the plane-wide
        # telemetry tier (docs/OBSERVABILITY.md "Federation & SLOs"): a
        # shipper rides the sampler and ships compact snapshots over
        # the row plane's -8 frames (once the app binds the plane's
        # senders, `df.federation.bind(senders)`), local SLO objectives
        # evaluate per sample, and the black-box flight recorder dumps
        # the bounded in-memory rings on node_error / recovery give-up.
        # Unset means obs.federation / obs.slo are never imported and
        # no -8 frame is ever sent — the same contract as trace=.
        if federate:
            from ..obs.federation import as_policy as _fed_as_policy
            self.federate = _fed_as_policy(federate)
            if self.metrics is None:
                # the shipper's only source is the sampler: with
                # neither metrics= nor sample_period= no snapshot is
                # ever built and the whole tier is silently inert —
                # the WF209 shape of silent no-op, warned once here
                # and reported by check/ as WF217 (docs/CHECKS.md)
                import warnings
                warnings.warn(
                    f"[WF217] Dataflow {name!r}: federate= is set but "
                    f"neither metrics= nor sample_period= is — the "
                    f"shipper's only source is the sampler, so nothing "
                    f"is ever shipped and federation is inert",
                    stacklevel=2)
        else:
            self.federate = None
        #: the live FederationShipper (built in run() when federate=
        #: and the sampler both exist); apps bind the row plane with
        #: ``df.federation.bind(senders)``
        self.federation = None
        self._blackbox = None
        if control is not None and self.metrics is None:
            # the controller's only sensor is the sampler (obs/sampler.py
            # subscription); with neither metrics= nor sample_period= it
            # never receives a snapshot and every rule is silently inert
            # — the WF207 shape of silent no-op, warned once here and
            # reported by check/ as WF209 (docs/CHECKS.md)
            import warnings
            warnings.warn(
                f"[WF209] Dataflow {name!r}: control= is set but neither "
                f"metrics= nor sample_period= is — the controller is "
                f"blind (no sampler snapshots) and no rule will ever "
                f"fire", stacklevel=2)
        self.nodes: list[Node] = []
        self._inboxes: dict[int, Inbox] = {}
        self._edges: list[tuple[Node, Node]] = []
        self._threads: list[threading.Thread] = []
        self._errors: list[BaseException] = []
        self._failed = threading.Event()
        #: quarantined poison batches (DeadLetter records, arrival order);
        #: inspect after wait() — only ever populated when an error budget
        #: is set (overload.error_budget or a node/pattern-level budget)
        self.dead_letters: list[DeadLetter] = []
        self._dead_lock = threading.Lock()
        self._stop_logged = False

    def _inbox_policy(self, node: Node) -> OverloadPolicy:
        """Shedding applies only at shed-safe inboxes (farm heads and
        stateless operators — dropping there means dropping raw stream
        items).  Internal farm edges (window multicast copies, dense-id
        result streams, ordering merges) keep blocking, so overload
        backpressures through them to the nearest shed-safe inbox
        upstream instead of silently corrupting window state.  A put
        deadline (block policy) is loud, not lossy, so it applies
        everywhere."""
        pol = self.overload
        if (pol is not None and pol.shed != "block"
                and not getattr(node, "shed_safe", False)):
            return None
        return pol

    def add(self, node: Node, ctx: RuntimeContext = None) -> Node:
        if ctx is not None:
            node.ctx = ctx
        self.nodes.append(node)
        inbox = _make_inbox(self.capacity, self._failed,
                            self._inbox_policy(node))
        if self.metrics is not None or self.sample_period is not None:
            inbox._track = True  # maintain the occupancy high-water mark
        self._inboxes[id(node)] = inbox
        return node

    def connect(self, src: Node, dst: Node):
        """Add an edge; the order of connect() calls from one src defines its
        output-channel indexing (emit_to)."""
        inbox = self._inboxes[id(dst)]
        slot = inbox.register_source()
        src._outputs.append((inbox, slot))
        self._edges.append((src, dst))

    def on_epoch_sealed(self, fn):
        """Register ``fn(epoch)`` to fire each time the recovery
        supervisor seals a checkpoint epoch (every expected node's blob
        committed).  This is the durability boundary a resumable wire
        edge cares about: wiring ``receiver.ack_epoch`` here acks
        sealed epochs back to remote RowSenders so their replay
        journals trim (docs/ROBUSTNESS.md "Wire resume").  Listeners
        run on the checkpoint-writer thread; exceptions are swallowed
        (a telemetry hook must not fail a seal).  Requires
        ``recovery=`` with a checkpoint_dir — without a store nothing
        ever seals, so the hook never fires.  Returns ``fn`` for
        decorator use."""
        self._seal_listeners.append(fn)
        return fn

    def request_drain(self, timeout: float = None) -> bool:
        """Gate every source and wait for the in-flight work to settle
        (the quiesce leg of a rolling restart, docs/ROBUSTNESS.md
        "Cross-host recovery").  Requires a running graph with a
        ``control=`` policy declaring a :class:`~windflow_tpu.control.
        Drain` rule; returns whether the graph fully quiesced within
        the deadline.  Pair with :meth:`release_drain`."""
        if self._controller is None:
            raise RuntimeError(
                "request_drain() needs a running dataflow with "
                "control=ControlPolicy([..., Drain(...)]) — call after "
                "run() (docs/CONTROL.md)")
        return self._controller.request_drain(timeout)

    def release_drain(self):
        """Reopen the source gate closed by :meth:`request_drain`."""
        if self._controller is None:
            raise RuntimeError(
                "release_drain() needs a running dataflow with "
                "control=ControlPolicy([..., Drain(...)])")
        self._controller.release_drain()

    # ------------------------------------------------------------------ run

    def _error_budget_of(self, node: Node) -> int:
        """Effective poison-tuple allowance: node-level override first
        (builders' withErrorBudget / a pattern's error_budget, propagated
        onto replicas by runtime/farm.py), then the dataflow policy —
        except for quarantine-exempt framework shells (emitters,
        collectors, ordering merges), which never inherit the policy
        default: an error there is a framework bug, not a poison tuple."""
        budget = getattr(node, "error_budget", None)
        if budget is None:
            if getattr(node, "quarantine_exempt", False):
                return 0
            budget = (self.overload.error_budget
                      if self.overload is not None else 0)
        return int(budget)

    def _quarantine(self, node: Node, batch, channel: int,
                    error: BaseException):
        letter = DeadLetter(node.name, batch, channel, error)
        with self._dead_lock:
            self.dead_letters.append(letter)
        if node.stats is not None:
            node.stats.record_quarantined()
        if self.events is not None:
            self.events.emit("quarantine", dataflow=self.name,
                             **letter.to_event())

    def _run_node(self, node: Node):
        events = self.events
        tracer = self.tracer
        _Stamped = self._Stamped
        try:
            node.n_input_channels = self._inboxes[id(node)].n_sources
            if self.trace_dir or self.metrics is not None \
                    or self.sample_period is not None \
                    or tracer is not None:
                from ..utils.tracing import node_stats_name
                # index disambiguates same-named nodes (two 'map.0' stages)
                idx = self.nodes.index(node)
                node._hop_id = node_stats_name(self.name, idx, node.name)
            if self.trace_dir or self.metrics is not None \
                    or self.sample_period is not None:
                from ..utils.tracing import NodeStats
                node.stats = NodeStats(node._hop_id)
            if tracer is not None:
                # span-sampling hooks (obs/trace.py): sources make the
                # sampling/adoption decision at emit; every node wraps
                # traced emissions for the inbox crossing (Comb forwards
                # these onto its fused stages in svc_init)
                node._tracer = tracer
                node._trace_origin = isinstance(node, SourceNode)
            if self.metrics is not None:
                # rich user functions may bump custom metrics through
                # their RuntimeContext (ctx.metrics.counter(...).inc())
                node.ctx.metrics = self.metrics
            if events is not None:
                events.emit("node_start", dataflow=self.name,
                            node=node.name,
                            source=isinstance(node, SourceNode))
            node.svc_init()
            supervised = (node._recov is not None
                          and not isinstance(node, SourceNode))
            if isinstance(node, SourceNode):
                if node._recov is not None:
                    # sequence-tag emissions + epoch-marker injection
                    # (recovery/epoch.py); sources are not restartable —
                    # a generate() failure propagates exactly as today
                    node._recov.begin(len(node._outputs), 0, 0)
                node.generate()
            elif supervised:
                self._run_supervised(node, events)
            else:
                inbox = self._inboxes[id(node)]
                live = inbox.n_sources
                stats = node.stats
                budget = self._error_budget_of(node)
                while live > 0:
                    src, item = inbox.get()
                    if item is _EOS:
                        live -= 1
                        if tracer is not None:
                            # channel-EOS flushes (ordering drains, farm
                            # collector merges) are not attributable to
                            # any sampled batch: clear the previous
                            # iteration's span before they emit
                            tracer.set_current(None)
                        node.on_channel_eos(src)
                        if events is not None:
                            events.emit("eos", dataflow=self.name,
                                        node=node.name, channel=src,
                                        live=live)
                        continue
                    ctx = None
                    if tracer is not None:
                        # unwrap a traced batch and expose its span to
                        # this svc call's emissions via the thread-local
                        # (set for EVERY batch — a stale ctx must never
                        # leak onto the next, untraced one)
                        if type(item) is _Stamped:
                            item, ctx, parent, span, q_ns = \
                                tracer.incoming(item)
                            tracer.set_current(ctx, span, node._hop_id)
                        else:
                            tracer.set_current(None)
                    timed = stats is not None or ctx is not None
                    if budget > 0:
                        # poison-tuple quarantine: an svc error within
                        # budget parks the batch in the dead-letter queue
                        # and the node lives on; once the budget is spent
                        # the next error fails fast exactly like default
                        try:
                            if timed:
                                t0 = _pc_ns()
                                node.svc(item, src)
                                dt = _pc_ns() - t0
                                if stats is not None:
                                    stats.record_svc(len(item), dt)
                            else:
                                node.svc(item, src)
                        except OverloadError:
                            # a put deadline expiring inside svc's emit is
                            # backpressure failure, not a poison tuple —
                            # it must fail fast, not burn the budget
                            raise
                        except Exception as e:  # _Cancelled passes through
                            budget -= 1
                            self._quarantine(node, item, src, e)
                            continue    # no span: the batch died here
                    elif timed:
                        t0 = _pc_ns()
                        node.svc(item, src)
                        dt = _pc_ns() - t0
                        if stats is not None:
                            stats.record_svc(len(item), dt)
                    else:
                        node.svc(item, src)
                    if ctx is not None:
                        tracer.record_hop(ctx, node._hop_id, span, parent,
                                          q_ns, dt, len(item))
            if tracer is not None:
                # EOS flushes are not attributable to any sampled batch:
                # clear the thread-local so the last traced batch's span
                # cannot leak onto eosnotify emissions
                tracer.set_current(None)
            if not supervised:
                # the supervised loop already ran eosnotify inside its
                # restart-protected region (a flush crash restores +
                # replays + re-flushes)
                node.eosnotify()
            node.svc_end()
            if node.stats is not None:
                shed = getattr(self._inboxes[id(node)], "shed", 0)
                if shed:
                    node.stats.record_shed(shed)
                if self.trace_dir:
                    node.stats.write(self.trace_dir)
            if events is not None:
                stop = {"dataflow": self.name, "node": node.name}
                if node.stats is not None:
                    stop["rcv_batches"] = node.stats.rcv_batches
                    stop["rcv_tuples"] = node.stats.rcv_tuples
                    stop.update({k: v for k, v
                                 in node.stats.counters.items()
                                 if k not in ("t", "event")})
                events.emit("node_stop", **stop)
        except _Cancelled:
            pass  # the graph failed elsewhere; exit quietly
        except BaseException as e:  # propagate to run_and_wait_end
            self._errors.append(e)
            self._failed.set()  # unblock producers stuck on our inbox
            if events is not None:
                events.emit("node_error", dataflow=self.name,
                            node=node.name, error=type(e).__name__,
                            message=str(e))
            if self._blackbox is not None:
                # flight recorder (docs/OBSERVABILITY.md "Federation &
                # SLOs"): dump the bounded rings while they still hold
                # the moments before the failure
                self._blackbox.dump("node_error", failed_node=node.name,
                                    error=type(e).__name__,
                                    message=str(e))
            for inbox in self._inboxes.values():
                inbox.cancel()  # native rings wake instantly
        finally:
            try:
                for inbox, src in node._outputs:
                    inbox.put_eos(src)
            except _Cancelled:
                pass

    # ----------------------------------------------------------- recovery
    # The supervised receive loop (docs/ROBUSTNESS.md "Recovery"): only
    # entered when `recovery=` is set, so the seed loop above stays
    # byte-identical.  Items arrive as Tagged envelopes (per-edge seq
    # numbers, recovery/epoch.py); epoch barrier markers align across
    # input channels Chandy-Lamport style; on alignment the node drains
    # device queues (checkpoint_prepare), snapshots, and forwards the
    # marker; on failure the Supervisor authorizes restore-last-snapshot
    # + journal replay on this same thread, under the restart budget.

    def _run_supervised(self, node: Node, events):
        rec = node._recov
        inbox = self._inboxes[id(node)]
        rec.begin(len(node._outputs), inbox.n_sources,
                  self._error_budget_of(node))
        # epoch-0 snapshot: a crash before the first barrier must still
        # have a restore point (state fresh out of svc_init)
        self._checkpoint_node(node, rec, events, 0)
        restoring = False
        while True:
            try:
                if restoring:
                    # inside the protected region: a deterministic fault
                    # re-hit DURING replay burns another restart from
                    # the budget instead of tearing the graph down
                    restoring = False
                    self._restore_and_replay(node, rec, events)
                while rec.live > 0:
                    src, item = inbox.get()
                    if self._dispatch_supervised(node, rec, events, src,
                                                 item):
                        self._complete_barriers(node, rec, events)
                if self.tracer is not None:
                    # EOS flushes are not attributable to any sampled
                    # batch (see the seed loop)
                    self.tracer.set_current(None)
                node.eosnotify()
                return
            except (_Cancelled, OverloadError):
                # graph failed elsewhere / backpressure deadline: both
                # must fail exactly like the seed engine (a restart
                # would re-block on the same saturated downstream)
                raise
            except Exception as e:
                if getattr(e, "wf_no_restart", False):
                    # e.g. a failed rescale migration (control/rescale.py)
                    # left SIBLING workers' state inconsistent: restoring
                    # this node alone cannot fix the farm — fail the
                    # graph like the seed engine
                    raise
                if not self._supervisor.authorize_restart(node, rec, e):
                    raise
                restoring = True

    def _dispatch_supervised(self, node: Node, rec, events, src, item,
                             lvl: int = None) -> bool:
        """Handle one inbox item; True when barrier alignment may have
        advanced (the caller then completes any ready barriers — kept
        out of this function so a held-item drain can't checkpoint
        mid-iteration).  ``lvl`` is the item's channel epoch level at
        ARRIVAL: None for a fresh inbox item (the current level), an
        explicit value when replaying from the journal — replay must
        repeat the original hold-or-process decisions, and the restored
        ``chan_epoch`` only knows the commit-time (possibly later)
        level."""
        if item is _EOS:
            if lvl is None:
                lvl = rec.chan_epoch.get(src, 0)
            rec.journal_append(src, item, lvl)
            if lvl > rec.epoch:
                # the channel ran ahead of the node's epoch and its data
                # is held back — processing its EOS now would lift
                # order-sensitive consumers' watermarks past the held
                # rows, so the EOS waits its turn in arrival order
                rec.held.append((src, item, lvl))
                return False
            rec.live -= 1
            rec.eos.add(src)
            if self.tracer is not None:
                self.tracer.set_current(None)   # see the seed loop
            node.on_channel_eos(src)
            if events is not None:
                events.emit("eos", dataflow=self.name, node=node.name,
                            channel=src, live=rec.live)
            return True
        if type(item) is Tagged:
            seq, payload = item.seq, item.payload
            stale = seq <= rec.last_seen.get(src, -1)
        else:
            payload = item
            stale = False
        if type(payload) is EpochMarker:
            # markers apply EVEN when their seq is stale: a shed_oldest
            # eviction re-queues a marker at the inbox tail, behind
            # later same-channel seqs — dropping it as a duplicate
            # would stall barrier alignment forever.  The update is a
            # monotone max, so re-applying a truly replayed marker is
            # harmless.
            if not stale:
                rec.journal_append(src, item, 0)
                if type(item) is Tagged:
                    rec.last_seen[src] = item.seq
            if payload.epoch > rec.chan_epoch.get(src, 0):
                rec.chan_epoch[src] = payload.epoch
            return True
        if stale:
            return False            # duplicate from a restarted producer
        if lvl is None:
            lvl = rec.chan_epoch.get(src, 0)
        rec.journal_append(src, item, lvl)
        if type(item) is Tagged:
            rec.last_seen[src] = item.seq
        if lvl > rec.epoch:
            # this channel is past the node's epoch: hold its data back
            # until the barrier completes, so the snapshot is a
            # consistent cut.  ``lvl`` pins the item's content epoch
            # (lvl+1) — the barrier drain orders by it, since the
            # channel's CURRENT epoch may advance further meanwhile.
            rec.held.append((src, item, lvl))
            return False
        self._svc_supervised(node, rec, src, payload)
        return False

    def _apply_held(self, node: Node, rec, events, src, item):
        """Process one held-back item: already deduped and journaled on
        first receipt, and its turn has come — no further checks."""
        if item is _EOS:
            rec.live -= 1
            rec.eos.add(src)
            if self.tracer is not None:
                self.tracer.set_current(None)   # see the seed loop
            node.on_channel_eos(src)
            if events is not None:
                events.emit("eos", dataflow=self.name, node=node.name,
                            channel=src, live=rec.live)
            return
        payload = item.payload if type(item) is Tagged else item
        self._svc_supervised(node, rec, src, payload)

    def _svc_supervised(self, node: Node, rec, src, payload):
        """svc + stats + poison-tuple quarantine, mirroring the seed
        loop; budget lives on the recovery record so restarts restore
        it with the snapshot.  Traced batches (obs/trace.py Stamped —
        the recovery envelope wraps outside it, so held-back and
        journal-replayed items arrive here still stamped) unwrap and
        record their hop span; a replayed hop re-records honestly, with
        the restore time inside its queue wait."""
        stats = node.stats
        tracer = self.tracer
        ctx = None
        if tracer is not None:
            if type(payload) is self._Stamped:
                payload, ctx, parent, span, q_ns = \
                    tracer.incoming(payload)
                tracer.set_current(ctx, span, node._hop_id)
            else:
                tracer.set_current(None)
        timed = stats is not None or ctx is not None
        if rec.budget > 0:
            try:
                if timed:
                    t0 = _pc_ns()
                    node.svc(payload, src)
                    dt = _pc_ns() - t0
                    if stats is not None:
                        stats.record_svc(len(payload), dt)
                else:
                    node.svc(payload, src)
            except OverloadError:
                raise
            except Exception as e:
                rec.budget -= 1
                if rec.requarantine_skip > 0:
                    # journal replay re-raising on an already-
                    # quarantined batch: spend the budget again (the
                    # snapshot restored it) but don't duplicate the
                    # dead letter / event the original pass recorded
                    rec.requarantine_skip -= 1
                else:
                    rec.quarantined += 1
                    self._quarantine(node, payload, src, e)
                return      # no span: the batch died here
        elif timed:
            t0 = _pc_ns()
            node.svc(payload, src)
            dt = _pc_ns() - t0
            if stats is not None:
                stats.record_svc(len(payload), dt)
        else:
            node.svc(payload, src)
        if ctx is not None:
            tracer.record_hop(ctx, node._hop_id, span, parent, q_ns, dt,
                              len(payload))

    def _complete_barriers(self, node: Node, rec, events):
        while True:
            epoch = rec.barrier_ready()
            if epoch is None:
                return
            if epoch == "eos":
                # every channel reached EOS: no further barrier can
                # complete, so the remaining held items process now, in
                # arrival order, ahead of the EOS flush (EOS aligns a
                # channel to every epoch)
                rec.epoch = max(rec.chan_epoch.values(),
                                default=rec.epoch)
                pending, rec.held = rec.held, []
                for src, item, _lvl in pending:
                    self._apply_held(node, rec, events, src, item)
                continue
            # a held item at level L is content of epoch L+1.  When the
            # barrier min jumps several epochs at once (a lagging
            # channel EOSing, wire sources skipping epochs), items with
            # L < epoch are content the epoch-`epoch` snapshot claims to
            # cover — they process BEFORE it; items at exactly L ==
            # epoch open the next epoch and process after the marker.
            early = [(s, i) for s, i, l in rec.held if l < epoch]
            # keep the still-unprocessed items in rec.held through the
            # checkpoint: commit() journals exactly this set
            rec.held = [(s, i, l) for s, i, l in rec.held if l >= epoch]
            for src, item in early:
                self._apply_held(node, rec, events, src, item)
            self._checkpoint_node(node, rec, events, epoch)
            hook = node._ctl_epoch_hook
            if hook is not None:
                # control plane (docs/CONTROL.md): a pending live rescale
                # seals HERE — after the snapshot committed and the
                # marker went downstream, before any post-barrier item
                # processes, so the migration cut is exactly this epoch
                hook(epoch)
            if events is not None:
                events.emit("epoch", dataflow=self.name,
                            node=node.name, epoch=epoch)
            now = [(s, i) for s, i, l in rec.held if l <= epoch]
            rec.held = [(s, i, l) for s, i, l in rec.held if l > epoch]
            for src, item in now:
                self._apply_held(node, rec, events, src, item)

    def _checkpoint_node(self, node: Node, rec, events, epoch: int):
        """Snapshot one node at a completed barrier: drain async device
        work (its results pre-date the barrier), snapshot state, commit
        in-memory, and hand the blob to the supervisor's writer."""
        t0 = _monotonic()
        if self.tracer is not None:
            # barrier drains are not attributable to any sampled batch
            # (the EOS-flush rule): without this clear, the LAST
            # processed batch's span would leak onto every
            # checkpoint_prepare emission below
            self.tracer.set_current(None)
        for out in (node.checkpoint_prepare() or ()):
            if out is not None and len(out):
                node.emit(out)
        if epoch > 0:
            pre = node._ctl_seal_hook
            if pre is not None:
                # control plane: a farm emitter ANNOUNCES a pending
                # rescale's seal epoch before the marker leaves, so a
                # worker racing ahead on the marker always finds the
                # seal already published (control/rescale.py)
                pre(epoch)
            # forward the barrier BEFORE committing, so the snapshot's
            # output sequence counters include the marker — a restored
            # node's first re-emission must not collide with the
            # marker's seq (downstream would drop it as a duplicate)
            rec.forward_marker(node._outputs, epoch)
        if not rec.journaling:
            # non-snapshotable node: just track the epoch so held-back
            # items and marker forwarding stay aligned
            rec.epoch = epoch
            return
        try:
            state = node.state_snapshot()
        except SnapshotUnsupported as e:
            rec.mark_unrecoverable(str(e) or type(e).__name__)
            rec.epoch = epoch
            return
        rec.commit(epoch, state)
        self._supervisor.note_checkpoint(node, rec, epoch,
                                         _monotonic() - t0)
        self._supervisor.enqueue_blob(rec, epoch, state)
        if self.tracer is not None:
            # control-plane span (obs/trace.py): the barrier stall this
            # node's traced batches sat behind, on the Perfetto timeline
            self.tracer.record_ctrl(node._hop_id or node.name,
                                    "checkpoint", epoch,
                                    _monotonic() - t0)

    def _restore_and_replay(self, node: Node, rec, events):
        t0 = _monotonic()
        node_state, todo = rec.restore()
        replayed = -1      # -1: state_restore itself not yet done
        try:
            node.state_restore(node_state)
            replayed = 0
            for src, item, lvl in todo:
                if self._dispatch_supervised(node, rec, events, src, item,
                                             lvl=lvl):
                    self._complete_barriers(node, rec, events)
                replayed += 1
        except BaseException:
            # a fault re-hit mid-replay: the crashing item is already
            # back in the journal (dispatch appends before handling) —
            # re-attach the unreplayed tail so the NEXT restore still
            # sees the full post-snapshot input sequence.  A failure in
            # state_restore itself (replayed == -1) re-attaches ALL of
            # it: nothing was consumed yet.
            rec.journal.extend(todo[replayed + 1:] if replayed >= 0
                               else todo)
            raise
        # a transient original fault may not re-raise on replay:
        # leftover skips must never swallow a future real quarantine
        rec.requarantine_skip = 0
        self._supervisor.note_restored(node, rec, len(todo),
                                       _monotonic() - t0)

    # ---------------------------------------------------------------- run

    def run(self):
        if self._threads:
            raise RuntimeError(
                f"Dataflow {self.name!r} already started; a graph runs once")
        if self.check not in (None, "off"):
            # pre-flight static analysis (docs/CHECKS.md): warn or — in
            # 'error' mode — raise CheckError BEFORE any thread (node,
            # sampler, supervisor writer) starts.  Lazily imported: the
            # unset default never touches the check package.
            from ..check import enforce
            enforce(self)
        if self.recovery is not None and self._supervisor is None:
            from ..recovery.supervisor import Supervisor
            self._supervisor = Supervisor(self, self.recovery)
            self._supervisor.attach_all()
        if (self.control is not None and self._controller is None
                and self.metrics is not None):
            # after the supervisor (rescale validation needs the
            # NodeRecovery records), before any thread (the controller
            # wraps source emission and installs epoch hooks)
            from ..control.controller import Controller
            self._controller = Controller(self, self.control)
            self._controller.attach()
        if self.events is not None:
            self.events.emit("dataflow_start", dataflow=self.name,
                             nodes=len(self.nodes),
                             sample_period=self.sample_period)
        for node in self.nodes:
            t = threading.Thread(target=self._run_node, args=(node,),
                                 name=f"{self.name}/{node.name}", daemon=True)
            self._threads.append(t)
            t.start()
        period = self.sample_period
        if period is None and self._controller is not None:
            # control without an explicit cadence: the sampler is the
            # controller's sensor bus, so run it at the policy's period
            period = self.control.period
        if (period is None and self.federate is not None
                and self.metrics is not None):
            # federation without an explicit cadence: the shipper rides
            # the sampler, so run it at the ship period
            period = self.federate.period
        if period is not None and self._sampler is None:
            from ..obs.sampler import Sampler
            self._sampler = Sampler(self, period)
            if self._controller is not None:
                self._sampler.subscribe(self._controller.on_sample)
            if self.federate is not None and self.metrics is not None:
                # the plane-wide telemetry tier (docs/OBSERVABILITY.md
                # "Federation & SLOs"): the shipper rides the sampler
                # like the controller does; the app binds the row
                # plane's senders with df.federation.bind(senders)
                from ..obs.federation import BlackBox, FederationShipper
                self.federation = FederationShipper(
                    self.federate, host=self.federate.host or self.name,
                    dataflow_name=self.name, metrics=self.metrics,
                    events=self.events)
                self._sampler.subscribe(self.federation.on_sample)
                if self.federate.blackbox:
                    self._blackbox = BlackBox(
                        self.trace_dir, self.name, events=self.events,
                        tracer=self.tracer, shipper=self.federation)
            self._sampler.start()

    def wait(self, timeout: float = None):
        """Join every node thread and re-raise the first node error.

        ``timeout`` (seconds, None = wait forever) bounds a hung graph:
        on expiry the graph is cancelled (failure flag + inbox wakeups,
        so blocked threads exit) and :class:`TimeoutError` is raised
        naming the still-running nodes — for soaks and CI, a loud bound
        instead of a suite-level kill.

        When several nodes failed, the first error is raised with the
        second chained as its ``__cause__`` and the full tuple attached
        as ``error.dataflow_errors`` — multi-node crashes stay
        diagnosable instead of silently dropping all but one."""
        timed_out = False
        try:
            if timeout is None:
                for t in self._threads:
                    t.join()
            else:
                t_end = _monotonic() + float(timeout)
                for t in self._threads:
                    t.join(max(t_end - _monotonic(), 0.0))
                    if t.is_alive():
                        timed_out = True
                        break
                if timed_out:
                    # unblock everything, then a short grace to exit
                    self._failed.set()
                    for inbox in self._inboxes.values():
                        inbox.cancel()
                    for t in self._threads:
                        t.join(timeout=1.0)
        finally:
            if self._sampler is not None:
                self._sampler.stop()   # takes the final flush sample
                self._sampler = None
            if self._controller is not None:
                # restore controller-tuned knobs on user-owned policy
                # objects (idempotent; controller.py close())
                self._controller.close()
            if self._supervisor is not None:
                # flush pending checkpoint blobs — briefly on the
                # timeout path, so wait(timeout=) keeps its bound
                self._supervisor.stop(wait_s=1.0 if timed_out else 30.0)
            if self.tracer is not None:
                self.tracer.close()     # flush buffered spans to disk
            if self.events is not None and not self._stop_logged:
                self._stop_logged = True
                self.events.emit("dataflow_stop", dataflow=self.name,
                                 errors=len(self._errors),
                                 dead_letters=len(self.dead_letters))
                self.events.close()
        if timed_out:
            alive = [t.name for t in self._threads if t.is_alive()]
            err = TimeoutError(
                f"Dataflow {self.name!r} still running after {timeout}s "
                f"(alive: {alive or 'draining'}); graph cancelled")
            if self._errors:
                # a node failure often CAUSES the hang (a sibling stuck
                # in user code past the cancel): keep the root cause
                # visible instead of masking it with the timeout
                err.dataflow_errors = tuple(self._errors)
                raise err from self._errors[0]
            raise err
        if self._errors:
            first = self._errors[0]
            rest = [e for e in self._errors[1:] if e is not first]
            if rest:
                first.dataflow_errors = tuple(self._errors)
                if first.__cause__ is None and first.__context__ is None:
                    first.__cause__ = rest[0]
            raise first

    def run_and_wait_end(self, timeout: float = None):
        self.run()
        self.wait(timeout=timeout)

    def cardinality(self) -> int:
        """Number of execution threads (multipipe.hpp:973)."""
        return len(self.nodes)

    def shed_counts(self) -> dict[str, int]:
        """Items shed per node (the node whose inbox dropped them), for
        graphs running a shedding OverloadPolicy; empty under the default
        blocking policy.  Stable once wait() returned."""
        out: dict[str, int] = {}
        for node in self.nodes:
            shed = getattr(self._inboxes[id(node)], "shed", 0)
            if shed:
                out[node.name] = out.get(node.name, 0) + shed
        return out
