"""Threaded dataflow engine — the runtime replacing FastFlow's pipeline of
pinned threads + lock-free SPSC queues (SURVEY.md §2.8).

Host-side dataflow stays on CPU threads exactly like the reference; the
difference is that channel payloads are whole batches, so queue traffic is
O(stream/chunk) instead of O(stream), and the Python GIL is released inside
the numpy/XLA kernels doing the real work.  When the native C++ substrate is
built (native/), Inbox transparently switches to the lock-free MPSC ring.

Topology model: a directed graph of Nodes. Each node owns one Inbox; an edge
(a -> b) reserves a source-slot in b's inbox so b can count per-channel EOS
(the FastFlow multi-in protocol) and ordering nodes can tell channels apart.
"""

from __future__ import annotations

import queue
import threading
from time import perf_counter_ns as _pc_ns

from .node import Node, RuntimeContext, SourceNode

_EOS = object()


class _Cancelled(BaseException):
    """Raised inside a node thread when the dataflow failed elsewhere —
    unblocks producers stuck on a dead consumer's bounded queue."""


class Inbox:
    """MPSC channel carrying (src_slot, batch) pairs.  Blocking operations
    poll the dataflow's failure flag so a raised node cannot deadlock the
    graph (a full queue whose consumer died would block producers
    forever)."""

    def __init__(self, capacity: int = 0, failed: threading.Event = None):
        self._q = queue.Queue(maxsize=capacity)
        self.n_sources = 0
        self._failed = failed

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _blocking(self, op):
        while True:
            try:
                return op()
            except (queue.Full, queue.Empty):
                if self._failed is not None and self._failed.is_set():
                    raise _Cancelled() from None

    def put(self, src: int, item):
        self._blocking(lambda: self._q.put((src, item), timeout=0.05))

    def put_eos(self, src: int):
        self._blocking(lambda: self._q.put((src, _EOS), timeout=0.05))

    def get(self):
        return self._blocking(lambda: self._q.get(timeout=0.05))


class Dataflow:
    """A graph of nodes executed by one thread per node
    (MultiPipe::run_and_wait_end spawns cardinality()-1 threads,
    multipipe.hpp:1010; same model here)."""

    def __init__(self, name: str = "dataflow", capacity: int = 16,
                 trace_dir: str = None):
        # bounded inboxes give natural backpressure (FastFlow's
        # FF_BOUNDED_BUFFER, the yahoo Makefile default): a source cannot
        # run unboundedly ahead of a slow consumer, keeping queue latency
        # proportional to capacity x batch size.  0 = unbounded.
        from ..utils.tracing import default_trace_dir
        self.name = name
        self.capacity = capacity
        self.trace_dir = trace_dir or default_trace_dir()
        self.nodes: list[Node] = []
        self._inboxes: dict[int, Inbox] = {}
        self._edges: list[tuple[Node, Node]] = []
        self._threads: list[threading.Thread] = []
        self._errors: list[BaseException] = []
        self._failed = threading.Event()

    def add(self, node: Node, ctx: RuntimeContext = None) -> Node:
        if ctx is not None:
            node.ctx = ctx
        self.nodes.append(node)
        self._inboxes[id(node)] = Inbox(self.capacity, self._failed)
        return node

    def connect(self, src: Node, dst: Node):
        """Add an edge; the order of connect() calls from one src defines its
        output-channel indexing (emit_to)."""
        inbox = self._inboxes[id(dst)]
        slot = inbox.register_source()
        src._outputs.append((inbox, slot))
        self._edges.append((src, dst))

    # ------------------------------------------------------------------ run

    def _run_node(self, node: Node):
        try:
            node.n_input_channels = self._inboxes[id(node)].n_sources
            if self.trace_dir:
                from ..utils.tracing import NodeStats
                # index disambiguates same-named nodes (two 'map.0' stages)
                idx = self.nodes.index(node)
                node.stats = NodeStats(f"{self.name}_{idx:02d}_{node.name}")
            node.svc_init()
            if isinstance(node, SourceNode):
                node.generate()
            else:
                inbox = self._inboxes[id(node)]
                live = inbox.n_sources
                stats = node.stats
                while live > 0:
                    src, item = inbox.get()
                    if item is _EOS:
                        live -= 1
                        node.on_channel_eos(src)
                    elif stats is None:
                        node.svc(item, src)
                    else:
                        t0 = _pc_ns()
                        node.svc(item, src)
                        stats.record_svc(len(item), _pc_ns() - t0)
            node.eosnotify()
            node.svc_end()
            if node.stats is not None:
                node.stats.write(self.trace_dir)
        except _Cancelled:
            pass  # the graph failed elsewhere; exit quietly
        except BaseException as e:  # propagate to run_and_wait_end
            self._errors.append(e)
            self._failed.set()  # unblock producers stuck on our inbox
        finally:
            try:
                for inbox, src in node._outputs:
                    inbox.put_eos(src)
            except _Cancelled:
                pass

    def run(self):
        if self._threads:
            raise RuntimeError(
                f"Dataflow {self.name!r} already started; a graph runs once")
        for node in self.nodes:
            t = threading.Thread(target=self._run_node, args=(node,),
                                 name=f"{self.name}/{node.name}", daemon=True)
            self._threads.append(t)
            t.start()

    def wait(self):
        for t in self._threads:
            t.join()
        if self._errors:
            raise self._errors[0]

    def run_and_wait_end(self):
        self.run()
        self.wait()

    def cardinality(self) -> int:
        """Number of execution threads (multipipe.hpp:973)."""
        return len(self.nodes)
