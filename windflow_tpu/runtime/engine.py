"""Threaded dataflow engine — the runtime replacing FastFlow's pipeline of
pinned threads + lock-free SPSC queues (SURVEY.md §2.8).

Host-side dataflow stays on CPU threads exactly like the reference; the
difference is that channel payloads are whole batches, so queue traffic is
O(stream/chunk) instead of O(stream), and the Python GIL is released inside
the numpy/XLA kernels doing the real work.  When the native C++ substrate is
built (native/), Inbox transparently switches to the native blocking MPSC
ring (mutex + condvar — the win over queue.Queue is GIL-released futex
waits instead of 50 ms polling, not lock-freedom).

Topology model: a directed graph of Nodes. Each node owns one Inbox; an edge
(a -> b) reserves a source-slot in b's inbox so b can count per-channel EOS
(the FastFlow multi-in protocol) and ordering nodes can tell channels apart.
"""

from __future__ import annotations

import os
import queue
import threading
from time import monotonic as _monotonic
from time import perf_counter_ns as _pc_ns
from time import sleep as _sleep

from .node import Node, RuntimeContext, SourceNode
from .overload import DeadLetter, OverloadError, OverloadPolicy

_EOS = object()


class _Cancelled(BaseException):
    """Raised inside a node thread when the dataflow failed elsewhere —
    unblocks producers stuck on a dead consumer's bounded queue."""


class Inbox:
    """MPSC channel carrying (src_slot, batch) pairs.  Blocking operations
    poll the dataflow's failure flag so a raised node cannot deadlock the
    graph (a full queue whose consumer died would block producers
    forever).

    An :class:`~windflow_tpu.runtime.overload.OverloadPolicy` reshapes the
    ``put`` side only (shed_oldest / shed_newest / deadline-bounded block);
    ``put_eos`` and ``get`` are policy-exempt — an EOS that is shed or
    timed out would corrupt the per-channel EOS counting.  Shed items are
    counted in ``self.shed`` (surfaced per node via tracing.NodeStats and
    ``Dataflow.shed_counts``)."""

    def __init__(self, capacity: int = 0, failed: threading.Event = None,
                 policy: OverloadPolicy = None):
        self._q = queue.Queue(maxsize=capacity)
        self.n_sources = 0
        self._failed = failed
        self._policy = policy if (policy is not None
                                  and policy.reshapes_put) else None
        self.shed = 0
        self._shed_lock = threading.Lock()
        #: occupancy high-water mark, maintained only when the dataflow
        #: is observed (metrics/sample_period): the put-side cost is a
        #: single predictable `_track` branch when off.  Updated without
        #: a lock — a lost race understates the mark by at most one
        #: concurrent put, a fine trade for a telemetry-only value.
        self.hwm = 0
        self._track = False

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _blocking(self, op):
        while True:
            try:
                return op()
            except (queue.Full, queue.Empty):
                if self._failed is not None and self._failed.is_set():
                    raise _Cancelled() from None

    def _record_shed(self):
        with self._shed_lock:
            self.shed += 1

    def _cancelled(self) -> bool:
        return self._failed is not None and self._failed.is_set()

    def put(self, src: int, item):
        pol = self._policy
        if pol is None:
            self._blocking(lambda: self._q.put((src, item), timeout=0.05))
        elif pol.shed == "shed_newest":
            try:
                self._q.put_nowait((src, item))
            except queue.Full:
                if self._cancelled():
                    # shed_newest never blocks, so this is the only spot
                    # a producer can observe a failed graph — without it
                    # an unbounded source would generate forever
                    raise _Cancelled() from None
                self._record_shed()
        elif pol.shed == "shed_oldest":
            self._put_shed_oldest(src, item)
        else:  # block with a deadline
            self._put_deadline(src, item, pol.put_deadline)
        if self._track:
            depth = self._q.qsize()
            if depth > self.hwm:
                self.hwm = depth

    def depth(self) -> int:
        """Current occupancy (items incl. queued EOS frames) — sampled
        by the observability layer, racy by design."""
        return self._q.qsize()

    def _put_shed_oldest(self, src: int, item):
        while True:
            try:
                return self._q.put_nowait((src, item))
            except queue.Full:
                if self._cancelled():
                    raise _Cancelled() from None
            # evict the head to admit the new item.  EOS frames must
            # survive: re-queue them at the tail (safe — EOS is its
            # channel's LAST frame, so per-channel order is preserved)
            try:
                victim = self._q.get_nowait()
            except queue.Empty:
                continue    # consumer drained it meanwhile; retry the put
            if victim[1] is _EOS:
                self._blocking(
                    lambda: self._q.put(victim, timeout=0.05))
                # shutdown skew: a full queue of only EOS frames would
                # otherwise hot-spin evict/re-queue until the (slow —
                # that's why shedding is on) consumer drains one
                _sleep(0.001)
            else:
                self._record_shed()

    def _put_deadline(self, src: int, item, deadline: float):
        t_end = _monotonic() + deadline
        while True:
            try:
                return self._q.put((src, item), timeout=0.05)
            except queue.Full:
                if self._cancelled():
                    raise _Cancelled() from None
                if _monotonic() >= t_end:
                    raise OverloadError(
                        f"inbox put blocked longer than the "
                        f"{deadline}s deadline (capacity "
                        f"{self._q.maxsize}): downstream stage is not "
                        f"keeping up") from None

    def put_eos(self, src: int):
        self._blocking(lambda: self._q.put((src, _EOS), timeout=0.05))

    def get(self):
        return self._blocking(lambda: self._q.get(timeout=0.05))

    def cancel(self):
        """Failure path: wake any blocked producer/consumer (the Python
        queue relies on the 50 ms poll; the native ring wakes instantly)."""


class NativeInbox:
    """Inbox over the C++ blocking ring (native/wf_native.cpp NativeQueue):
    blocking push/pop wait on a futex with the GIL released instead of the
    Python queue's 50 ms timeout polling.  Batch objects never cross the
    ABI — they sit in a side table keyed by the slot id the ring carries
    (the payload-pointer discipline of FastFlow's SPSC queues)."""

    def __init__(self, capacity: int, failed: threading.Event = None,
                 lib=None, policy: OverloadPolicy = None):
        self._lib = lib
        self._h = lib.wf_queue_new(capacity)
        self._items = {}
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.n_sources = 0
        self._policy = policy if (policy is not None
                                  and policy.reshapes_put) else None
        self.shed = 0
        self._shed_lock = threading.Lock()
        self.hwm = 0         # see Inbox: observed-dataflow occupancy mark
        self._track = False

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            # wf_queue_free closes first and spins until the last blocked
            # thread has left push/pop before destroying the mutex
            self._lib.wf_queue_free(h)
            self._h = None

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _slot_for(self, item) -> int:
        with self._seq_lock:
            self._seq += 1
            slot = self._seq
        self._items[slot] = item
        return slot

    def _push(self, src: int, item):
        slot = self._slot_for(item)
        if self._lib.wf_queue_push(self._h, src, slot) != 0:
            self._items.pop(slot, None)
            raise _Cancelled()

    def _record_shed(self):
        with self._shed_lock:
            self.shed += 1

    def put(self, src: int, item):
        pol = self._policy
        if pol is None:
            self._push(src, item)
        elif pol.shed == "shed_newest":
            slot = self._slot_for(item)
            rc = self._lib.wf_queue_try_push(self._h, src, slot)
            if rc != 0:
                self._items.pop(slot, None)
                if rc < 0:
                    raise _Cancelled()
                self._record_shed()
        elif pol.shed == "shed_oldest":
            self._put_shed_oldest(src, self._slot_for(item))
        else:  # block with a deadline
            slot = self._slot_for(item)
            rc = self._lib.wf_queue_push_timed(
                self._h, src, slot, int(pol.put_deadline * 1000))
            if rc != 0:
                self._items.pop(slot, None)
                if rc < 0:
                    raise _Cancelled()
                raise OverloadError(
                    f"inbox put blocked longer than the "
                    f"{pol.put_deadline}s deadline (native ring): "
                    f"downstream stage is not keeping up")
        if self._track:
            depth = len(self._items)
            if depth > self.hwm:
                self.hwm = depth

    def depth(self) -> int:
        """Occupancy proxy: the payload side table holds exactly the
        items whose slot ids sit in the ring (plus any mid-handoff)."""
        return len(self._items)

    def _put_shed_oldest(self, src: int, slot: int):
        import ctypes
        lib = self._lib
        vsrc = ctypes.c_longlong()
        vslot = ctypes.c_longlong()
        while True:
            rc = lib.wf_queue_try_push(self._h, src, slot)
            if rc == 0:
                return
            if rc < 0:
                self._items.pop(slot, None)
                raise _Cancelled()
            # full: evict the head to admit the new item (EOS survives —
            # re-queued at the tail, see Inbox._put_shed_oldest)
            rc2 = lib.wf_queue_try_pop(self._h, ctypes.byref(vsrc),
                                       ctypes.byref(vslot))
            if rc2 < 0:
                self._items.pop(slot, None)
                raise _Cancelled()
            if rc2 == 1:
                continue    # consumer drained it meanwhile; retry the push
            victim = self._items.pop(vslot.value)
            if victim is _EOS:
                self._push(vsrc.value, victim)
                _sleep(0.001)   # see Inbox._put_shed_oldest: no hot spin
            else:
                self._record_shed()

    def put_eos(self, src: int):
        self._push(src, _EOS)

    def get(self):
        import ctypes
        src = ctypes.c_longlong()
        slot = ctypes.c_longlong()
        if self._lib.wf_queue_pop(self._h, ctypes.byref(src),
                                  ctypes.byref(slot)) != 0:
            raise _Cancelled()
        return src.value, self._items.pop(slot.value)

    def cancel(self):
        self._lib.wf_queue_close(self._h)


def _make_inbox(capacity: int, failed: threading.Event,
                policy: OverloadPolicy = None):
    if capacity > 0:  # capacity 0 = unbounded, which only the Python
        from ..native import enabled  # queue implements
        lib = enabled()
        if lib is not None and (
                policy is None or not policy.reshapes_put
                or getattr(lib, "wf_has_overload_queue", False)):
            # an old .so without the overload entry points still serves
            # every default path; only active shed/deadline knobs fall
            # back to the Python queue
            return NativeInbox(capacity, failed, lib=lib, policy=policy)
    return Inbox(capacity, failed, policy)


class Dataflow:
    """A graph of nodes executed by one thread per node
    (MultiPipe::run_and_wait_end spawns cardinality()-1 threads,
    multipipe.hpp:1010; same model here)."""

    def __init__(self, name: str = "dataflow", capacity: int = 16,
                 trace_dir: str = None, overload: OverloadPolicy = None,
                 metrics=None, sample_period: float = None):
        # bounded inboxes give natural backpressure (FastFlow's
        # FF_BOUNDED_BUFFER, the yahoo Makefile default): a source cannot
        # run unboundedly ahead of a slow consumer, keeping queue latency
        # proportional to capacity x batch size.  0 = unbounded.
        # `overload` (runtime/overload.py) opts the graph into shedding /
        # put deadlines / poison-tuple quarantine; None = seed behavior.
        # `metrics` (a MetricsRegistry, or truthy for a fresh one) and
        # `sample_period` (seconds; also the WF_SAMPLE_PERIOD env hook)
        # opt into the observability layer (docs/OBSERVABILITY.md):
        # a background sampler owned by this graph writes
        # <trace_dir>/metrics.jsonl and a structured event log writes
        # <trace_dir>/events.jsonl.  Both unset = no thread, no files,
        # and inbox hot paths keep a single disabled branch.
        from ..utils.tracing import default_sample_period, default_trace_dir
        if overload is not None and overload.reshapes_put and capacity <= 0:
            # an unbounded queue never fills: every shed/deadline knob
            # would be silently inert while memory grows without bound
            raise ValueError(
                f"OverloadPolicy with shed={overload.shed!r}/"
                f"put_deadline={overload.put_deadline} needs a bounded "
                f"inbox (capacity > 0, got {capacity}): an unbounded "
                f"queue never sheds and never times out")
        self.name = name
        self.capacity = capacity
        self.trace_dir = trace_dir or default_trace_dir()
        self.overload = overload
        if sample_period is None:
            sample_period = default_sample_period()
        if sample_period is not None and float(sample_period) <= 0:
            raise ValueError(f"sample_period must be positive seconds, "
                             f"got {sample_period}")
        self.sample_period = sample_period
        self._sampler = None
        # truthiness, not `is not None`: metrics=False/0 must mean OFF
        # (docs/OBSERVABILITY.md — "any truthy value for a fresh one")
        if metrics or sample_period is not None:
            from ..obs import EventLog, MetricsRegistry
            #: live metrics registry shared with channels/user functions
            self.metrics = (metrics if isinstance(metrics, MetricsRegistry)
                            else MetricsRegistry())
            #: structured runtime event log (file iff trace_dir is set;
            #: the file opens lazily, so a never-run preview graph
            #: creates nothing on disk)
            self.events = EventLog(
                os.path.join(self.trace_dir, "events.jsonl")
                if self.trace_dir else None)
        else:
            self.metrics = None
            self.events = None
        self.nodes: list[Node] = []
        self._inboxes: dict[int, Inbox] = {}
        self._edges: list[tuple[Node, Node]] = []
        self._threads: list[threading.Thread] = []
        self._errors: list[BaseException] = []
        self._failed = threading.Event()
        #: quarantined poison batches (DeadLetter records, arrival order);
        #: inspect after wait() — only ever populated when an error budget
        #: is set (overload.error_budget or a node/pattern-level budget)
        self.dead_letters: list[DeadLetter] = []
        self._dead_lock = threading.Lock()
        self._stop_logged = False

    def _inbox_policy(self, node: Node) -> OverloadPolicy:
        """Shedding applies only at shed-safe inboxes (farm heads and
        stateless operators — dropping there means dropping raw stream
        items).  Internal farm edges (window multicast copies, dense-id
        result streams, ordering merges) keep blocking, so overload
        backpressures through them to the nearest shed-safe inbox
        upstream instead of silently corrupting window state.  A put
        deadline (block policy) is loud, not lossy, so it applies
        everywhere."""
        pol = self.overload
        if (pol is not None and pol.shed != "block"
                and not getattr(node, "shed_safe", False)):
            return None
        return pol

    def add(self, node: Node, ctx: RuntimeContext = None) -> Node:
        if ctx is not None:
            node.ctx = ctx
        self.nodes.append(node)
        inbox = _make_inbox(self.capacity, self._failed,
                            self._inbox_policy(node))
        if self.metrics is not None or self.sample_period is not None:
            inbox._track = True  # maintain the occupancy high-water mark
        self._inboxes[id(node)] = inbox
        return node

    def connect(self, src: Node, dst: Node):
        """Add an edge; the order of connect() calls from one src defines its
        output-channel indexing (emit_to)."""
        inbox = self._inboxes[id(dst)]
        slot = inbox.register_source()
        src._outputs.append((inbox, slot))
        self._edges.append((src, dst))

    # ------------------------------------------------------------------ run

    def _error_budget_of(self, node: Node) -> int:
        """Effective poison-tuple allowance: node-level override first
        (builders' withErrorBudget / a pattern's error_budget, propagated
        onto replicas by runtime/farm.py), then the dataflow policy —
        except for quarantine-exempt framework shells (emitters,
        collectors, ordering merges), which never inherit the policy
        default: an error there is a framework bug, not a poison tuple."""
        budget = getattr(node, "error_budget", None)
        if budget is None:
            if getattr(node, "quarantine_exempt", False):
                return 0
            budget = (self.overload.error_budget
                      if self.overload is not None else 0)
        return int(budget)

    def _quarantine(self, node: Node, batch, channel: int,
                    error: BaseException):
        letter = DeadLetter(node.name, batch, channel, error)
        with self._dead_lock:
            self.dead_letters.append(letter)
        if node.stats is not None:
            node.stats.record_quarantined()
        if self.events is not None:
            self.events.emit("quarantine", dataflow=self.name,
                             **letter.to_event())

    def _run_node(self, node: Node):
        events = self.events
        try:
            node.n_input_channels = self._inboxes[id(node)].n_sources
            if self.trace_dir or self.metrics is not None \
                    or self.sample_period is not None:
                from ..utils.tracing import NodeStats, node_stats_name
                # index disambiguates same-named nodes (two 'map.0' stages)
                idx = self.nodes.index(node)
                node.stats = NodeStats(node_stats_name(self.name, idx,
                                                       node.name))
            if self.metrics is not None:
                # rich user functions may bump custom metrics through
                # their RuntimeContext (ctx.metrics.counter(...).inc())
                node.ctx.metrics = self.metrics
            if events is not None:
                events.emit("node_start", dataflow=self.name,
                            node=node.name,
                            source=isinstance(node, SourceNode))
            node.svc_init()
            if isinstance(node, SourceNode):
                node.generate()
            else:
                inbox = self._inboxes[id(node)]
                live = inbox.n_sources
                stats = node.stats
                budget = self._error_budget_of(node)
                while live > 0:
                    src, item = inbox.get()
                    if item is _EOS:
                        live -= 1
                        node.on_channel_eos(src)
                        if events is not None:
                            events.emit("eos", dataflow=self.name,
                                        node=node.name, channel=src,
                                        live=live)
                    elif budget > 0:
                        # poison-tuple quarantine: an svc error within
                        # budget parks the batch in the dead-letter queue
                        # and the node lives on; once the budget is spent
                        # the next error fails fast exactly like default
                        try:
                            if stats is None:
                                node.svc(item, src)
                            else:
                                t0 = _pc_ns()
                                node.svc(item, src)
                                stats.record_svc(len(item), _pc_ns() - t0)
                        except OverloadError:
                            # a put deadline expiring inside svc's emit is
                            # backpressure failure, not a poison tuple —
                            # it must fail fast, not burn the budget
                            raise
                        except Exception as e:  # _Cancelled passes through
                            budget -= 1
                            self._quarantine(node, item, src, e)
                    elif stats is None:
                        node.svc(item, src)
                    else:
                        t0 = _pc_ns()
                        node.svc(item, src)
                        stats.record_svc(len(item), _pc_ns() - t0)
            node.eosnotify()
            node.svc_end()
            if node.stats is not None:
                shed = getattr(self._inboxes[id(node)], "shed", 0)
                if shed:
                    node.stats.record_shed(shed)
                if self.trace_dir:
                    node.stats.write(self.trace_dir)
            if events is not None:
                stop = {"dataflow": self.name, "node": node.name}
                if node.stats is not None:
                    stop["rcv_batches"] = node.stats.rcv_batches
                    stop["rcv_tuples"] = node.stats.rcv_tuples
                    stop.update({k: v for k, v
                                 in node.stats.counters.items()
                                 if k not in ("t", "event")})
                events.emit("node_stop", **stop)
        except _Cancelled:
            pass  # the graph failed elsewhere; exit quietly
        except BaseException as e:  # propagate to run_and_wait_end
            self._errors.append(e)
            self._failed.set()  # unblock producers stuck on our inbox
            if events is not None:
                events.emit("node_error", dataflow=self.name,
                            node=node.name, error=type(e).__name__,
                            message=str(e))
            for inbox in self._inboxes.values():
                inbox.cancel()  # native rings wake instantly
        finally:
            try:
                for inbox, src in node._outputs:
                    inbox.put_eos(src)
            except _Cancelled:
                pass

    def run(self):
        if self._threads:
            raise RuntimeError(
                f"Dataflow {self.name!r} already started; a graph runs once")
        if self.events is not None:
            self.events.emit("dataflow_start", dataflow=self.name,
                             nodes=len(self.nodes),
                             sample_period=self.sample_period)
        for node in self.nodes:
            t = threading.Thread(target=self._run_node, args=(node,),
                                 name=f"{self.name}/{node.name}", daemon=True)
            self._threads.append(t)
            t.start()
        if self.sample_period is not None and self._sampler is None:
            from ..obs.sampler import Sampler
            self._sampler = Sampler(self, self.sample_period)
            self._sampler.start()

    def wait(self):
        try:
            for t in self._threads:
                t.join()
        finally:
            if self._sampler is not None:
                self._sampler.stop()   # takes the final flush sample
                self._sampler = None
            if self.events is not None and not self._stop_logged:
                self._stop_logged = True
                self.events.emit("dataflow_stop", dataflow=self.name,
                                 errors=len(self._errors),
                                 dead_letters=len(self.dead_letters))
                self.events.close()
        if self._errors:
            raise self._errors[0]

    def run_and_wait_end(self):
        self.run()
        self.wait()

    def cardinality(self) -> int:
        """Number of execution threads (multipipe.hpp:973)."""
        return len(self.nodes)

    def shed_counts(self) -> dict[str, int]:
        """Items shed per node (the node whose inbox dropped them), for
        graphs running a shedding OverloadPolicy; empty under the default
        blocking policy.  Stable once wait() returned."""
        out: dict[str, int] = {}
        for node in self.nodes:
            shed = getattr(self._inboxes[id(node)], "shed", 0)
            if shed:
                out[node.name] = out.get(node.name, 0) + shed
        return out
