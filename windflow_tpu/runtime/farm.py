"""Wiring helpers: build farm shells (emitter -> replicas -> collector) into
a Dataflow graph — the structural equivalent of the reference's
``ff_farm(emitter, workers, collector)`` containers (map.hpp:196-209) and of
pipeline composition.  MultiPipe (api/) layers the fluent construction on
top of these primitives.
"""

from __future__ import annotations

from .engine import Dataflow
from .node import Node

#: sentinel: "use the pattern's default shell node"; pass None to fuse it away
DEFAULT = object()


def _apply_error_budget(pattern, replicas: list[Node]) -> list[Node]:
    """Propagate per-node policy knobs a pattern carries onto the worker
    nodes the engine actually runs — shell nodes (emitter/collector)
    keep their class defaults:

    * ``error_budget`` (builders' withErrorBudget): poison-tuple
      quarantine allowance — an error in a shell is a framework bug,
      not a poison tuple, so shells never inherit it;
    * ``recoverable`` (a pattern attribute, default absent): an explicit
      False opts the pattern's workers out of supervised restart
      (docs/ROBUSTNESS.md "Recovery") — e.g. a sink with irreversible
      external side effects where replayed emissions must not re-fire.
    """
    budget = getattr(pattern, "error_budget", None)
    if budget is not None:
        for r in replicas:
            r.error_budget = int(budget)
    recover = getattr(pattern, "recoverable", None)
    if recover is not None:
        for r in replicas:
            r.recoverable = bool(recover)
    return replicas


def _provision_rescale(df: Dataflow, pattern) -> int | None:
    """Control-plane pre-provisioning (docs/CONTROL.md): when a
    ``Rescale`` rule targets this pattern, widen its worker set to the
    rule's ``max_workers`` at build time — the engine graph is fixed once
    ``run()`` starts, so elasticity means building the ceiling and
    routing over an *active* subset (emitters' ``n_active``).  Returns
    the initial active width (the pattern's declared parallelism), or
    None when no rule applies."""
    ctl = getattr(df, "control", None)
    rule = (ctl.rescale_for(getattr(pattern, "name", None))
            if ctl is not None else None)
    if rule is None:
        return None
    if getattr(df, "metrics", None) is None:
        # blind control (WF209): the engine never attaches a Controller,
        # so pre-provisioned spare workers could never activate — build
        # the farm at its declared width instead of parking idle threads
        return None
    if getattr(pattern, "routing", None) is None:
        raise ValueError(
            f"[WF210] Rescale rule targets {pattern.name!r}, which is "
            f"not key-partitioned (no keyed routing): live rescale "
            f"migrates per-key state between workers, and a "
            f"window-parallel farm's workers own window slices, not "
            f"keys — wrap the computation in a Key_Farm "
            f"(docs/CONTROL.md)")
    if getattr(pattern, "recoverable", None) is False:
        raise ValueError(
            f"[WF210] Rescale rule targets {pattern.name!r}, whose "
            f"recoverable flag is opted out: a pattern that cannot "
            f"snapshot cannot seal the migration cut — drop the "
            f"opt-out or the rule (docs/CONTROL.md)")
    if getattr(pattern, "n_emitters", 1) > 1:
        raise ValueError(
            f"Rescale rule targets multi-emitter farm {pattern.name!r}: "
            f"ordered multi-emitter merges pin the channel count at "
            f"build time and cannot rescale")
    n0 = getattr(pattern, "_ctl_width0", None)
    if n0 is None:
        n0 = pattern.parallelism
        pattern._ctl_width0 = n0
    # validated on EVERY build, stamped or not: a pattern reused under a
    # different rule must not route n_active past the new ceiling
    if not rule.min_workers <= n0 <= rule.max_workers:
        raise ValueError(
            f"{pattern.name!r}: declared parallelism {n0} outside "
            f"the Rescale rule's [{rule.min_workers}, "
            f"{rule.max_workers}] range")
    # widen for THIS build only — add_farm restores the declared width
    # after wiring, so the user's pattern object is not permanently
    # mutated (a later control-less build must not inherit the ceiling)
    pattern.parallelism = rule.max_workers
    return n0


def add_farm(df: Dataflow, pattern, upstreams: list[Node],
             emitter: Node = DEFAULT, collector: Node = DEFAULT) -> list[Node]:
    """Instantiate `pattern` as emitter -> replicas -> collector, feeding it
    from `upstreams`.  Pass emitter/collector = None to fuse the shell node
    away (the LEVEL1 `ff_comb` analog, pane_farm.hpp:435).  Pass-through
    shells at parallelism 1 are skipped automatically.  Returns the nodes
    downstream should connect from."""
    if hasattr(pattern, "instantiate"):
        # composite pattern (a pipeline of farms, e.g. Pane_Farm): it wires
        # its own stages (reference: Pane_Farm is an ff_pipeline of two
        # Win_Seq/Win_Farm stages, pane_farm.hpp:149-181)
        if emitter is not DEFAULT or collector is not DEFAULT:
            raise ValueError(
                "emitter/collector overrides do not apply to composite "
                f"patterns ({type(pattern).__name__} wires its own stages)")
        return pattern.instantiate(df, upstreams)
    n_emitters = getattr(pattern, "n_emitters", 1)
    if n_emitters > 1 and emitter is DEFAULT:
        # multi-emitter farm (win_farm.hpp:147-166): one emitter clone per
        # upstream producer, all-to-all into OrderingCore-fronted workers
        # that k-way-merge the emitters' interleaved substreams
        if len(upstreams) != n_emitters:
            raise ValueError(
                f"{pattern.name}: n_emitters={n_emitters} needs exactly "
                f"that many upstream producers, got {len(upstreams)}")
        replicas = _apply_error_budget(pattern, pattern.replicas())
        for r in replicas:
            df.add(r)
        for up in upstreams:
            em = pattern.emitter()
            df.add(em)
            df.connect(up, em)
            for r in replicas:
                df.connect(em, r)
        if collector is DEFAULT:
            collector = pattern.collector()
        if collector is not None:
            df.add(collector)
            for r in replicas:
                df.connect(r, collector)
            return [collector]
        return replicas
    rescale_width = _provision_rescale(df, pattern)
    try:
        replicas = _apply_error_budget(pattern, pattern.replicas())
        for r in replicas:
            df.add(r)
        if emitter is DEFAULT:
            emitter = pattern.emitter()
            # a 1-replica unrouted farm needs no emitter thread: the
            # engine's multi-in inboxes merge upstreams at the replica
            # directly
            if (emitter is not None
                    and type(emitter).__name__ == "StandardEmitter"
                    and pattern.parallelism == 1):
                emitter = None
        if rescale_width is not None:
            if emitter is None or not hasattr(emitter, "n_active"):
                raise ValueError(
                    f"Rescale rule targets {pattern.name!r} but its farm "
                    f"has no routing emitter to move the active width on")
            emitter.n_active = rescale_width
            df._farms.append({
                "pattern": pattern, "emitter": emitter,
                "workers": replicas,
                "rule": df.control.rescale_for(pattern.name),
                "width": rescale_width,
            })
        if collector is DEFAULT:
            collector = pattern.collector()
            if (collector is not None
                    and type(collector).__name__ == "Collector"
                    and pattern.parallelism == 1):
                collector = None
    finally:
        if rescale_width is not None:
            # the widening was for shell/replica construction only (the
            # emitter/collector fuse checks above must see the ceiling):
            # hand the user's pattern object back at its declared width
            # on EVERY exit, so neither a later control-less build nor a
            # failed one inherits max_workers
            pattern.parallelism = rescale_width
    if emitter is not None:
        df.add(emitter)
        for up in upstreams:
            df.connect(up, emitter)
        for r in replicas:
            df.connect(emitter, r)
    elif upstreams:
        # fused emitter: wire upstreams straight to replicas
        if len(replicas) == 1:
            for up in upstreams:
                df.connect(up, replicas[0])
        elif len(upstreams) == len(replicas):
            for up, r in zip(upstreams, replicas):
                df.connect(up, r)
        else:
            raise ValueError(
                f"cannot fuse emitter: {len(upstreams)} upstreams vs "
                f"{len(replicas)} replicas (all-to-all would duplicate data)")
    if collector is not None:
        df.add(collector)
        for r in replicas:
            df.connect(r, collector)
        return [collector]
    return replicas


def _is_passthrough_emitter(em) -> bool:
    return em is None or type(em).__name__ == "StandardEmitter"


def fuse_two_stage(df: Dataflow, stage1, stage2, upstreams: list[Node],
                   level: int) -> list[Node]:
    """LEVEL1/LEVEL2 fusion of a two-stage windowed composite — the
    engine-side port of ``optimize_PaneFarm`` / ``optimize_WinMapReduce``
    (pane_farm.hpp:426-466, win_mapreduce.hpp's mirror).

    * LEVEL1: both boundary nodes survive but run in ONE thread — the
      stage-1 collector and stage-2 emitter become a :class:`Comb`
      (``combine_nodes_in_pipeline``, pane_farm.hpp:435-449).  With both
      stages at degree 1 the two window cores themselves fuse into one
      thread.
    * LEVEL2: the stage-1 collector is REMOVED; a clone of stage 2's
      emitter is fused onto every stage-1 worker
      (``combine_farms(plq, wlq_emitter, wlq, OrderingNode)``,
      pane_farm.hpp:459), and every stage-2 worker is fronted by an
      OrderingCore that k-way merges the stage-1 workers' substreams
      (the ff_comb(OrderingNode, worker) of multipipe.hpp:218-224).
    """
    from ..runtime.comb import make_comb
    from ..runtime.node import RuntimeContext
    from ..runtime.ordering import OrderingMode
    from ..patterns.win_farm import WinFarm, _OrderedWorkerNode
    from ..core.windows import WinType

    P = stage1.parallelism
    W = stage2.parallelism

    if level >= 2:
        # ---- stage 1 workers, each with a fused stage-2 emitter clone ----
        s1_workers = _apply_error_budget(stage1, stage1.replicas())
        need_emitter = (W > 1
                        and not _is_passthrough_emitter(stage2.emitter()))
        combs = []
        for w in s1_workers:
            if not need_emitter:
                combs.append(w)   # single consumer: no routing needed
            else:
                em = stage2.emitter()
                combs.append(make_comb([w, em], name=f"{w.name}+{em.name}"))
        for c in combs:
            df.add(c)
        s1_em = stage1.emitter()
        if _is_passthrough_emitter(s1_em) and P == 1:
            for up in upstreams:
                df.connect(up, combs[0])
        else:
            df.add(s1_em)
            for up in upstreams:
                df.connect(up, s1_em)
            for c in combs:
                df.connect(s1_em, c)
        # ---- stage 2 workers fronted by an OrderingCore over P channels ----
        # per-key watermarks: stage-1 workers emit per-key renumbered ids
        # (PLQ/MAP role), which are NOT globally monotone per channel
        if isinstance(stage2, WinFarm):
            stage2.n_emitters = P   # replicas become _OrderedWorkerNodes
            stage2.ordering_per_key = True
            s2_workers = _apply_error_budget(stage2, stage2.replicas())
        else:  # degree-1 sequential stage
            mode = (OrderingMode.ID
                    if stage2.spec.win_type is WinType.CB else OrderingMode.TS)
            node = _OrderedWorkerNode(stage2.make_core(), P, mode,
                                      f"{stage2.name}.0", per_key=True)
            node.ctx = RuntimeContext(1, 0, stage2.name)
            s2_workers = [node]
        for r in s2_workers:
            df.add(r)
        for c in combs:
            for r in s2_workers:
                df.connect(c, r)
        collector = stage2.collector() if hasattr(stage2, "collector") else None
        if collector is not None and not (
                type(collector).__name__ == "Collector" and W == 1):
            df.add(collector)
            for r in s2_workers:
                df.connect(r, collector)
            return [collector]
        return s2_workers

    # ---- LEVEL1 ----
    if P == 1 and W == 1:
        # two sequential cores in one thread (ff_comb of the two Win_Seqs)
        s1 = stage1.replicas()[0]
        s2 = stage2.replicas()[0]
        comb = make_comb([s1, s2], name=f"{s1.name}+{s2.name}")
        df.add(comb)
        for up in upstreams:
            df.connect(up, comb)
        return [comb]
    # fuse the boundary: stage-1 collector + stage-2 emitter in one thread
    s1_coll = stage1.collector()
    s2_em = stage2.emitter()
    if s1_coll is None or _is_passthrough_emitter(s2_em):
        tails = add_farm(df, stage1, upstreams)
        return add_farm(df, stage2, tails)
    boundary = make_comb([s1_coll, s2_em],
                         name=f"{s1_coll.name}+{s2_em.name}")
    add_farm(df, stage1, upstreams, collector=boundary)
    # the fused emitter routes per output channel: boundary channel d is
    # stage-2 worker d (connect order defines emit_to indexing)
    reps = stage2.replicas()
    for r in reps:
        df.add(r)
        df.connect(boundary, r)
    collector = stage2.collector()
    if collector is not None and not (
            type(collector).__name__ == "Collector" and W == 1):
        df.add(collector)
        for r in reps:
            df.connect(r, collector)
        return [collector]
    return reps


def build_pipeline(df: Dataflow, patterns: list) -> list[Node]:
    """Chain patterns into a linear pipeline; returns the tail nodes."""
    tails: list[Node] = []
    for p in patterns:
        tails = add_farm(df, p, tails)
    return tails
