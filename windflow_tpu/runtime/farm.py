"""Wiring helpers: build farm shells (emitter -> replicas -> collector) into
a Dataflow graph — the structural equivalent of the reference's
``ff_farm(emitter, workers, collector)`` containers (map.hpp:196-209) and of
pipeline composition.  MultiPipe (api/) layers the fluent construction on
top of these primitives.
"""

from __future__ import annotations

from .engine import Dataflow
from .node import Node

#: sentinel: "use the pattern's default shell node"; pass None to fuse it away
DEFAULT = object()


def add_farm(df: Dataflow, pattern, upstreams: list[Node],
             emitter: Node = DEFAULT, collector: Node = DEFAULT) -> list[Node]:
    """Instantiate `pattern` as emitter -> replicas -> collector, feeding it
    from `upstreams`.  Pass emitter/collector = None to fuse the shell node
    away (the LEVEL1 `ff_comb` analog, pane_farm.hpp:435).  Pass-through
    shells at parallelism 1 are skipped automatically.  Returns the nodes
    downstream should connect from."""
    if hasattr(pattern, "instantiate"):
        # composite pattern (a pipeline of farms, e.g. Pane_Farm): it wires
        # its own stages (reference: Pane_Farm is an ff_pipeline of two
        # Win_Seq/Win_Farm stages, pane_farm.hpp:149-181)
        if emitter is not DEFAULT or collector is not DEFAULT:
            raise ValueError(
                "emitter/collector overrides do not apply to composite "
                f"patterns ({type(pattern).__name__} wires its own stages)")
        return pattern.instantiate(df, upstreams)
    replicas = pattern.replicas()
    for r in replicas:
        df.add(r)
    if emitter is DEFAULT:
        emitter = pattern.emitter()
        # a 1-replica unrouted farm needs no emitter thread: the engine's
        # multi-in inboxes merge upstreams at the replica directly
        if (emitter is not None and type(emitter).__name__ == "StandardEmitter"
                and pattern.parallelism == 1):
            emitter = None
    if collector is DEFAULT:
        collector = pattern.collector()
        if (collector is not None and type(collector).__name__ == "Collector"
                and pattern.parallelism == 1):
            collector = None
    if emitter is not None:
        df.add(emitter)
        for up in upstreams:
            df.connect(up, emitter)
        for r in replicas:
            df.connect(emitter, r)
    elif upstreams:
        # fused emitter: wire upstreams straight to replicas
        if len(replicas) == 1:
            for up in upstreams:
                df.connect(up, replicas[0])
        elif len(upstreams) == len(replicas):
            for up, r in zip(upstreams, replicas):
                df.connect(up, r)
        else:
            raise ValueError(
                f"cannot fuse emitter: {len(upstreams)} upstreams vs "
                f"{len(replicas)} replicas (all-to-all would duplicate data)")
    if collector is not None:
        df.add(collector)
        for r in replicas:
            df.connect(r, collector)
        return [collector]
    return replicas


def build_pipeline(df: Dataflow, patterns: list) -> list[Node]:
    """Chain patterns into a linear pipeline; returns the tail nodes."""
    tails: list[Node] = []
    for p in patterns:
        tails = add_farm(df, p, tails)
    return tails
