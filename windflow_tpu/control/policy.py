"""ControlPolicy — the declarative rule set of the closed-loop control
plane (docs/CONTROL.md).

Passing a policy to ``Dataflow``/``MultiPipe`` (``control=``) opts the
graph in; ``None`` (the default everywhere) keeps every code path
seed-identical and the ``windflow_tpu.control`` package unimported — the
same contract as ``overload=``/``metrics=``/``recovery=``/``check=``.

A policy is a list of rules, each closing one loop between the sensors
PR 4 built (sampler snapshots: inbox depth, shed counters) and an
actuator:

* :class:`Rescale` — grow/shrink a key-partitioned farm's active worker
  set at the next epoch barrier (the PR 8 consistent cut), migrating
  per-key window state between workers (control/rescale.py);
* :class:`AdaptiveShed` — tighten/relax the running
  :class:`~windflow_tpu.runtime.overload.OverloadPolicy`'s ``soft_limit``
  under sustained backpressure, so shedding starts *before* inboxes are
  full;
* :class:`Admission` — a token-bucket rate cap on source emission the
  controller moves between ``min_rate`` and ``max_rate``.

Every rule shares one trigger shape: a high and a low threshold over a
sampled signal, ``hysteresis`` consecutive samples required on the same
side before acting, and a ``cooldown`` (seconds) after every action —
the classic anti-flap pair.  ``observe()`` is a pure state machine over
``(value, now)`` pairs, unit-testable without a running graph
(tests/test_control.py).
"""

from __future__ import annotations

_NEG_INF = float("-inf")


class _ThresholdRule:
    """Shared high/low trigger with hysteresis + cooldown (see module
    docstring).  Subclasses define what "high" actuates."""

    def __init__(self, high, low, hysteresis: int = 2,
                 cooldown: float = 2.0):
        if high is not None and low is not None and low >= high:
            raise ValueError(
                f"{type(self).__name__}: low threshold ({low}) must be < "
                f"high threshold ({high}) — equal or inverted thresholds "
                f"oscillate on every sample")
        if int(hysteresis) < 1:
            raise ValueError("hysteresis must be >= 1 sample")
        if float(cooldown) < 0:
            raise ValueError("cooldown must be >= 0 seconds")
        self.high = high
        self.low = low
        self.hysteresis = int(hysteresis)
        self.cooldown = float(cooldown)
        self._high_n = 0
        self._low_n = 0
        self._last_t = _NEG_INF

    def _classify(self, value) -> int:
        """+1 when the signal is at/above ``high``, -1 when at/below
        ``low``, else 0 — subclasses with several signals override."""
        if self.high is not None and value >= self.high:
            return 1
        if self.low is not None and value <= self.low:
            return -1
        return 0

    def observe(self, value, now: float) -> int:
        """Feed one sample; returns +1 (high side persisted), -1 (low
        side persisted) or 0.  Streaks reset on every side change and on
        every action; during the cooldown window samples still feed the
        streaks but no action fires."""
        side = self._classify(value)
        self._high_n = self._high_n + 1 if side > 0 else 0
        self._low_n = self._low_n + 1 if side < 0 else 0
        if now - self._last_t < self.cooldown:
            return 0
        if self._high_n >= self.hysteresis:
            self._fired(now)
            return 1
        if self._low_n >= self.hysteresis:
            self._fired(now)
            return -1
        return 0

    def _fired(self, now: float):
        self._last_t = now
        self._high_n = self._low_n = 0

    def reset(self):
        """Clear the trigger state (streaks + cooldown clock) — the
        Controller calls this at attach so a policy object reused for a
        second run does not inherit the first run's cooldowns.  (Do not
        share one live policy between two CONCURRENTLY running graphs:
        two sampler threads would drive one unsynchronized state
        machine.)"""
        self._high_n = self._low_n = 0
        self._last_t = _NEG_INF


class Rescale(_ThresholdRule):
    """Elastic width for one key-partitioned farm (Key_Farm, keyed
    Accumulator/stateless farms): the farm is built with
    ``max_workers`` replicas, ``pattern.parallelism`` of them initially
    active, and the controller moves the active width by ``step`` at the
    next epoch barrier when the rule fires.

    Signals (per sample): the **max inbox depth across active workers**
    against ``up_depth``/``down_depth``, the farm head's **shed rate**
    (items/s since the previous sample) against ``up_shed`` — sustained
    shedding at the emitter means the whole farm is saturated regardless
    of how the backlog distributes — and the **max sampled queue-wait
    p95 across active workers** (µs, the ``q_p95_us`` field the span
    tracer feeds into every sampler record, docs/OBSERVABILITY.md
    §tracing) against ``up_q95_us``: the tail-latency trigger — a farm
    can hold a shallow average depth yet still bind a latency SLO, and
    depth thresholds cannot see that.  ``up_q95_us`` needs the dataflow
    to run ``trace=`` (the controller warns once and the signal stays 0
    otherwise).

    ``up_slo_burn`` closes the loop from the SLO layer (obs/slo.py):
    the sampler record's ``slo_burn_max`` gauge — the max over
    objectives of min(fast burn, slow burn), published by the local
    :class:`~windflow_tpu.obs.slo.SloEvaluator` the federation shipper
    drives — triggers a grow when it stays at/above the threshold
    (``1.0`` = burning exactly at budget).  Needs the dataflow to run
    ``federate=`` with an ``slo=`` policy (the controller warns once
    and the signal stays 0 otherwise).

    Requires ``recovery=`` on the dataflow (epoch barriers are the
    consistent cut the migration seals at — the Dataflow constructor
    refuses the combination otherwise, WF211) and workers whose cores
    can export/import per-key state (host window cores; device and
    native cores decline, docs/CONTROL.md).
    """

    def __init__(self, pattern: str, max_workers: int,
                 min_workers: int = 1, up_depth=None, down_depth=None,
                 up_shed=None, up_q95_us=None, up_slo_burn=None,
                 step: int = 1, hysteresis: int = 2,
                 cooldown: float = 5.0):
        super().__init__(up_depth, down_depth, hysteresis, cooldown)
        if not pattern:
            raise ValueError("Rescale needs the target pattern's name")
        if int(min_workers) < 1:
            raise ValueError("min_workers must be >= 1")
        if int(max_workers) <= int(min_workers):
            raise ValueError(
                f"max_workers ({max_workers}) must be > min_workers "
                f"({min_workers}): an equal pair leaves nothing to "
                f"rescale")
        if int(step) < 1:
            raise ValueError("step must be >= 1 worker")
        if up_shed is not None and float(up_shed) <= 0:
            raise ValueError("up_shed must be a positive items/s rate")
        if up_q95_us is not None and float(up_q95_us) <= 0:
            raise ValueError("up_q95_us must be a positive queue-wait "
                             "p95 in microseconds")
        if up_slo_burn is not None and float(up_slo_burn) <= 0:
            raise ValueError("up_slo_burn must be a positive burn-rate "
                             "multiple (1.0 = burning exactly at "
                             "budget)")
        self.pattern = str(pattern)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.up_shed = None if up_shed is None else float(up_shed)
        self.up_q95_us = None if up_q95_us is None else float(up_q95_us)
        self.up_slo_burn = (None if up_slo_burn is None
                            else float(up_slo_burn))
        self.step = int(step)

    # the rescale signal is (max worker depth, head shed rate[, max
    # worker queue-wait p95 µs[, slo_burn_max]]); the shorter tuple
    # forms stay accepted so pre-trace / pre-SLO callers of the pure
    # observe() path are unchanged
    def _classify(self, value) -> int:
        depth, shed_rate, *rest = value
        q95_us = rest[0] if rest else 0.0
        slo_burn = rest[1] if len(rest) > 1 else 0.0
        if self.high is not None and depth >= self.high:
            return 1
        if self.up_shed is not None and shed_rate >= self.up_shed:
            return 1
        if self.up_q95_us is not None and q95_us >= self.up_q95_us:
            return 1
        if self.up_slo_burn is not None and slo_burn >= self.up_slo_burn:
            return 1
        if self.low is not None and depth <= self.low:
            return -1
        return 0

    def _key(self):
        return ("rescale", self.pattern, self.min_workers,
                self.max_workers, self.high, self.low, self.up_shed,
                self.up_q95_us, self.up_slo_burn, self.step,
                self.hysteresis, self.cooldown)

    def __repr__(self):
        return (f"Rescale({self.pattern!r}, {self.min_workers}.."
                f"{self.max_workers}, up_depth={self.high}, "
                f"down_depth={self.low}, up_shed={self.up_shed}, "
                f"up_q95_us={self.up_q95_us}, "
                f"up_slo_burn={self.up_slo_burn}, step={self.step})")


class AdaptiveShed(_ThresholdRule):
    """Move the running OverloadPolicy's ``soft_limit`` (the depth at
    which shed disciplines start dropping, runtime/overload.py) between
    ``min_limit`` and the inbox capacity: tighten by ``step`` while the
    max inbox depth stays at/above ``high_depth``, relax while it stays
    at/below ``low_depth`` (``soft_limit`` returns to ``None`` — shed
    only when full — once it reaches capacity again).

    Requires the dataflow to run a shedding ``OverloadPolicy``
    (``shed_oldest``/``shed_newest``); the controller refuses to attach
    otherwise — there is no shed threshold to move under ``block``.
    """

    def __init__(self, high_depth, low_depth, min_limit: int = 1,
                 step: int = None, hysteresis: int = 2,
                 cooldown: float = 2.0):
        super().__init__(high_depth, low_depth, hysteresis, cooldown)
        if self.high is None or self.low is None:
            raise ValueError("AdaptiveShed needs both high_depth and "
                             "low_depth")
        if int(min_limit) < 1:
            raise ValueError("min_limit must be >= 1 item")
        if step is not None and int(step) < 1:
            raise ValueError("step must be >= 1 item (None = capacity/4)")
        self.min_limit = int(min_limit)
        self.step = None if step is None else int(step)

    def _key(self):
        return ("shed", self.high, self.low, self.min_limit, self.step,
                self.hysteresis, self.cooldown)

    def __repr__(self):
        return (f"AdaptiveShed(high_depth={self.high}, "
                f"low_depth={self.low}, min_limit={self.min_limit}, "
                f"step={self.step})")


class Admission(_ThresholdRule):
    """Source admission control: a token bucket caps source emission at
    ``rate`` tuples/second (burst of ``burst`` tuples, default one
    second's worth).  The controller multiplies the rate by ``down``
    while the max inbox depth stays at/above ``high_depth`` and by
    ``up`` while it stays at/below ``low_depth``, clamped to
    ``[min_rate, max_rate]`` — multiplicative-decrease keeps the source
    from oscillating around the knee.

    ``pattern`` names one source pattern; ``None`` caps every source in
    the graph.  The cap starts at ``max_rate`` (uncontended sources run
    at full speed until backpressure shows).
    """

    def __init__(self, max_rate, min_rate, high_depth, low_depth,
                 pattern: str = None, down: float = 0.5, up: float = 1.25,
                 burst=None, hysteresis: int = 2, cooldown: float = 2.0):
        super().__init__(high_depth, low_depth, hysteresis, cooldown)
        if self.high is None or self.low is None:
            raise ValueError("Admission needs both high_depth and "
                             "low_depth")
        if float(min_rate) <= 0 or float(max_rate) < float(min_rate):
            raise ValueError(
                f"need 0 < min_rate <= max_rate, got {min_rate}.."
                f"{max_rate}")
        if not (0 < float(down) < 1):
            raise ValueError("down must be in (0, 1) — a multiplicative "
                             "decrease")
        if float(up) <= 1:
            raise ValueError("up must be > 1 — a multiplicative increase")
        if burst is not None and float(burst) <= 0:
            raise ValueError("burst must be positive tuples (None = one "
                             "second at max_rate)")
        self.pattern = None if pattern is None else str(pattern)
        self.min_rate = float(min_rate)
        self.max_rate = float(max_rate)
        self.down = float(down)
        self.up = float(up)
        self.burst = None if burst is None else float(burst)

    def _key(self):
        return ("admission", self.pattern, self.min_rate, self.max_rate,
                self.high, self.low, self.down, self.up, self.burst,
                self.hysteresis, self.cooldown)

    def __repr__(self):
        return (f"Admission({self.pattern!r}, {self.min_rate}.."
                f"{self.max_rate}/s, high_depth={self.high}, "
                f"low_depth={self.low})")


class Drain:
    """Manual quiesce actuator: registering a ``Drain`` rule arms
    ``Dataflow.request_drain()`` / ``release_drain()`` — the first leg
    of the rolling-restart sequence (docs/ROBUSTNESS.md "Cross-host
    recovery", scripts/wf_roll.py).

    Draining closes a gate in front of EVERY source's emission (the
    same wrap point as :class:`Admission`'s token bucket, so already
    -emitted batches keep flowing downstream), then waits for the
    in-flight work to settle: ``request_drain`` returns once every node
    inbox has stayed empty, or ``deadline`` seconds elapsed — the
    caller seals a checkpoint on the quiesced graph and hands off.
    ``release_drain`` reopens the gate; sources resume exactly where
    they blocked, no record dropped.

    Unlike the threshold rules this one never fires from samples — it
    is driven by the operator (a roll sequencer, a scripted failover).
    At most one per policy: there is one gate.
    """

    __slots__ = ("deadline", "poll")

    def __init__(self, deadline: float = 30.0, poll: float = 0.05):
        if float(deadline) <= 0:
            raise ValueError("deadline must be positive seconds")
        if float(poll) <= 0:
            raise ValueError("poll must be positive seconds")
        self.deadline = float(deadline)
        self.poll = float(poll)

    def reset(self):
        """No trigger state to clear (manual actuator) — present so the
        Controller's uniform ``rule.reset()`` at attach stays simple."""

    def observe(self, value, now: float) -> int:
        return 0    # never fires from samples

    def _key(self):
        return ("drain", self.deadline, self.poll)

    def __repr__(self):
        return f"Drain(deadline={self.deadline}, poll={self.poll})"


class ControlPolicy:
    """Per-dataflow control-plane knobs: the rules plus the evaluation
    cadence.

    Parameters
    ----------
    rules:
        Non-empty list of :class:`Rescale` / :class:`AdaptiveShed` /
        :class:`Admission` / :class:`Drain` rules.  At most one
        ``Rescale`` per pattern name, at most one ``AdaptiveShed`` (it
        moves one dataflow-wide knob) and at most one ``Drain`` (one
        gate).
    period:
        Controller evaluation cadence in seconds.  The controller is fed
        by the observability sampler (``Sampler.subscribe``): when
        ``sample_period=`` is set it rides that cadence; otherwise — with
        ``metrics=`` on — the engine starts the sampler at this period.
        With *neither* ``metrics=`` nor ``sample_period=`` the controller
        never receives a snapshot and the whole policy is inert (the
        engine warns once at construction; check/ reports it as WF209).
    """

    __slots__ = ("rules", "period")

    def __init__(self, rules, period: float = 0.5):
        rules = list(rules)
        if not rules:
            raise ValueError("ControlPolicy needs at least one rule")
        for r in rules:
            if not isinstance(r, (Rescale, AdaptiveShed, Admission,
                                  Drain)):
                raise TypeError(
                    f"unknown rule type {type(r).__name__} (want "
                    f"Rescale / AdaptiveShed / Admission / Drain)")
        seen = set()
        for r in rules:
            if isinstance(r, Rescale):
                if r.pattern in seen:
                    raise ValueError(
                        f"duplicate Rescale rule for pattern "
                        f"{r.pattern!r} — one rule owns one farm's width")
                seen.add(r.pattern)
        if sum(isinstance(r, AdaptiveShed) for r in rules) > 1:
            raise ValueError("at most one AdaptiveShed rule: it moves "
                             "the single dataflow-wide soft_limit")
        if sum(isinstance(r, Drain) for r in rules) > 1:
            raise ValueError("at most one Drain rule: it owns the "
                             "single dataflow-wide source gate")
        adm = [r for r in rules if isinstance(r, Admission)]
        adm_pats = [r.pattern for r in adm]
        if len(adm) > 1 and (None in adm_pats
                             or len(set(adm_pats)) != len(adm_pats)):
            raise ValueError(
                "overlapping Admission rules: at most one per source "
                "pattern, and a pattern=None rule (all sources) must be "
                "the only one — overlapping buckets would double-"
                "throttle the same source")
        if float(period) <= 0:
            raise ValueError("period must be positive seconds")
        self.rules = rules
        self.period = float(period)

    @property
    def has_rescale(self) -> bool:
        return any(isinstance(r, Rescale) for r in self.rules)

    def rescale_for(self, pattern_name) -> Rescale | None:
        """The Rescale rule targeting ``pattern_name``, if any — the
        wiring layer (runtime/farm.py) calls this to pre-provision the
        farm's worker set to ``max_workers``."""
        if pattern_name is None:
            return None
        for r in self.rules:
            if isinstance(r, Rescale) and r.pattern == pattern_name:
                return r
        return None

    def agrees_with(self, other: "ControlPolicy") -> bool:
        """Structural equality — the union-merge conflict rule (one
        Dataflow runs one control policy, api/multipipe.py)."""
        if self.period != other.period or len(self.rules) != len(other.rules):
            return False
        return all(a._key() == b._key()
                   for a, b in zip(self.rules, other.rules))

    def __repr__(self):
        return (f"ControlPolicy(period={self.period}, rules="
                f"{self.rules!r})")
