"""The per-Dataflow controller: sensors -> rules -> actuators.

Created by ``Dataflow.run()`` when ``control=`` is set (and the graph is
observed — the controller's only sensor is the observability sampler).
It owns **no thread**: rule evaluation runs on the sampler's cadence via
the ``Sampler.subscribe`` hook (one in-process callback per snapshot, no
file I/O), and the heavyweight actuation — the live rescale — runs on
the farm's own node threads at the next epoch barrier
(control/rescale.py).  The two cheap actuators apply immediately:

* **adaptive shedding** moves the running OverloadPolicy's
  ``soft_limit`` (a GIL-atomic attribute store the inbox shed paths read
  per put);
* **admission control** adjusts the token-bucket rate cap wrapped around
  source emission.

Every decision is observable: a ``control`` event per actuation, a
``rescale`` event per completed migration, and ``ctl_*``
counters/gauges in the metrics registry (rendered by
``scripts/wf_top.py``; docs/CONTROL.md lists the full table).
"""

from __future__ import annotations

import threading
from time import monotonic as _monotonic
from time import sleep as _sleep

from .policy import Admission, AdaptiveShed, ControlPolicy, Drain, Rescale


class TokenBucket:
    """Continuous-refill token bucket: ``rate`` tokens/second up to a
    ``burst`` ceiling.  ``throttle(n)`` blocks (in failure-polling
    slices) until ``n`` tokens are available; batches larger than the
    burst run the bucket into debt instead of deadlocking, so huge
    chunks are still rate-bound on average.  ``rate`` is read each
    refill — the controller retunes it with one attribute store."""

    def __init__(self, rate: float, burst: float = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None else max(self.rate,
                                                                1.0)
        self._tokens = self.burst
        self._t = _monotonic()
        self._mu = threading.Lock()

    def throttle(self, n: int, failed: threading.Event = None):
        while True:
            with self._mu:
                now = _monotonic()
                rate = self.rate
                self._tokens = min(self.burst,
                                   self._tokens + (now - self._t) * rate)
                self._t = now
                need = min(float(n), self.burst)
                if self._tokens >= need:
                    self._tokens -= n      # may go negative: debt
                    return
                wait = min((need - self._tokens) / rate, 0.05)
            if failed is not None and failed.is_set():
                from ..runtime.engine import _Cancelled
                raise _Cancelled()
            _sleep(wait)


class _AdmissionState:
    """One Admission rule bound to its bucket and wrapped sources."""

    __slots__ = ("rule", "bucket", "gauge", "sources")

    def __init__(self, rule: Admission, bucket: TokenBucket, gauge,
                 sources):
        self.rule = rule
        self.bucket = bucket
        self.gauge = gauge
        self.sources = sources


class Controller:
    """See module docstring.  Wiring happens in :meth:`attach` (before
    any node thread starts); evaluation in :meth:`on_sample` (sampler
    thread)."""

    def __init__(self, df, policy: ControlPolicy):
        self.df = df
        self.policy = policy
        self.farms = []               # FarmController per Rescale target
        self._farm_ids = {}           # FarmController -> (worker ids, em id)
        self.shed_rule: AdaptiveShed | None = None
        self._shed_step = 1
        self._orig_soft_limit = None
        self.admissions: list[_AdmissionState] = []
        self._prev_shed: dict[str, tuple[float, int]] = {}
        self.drain_rule: Drain | None = None
        #: set = sources flow; cleared = sources gate at emit
        self._drain_gate = threading.Event()
        self._drain_gate.set()

    # ------------------------------------------------------------ wiring

    def attach(self):
        from ..runtime.node import SourceNode
        from ..utils.tracing import node_stats_name
        from .rescale import FarmController
        df = self.df

        def _sid(node):
            return node_stats_name(df.name, df.nodes.index(node),
                                   node.name)

        for rule in self.policy.rules:
            # a policy object reused for a second run must not inherit
            # the first run's cooldown clocks / hysteresis streaks
            rule.reset()
        matched = set()
        wrapped: dict[int, str] = {}   # source node -> owning rule target
        for handle in df._farms:
            fc = FarmController(df, handle)
            fc.validate()
            fc.install_hooks()
            self.farms.append(fc)
            self._farm_ids[id(fc)] = ([_sid(w) for w in fc.workers],
                                      _sid(fc.emitter))
            matched.add(fc.rule.pattern)
            df.metrics.gauge(f"ctl_width_{fc.pattern.name}").set(fc.width)
        for rule in self.policy.rules:
            if isinstance(rule, Rescale) and rule.pattern not in matched:
                raise ValueError(
                    f"Rescale rule targets {rule.pattern!r}, but no "
                    f"key-partitioned farm of that name was wired into "
                    f"Dataflow {df.name!r}")
            if (isinstance(rule, Rescale) and rule.up_q95_us is not None
                    and getattr(df, "tracer", None) is None):
                # the tail-latency signal is fed by the span tracer's
                # per-node histograms; without trace= it reads 0 forever
                # — the WF209 shape of silent inertness, for one signal
                import warnings
                warnings.warn(
                    f"Rescale({rule.pattern!r}): up_q95_us is set but "
                    f"the dataflow runs without trace= — the queue-wait "
                    f"p95 signal never populates, so this trigger is "
                    f"inert (docs/OBSERVABILITY.md §tracing)",
                    stacklevel=2)
            if (isinstance(rule, Rescale)
                    and rule.up_slo_burn is not None
                    and (getattr(df, "federate", None) is None
                         or df.federate.slo is None)):
                # the burn signal is the slo_burn_max gauge the local
                # SloEvaluator publishes; without federate=(slo=...) it
                # never populates — same inert-signal shape as up_q95_us
                import warnings
                warnings.warn(
                    f"Rescale({rule.pattern!r}): up_slo_burn is set but "
                    f"the dataflow runs without federate= (or its "
                    f"FederationPolicy has no slo=) — the slo_burn_max "
                    f"signal never populates, so this trigger is inert "
                    f"(docs/OBSERVABILITY.md §Federation & SLOs)",
                    stacklevel=2)
            if isinstance(rule, AdaptiveShed):
                pol = df.overload
                if pol is None or pol.shed == "block":
                    raise ValueError(
                        "AdaptiveShed needs the dataflow to run a "
                        "shedding OverloadPolicy (shed_oldest/"
                        "shed_newest) — there is no shed threshold to "
                        "move under 'block'")
                self.shed_rule = rule
                self._shed_step = (rule.step if rule.step is not None
                                   else max(1, df.capacity // 4))
                #: restored at close(): the tightened limit must not
                #: leak into later runs / other graphs sharing the
                #: user's OverloadPolicy object
                self._orig_soft_limit = pol.soft_limit
                df.metrics.gauge("ctl_soft_limit").set(
                    pol.soft_limit or 0)
            elif isinstance(rule, Admission):
                sources = [n for n in df.nodes
                           if isinstance(n, SourceNode)
                           and (rule.pattern is None
                                or n.name == rule.pattern
                                or n.name.rsplit(".", 1)[0]
                                == rule.pattern)]
                if not sources:
                    raise ValueError(
                        f"Admission rule targets "
                        f"{rule.pattern or '<all sources>'!r}, but no "
                        f"source node matches in Dataflow {df.name!r}")
                bucket = TokenBucket(rule.max_rate, rule.burst)
                name = ("ctl_admission_rate" if rule.pattern is None
                        else f"ctl_admission_rate_{rule.pattern}")
                gauge = df.metrics.gauge(name)
                gauge.set(bucket.rate)
                for s in sources:
                    other = wrapped.get(id(s))
                    if other is not None:
                        # the policy-level overlap refusal cannot see
                        # replica names ('src' vs 'src.0' both match
                        # node src.0): refuse the double wrap here
                        raise ValueError(
                            f"overlapping Admission rules: source "
                            f"{s.name!r} matches both {other!r} and "
                            f"{rule.pattern!r} — two buckets would "
                            f"double-throttle it")
                    wrapped[id(s)] = rule.pattern
                    self._wrap_source(s, bucket)
                self.admissions.append(
                    _AdmissionState(rule, bucket, gauge, sources))
            elif isinstance(rule, Drain):
                self.drain_rule = rule
        if self.drain_rule is not None:
            # gate OUTERMOST (after any Admission wrap): a drained
            # source parks before it spends bucket tokens, and resumes
            # rate-capped exactly as it left
            for n in df.nodes:
                if isinstance(n, SourceNode):
                    self._gate_source(n)
            df.metrics.gauge("ctl_draining").set(0)

    def _gate_source(self, node):
        inner = node.emit           # possibly the Admission wrapper
        gate = self._drain_gate
        failed = self.df._failed

        def emit(batch):
            while not gate.wait(0.05):
                if failed.is_set():
                    from ..runtime.engine import _Cancelled
                    raise _Cancelled()
            inner(batch)

        node.emit = emit            # Shipper captures this at generate()

    def _wrap_source(self, node, bucket: TokenBucket):
        inner = node.emit           # the bound class method
        failed = self.df._failed

        def emit(batch):
            if batch is not None and len(batch):
                bucket.throttle(len(batch), failed)
            inner(batch)

        node.emit = emit            # Shipper captures this at generate()

    # -------------------------------------------------------- evaluation

    def on_sample(self, rec: dict):
        """Sampler subscription callback — one rule evaluation per
        snapshot.  Cheap by construction: a handful of dict reads and at
        most one attribute store per actuator."""
        now = _monotonic()
        nodes = {n["id"]: n for n in rec.get("nodes", ())}
        # SLO burn signal (obs/slo.py): the local evaluator publishes
        # slo_burn_max into the registry, and the sampler embeds the
        # registry snapshot — so the controller reads it one sample
        # late, which is exactly the cadence lag the burn windows
        # already smooth over.  0.0 (inert) without federate=(slo=).
        slo_burn = float(rec.get("gauges", {}).get("slo_burn_max", 0.0))
        for fc in self.farms:
            if fc.busy:
                continue            # a rescale is already in flight
            ids, em_id = self._farm_ids[id(fc)]
            depth = max((nodes[i]["depth"] for i in ids[:fc.width]
                         if i in nodes), default=0)
            shed_rate = self._shed_rate(em_id, nodes, rec.get("t", now))
            # tail-latency signal (obs/trace.py): max sampled queue-wait
            # p95 across the active workers — 0.0 (inert) until the span
            # tracer populates the field
            q95_us = max((nodes[i].get("q_p95_us", 0.0)
                          for i in ids[:fc.width] if i in nodes),
                         default=0.0)
            d = fc.rule.observe((depth, shed_rate, q95_us, slo_burn),
                                now)
            if d:
                rule = fc.rule
                width = fc.width
                target = (min(width + rule.step, rule.max_workers)
                          if d > 0
                          else max(width - rule.step, rule.min_workers))
                if target != width and fc.request(target):
                    self._note("rescale_request", fc.pattern.name,
                               target, depth=depth,
                               shed_rate=round(shed_rate, 3),
                               q95_us=q95_us,
                               slo_burn=round(slo_burn, 3))
        if self.shed_rule is not None:
            self._drive_shed(self._max_depth(nodes), now)
        for adm in self.admissions:
            self._drive_admission(adm, self._max_depth(nodes), now)

    @staticmethod
    def _max_depth(nodes: dict) -> int:
        return max((n["depth"] for n in nodes.values()), default=0)

    def _shed_rate(self, node_id: str, nodes: dict, t: float) -> float:
        entry = nodes.get(node_id)
        if entry is None:
            return 0.0
        shed = int(entry.get("shed", 0))
        prev = self._prev_shed.get(node_id)
        self._prev_shed[node_id] = (t, shed)
        if prev is None or t <= prev[0]:
            return 0.0
        return (shed - prev[1]) / (t - prev[0])

    def _drive_shed(self, depth: int, now: float):
        rule = self.shed_rule
        d = rule.observe(depth, now)
        if not d:
            return
        pol = self.df.overload
        cap = self.df.capacity
        cur = pol.soft_limit if pol.soft_limit is not None else cap
        new = (max(rule.min_limit, cur - self._shed_step) if d > 0
               else min(cap, cur + self._shed_step))
        if new == cur:
            return
        pol.soft_limit = None if new >= cap else new
        self.df.metrics.gauge("ctl_soft_limit").set(pol.soft_limit or 0)
        self.df.metrics.counter("ctl_shed_tighten" if d > 0
                                else "ctl_shed_relax").inc()
        self._note("shed_tighten" if d > 0 else "shed_relax",
                   "overload", pol.soft_limit or 0, depth=depth)

    def _drive_admission(self, adm: _AdmissionState, depth: int,
                         now: float):
        rule = adm.rule
        d = rule.observe(depth, now)
        if not d:
            return
        cur = adm.bucket.rate
        new = (max(rule.min_rate, cur * rule.down) if d > 0
               else min(rule.max_rate, cur * rule.up))
        if new == cur:
            return
        adm.bucket.rate = new
        adm.gauge.set(new)
        self.df.metrics.counter("ctl_admission_down" if d > 0
                                else "ctl_admission_up").inc()
        self._note("admission_down" if d > 0 else "admission_up",
                   rule.pattern or "<sources>", round(new, 3),
                   depth=depth)

    # ------------------------------------------------------------- drain

    @property
    def draining(self) -> bool:
        return not self._drain_gate.is_set()

    def request_drain(self, timeout: float = None) -> bool:
        """Close the source gate and wait for the in-flight work to
        settle: returns True once every node inbox has stayed empty for
        two consecutive polls, False when ``timeout`` (default: the
        rule's ``deadline``) elapsed first — the graph is still gated
        either way, the caller decides whether a partial quiesce is
        good enough to seal on.  Idempotent while already draining."""
        rule = self.drain_rule
        if rule is None:
            raise RuntimeError(
                "request_drain() needs a Drain rule in the "
                "ControlPolicy — the source gate is only wired when "
                "the policy declares it (docs/CONTROL.md)")
        df = self.df
        deadline = rule.deadline if timeout is None else float(timeout)
        if self._drain_gate.is_set():
            self._drain_gate.clear()
            df.metrics.gauge("ctl_draining").set(1)
            df.metrics.counter("ctl_drains").inc()
            self._drain_note("requested", deadline=deadline)
        t0 = _monotonic()
        settled = 0
        while _monotonic() - t0 < deadline:
            if df._failed.is_set():
                self._drain_note("failed", reason="dataflow failed")
                return False
            depth = sum(ib.depth() for ib in df._inboxes.values())
            # two consecutive empty polls: one poll can race a batch
            # in flight between an inbox pop and the next node's put
            settled = settled + 1 if depth == 0 else 0
            if settled >= 2:
                self._drain_note("quiesced",
                                 ms=round((_monotonic() - t0) * 1e3, 1))
                return True
            _sleep(rule.poll)
        self._drain_note("timeout", deadline=deadline,
                         depth=sum(ib.depth()
                                   for ib in df._inboxes.values()))
        return False

    def release_drain(self):
        """Reopen the source gate (no-op when not draining): sources
        resume mid-iteration exactly where they parked."""
        if not self._drain_gate.is_set():
            self._drain_gate.set()
            self.df.metrics.gauge("ctl_draining").set(0)
            self._drain_note("released")

    def _drain_note(self, phase: str, **fields):
        df = self.df
        if df.events is not None:
            df.events.emit("drain", dataflow=df.name, phase=phase,
                           **fields)

    # --------------------------------------------------------- lifecycle

    def close(self):
        """Called from ``Dataflow.wait()``: undo runtime mutations of
        user-owned objects — the adaptively tightened ``soft_limit``
        belongs to this run, not to the OverloadPolicy instance the user
        may reuse elsewhere.  Also reopens the drain gate so a gated
        source thread cannot outlive the run parked.  Idempotent."""
        if self.shed_rule is not None and self.df.overload is not None:
            self.df.overload.soft_limit = self._orig_soft_limit
        self._drain_gate.set()

    # ------------------------------------------------------------ manual

    def request_rescale(self, pattern_name: str, width: int) -> bool:
        """Scripted/external rescale request (soaks, an external
        autoscaler): same barrier protocol as rule-driven decisions."""
        for fc in self.farms:
            if fc.pattern.name == pattern_name:
                if fc.request(width):
                    self._note("rescale_request", pattern_name, width,
                               manual=True)
                    return True
                return False
        raise KeyError(f"no rescalable farm named {pattern_name!r}")

    def width_of(self, pattern_name: str) -> int:
        for fc in self.farms:
            if fc.pattern.name == pattern_name:
                return fc.width
        raise KeyError(f"no rescalable farm named {pattern_name!r}")

    # ----------------------------------------------------- observability

    def _note(self, action: str, target: str, value, **fields):
        df = self.df
        df.metrics.counter("ctl_decisions").inc()
        if df.events is not None:
            df.events.emit("control", dataflow=df.name, action=action,
                           target=target, value=value, **fields)
