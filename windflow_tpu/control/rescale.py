"""Live rescale of a key-partitioned farm at an epoch barrier.

The engine graph is fixed once ``run()`` starts, so elasticity is built
as *capacity + active subset*: ``runtime/farm.py`` pre-provisions the
farm to the ``Rescale`` rule's ``max_workers`` replicas and the routing
emitter serves only ``n_active`` of them.  Changing the width then never
spawns a thread — it migrates per-key window state between sibling
workers and moves ``n_active``, all inside one epoch barrier (the PR 8
consistent cut):

1. the controller records a pending target width;
2. the **emitter**, completing its next epoch barrier (snapshot
   committed, marker already forwarded downstream, no post-barrier row
   routed yet — engine ``_complete_barriers``), publishes the seal epoch
   and parks;
3. each **worker** drains its pre-barrier input FIFO, seals the same
   epoch (its own snapshot commit), and parks in the seal barrier; the
   last worker to arrive — with every sibling provably quiescent —
   migrates the per-key state fragments (``keyed_state_export`` /
   ``keyed_state_import`` on the host window cores) to their new owners
   under the new width, then **re-commits every worker's snapshot at the
   seal epoch through the PR 8 writer path**, so a post-rescale crash
   restores post-migration state and the journal replay machinery keeps
   exactly-once intact;
4. everyone resumes; the emitter switches ``n_active``, re-commits its
   own snapshot (the active width is routing state — a replayed emitter
   must route the journal tail at the new width), and routes on.

Per-key order is preserved by construction: a migrating key's old owner
processed and emitted everything up to the barrier before the cut, the
new owner everything after it, and the collector's inbox serialises the
two (the old owner's puts happen-before the migration happens-before the
new owner's puts).

A failure *inside* the migration leaves sibling cores inconsistent in a
way no single node's snapshot can repair, so it aborts the whole graph
(``RescaleError.wf_no_restart`` — the engine refuses supervised restart
through it) instead of restoring silently-wrong state.
"""

from __future__ import annotations

import threading
import numpy as np
from time import monotonic as _monotonic

from ..runtime.engine import _Cancelled


class RescaleError(RuntimeError):
    """A live-rescale migration failed: sibling worker state may be
    inconsistent, so the graph fails like the seed engine (the engine's
    supervised loop checks ``wf_no_restart`` and never restores through
    this)."""

    wf_no_restart = True


def _migration_target(node):
    """The object carrying the keyed-state hooks for one worker node:
    its window core, or the node itself (keyed Accumulators).  None when
    neither supports migration.  Gated on the explicit
    ``keyed_migratable`` opt-in, NOT hook presence: device cores inherit
    the host hooks from WinSeqCore but mirror per-key state into device
    rings the hooks cannot move — they opt out (docs/CONTROL.md)."""
    core = getattr(node, "core", None)
    if core is not None and getattr(core, "keyed_migratable", False):
        return core
    if getattr(node, "keyed_migratable", False):
        return node
    return None


class FarmController:
    """Per-farm rescale coordinator (see module docstring).  Created by
    the :class:`~windflow_tpu.control.controller.Controller` from the
    registry ``runtime/farm.py`` stamped on the Dataflow."""

    def __init__(self, df, handle: dict):
        self.df = df
        self.pattern = handle["pattern"]
        self.rule = handle["rule"]
        self.emitter = handle["emitter"]
        self.workers = list(handle["workers"])
        self.width = int(handle["width"])
        self.routing = self.pattern.routing
        self._mu = threading.Lock()
        self._pending = None          # requested target width
        self._seal_epoch = None       # epoch the in-flight rescale seals at
        self._sealed: set[int] = set()
        self._done = threading.Event()
        self._aborted = None
        self._moved = 0
        self._t0 = 0.0
        #: completed rescales, (from, to, epoch) — inspectable in tests
        self.history: list[tuple[int, int, int]] = []

    # ------------------------------------------------------------ wiring

    def validate(self):
        """Pre-run checks (Controller.attach): every worker must be
        supervised + journaling (the barrier protocol rides the
        recovery machinery) and its core must export/import per-key
        state (host window cores, keyed accumulators, and native cores
        with the state ABI; device cores and native cores on a
        pre-ABI .so decline — docs/CONTROL.md)."""
        name = self.pattern.name
        if self.emitter._recov is None:
            raise ValueError(f"Rescale {name!r}: the farm emitter is not "
                             f"supervised (recovery= must cover the graph)")
        for w in self.workers:
            rec = w._recov
            if rec is None or not rec.journaling:
                raise ValueError(
                    f"[WF210] Rescale {name!r}: worker {w.name!r} is not "
                    f"restorable under recovery= (recoverable opt-out?) "
                    f"— it cannot seal a migration cut")
            if _migration_target(w) is None:
                raise ValueError(
                    f"Rescale {name!r}: worker {w.name!r} "
                    f"({type(getattr(w, 'core', w)).__name__}) has no "
                    f"keyed-state migration hooks — host window cores, "
                    f"keyed accumulators, and native cores with the "
                    f"state ABI rescale; device cores and pre-ABI "
                    f"native libraries decline (docs/CONTROL.md)")

    def install_hooks(self):
        # the ANNOUNCE runs before the emitter's marker leaves (engine
        # _checkpoint_node), so a worker racing ahead on that marker can
        # never miss the seal; the post-checkpoint hook then parks the
        # emitter until the migration lands
        self.emitter._ctl_seal_hook = self._seal_announce
        self.emitter._ctl_epoch_hook = self._emitter_hook
        for w in self.workers:
            w._ctl_epoch_hook = (lambda epoch, _n=w:
                                 self._worker_hook(_n, epoch))

    # ----------------------------------------------------------- control

    @property
    def busy(self) -> bool:
        return self._pending is not None

    def request(self, width: int) -> bool:
        """Ask for a new active width; takes effect at the emitter's
        next epoch barrier.  False when already at that width or another
        rescale is in flight."""
        width = int(width)
        if not self.rule.min_workers <= width <= self.rule.max_workers:
            raise ValueError(
                f"Rescale {self.pattern.name!r}: width {width} outside "
                f"[{self.rule.min_workers}, {self.rule.max_workers}]")
        with self._mu:
            if self._pending is not None or width == self.width:
                return False
            self._pending = width
            return True

    # ------------------------------------------------------------- hooks

    def _await(self, ev):
        failed = self.df._failed
        while not ev.wait(0.05):
            if failed.is_set():
                raise _Cancelled()

    def _seal_announce(self, epoch: int):
        """Emitter pre-marker hook: publish the seal epoch of a pending
        rescale BEFORE the barrier marker leaves — workers racing ahead
        on the marker must always find it announced."""
        with self._mu:
            if self._pending is None or self._seal_epoch is not None \
                    or epoch <= 0:
                return
            self._seal_epoch = epoch
            self._sealed = set()
            self._aborted = None
            self._moved = 0
            # a FRESH event per seal, never clear(): a round-N waiter
            # descheduled between the round-N set() and a round-N+1
            # clear() would re-park on the recycled event and deadlock
            # the barrier (its own seal is needed to set it again)
            self._done = threading.Event()
            self._t0 = _monotonic()

    def _emitter_hook(self, epoch: int):
        with self._mu:
            if self._seal_epoch != epoch or self._pending is None:
                return
            target = self._pending
        # park until every worker sealed this epoch and the migration
        # landed; upstream backpressures on our bounded inbox meanwhile
        self._await(self._done)
        if self._aborted:
            raise RescaleError(self._aborted)
        old = self.width
        em = self.emitter
        try:
            em.n_active = target
            # the active width is routing state: re-commit so a crashed
            # emitter replays its journal tail at the width it now routes
            self._recommit_node(em, epoch)
        except Exception as e:
            # workers already hold the migrated placement: a supervised
            # restore of the emitter to its pre-flip snapshot would
            # route migrated-away keys back to neutralized owners — fail
            # the graph loudly instead (wf_no_restart)
            raise RescaleError(
                f"{self.pattern.name}: post-migration width flip to "
                f"{target} failed: {type(e).__name__}: {e}") from e
        with self._mu:
            self.width = target
            self._pending = None
            self._seal_epoch = None
        self.history.append((old, target, epoch))
        self._note_done(old, target, epoch)

    def _worker_hook(self, node, epoch: int):
        with self._mu:
            se = self._seal_epoch
            if se is None or epoch < se:
                return
            self._sealed.add(id(node))
            last = len(self._sealed) == len(self.workers)
            target = self._pending
        if not last:
            self._await(self._done)
            if self._aborted:
                raise RescaleError(self._aborted)
            return
        # last sealer: every sibling is parked (quiescent cores) — do the
        # migration on this thread, then wake everyone
        try:
            self._moved = self._migrate(target)
            self._recommit_workers(se)
        except BaseException as e:
            self._aborted = (f"{self.pattern.name}: migration to width "
                             f"{target} failed: {type(e).__name__}: {e}")
            self._done.set()
            raise RescaleError(self._aborted) from e
        self._done.set()

    # --------------------------------------------------------- migration

    def _targets(self):
        targets = [_migration_target(w) for w in self.workers]
        # sibling LazySlidingCores may have picked different backings
        # (each decides on its own first chunk): harmonize before any
        # fragment crosses — escalation per-key -> lanes is lossless,
        # the reverse is not, so vec wins when any sibling runs it
        from ..core.vecinc import LazySlidingCore
        lazies = [t for t in targets if isinstance(t, LazySlidingCore)]
        if lazies:
            vec = any(l.backing_is_vec for l in lazies)
            for l in lazies:
                l.ensure_backing(vec)
        return targets

    def _migrate(self, new_width: int) -> int:
        """Repartition per-key state onto the first ``new_width``
        workers under the farm's own routing fn.  Export-all before
        import-any: a key moving 0->2 must not clobber one moving
        2->0 mid-flight."""
        targets = self._targets()
        routing = self.routing
        exports = []
        moved = 0
        for i, t in enumerate(targets):
            keys = np.ascontiguousarray(t.keyed_state_keys(),
                                        dtype=np.int64)
            if len(keys) == 0:
                continue
            dest = np.asarray(routing(keys, new_width))
            mv = dest != i
            if not mv.any():
                continue
            mk, md = keys[mv], dest[mv]
            for d in np.unique(md):
                sel = mk[md == d]
                exports.append((int(d), t.keyed_state_export(sel)))
                moved += len(sel)
        for d, frag in exports:
            targets[d].keyed_state_import(frag)
        return moved

    def _recommit_node(self, node, epoch: int):
        rec = node._recov
        if rec is None or not rec.journaling \
                or rec.unrecoverable is not None:
            return
        state = node.state_snapshot()
        rec.commit(epoch, state)
        sup = self.df._supervisor
        if sup is not None:
            sup.enqueue_blob(rec, epoch, state)

    def _recommit_workers(self, epoch: int):
        """Post-migration snapshots at the seal epoch, shipped through
        the PR 8 writer thread: a crash after the rescale must restore
        the migrated key placement, not resurrect the old one."""
        for w in self.workers:
            self._recommit_node(w, epoch)

    # ------------------------------------------------------ observability

    def _note_done(self, old: int, new: int, epoch: int):
        df = self.df
        ms = round((_monotonic() - self._t0) * 1e3, 3)
        if df.events is not None:
            df.events.emit("rescale", dataflow=df.name,
                           farm=self.pattern.name, epoch=epoch,
                           width_from=old, width_to=new,
                           moved_keys=self._moved, ms=ms)
        m = df.metrics
        if m is not None:
            m.counter("ctl_rescale_up" if new > old
                      else "ctl_rescale_down").inc()
            m.gauge(f"ctl_width_{self.pattern.name}").set(new)
        tracer = getattr(df, "tracer", None)
        if tracer is not None:
            # control-plane span (obs/trace.py): the migration window on
            # the Perfetto timeline, next to the batches it stalled
            tracer.record_ctrl(self.pattern.name, "rescale", epoch,
                               ms / 1e3, width_from=old, width_to=new,
                               moved_keys=self._moved)
