"""Closed-loop control plane (docs/CONTROL.md): the layer that *reads*
the sensors PR 4 built (sampler snapshots) and *drives* the actuators
PR 3 and PR 8 built (shed disciplines, admission, epoch-barrier
snapshots) — elastic rescale of key-partitioned farms, adaptive
shedding, and source admission control.

Contract with the engine (same as check/): ``control=`` unset means this
package is **never imported**; the engine's lazy imports are the only
coupling, so the seed hot paths stay byte-identical.

    from windflow_tpu.control import (ControlPolicy, Rescale,
                                      AdaptiveShed, Admission)

    pipe = MultiPipe("job", metrics=True, recovery=RecoveryPolicy(
                         epoch_batches=64),
                     control=ControlPolicy([
                         Rescale("kf", max_workers=8, up_depth=12,
                                 down_depth=2),
                         Admission(max_rate=2e6, min_rate=1e5,
                                   high_depth=14, low_depth=4),
                     ]))
"""

from __future__ import annotations

from .controller import Controller, TokenBucket
from .policy import Admission, AdaptiveShed, ControlPolicy, Drain, Rescale
from .rescale import FarmController, RescaleError

__all__ = ["ControlPolicy", "Rescale", "AdaptiveShed", "Admission",
           "Drain", "Controller", "TokenBucket", "FarmController",
           "RescaleError"]
