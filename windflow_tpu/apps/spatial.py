"""Spatial queries over time-based windows — the port of the reference's
``src/spatial_test`` suite (skytree.hpp skyline operator, sq_generator.hpp,
test_spatial_{wf,pf,wf+pf}.cpp): a *heavy* non-incremental window function
(skyline / pareto frontier, ms-scale per window) exercised through Win_Farm,
Pane_Farm and the nested WF(PF) composition.

The skyline is decomposable — ``skyline(A ∪ B) = skyline(skyline(A) ∪
skyline(B))`` — which is exactly what Pane_Farm exploits: the PLQ computes
per-pane skylines (carried as an object-dtype payload column, the analog of
the reference's container-valued ``result_t``), and the WLQ merges pane
skylines per window.
"""

from __future__ import annotations

import numpy as np

from ..core.tuples import Schema
from ..ops.functions import WindowFunction

#: input stream schema: one d=2 point per tuple
POINT_SCHEMA = Schema(x=np.float64, y=np.float64)

#: full-result fields: skyline cardinality + coordinate checksum
RESULT_FIELDS = {"size": np.int64, "checksum": np.float64}


def skyline_mask(pts: np.ndarray) -> np.ndarray:
    """Boolean mask of non-dominated points (minimisation in all dims).
    O(n^2) dominance test, vectorised; `pts` is (n, d)."""
    if len(pts) == 0:
        return np.zeros(0, dtype=bool)
    # a dominates b  <=>  all(a <= b) and any(a < b)
    le = np.all(pts[None, :, :] <= pts[:, None, :], axis=2)   # le[i,j]: j<=i
    lt = np.any(pts[None, :, :] < pts[:, None, :], axis=2)
    dominated = np.any(le & lt, axis=1)
    return ~dominated


def skyline(pts: np.ndarray) -> np.ndarray:
    return pts[skyline_mask(pts)]


class SkylineWindow(WindowFunction):
    """NIC window function: full skyline of the window's points
    (the skytree.hpp operator's role in test_spatial_wf.cpp)."""

    result_fields = RESULT_FIELDS
    required_fields = ("x", "y")

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        sk = skyline(pts)
        return (len(sk), float(sk.sum()))


class SkylinePLQ(WindowFunction):
    """Pane stage: per-pane skyline carried as an object payload (the
    container-valued result the reference expresses with an arbitrary C++
    result_t)."""

    result_fields = {"pts": np.dtype(object)}
    required_fields = ("x", "y")

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        return (skyline(pts),)


class SkylineWLQ(WindowFunction):
    """Window stage: merge the pane skylines of one window."""

    result_fields = RESULT_FIELDS
    required_fields = ("pts",)

    def apply(self, key, gwid, rows):
        parts = [p for p in rows["pts"] if p is not None and len(p)]
        pts = np.concatenate(parts) if parts else np.zeros((0, 2))
        sk = skyline(pts)
        return (len(sk), float(sk.sum()))


def device_skyline():
    """The skyline as a *device* window function — the showcase for
    arbitrary JAX window functions (JaxWindowFunction): the O(n^2)
    dominance test runs as one masked (B, pad, pad) comparison on the
    VPU, all windows of the batch at once.  Note device floats compute in
    float32 (jax default); exact parity with the host float64 skyline
    needs float32-representable coordinates (the tests use a 1/256 grid).
    """
    import jax.numpy as jnp

    from ..patterns.win_seq_tpu import JaxWindowFunction

    def fn(keys, gwids, cols, mask):
        x, y = cols["x"], cols["y"]                       # (B, pad)
        le = ((x[:, None, :] <= x[:, :, None])
              & (y[:, None, :] <= y[:, :, None]))         # j <= i per dim
        lt = ((x[:, None, :] < x[:, :, None])
              | (y[:, None, :] < y[:, :, None]))
        dom = le & lt & mask[:, None, :]                  # j must be real
        alive = mask & ~jnp.any(dom, axis=2)
        size = jnp.sum(alive, axis=1)
        checksum = jnp.sum(jnp.where(alive, x + y, 0.0), axis=1)
        return size, checksum

    return JaxWindowFunction(fn, fields=("x", "y"),
                             result_fields=dict(RESULT_FIELDS),
                             # device-resident variant (use_resident=True):
                             # coordinate rings in float32, matching the
                             # fn's on-device compute precision
                             field_dtypes={"x": np.float32,
                                           "y": np.float32})


# ---------------------------------------------------------------- k-means

#: number of clusters (dkm.hpp N_CENTROIDS)
N_CENTROIDS = 3

#: centroid result columns: N_CENTROIDS x 2 coordinates, canonically
#: ordered, plus the Lloyd iteration count
KMEANS_FIELDS = {f"c{i}{a}": np.float64
                 for i in range(N_CENTROIDS) for a in ("x", "y")}
KMEANS_FIELDS["iters"] = np.int64


def kmeans_lloyd(pts: np.ndarray, k: int = N_CENTROIDS, seed: int = 1,
                 max_iters: int = 1000):
    """Lloyd's k-means with deterministic initialisation — the behavioral
    re-derivation of the reference's dkm.hpp fixture (kmeans_lloyd,
    dkm.hpp:236-258: iterate assignment + means until the means stop
    moving exactly; empty clusters keep their previous mean,
    :198-221; deterministic seed-point selection replaces kmeans++ for
    reproducible runs, random_my :151-166).  Vectorised numpy; returns
    (means (k, d), clusters (n,), iterations)."""
    n = len(pts)
    if n == 0:
        return np.zeros((k, pts.shape[1] if pts.ndim == 2 else 2)), \
            np.zeros(0, dtype=np.int64), 0
    if n < k:
        # the reference asserts data.size() >= k (dkm.hpp:241); windows
        # smaller than k (EOS partials) pad with the last point instead
        means = pts[np.minimum(np.arange(k), n - 1)]
        return means, np.minimum(np.arange(n), k - 1), 0
    rng = np.random.default_rng(seed)
    means = pts[rng.choice(n, size=k, replace=False)]
    it = 0
    for it in range(1, max_iters + 1):
        d2 = ((pts[:, None, :] - means[None, :, :]) ** 2).sum(axis=2)
        cl = d2.argmin(axis=1)
        new = np.empty_like(means)
        for c in range(k):
            m = cl == c
            new[c] = pts[m].mean(axis=0) if m.any() else means[c]
        if np.array_equal(new, means):   # exact convergence (dkm.hpp:255)
            break
        means = new
    return means, cl, it


def _centroid_payload(means: np.ndarray, iters: int) -> tuple:
    """Flatten centroids into the fixed result columns, canonically
    sorted so every parallel composition emits identical rows."""
    order = np.lexsort((means[:, 1], means[:, 0]))
    flat = means[order].reshape(-1)
    return tuple(flat) + (iters,)


class KMeansWindow(WindowFunction):
    """NIC-only heavy window function (dkm.hpp:KmeansFunction): k-means is
    NOT decomposable — it has no incremental form and no pane
    decomposition, so this is exactly the workload class that must run on
    the whole-window NIC path (Win_Farm / Key_Farm; Pane_Farm cannot
    help — the point of the fixture)."""

    result_fields = dict(KMEANS_FIELDS)
    required_fields = ("x", "y")

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        means, _, iters = kmeans_lloyd(pts)
        return _centroid_payload(means, iters)


class KMeansOverSkylines(WindowFunction):
    """The fixture's actual signature: k-means over the de-duplicated
    union of SKYLINE results (KmeansFunction consumes Iterable<Skyline>
    and a std::set union of their points, dkm.hpp:262-276) — the second
    stage behind a skyline operator carrying full-content payloads."""

    result_fields = dict(KMEANS_FIELDS)
    required_fields = ("pts",)

    def apply(self, key, gwid, rows):
        parts = [p for p in rows["pts"] if p is not None and len(p)]
        pts = (np.unique(np.concatenate(parts), axis=0) if parts
               else np.zeros((0, 2)))   # sorted-set union (dkm.hpp:265-269)
        means, _, iters = kmeans_lloyd(pts)
        return _centroid_payload(means, iters)


def point_batches(n_points, keys=1, chunk=512, seed=7, ts_step=5):
    """Synthetic point stream (sq_generator.hpp analog): uniform points
    with a linear timestamp ramp per key."""
    rng = np.random.default_rng(seed)
    out = []
    for lo in range(0, n_points, chunk):
        m = min(chunk, n_points - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys)
        ks = np.tile(np.arange(keys), m)
        out.append(_pt_batch(ids, ks, ids * ts_step,
                             rng.uniform(0, 100, m * keys),
                             rng.uniform(0, 100, m * keys)))
    return out


def _pt_batch(ids, keys, ts, x, y):
    from ..core.tuples import batch_from_columns
    return batch_from_columns(POINT_SCHEMA, key=keys, id=ids, ts=ts,
                              x=x, y=y)
