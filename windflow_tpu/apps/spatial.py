"""Spatial queries over time-based windows — the port of the reference's
``src/spatial_test`` suite (skytree.hpp skyline operator, sq_generator.hpp,
test_spatial_{wf,pf,wf+pf}.cpp): a *heavy* non-incremental window function
(skyline / pareto frontier, ms-scale per window) exercised through Win_Farm,
Pane_Farm and the nested WF(PF) composition.

The skyline is decomposable — ``skyline(A ∪ B) = skyline(skyline(A) ∪
skyline(B))`` — which is exactly what Pane_Farm exploits: the PLQ computes
per-pane skylines, and the WLQ merges pane skylines per window.

The pane payload (the reference's container-valued ``result_t``) rides
FIXED-WIDTH SoA columns — ``sk_x``/``sk_y`` sub-array fields of
``PANE_CAP`` slots plus a ``sk_n`` count — not an object-dtype column:
the one schema shape every engine path (vectorised emitters, ordering,
channels, device staging) already speaks (VERDICT r3 weak #6).  A pane
skyline of uniform points is O(log n) expected, so the default cap of 64
is deep; an overflow raises loudly rather than truncating a result.
"""

from __future__ import annotations

import numpy as np

from ..core.tuples import Schema
from ..ops.functions import WindowFunction

#: input stream schema: one d=2 point per tuple
POINT_SCHEMA = Schema(x=np.float64, y=np.float64)

#: full-result fields: skyline cardinality + coordinate checksum
RESULT_FIELDS = {"size": np.int64, "checksum": np.float64}


def skyline_mask(pts: np.ndarray) -> np.ndarray:
    """Boolean mask of non-dominated points (minimisation in all dims).
    O(n^2) dominance test, vectorised; `pts` is (n, d)."""
    if len(pts) == 0:
        return np.zeros(0, dtype=bool)
    # a dominates b  <=>  all(a <= b) and any(a < b)
    le = np.all(pts[None, :, :] <= pts[:, None, :], axis=2)   # le[i,j]: j<=i
    lt = np.any(pts[None, :, :] < pts[:, None, :], axis=2)
    dominated = np.any(le & lt, axis=1)
    return ~dominated


def skyline(pts: np.ndarray) -> np.ndarray:
    return pts[skyline_mask(pts)]


class SkylineWindow(WindowFunction):
    """NIC window function: full skyline of the window's points
    (the skytree.hpp operator's role in test_spatial_wf.cpp)."""

    result_fields = RESULT_FIELDS
    required_fields = ("x", "y")

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        sk = skyline(pts)
        return (len(sk), float(sk.sum()))


#: pane-payload capacity: slots per pane skyline in the fixed-width SoA
#: columns (expected skyline cardinality of n uniform 2-d points is
#: O(ln n), so 64 covers panes orders of magnitude past the bench shapes)
PANE_CAP = 64


def pane_payload_fields(cap: int = PANE_CAP):
    """SoA pane-skyline schema: (cap,)-shaped coordinate sub-arrays + a
    count — the fixed-width form of the reference's container result."""
    return {"sk_x": np.dtype((np.float64, (cap,))),
            "sk_y": np.dtype((np.float64, (cap,))),
            "sk_n": np.int64}


def _pack_pane(sk: np.ndarray, cap: int):
    """(n, 2) skyline -> (x[cap], y[cap], n); loud on overflow — a
    silently truncated pane would silently corrupt every window that
    merges it."""
    n = len(sk)
    if n > cap:
        raise ValueError(
            f"pane skyline cardinality {n} exceeds the payload capacity "
            f"{cap}; raise the stage's cap= (pane_payload_fields)")
    x = np.zeros(cap)
    y = np.zeros(cap)
    x[:n] = sk[:, 0]
    y[:n] = sk[:, 1]
    return x, y, n


def _unpack_panes(rows) -> np.ndarray:
    """Concatenate the live slots of every pane row into one (m, 2) set."""
    ns = rows["sk_n"]
    if not len(ns) or not ns.sum():
        return np.zeros((0, 2))
    alive = np.arange(rows["sk_x"].shape[1])[None, :] < ns[:, None]
    return np.stack([rows["sk_x"][alive], rows["sk_y"][alive]], axis=1)


class SkylinePLQ(WindowFunction):
    """Pane stage: per-pane skyline packed into the fixed-width SoA
    payload (the container-valued result the reference expresses with an
    arbitrary C++ result_t)."""

    required_fields = ("x", "y")

    def __init__(self, cap: int = PANE_CAP):
        self.cap = int(cap)
        self.result_fields = pane_payload_fields(self.cap)

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        return _pack_pane(skyline(pts), self.cap)


class SkylineWLQ(WindowFunction):
    """Window stage: merge the pane skylines of one window."""

    result_fields = RESULT_FIELDS
    required_fields = ("sk_x", "sk_y", "sk_n")

    def apply(self, key, gwid, rows):
        sk = skyline(_unpack_panes(rows))
        return (len(sk), float(sk.sum()))


def device_skyline():
    """The skyline as a *device* window function — the showcase for
    arbitrary JAX window functions (JaxWindowFunction): the O(n^2)
    dominance test runs as one masked (B, pad, pad) comparison on the
    VPU, all windows of the batch at once.  Note device floats compute in
    float32 (jax default); exact parity with the host float64 skyline
    needs float32-representable coordinates (the tests use a 1/256 grid).
    """
    import jax.numpy as jnp

    from ..patterns.win_seq_tpu import JaxWindowFunction

    def fn(keys, gwids, cols, mask):
        x, y = cols["x"], cols["y"]                       # (B, pad)
        le = ((x[:, None, :] <= x[:, :, None])
              & (y[:, None, :] <= y[:, :, None]))         # j <= i per dim
        lt = ((x[:, None, :] < x[:, :, None])
              | (y[:, None, :] < y[:, :, None]))
        dom = le & lt & mask[:, None, :]                  # j must be real
        alive = mask & ~jnp.any(dom, axis=2)
        size = jnp.sum(alive, axis=1)
        checksum = jnp.sum(jnp.where(alive, x + y, 0.0), axis=1)
        return size, checksum

    return JaxWindowFunction(fn, fields=("x", "y"),
                             result_fields=dict(RESULT_FIELDS),
                             # device-resident variant (use_resident=True):
                             # coordinate rings in float32, matching the
                             # fn's on-device compute precision
                             field_dtypes={"x": np.float32,
                                           "y": np.float32})


# ---------------------------------------------------------------- k-means

#: number of clusters (dkm.hpp N_CENTROIDS)
N_CENTROIDS = 3

#: centroid result columns: N_CENTROIDS x 2 coordinates, canonically
#: ordered, plus the Lloyd iteration count
KMEANS_FIELDS = {f"c{i}{a}": np.float64
                 for i in range(N_CENTROIDS) for a in ("x", "y")}
KMEANS_FIELDS["iters"] = np.int64


def kmeans_lloyd(pts: np.ndarray, k: int = N_CENTROIDS, seed: int = 1,
                 max_iters: int = 1000):
    """Lloyd's k-means with deterministic initialisation — the behavioral
    re-derivation of the reference's dkm.hpp fixture (kmeans_lloyd,
    dkm.hpp:236-258: iterate assignment + means until the means stop
    moving exactly; empty clusters keep their previous mean,
    :198-221; deterministic seed-point selection replaces kmeans++ for
    reproducible runs, random_my :151-166).  Vectorised numpy; returns
    (means (k, d), clusters (n,), iterations)."""
    n = len(pts)
    if n == 0:
        return np.zeros((k, pts.shape[1] if pts.ndim == 2 else 2)), \
            np.zeros(0, dtype=np.int64), 0
    if n < k:
        # the reference asserts data.size() >= k (dkm.hpp:241); windows
        # smaller than k (EOS partials) pad with the last point instead
        means = pts[np.minimum(np.arange(k), n - 1)]
        return means, np.minimum(np.arange(n), k - 1), 0
    rng = np.random.default_rng(seed)
    means = pts[rng.choice(n, size=k, replace=False)]
    it = 0
    for it in range(1, max_iters + 1):
        d2 = ((pts[:, None, :] - means[None, :, :]) ** 2).sum(axis=2)
        cl = d2.argmin(axis=1)
        new = np.empty_like(means)
        for c in range(k):
            m = cl == c
            new[c] = pts[m].mean(axis=0) if m.any() else means[c]
        if np.array_equal(new, means):   # exact convergence (dkm.hpp:255)
            break
        means = new
    return means, cl, it


def _centroid_payload(means: np.ndarray, iters: int) -> tuple:
    """Flatten centroids into the fixed result columns, canonically
    sorted so every parallel composition emits identical rows."""
    order = np.lexsort((means[:, 1], means[:, 0]))
    flat = means[order].reshape(-1)
    return tuple(flat) + (iters,)


class KMeansWindow(WindowFunction):
    """NIC-only heavy window function (dkm.hpp:KmeansFunction): k-means is
    NOT decomposable — it has no incremental form and no pane
    decomposition, so this is exactly the workload class that must run on
    the whole-window NIC path (Win_Farm / Key_Farm; Pane_Farm cannot
    help — the point of the fixture)."""

    result_fields = dict(KMEANS_FIELDS)
    required_fields = ("x", "y")

    def apply(self, key, gwid, rows):
        pts = np.stack([rows["x"], rows["y"]], axis=1) if len(rows) \
            else np.zeros((0, 2))
        means, _, iters = kmeans_lloyd(pts)
        return _centroid_payload(means, iters)


class KMeansOverSkylines(WindowFunction):
    """The fixture's actual signature: k-means over the de-duplicated
    union of SKYLINE results (KmeansFunction consumes Iterable<Skyline>
    and a std::set union of their points, dkm.hpp:262-276) — the second
    stage behind a skyline operator carrying full-content SoA payloads."""

    result_fields = dict(KMEANS_FIELDS)
    required_fields = ("sk_x", "sk_y", "sk_n")

    def apply(self, key, gwid, rows):
        pts = _unpack_panes(rows)
        if len(pts):
            pts = np.unique(pts, axis=0)   # sorted-set union (dkm.hpp:265-269)
        means, _, iters = kmeans_lloyd(pts)
        return _centroid_payload(means, iters)


def point_batches(n_points, keys=1, chunk=512, seed=7, ts_step=5):
    """Synthetic point stream (sq_generator.hpp analog): uniform points
    with a linear timestamp ramp per key."""
    rng = np.random.default_rng(seed)
    out = []
    for lo in range(0, n_points, chunk):
        m = min(chunk, n_points - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys)
        ks = np.tile(np.arange(keys), m)
        out.append(_pt_batch(ids, ks, ids * ts_step,
                             rng.uniform(0, 100, m * keys),
                             rng.uniform(0, 100, m * keys)))
    return out


def _pt_batch(ids, keys, ts, x, y):
    from ..core.tuples import batch_from_columns
    return batch_from_columns(POINT_SCHEMA, key=keys, id=ids, ts=ts,
                              x=x, y=y)


# ------------------------------------------------------------ benchmark
#
# spatial_test perf runner — the measurement shape of the reference's
# src/spatial_test (test_spatial_wf.cpp / test_spatial_pf.cpp): a
# RATE-PACED generator stamps each point with its wall microseconds since
# start, TB windows close on that event time, and the sink reports
# events/sec plus per-window close-to-delivery latency (the reference's
# generator emits on a timer for exactly this reason — window cardinality
# is rate * win, a controlled experiment knob, and the O(n^2) skyline's
# per-window cost with it).  A variant that cannot keep up backpressures
# the generator through the bounded channels, so its measured events/sec
# drops below the target rate — throughput AND latency both
# differentiate, as in the reference's WF-vs-PF comparison.

import time as _time


def spatial_event_batches(duration_sec: float, chunk: int,
                          rate: float = 80_000.0, keys: int = 1,
                          seed: int = 7, time_fn=_time.monotonic,
                          sleep_fn=_time.sleep):
    """Rate-paced point generator: at most ``rate`` points/sec, ts = wall
    microseconds since start."""
    rng = np.random.default_rng(seed)
    v0 = 0
    t0 = time_fn()
    while True:
        now = time_fn() - t0
        if now >= duration_sec:
            return
        # pace to the chunk's LAST tuple: emitting when only the first id
        # is due would hand downstream tuples stamped up to chunk/rate in
        # the FUTURE of the wall clock, closing windows before their end
        # time and understating measured latency by that much
        ahead = (v0 + chunk) / rate - now    # seconds of lead over the pace
        if ahead > 0:
            sleep_fn(min(ahead, duration_sec - now))
            now = time_fn() - t0
            if now >= duration_sec:
                return
        ids = np.arange(v0, v0 + chunk, dtype=np.int64)
        # per-tuple event time from the pace (tuple v is generated at
        # ~v/rate seconds): one shared wall stamp per chunk makes every
        # chunk a single 0-width ts point, so whole PANES land on one
        # farm worker in ~chunk-cadence beats and a worker's open pane
        # cannot close until the alternation returns (~0.5 s of pure
        # artifact latency measured at rate 1250 / chunk 64)
        yield _pt_batch(ids, ids % keys,
                        (ids * (1e6 / rate)).astype(np.int64),
                        rng.uniform(0, 100, chunk),
                        rng.uniform(0, 100, chunk))
        v0 += chunk


class SpatialSink:
    """Per-window latency accounting with percentiles: a TB window's
    result ts is its window-end event time (µs since start), so
    ``now - (start_wall + ts)`` is its close-to-delivery latency."""

    def __init__(self, start_wall_us: int):
        self.start_wall_us = start_wall_us
        self.received = 0
        self.skyline_points = 0
        self.lat_us = []

    def __call__(self, batch):
        if batch is None or not len(batch):
            return
        now = int(_time.time() * 1e6)
        lat = now - (batch["ts"] + self.start_wall_us)
        self.received += len(batch)
        self.skyline_points += int(batch["size"].sum())
        self.lat_us.extend(int(v) for v in lat)

    def stats(self):
        from ..utils.latency import summarize
        s = summarize([np.asarray(self.lat_us, dtype=np.float64)],
                      scale=1e-3)
        if not s:
            return {"windows": 0}
        return {"windows": self.received,
                "skyline_points": self.skyline_points,
                "avg_latency_ms": s["avg"],
                "p50_latency_ms": s["p50"],
                "p95_latency_ms": s["p95"],
                "p99_latency_ms": s["p99"],
                "n_latency_samples": s["n"]}


def build_spatial(variant: str, duration_sec: float, pardegree: int,
                  win_ms: float, slide_ms: float, chunk: int,
                  rate: float = 80_000.0, batches=None,
                  batch_len: int = 256, max_delay_ms: float = None):
    """Assemble one spatial composition.  `variant`: 'wf' (whole-window
    skyline through Win_Farm, test_spatial_wf.cpp), 'pf' (pane
    decomposition, test_spatial_pf.cpp), 'nested' (WF(PF)), 'wf-tpu'
    (the device skyline through WinFarmTPU)."""
    from ..api import MultiPipe
    from ..patterns.basic import Sink, Source

    win_us = int(win_ms * 1e3)
    slide_us = int(slide_ms * 1e3)
    from ..core.windows import WinType
    if variant == "wf":
        from ..patterns.win_farm import WinFarm
        agg = WinFarm(SkylineWindow(), win_us, slide_us, WinType.TB,
                      pardegree=pardegree, name="sky_wf")
    elif variant == "pf":
        from ..patterns.pane_farm import PaneFarm
        agg = PaneFarm(SkylinePLQ(), SkylineWLQ(), win_us, slide_us,
                       WinType.TB, plq_degree=pardegree,
                       wlq_degree=max(pardegree // 2, 1), name="sky_pf")
    elif variant == "nested":
        from ..patterns.nesting import WinFarmOf
        from ..patterns.pane_farm import PaneFarm
        inner = PaneFarm(SkylinePLQ(), SkylineWLQ(), win_us, slide_us,
                         WinType.TB, plq_degree=max(pardegree // 2, 1),
                         wlq_degree=1, name="sky_pf_inner")
        agg = WinFarmOf(inner, pardegree=max(pardegree // 2, 1),
                        name="sky_wf_pf")
    elif variant == "wf-tpu":
        from ..patterns.win_seq_tpu import WinFarmTPU
        agg = WinFarmTPU(device_skyline(), win_us, slide_us, WinType.TB,
                         pardegree=pardegree, batch_len=batch_len,
                         use_resident=True, name="sky_wf_tpu",
                         max_delay_ms=max_delay_ms)
    else:
        raise ValueError(f"unknown spatial variant {variant!r}")
    if max_delay_ms is not None and variant != "wf-tpu":
        # same guard as ysb.py: the host variants have no force-flush
        # timer — silently printing their latencies as "budget-bounded"
        # would misreport what bounded them (nothing)
        raise ValueError("--max-delay-ms applies to the wf-tpu variant "
                         f"only (got {variant!r})")

    start_wall = int(_time.time() * 1e6)
    sink = SpatialSink(start_wall)
    gen = (iter(batches) if batches is not None
           else spatial_event_batches(duration_sec, chunk, rate))
    n_gen = [0]

    def src(shipper):
        for b in gen:
            n_gen[0] += len(b)
            shipper.push_batch(b)

    pipe = (MultiPipe(f"spatial_{variant}")
            .add_source(Source(src, POINT_SCHEMA, name="sq_gen"))
            .add(agg)
            .chain_sink(Sink(sink, vectorized=True)))
    return pipe, sink, n_gen


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): tiny
    never-run instances of the host skyline topologies (whole-window
    farm and the pane decomposition — 50/12.5 ms keeps the pane factor
    divisible, the WF103-clean geometry)."""
    out = []
    for variant in ("wf", "pf"):
        pipe, _sink, _n = build_spatial(variant, 0.0, 2, 50.0, 12.5, 256,
                                        batches=[])
        out.append(pipe)
    return out


def run(variant="wf", duration_sec=8.0, pardegree=2, win_ms=50.0,
        slide_ms=12.5, chunk=2048, rate=80_000.0, warm=True,
        max_delay_ms=None):
    """Run one spatial benchmark variant; returns the reference's metric
    pair (events/sec + per-window latency) with wire diagnostics."""
    from ..ops import resident
    if warm:
        # short warm pass: compiles the device buckets (wf-tpu) and
        # first-touches every composition path outside the timed window
        wp, _ws, _wn = build_spatial(variant, 1.0, pardegree, win_ms,
                                     slide_ms, chunk, rate,
                                     max_delay_ms=max_delay_ms)
        wp.run_and_wait_end()
        if variant == "wf-tpu":
            resident.prewarm_regular_ladder()
    pipe, sink, n_gen = build_spatial(variant, duration_sec, pardegree,
                                      win_ms, slide_ms, chunk, rate,
                                      max_delay_ms=max_delay_ms)
    resident.stats_snapshot(reset=True)
    t0 = _time.perf_counter()
    pipe.run_and_wait_end()
    elapsed = _time.perf_counter() - t0
    diag = resident.stats_snapshot(reset=True)
    out = {"variant": variant, "generated": n_gen[0],
           "elapsed_sec": round(elapsed, 3),
           "events_per_sec": round(n_gen[0] / max(elapsed, 1e-9), 1),
           # sustained ingest during the generation window (ysb.py's
           # gen_events_per_sec twin): end-to-end divides by elapsed
           # including the drain, this by the generation time only
           "gen_events_per_sec": round(
               n_gen[0] / max(duration_sec, 1e-9), 1),
           **sink.stats()}
    if variant == "wf-tpu":
        out.update({k: diag[k] for k in ("dispatches", "merges",
                                         "mean_launch_ms")})
    return out


def main(argv=None):
    import argparse
    import json
    ap = argparse.ArgumentParser(description="spatial_test benchmark")
    ap.add_argument("-v", "--variants", default="wf,pf,nested,wf-tpu")
    ap.add_argument("-l", "--length", type=float, default=8.0)
    ap.add_argument("-p", "--pardegree", type=int, default=2)
    ap.add_argument("--win-ms", type=float, default=50.0)
    ap.add_argument("--slide-ms", type=float, default=12.5)
    ap.add_argument("--chunk", type=int, default=2048)
    ap.add_argument("--rate", type=float, default=80_000.0,
                    help="generator pace, points/sec (window cardinality "
                         "= rate * win)")
    ap.add_argument("--rounds", type=int, default=2,
                    help="interleaved rounds per variant (weather fairness)")
    ap.add_argument("--budget-ms", type=float, default=None,
                    help="sustainable-throughput mode: step through "
                         "--rates ascending per variant and report the "
                         "highest rate whose p95 window latency meets "
                         "this budget (the streaming-benchmark "
                         "methodology; saturation latencies at a "
                         "too-fast pace are queue backlog, not service)")
    ap.add_argument("--rates", default="2500,5000,10000,20000,40000,80000",
                    help="ascending rate ladder for --budget-ms mode")
    ap.add_argument("--max-delay-ms", type=float, default=None,
                    help="device-core force-flush bound (wf-tpu); "
                         "defaults to budget/2 in --budget-ms mode")
    a = ap.parse_args(argv)
    variants = [v.strip() for v in a.variants.split(",") if v.strip()]
    if a.budget_ms is not None:
        # sustainable throughput under a latency budget (VERDICT r4
        # item 5): per variant, climb the rate ladder while p95 meets
        # the budget; a first violation ends that variant's climb (the
        # saturated regime only gets worse with rate)
        rates = [float(r) for r in a.rates.split(",") if r.strip()]
        for v in variants:
            dly = a.max_delay_ms
            if dly is None and v == "wf-tpu":
                dly = a.budget_ms / 2
            best = None
            for r in rates:
                # chunk ~ one slide period of points: at 2.5k pts/s the
                # default 2048-chunk takes 0.8 s to FILL — pure source
                # batching delay that would dominate any budget
                chunk = min(a.chunk, max(64, int(r * a.slide_ms / 1e3)))
                # wf-tpu re-warms at every rung: window cardinality grows
                # with rate (32x across the default ladder), and a cold
                # device-shape compile inside the timed window would end
                # the climb on compile latency, not saturation
                out = run(v, a.length, a.pardegree, a.win_ms, a.slide_ms,
                          chunk, r, warm=(best is None or v == "wf-tpu"),
                          max_delay_ms=dly)
                out["rate"] = r
                out["within_budget"] = bool(
                    out.get("p95_latency_ms", float("inf")) <= a.budget_ms)
                print(json.dumps(out), flush=True)
                if not out["within_budget"]:
                    break
                best = out
            print(json.dumps({
                "metric": f"spatial_test {v} sustainable@p95<="
                          f"{a.budget_ms:g}ms",
                **(best or {"rate": 0, "note": "no rate met the budget"}),
            }), flush=True)
        return 0
    rows = {v: [] for v in variants}
    for _ in range(a.rounds):
        for v in variants:
            out = run(v, a.length, a.pardegree, a.win_ms, a.slide_ms,
                      a.chunk, a.rate, warm=not rows[v],
                      max_delay_ms=a.max_delay_ms)
            rows[v].append(out)
            print(json.dumps(out), flush=True)
    for v in variants:
        best = max(rows[v], key=lambda r: r["events_per_sec"])
        print(json.dumps({"metric": f"spatial_test {v} best", **best}),
              flush=True)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
