"""Micro-pipeline benchmark — the port of the reference's
``src/microbenchmarks/test_micro_1.cpp``: Source → Map → Filter → FlatMap →
Sink measuring end-to-end throughput and per-tuple latency via the same
counters (sentCounter / rcvResults / latency_sum, test_micro_1.cpp:31-37).

Latency here is measured per *batch* at the sink against the generation
timestamp carried in ``ts`` (wall-clock microseconds), then averaged per
tuple — the batch idiom's analog of the reference's per-tuple
``current_time_usecs() - t.ts``.
"""

from __future__ import annotations

import time

import numpy as np

from ..api import MultiPipe
from ..core.tuples import Schema, batch_from_columns
from ..patterns.basic import Filter, FlatMap, Map, Sink, Source

SCHEMA = Schema(value=np.int64)


def build_micro(duration_sec=5.0, chunk=4096, pardegree=1, capacity=2):
    """Assemble the micro pipeline without running it; returns
    ``(pipe, counters)`` with the shared counter cells the closures
    update (``sent``/``rcv``/``lat_sum``) so ``run`` — and the static
    analyzer (scripts/wf_lint.py) — drive the same topology."""
    import threading
    sent = [0]
    sent_lock = threading.Lock()

    def gen(shipper):
        t0 = time.monotonic()
        v0 = 0
        n = 0
        while time.monotonic() - t0 < duration_sec:
            now_us = int(time.time() * 1e6)
            v = np.arange(v0, v0 + chunk, dtype=np.int64)
            shipper.push_batch(batch_from_columns(
                SCHEMA, key=v % 16, id=v,
                ts=np.full(chunk, now_us, dtype=np.int64), value=v))
            n += chunk
            v0 += chunk
        with sent_lock:  # replicas race on the shared counter
            sent[0] += n

    def fm(batch, shipper):
        # 1-to-1 flatmap (the reference's shipper exercise)
        shipper.push_batch(batch)

    rcv = [0]
    lat_sum = [0.0]

    def sink(batch):
        if batch is None:
            return
        now_us = time.time() * 1e6
        rcv[0] += len(batch)
        lat_sum[0] += float((now_us - batch["ts"]).sum())

    # end-to-end latency ~= stages x capacity x chunk / throughput: the
    # two knobs below trade latency against batching efficiency
    pipe = (MultiPipe("micro", capacity=capacity)
            .add_source(Source(gen, SCHEMA, parallelism=pardegree,
                               name="micro_src"))
            .add(Map(lambda b: b.__setitem__("value", b["value"] * 3),
                     vectorized=True, parallelism=pardegree))
            .add(Filter(lambda b: b["value"] % 2 == 0, vectorized=True,
                        parallelism=pardegree))
            .add(FlatMap(fm, SCHEMA, vectorized=True, parallelism=pardegree))
            .chain_sink(Sink(sink, vectorized=True)))
    return pipe, {"sent": sent, "rcv": rcv, "lat_sum": lat_sum}


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the benchmark topology.  pardegree 2 so
    the closure race analyzer sees the replica-shared generator (whose
    counter updates are lock-guarded — the pattern it must NOT flag)."""
    pipe, _counters = build_micro(0.0, chunk=1024, pardegree=2)
    return [pipe]


def run(duration_sec=5.0, chunk=4096, pardegree=1, capacity=2):
    pipe, counters = build_micro(duration_sec, chunk, pardegree, capacity)
    sent, rcv, lat_sum = (counters["sent"], counters["rcv"],
                          counters["lat_sum"])
    from ..ops import resident
    resident.stats_snapshot(reset=True)
    t0 = time.perf_counter()
    pipe.run_and_wait_end()
    elapsed = time.perf_counter() - t0
    return {
        "sent": sent[0],
        "received": rcv[0],
        "tuples_per_sec": round(sent[0] / elapsed, 1),
        "avg_latency_us": round(lat_sum[0] / max(rcv[0], 1), 1),
        "elapsed_sec": round(elapsed, 3),
        # wire diagnostics (bench.py discipline; zeros: no device stage)
        **resident.stats_snapshot(reset=True),
    }


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="micro pipeline benchmark")
    ap.add_argument("-l", "--length", type=float, default=5.0)
    ap.add_argument("-p", "--pardegree", type=int, default=1)
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--capacity", type=int, default=2,
                    help="per-queue chunk capacity (latency knob)")
    a = ap.parse_args(argv)
    m = run(a.length, a.chunk, a.pardegree, a.capacity)
    for k, v in m.items():
        print(f"[micro] {k}: {v}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
