"""pipe_test_tpu — the end-to-end device-pipeline benchmark: the TPU port
of the reference's ``src/pipe_test_gpu`` suite (e.g.
``test_pipe_wf_gpu_cb.cpp``): Source -> chain(Map) -> chain(Filter) ->
Win_Farm_GPU -> Sink, measuring input tuples/sec and per-window latency.

Differences from ``bench.py`` (the sum_test_tpu headline): this drives the
FULL pipeline machinery — chained stateless stages fused into the source
thread (multipipe.hpp:244-271's chain_operator), the TS_RENUMBERING merge
the MultiPipe interposes in front of a count-window farm fed by a filtered
stream (multipipe.hpp:494-537's CB mode table), a pardegree>=2
``WinFarmTPU`` whose workers run the native resident device cores, and an
ordered collector.  Latency is measured the reference's way: every tuple
carries its generation wall-clock in ``ts``; a CB window result's ts is its
last contributing tuple's, so ``now - result.ts`` at the sink is the
per-window close-to-delivery latency (ysb_nodes.hpp:231-238).

Prints one JSON line with tuples/sec, latency, and the wire diagnostics
(dispatches / merges / mean launch service) of each timed run.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..api import MultiPipe
from ..core.tuples import Schema, batch_from_columns
from ..core.windows import WinType
from ..ops import resident
from ..ops.functions import Reducer
from ..patterns.basic import Filter, Map, Sink, Source
from ..patterns.win_seq_tpu import WinFarmTPU

SCHEMA = Schema(value=np.int64)

N_KEYS = 64
WIN, SLIDE = 256, 64
VAL_LO, VAL_HI = 0, 100          # pre-Map value range


def make_values(n_tuples: int, chunk: int, seed: int = 7):
    """Deterministic keyed value TEMPLATE batches (sum_cb.hpp:89-117
    shape), prebuilt as full structured arrays outside the timed loop:
    the per-run source memcpys a template and stamps ``ts`` — assembling
    columns into the interleaved record layout per push was 0.21 s of
    the timed 8M-row run (r4 profile), pure setup cost masquerading as
    streaming work."""
    rng = np.random.default_rng(seed)
    per_key = n_tuples // N_KEYS
    rows_per_chunk = max(chunk // N_KEYS, 1)
    out = []
    for lo in range(0, per_key, rows_per_chunk):
        m = min(rows_per_chunk, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), N_KEYS)
        keys = np.tile(np.arange(N_KEYS), m)
        vals = rng.integers(VAL_LO, VAL_HI, size=m * N_KEYS).astype(np.int64)
        out.append(batch_from_columns(
            SCHEMA, key=keys, id=ids,
            ts=np.zeros(m * N_KEYS, dtype=np.int64), value=vals))
    return out


def transform(vals: np.ndarray) -> np.ndarray:
    return vals * 3 + 1


def transform_inplace(batch: np.ndarray) -> None:
    """The pipeline Map: same function as :func:`transform`, written
    with out= ufuncs so the fused in-place path (map.hpp:141 semantics,
    node.py ownership protocol) rewrites the value column without any
    temporaries."""
    v = batch["value"]
    np.multiply(v, 3, out=v)
    np.add(v, 1, out=v)


def keep(vals: np.ndarray) -> np.ndarray:
    return vals % 5 != 0


def expected(chunks) -> tuple[int, int]:
    """Host oracle: the filtered/mapped stream's windowed sums.  The
    MultiPipe interposes TS_RENUMBERING in front of the CB farm (the
    filtered stream's ids are no longer dense), so windows count the
    SURVIVING tuples per key — dense positions over the kept rows."""
    vals = np.concatenate([transform(t["value"]) for t in chunks])
    keys = np.concatenate([t["key"] for t in chunks])
    m = keep(vals)
    vals, keys = vals[m], keys[m]
    total = n_windows = 0
    for k in range(N_KEYS):
        v = vals[keys == k]
        if not len(v):
            continue
        c = np.concatenate([[0], np.cumsum(v)])
        n_wins = (len(v) - 1) // SLIDE + 1
        starts = np.arange(n_wins) * SLIDE
        total += int(np.sum(c[np.minimum(starts + WIN, len(v))] - c[starts]))
        n_windows += n_wins
    return total, n_windows


def build_pipe(chunks, pardegree, flush_rows, depth, capacity,
               max_delay_ms=None, rate=None, trace=None, trace_dir=None):
    """Assemble the pipe_test_tpu MultiPipe without running it; returns
    ``(pipe, state)`` where ``state`` is the sink's result-accumulator
    dict — shared by the timed ``run_once`` and the static analyzer
    (scripts/wf_lint.py).  ``trace`` (a sample-rate fraction or
    obs.trace.TracePolicy) + ``trace_dir`` opt the run into end-to-end
    span tracing: <trace_dir>/trace.jsonl feeds scripts/wf_trace.py
    (docs/OBSERVABILITY.md §tracing)."""
    state = {"rcv": 0, "total": 0, "lat_us": []}

    def gen(shipper):
        t0 = time.monotonic()
        sent = 0
        for t in chunks:
            if rate:
                # paced source (latency-budget mode): full-speed pushing
                # stamps the whole stream up front and measures pipeline
                # BACKLOG as "latency"; a sub-capacity pace keeps queues
                # shallow so the p95 reflects window close-to-delivery
                # delay, the thing a budget can govern
                ahead = sent / rate - (time.monotonic() - t0)
                if ahead > 0:
                    time.sleep(ahead)
            # one contiguous memcpy of the template, then the ts stamp:
            # the copy is what makes the pushed batch transfer-owned
            # (Source fresh=True) so the fused Map may mutate it in place
            b = t.copy()
            b["ts"] = int(time.time() * 1e6)
            shipper.push_batch(b)
            sent += len(b)

    def consume(rows):
        if rows is None or not len(rows):
            return
        now_us = time.time() * 1e6
        state["rcv"] += len(rows)
        state["lat_us"].append((now_us - rows["ts"]).astype(np.float64))
        state["total"] += int(rows["value"].sum())

    # values after Map stay in [1, 3*VAL_HI]: declare it so the resident
    # path runs warning-clean with a provably safe int32 accumulate
    red = Reducer("sum", value_range=(0, 3 * VAL_HI + 1))
    pipe = (MultiPipe("pipe_test_tpu", capacity=capacity,
                      trace=trace, trace_dir=trace_dir)
            .add_source(Source(gen, SCHEMA, name="src", fresh=True))
            # Map before Filter: the predicate reads the mapped column, so
            # this order computes transform() once per batch (both stages
            # fuse into the source thread — a second pass would directly
            # depress the measured pipeline throughput)
            .chain(Map(transform_inplace, vectorized=True))
            .chain(Filter(lambda b: keep(b["value"]), vectorized=True))
            .add(WinFarmTPU(red, WIN, SLIDE, WinType.CB,
                            pardegree=pardegree, batch_len=1 << 15,
                            flush_rows=flush_rows, depth=depth,
                            max_delay_ms=max_delay_ms))
            .chain_sink(Sink(consume, vectorized=True)))
    return pipe, state


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the benchmark topology."""
    pipe, _state = build_pipe([], pardegree=2, flush_rows=1 << 16,
                              depth=2, capacity=16)
    return [pipe]


def run_once(chunks, pardegree, flush_rows, depth, capacity,
             max_delay_ms=None, rate=None, trace=None, trace_dir=None):
    pipe, state = build_pipe(chunks, pardegree, flush_rows, depth,
                             capacity, max_delay_ms=max_delay_ms,
                             rate=rate, trace=trace, trace_dir=trace_dir)
    resident.stats_snapshot(reset=True)
    t0 = time.perf_counter()
    pipe.run_and_wait_end()
    dt = time.perf_counter() - t0
    diag = resident.stats_snapshot(reset=True)
    return dt, state, diag


def _lat_stats(state):
    from ..utils.latency import summarize
    s = summarize(state["lat_us"], scale=1e-3)
    if not s:
        return {"avg_window_latency_ms": 0.0}
    return {"avg_window_latency_ms": s["avg"],
            "p50_window_latency_ms": s["p50"],
            "p95_window_latency_ms": s["p95"],
            "p99_window_latency_ms": s["p99"],
            "n_window_results": s["n"]}


def run(n_tuples=8_000_000, pardegree=2, chunk=1 << 20,
        flush_rows=1 << 19, depth=48, capacity=4, runs=3,
        max_delay_ms=None, rate=None, trace=None, trace_dir=None):
    """Throughput mode (max_delay_ms=None) tunes for tuples/sec; the
    LATENCY-BUDGET mode (max_delay_ms=B with a sub-capacity ``rate``)
    bounds window close-to-delivery delay via the cores' force-flush
    timers and reports the throughput achieved *within* the budget,
    p95/p99 included — the reference's per-result latency is its
    headline metric alongside throughput (ysb_nodes.hpp:231-246).
    Without pacing, a finite full-speed drain's "latency" is queue
    backlog, which no flush cadence can govern."""
    if max_delay_ms is not None and chunk == 1 << 20:
        # default chunk only: finer pacing granularity (~8 pushes/sec at
        # 1M/s); an EXPLICIT --chunk is honored as given
        chunk = 1 << 17
    chunks = make_values(n_tuples, chunk)
    want_total, want_windows = expected(chunks)
    # warmup (compiles every shape bucket) + the coalescing shape ladder,
    # on every device the farm's workers own (jit caches per placement)
    run_once(chunks, pardegree, flush_rows, depth, capacity, max_delay_ms)
    import jax
    devs = jax.devices()
    resident.prewarm_regular_ladder(devices=list(dict.fromkeys(
        devs[i % len(devs)] for i in range(pardegree))))
    best = None
    all_runs = []
    for _ in range(runs):
        dt, state, diag = run_once(chunks, pardegree, flush_rows, depth,
                                   capacity, max_delay_ms, rate,
                                   trace=trace, trace_dir=trace_dir)
        if state["total"] != want_total or state["rcv"] != want_windows:
            raise AssertionError(
                f"pipe_test_tpu mismatch: sum {state['total']} != "
                f"{want_total} or windows {state['rcv']} != {want_windows}")
        r = {"tps": round(n_tuples / dt, 1), **_lat_stats(state), **diag}
        if max_delay_ms is not None:
            r["within_budget"] = bool(
                r.get("p95_window_latency_ms", 0.0) <= max_delay_ms)
        all_runs.append(r)
        if best is None or r["tps"] > best["tps"]:
            best = r
    if max_delay_ms is not None:
        # the number of record under a latency budget is the fastest run
        # whose p95 met it — a throughput-best that blew the budget is
        # not an achievement in this mode
        ok = [r for r in all_runs if r.get("within_budget")]
        best = (max(ok, key=lambda r: r["tps"]) if ok else best)
    return {
        "metric": "pipe_test_tpu Source>Map>Filter>WinFarmTPU(x"
                  f"{pardegree})>Sink input tuples/sec (win={WIN} "
                  f"slide={SLIDE} keys={N_KEYS}, {want_windows} windows"
                  + (f", p95 budget {max_delay_ms} ms"
                     if max_delay_ms is not None else "") + ")",
        "value": best["tps"],
        "unit": "tuples/sec",
        **{k: v for k, v in best.items() if k != "tps"},
        "runs": all_runs,
    }


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="pipe_test_tpu benchmark")
    ap.add_argument("-n", "--tuples", type=int, default=8_000_000)
    ap.add_argument("-p", "--pardegree", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=1 << 20)
    # same-session A/B: 2^19 -> 26 dispatches / ~1.6M tps vs 2^18 ->
    # 40-43 dispatches / ~1.16M in identical weather (each dispatch costs
    # an amortized wire RTT; two farm workers halve the per-core cadence)
    ap.add_argument("--flush-rows", type=int, default=1 << 19)
    ap.add_argument("--depth", type=int, default=48)
    ap.add_argument("--capacity", type=int, default=4)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--max-delay-ms", type=float, default=None,
                    help="latency-budget mode: bound window "
                         "close-to-delivery delay (force-flush timer) and "
                         "report throughput within the p95 budget")
    ap.add_argument("--rate", type=float, default=None,
                    help="paced source, tuples/sec (latency-budget mode "
                         "needs a sub-capacity pace; default full speed)")
    ap.add_argument("--trace", type=float, default=None,
                    help="span-trace a sampled fraction of batches "
                         "(0..1]; spans land in <trace-dir>/trace.jsonl "
                         "for scripts/wf_trace.py / Perfetto")
    ap.add_argument("--trace-dir", default=None,
                    help="span/telemetry output directory (defaults to "
                         "WF_LOG_DIR)")
    a = ap.parse_args(argv)
    out = run(a.tuples, a.pardegree, a.chunk, a.flush_rows, a.depth,
              a.capacity, a.runs, a.max_delay_ms, a.rate,
              trace=a.trace, trace_dir=a.trace_dir)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
