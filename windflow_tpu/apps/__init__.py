"""Benchmark applications (the reference's src/ application suites)."""
