"""Yahoo! Streaming Benchmark — the TPU-framework port of the reference's
``src/yahoo_test_cpu`` suite (test_ysb_kf.cpp / test_ysb_wmr.cpp,
ysb_nodes.hpp, campaign_generator.hpp, yahoo_app.hpp; StreamBench variant).

Pipeline (test_ysb_kf.cpp:90-110):
    Source -> chain(Filter event_type==0) -> chain(Join ad->campaign)
           -> Key_Farm(TB tumbling 10s, per-campaign COUNT + MAX(ts))
           -> chain(Sink latency/throughput accounting)

Differences, per the framework's batch idiom:

* the Source generates whole event *batches* (SoA) with the reference's
  exact per-event recurrences (ysb_nodes.hpp:104-115: ``ad_id =
  (v % 100000) % (N_CAMPAIGNS * adsPerCampaign)``, ``event_type =
  (v % 100000) % 3``), vectorised;
* the Join's hashmap probe (ysb_nodes.hpp:188-210) becomes an O(1) numpy
  table gather ``cmp = ad_to_cmp[ad_id]`` — every ad is in the table, so
  the FlatMap's "drop on miss" arm never fires (same as the reference's
  generated workload);
* the aggregate (yahoo_app.hpp:150-156: ``count++``, ``lastUpdate =
  max(ts)``) exists in three flavours: the incremental fold
  ``YSBAggregateINC`` (the KF stage, matching the reference's INC flavour),
  the NIC ``YSBAggregate`` (the WMR MAP stage), and the device
  ``device_aggregate`` (the kf-tpu stage — count/max are monoids).
"""

from __future__ import annotations

import time

import numpy as np

from ..api import MultiPipe
from ..core.tuples import Schema, batch_from_columns
from ..core.windows import WinType
from ..ops.functions import WindowFunction, WindowUpdate
from ..patterns.basic import Filter, Map, Sink, Source
from ..patterns.key_farm import KeyFarm
from ..patterns.win_mapreduce import WinMapReduce

N_CAMPAIGNS = 100          # -DN_CAMPAIGNS=100 (yahoo Makefile:26)
ADS_PER_CAMPAIGN = 10      # CampaignGenerator default

EVENT_SCHEMA = Schema(ad_id=np.int64, event_type=np.int8,
                      revenue=np.int64)
#: key=cmp_id, ts carries the event time; revenue rides to the aggregate
JOINED_SCHEMA = Schema(revenue=np.int64)


class CampaignGenerator:
    """Synthetic campaign table (campaign_generator.hpp): sequential ad ids
    0..N*ads-1, campaign k owning ads [k*ads, (k+1)*ads)."""

    def __init__(self, n_campaigns: int = N_CAMPAIGNS,
                 ads_per_campaign: int = ADS_PER_CAMPAIGN):
        self.n_campaigns = n_campaigns
        self.ads_per_campaign = ads_per_campaign
        self.n_ads = n_campaigns * ads_per_campaign
        #: ad_id -> campaign id (the relational table + hashmap in one)
        self.ad_to_cmp = np.arange(self.n_ads) // ads_per_campaign


class YSBAggregate(WindowFunction):
    """Per-campaign tumbling-window COUNT(*) + MAX(ts) + SUM(revenue)
    (aggregateFunctionINC, yahoo_app.hpp:150-168; the revenue sum is the
    r3 extension making the aggregate device-worthy — counts and max-ts
    are answerable from host bookkeeping alone, a per-event revenue fold
    is not)."""

    result_fields = {"count": np.int64, "lastUpdate": np.int64,
                     "revenue": np.int64}
    required_fields = ("ts", "revenue")  # staged to apply_batch / device

    def apply(self, key, gwid, rows):
        return (len(rows),
                int(rows["ts"].max()) if len(rows) else 0,
                int(rows["revenue"].sum()) if len(rows) else 0)

    def apply_batch(self, keys, gwids, cols, lens):
        # ts is a header column; reconstructing MAX(ts) from the window
        # extents is not possible in general, so this path receives ts via
        # cols
        ts = cols["ts"]
        pad = ts.shape[1]
        mask = np.arange(pad)[None, :] < lens[:, None]
        return {"count": lens.astype(np.int64),
                "lastUpdate": np.where(mask, ts, 0).max(axis=1),
                "revenue": np.where(mask, cols["revenue"], 0).sum(axis=1)}


class YSBAggregateINC(WindowUpdate):
    """The same aggregate as an *incremental* per-chunk fold — the
    reference's actual flavour (aggregateFunctionINC, yahoo_app.hpp:150-156):
    O(1) state per open window, no archive.  This is what the kf variant
    runs; the NIC twin above serves the WMR MAP stage and the device path."""

    result_fields = {"count": np.int64, "lastUpdate": np.int64,
                     "revenue": np.int64}

    def update(self, key, gwid, row, acc):
        acc["count"] += 1
        acc["lastUpdate"] = max(acc["lastUpdate"], row["ts"])
        acc["revenue"] += row["revenue"]

    def update_many(self, key, gwid, rows, acc):
        if len(rows):
            acc["count"] += len(rows)
            acc["lastUpdate"] = max(int(acc["lastUpdate"]),
                                    int(rows["ts"].max()))
            acc["revenue"] += int(rows["revenue"].sum())


class YSBReduce(WindowFunction):
    """Combine per-partition partials (reduceFunctionINC,
    yahoo_app.hpp:159-165)."""

    result_fields = {"count": np.int64, "lastUpdate": np.int64,
                     "revenue": np.int64}

    def apply(self, key, gwid, rows):
        return (int(rows["count"].sum()) if len(rows) else 0,
                int(rows["lastUpdate"].max()) if len(rows) else 0,
                int(rows["revenue"].sum()) if len(rows) else 0)


def device_aggregate(rich: bool = False):
    """The YSB aggregate as a multi-stat resident reduction: COUNT(*) +
    MAX(ts) + SUM(revenue) (yahoo_app.hpp:150-168).  SUM(revenue) is NOT
    host-free (r2 VERDICT item 5: counts come from window lengths and
    max-ts from the position-ordered archive, but a per-event revenue fold
    is real device work), so this routes to the multi-field resident
    rings: the ts and revenue columns each cross the wire ONCE and every
    stat evaluates in one fused dispatch per flush (ops/resident.py:
    MultiFieldResidentExecutor).  Event timestamps are relative
    microseconds (event_batches), so the declared value_range proves the
    int32 accumulate exact for runs under ~35 minutes.  Revenue keeps the
    host variants' int64 result dtype (one shared result schema across
    kf/kf-tpu/wmr/wmr-tpu) over the default int32 device accumulate; a TB
    window's row count is unbounded, so the accumulate-wrap warning stays
    armed for this stat by design (ADVICE r3) — the declared per-event
    range documents the input but cannot prove a TB sum fits."""
    from ..ops.functions import MultiReducer, Reducer

    stats = [
        Reducer("count", out_field="count"),
        Reducer("max", "ts", "lastUpdate",
                value_range=(0, 2_100_000_000)),
        Reducer("sum", "revenue", "revenue", value_range=(0, 98))]
    if rich:
        # --rich-stats: MIN(ts) = the window's earliest event.  Since the
        # r5 pos-extrema split, MIN over the position field is as free as
        # MAX — the position-ordered archive's first window row holds it
        # — so firstUpdate costs nothing and the device half stays the
        # single revenue ring.  (It briefly shipped ts as a second device
        # field, which is how the multi-field path got its on-chip
        # measurement — BASELINE.md round 5; that path remains exercised
        # by tests/test_native.py's multifield suite.)
        stats.append(Reducer("min", "ts", "firstUpdate",
                             value_range=(0, 2_100_000_000)))
    return MultiReducer(*stats)


def event_batches(duration_sec: float, chunk: int, campaigns,
                  time_fn=time.monotonic):
    """Generator of event batches at full speed for `duration_sec`
    (ysb_nodes.hpp:103-125): ts is microseconds since start."""
    n_ads = campaigns.n_ads
    v0 = 0
    t0 = time_fn()
    while True:
        now = time_fn() - t0
        if now >= duration_sec:
            return
        v = np.arange(v0, v0 + chunk, dtype=np.int64)
        vm = v % 100000
        ts = np.full(chunk, int(now * 1e6), dtype=np.int64)
        yield batch_from_columns(
            EVENT_SCHEMA, key=np.zeros(chunk, dtype=np.int64),
            id=v, ts=ts, ad_id=vm % n_ads,
            event_type=(vm % 3).astype(np.int8),
            revenue=(vm % 97) + 1)
        v0 += chunk


class YSBSink:
    """Latency / count accounting (YSBSink, ysb_nodes.hpp:215-246)."""

    def __init__(self, start_wall_us: int, now_us=None, on_result=None):
        self.start_wall_us = start_wall_us
        self.now_us = now_us or (lambda: int(time.time() * 1e6))
        self.on_result = on_result
        self.received = 0
        self._lat_us = []   # per-result latencies -> avg/p95/p99 (the
        #                     reference's headline metric pair is
        #                     throughput AND per-result latency,
        #                     ysb_nodes.hpp:231-246); avg derives from
        #                     the same arrays as the percentiles so the
        #                     two can never disagree

    def __call__(self, batch):
        if batch is None:
            return
        live = batch[batch["count"] > 0]
        if not len(live):
            return
        now = self.now_us()
        lat = now - (live["lastUpdate"] + self.start_wall_us)
        self.received += len(live)
        self._lat_us.append(np.asarray(lat, dtype=np.float64))
        if self.on_result is not None:
            self.on_result(live)

    def latency_summary_us(self):
        """One summarize() pass over the full latency history: avg and
        percentiles derive from the same arrays, computed once."""
        from ..utils.latency import summarize
        s = summarize(self._lat_us, ndigits=1)
        if not s:
            return {"avg_latency_us": 0.0}
        return {"avg_latency_us": s["avg"], "p50_latency_us": s["p50"],
                "p95_latency_us": s["p95"], "p99_latency_us": s["p99"],
                "n_latency_samples": s["n"]}

    @property
    def avg_latency_us(self):
        return self.latency_summary_us()["avg_latency_us"]


def build_pipeline(variant: str, duration_sec: float, pardegree1: int,
                   pardegree2: int, win_sec: float = 10.0,
                   chunk: int = 262144, batches=None, on_result=None,
                   opt_level: int = 0, force_device: bool = False,
                   max_delay_ms=None, rich_stats: bool = False):
    """Assemble the YSB MultiPipe.  `variant`: 'kf' (test_ysb_kf) or 'wmr'
    (test_ysb_wmr).  Pass `batches` to override the timed generator with a
    deterministic list (tests)."""
    campaigns = CampaignGenerator()
    ad_to_cmp = campaigns.ad_to_cmp
    win_us = int(win_sec * 1e6)

    sent = [0]

    def gen(shipper):
        src = batches if batches is not None else event_batches(
            duration_sec, chunk, campaigns)
        for b in src:
            sent[0] += len(b)
            shipper.push_batch(b)

    def join(b, out):
        # re-key each surviving event by its campaign id (id/ts flow
        # through via the non-in-place Map header copy; payload columns
        # must be forwarded explicitly)
        out["key"] = ad_to_cmp[b["ad_id"]]
        out["revenue"] = b["revenue"]

    start_wall_us = int(time.time() * 1e6)
    sink = YSBSink(start_wall_us, on_result=on_result)

    if variant == "kf":
        agg = KeyFarm(YSBAggregateINC(), win_us, win_us, WinType.TB,
                      pardegree=pardegree2, name="ysb_kf")
    elif variant == "kf-tpu":
        # the tracked yahoo_test_tpu config: COUNT + MAX(ts) + SUM(revenue)
        # over multi-field device-resident rings.  The revenue sum gives
        # the window stage real device compute (r2 VERDICT item 5 — the r2
        # aggregate was host-free and make_core_for rightly routed it to
        # the host, leaving the tracked config deviceless); --force-device
        # is retained as an explicit pin (the default already selects the
        # resident path now that the aggregate is not host-free)
        from ..patterns.win_seq_tpu import KeyFarmTPU
        agg = KeyFarmTPU(device_aggregate(rich=rich_stats), win_us, win_us,
                         WinType.TB,
                         pardegree=pardegree2, batch_len=256,
                         name="ysb_kf_tpu", max_delay_ms=max_delay_ms,
                         use_resident=True if force_device else None)
    elif variant == "wmr":
        agg = WinMapReduce(YSBAggregate(), YSBReduce(), win_us, win_us,
                           WinType.TB, map_degree=max(pardegree2, 2),
                           name="ysb_wmr", opt_level=opt_level)
    elif variant == "wmr-tpu":
        # Win_MapReduce with the MAP stage device-batched (the reference's
        # Win_MapReduce_GPU per-stage placement, win_mapreduce_gpu.hpp):
        # each MAP partition computes COUNT + MAX(ts) + SUM(revenue) on the
        # resident ring (only revenue ships — pos-max split), REDUCE
        # combines the partials host-side as a multi-field MultiReducer
        from ..ops.functions import MultiReducer, Reducer
        from ..patterns.win_seq_tpu import WinMapReduceTPU
        # NOTE: no value_range on the reduce-stage max — its inputs are
        # MAP partials whose empty-partition identity is iinfo(int64).min,
        # far outside the raw-timestamp range (a declared range would
        # falsely suppress the int32-wrap warning if this stage were ever
        # flipped to reduce_on_device=True)
        reduce_agg = MultiReducer(
            Reducer("sum", "count", "count"),
            Reducer("max", "lastUpdate", "lastUpdate"),
            Reducer("sum", "revenue", "revenue"))
        agg = WinMapReduceTPU(device_aggregate(), reduce_agg, win_us,
                              win_us, WinType.TB,
                              map_degree=max(pardegree2, 2),
                              name="ysb_wmr_tpu", map_on_device=True,
                              reduce_on_device=False, opt_level=opt_level,
                              max_delay_ms=max_delay_ms)
    else:
        raise ValueError(f"unknown variant {variant!r}")
    if max_delay_ms is not None and not variant.endswith("-tpu"):
        # the host variants' windows close at watermark cadence with no
        # device queueing — there is no flush timer to budget, and
        # accepting the flag silently would let an operator read their
        # latency numbers as budget-bounded when nothing bounded them
        raise ValueError(
            f"--max-delay-ms applies to device variants only (got "
            f"{variant!r}: host windows have no device queue to bound)")

    pipe = (MultiPipe(f"ysb_{variant}")
            .add_source(Source(gen, EVENT_SCHEMA, parallelism=pardegree1,
                               name="ysb_source"))
            .chain(Filter(lambda b: b["event_type"] == 0, vectorized=True,
                          parallelism=pardegree1, name="ysb_filter"))
            .chain(Map(join, vectorized=True, output_schema=JOINED_SCHEMA,
                       parallelism=pardegree1, name="ysb_join"))
            .add(agg)
            .chain_sink(Sink(sink, vectorized=True, name="ysb_sink")))
    return pipe, sink, sent


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the benchmark topology (host KeyFarm
    variant — the device variants share the same shell wiring)."""
    pipe, _sink, _sent = build_pipeline("kf", 0.0, 1, 2, batches=[])
    return [pipe]


def warmup(variant, pardegree1, pardegree2, win_sec, chunk,
           force_device=False, rich_stats=False):
    """Compile-warm the device path before the timed run: pushes a few
    synthetic chunks through an identical pipeline so the XLA executables
    for the step's shape buckets are built and cached process-wide
    (bench.py warms the same way; first compiles cost tens of seconds
    over the tunnel and belong to no benchmark)."""
    campaigns = CampaignGenerator()
    n = [0]

    def fake_clock():
        # advances ~0.4 s per chunk so windows open/fire like a real run
        n[0] += 1
        return n[0] * 0.4

    batches = list(event_batches(4.0, chunk, campaigns, time_fn=fake_clock))
    pipe, _, _ = build_pipeline(variant, 0, pardegree1, pardegree2,
                                win_sec, chunk, batches=batches,
                                force_device=force_device,
                                rich_stats=rich_stats)
    pipe.run_and_wait_end()
    if variant.endswith("-tpu"):
        # the coalescing shape ladder: merged TB dispatch buckets only
        # occur under wire stall, when a cold compile hurts most
        import jax
        from ..ops import resident
        devs = jax.devices()
        resident.prewarm_regular_ladder(devices=list(dict.fromkeys(
            devs[i % len(devs)] for i in range(pardegree2))))


def run(variant="kf", duration_sec=10.0, pardegree1=1, pardegree2=4,
        win_sec=10.0, chunk=262144, warm=None, opt_level=0,
        force_device=False, max_delay_ms=None, rich_stats=False):
    """Run the benchmark; returns the reference's four stdout metrics
    (test_ysb_kf.cpp:113-116)."""
    if warm is None:
        # device variants warm by default: kf-tpu's aggregate now carries
        # real device compute (SUM(revenue)) whether or not it is pinned
        warm = variant.endswith("-tpu")
    if warm:
        warmup(variant, pardegree1, pardegree2, win_sec, chunk,
               force_device=force_device, rich_stats=rich_stats)
    pipe, sink, sent = build_pipeline(variant, duration_sec, pardegree1,
                                      pardegree2, win_sec, chunk,
                                      opt_level=opt_level,
                                      force_device=force_device,
                                      max_delay_ms=max_delay_ms,
                                      rich_stats=rich_stats)
    from ..ops import resident
    resident.stats_snapshot(reset=True)
    t0 = time.perf_counter()
    pipe.run_and_wait_end()
    elapsed = time.perf_counter() - t0
    return {
        "generated": sent[0],
        "results": sink.received,
        **sink.latency_summary_us(),
        "elapsed_sec": round(elapsed, 3),
        "events_per_sec": round(sent[0] / elapsed, 1),
        # sustained source-side rate DURING the generation window: the
        # end-to-end events/sec above divides by elapsed incl. the EOS
        # drain (device variants pay their in-flight launches' wire
        # service there), while this measures what the pipeline ingests
        # under backpressure while streaming — the steady-state capacity
        # an infinite stream would see.  Both are reported; neither is
        # the other's substitute.
        "gen_events_per_sec": round(sent[0] / max(duration_sec, 1e-9), 1),
        # wire diagnostics (bench.py discipline): zeros on host-only
        # variants; on device variants they separate wire weather from
        # framework regressions
        **resident.stats_snapshot(reset=True),
    }


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description="Yahoo Streaming Benchmark")
    ap.add_argument("-l", "--length", type=float, default=10.0,
                    help="generation time seconds (reference -l)")
    ap.add_argument("-p", "--pardegree1", type=int, default=1)
    ap.add_argument("-w", "--pardegree2", type=int, default=4)
    ap.add_argument("--variant",
                    choices=["kf", "kf-tpu", "wmr", "wmr-tpu"],
                    default="kf")
    ap.add_argument("--win-sec", type=float, default=10.0)
    ap.add_argument("--chunk", type=int, default=262144)
    ap.add_argument("--max-delay-ms", type=float, default=None,
                    help="latency-budget mode: bound the device cores' "
                         "queueing delay via their force-flush timers")
    ap.add_argument("--no-warmup", action="store_true",
                    help="skip the compile warmup (device variants warm "
                         "by default; first XLA compiles take tens of "
                         "seconds over the tunnel)")
    ap.add_argument("--opt", type=int, default=0, choices=[0, 1, 2],
                    help="graph optimisation level for the wmr variant "
                         "(optimize_WinMapReduce; LEVEL2 removes the "
                         "MAP-collector/REDUCE-emitter boundary)")
    ap.add_argument("--rich-stats", action="store_true",
                    help="kf-tpu: add MIN(ts) (firstUpdate) to the "
                         "aggregate — a second DEVICE field (ts ring "
                         "alongside revenue), driving the multi-field "
                         "resident executor on the real chip")
    ap.add_argument("--force-device", action="store_true",
                    help="kf-tpu: pin the window stage to the device-"
                         "resident ring even though YSB's aggregate is "
                         "host-free (wire benchmarking)")
    a = ap.parse_args(argv)
    if a.rich_stats and a.variant != "kf-tpu":
        raise SystemExit("--rich-stats applies to the kf-tpu variant only")
    m = run(a.variant, a.length, a.pardegree1, a.pardegree2, a.win_sec,
            a.chunk, warm=False if a.no_warmup else None, opt_level=a.opt,
            force_device=a.force_device, max_delay_ms=a.max_delay_ms,
            rich_stats=a.rich_stats)
    print(f"[Main] Total generated messages are {m['generated']}")
    print(f"[Main] Total received results are {m['results']}")
    print(f"[Main] Latency (usec) {m['avg_latency_us']}")
    if "p95_latency_us" in m:
        print(f"[Main] Latency p95/p99 (usec) {m['p95_latency_us']} / "
              f"{m['p99_latency_us']}")
    print(f"[Main] Total elapsed time (seconds) {m['elapsed_sec']}")
    print(f"[Main] Events/sec {m['events_per_sec']} "
          f"(ingest {m['gen_events_per_sec']})")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
