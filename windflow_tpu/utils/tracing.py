"""Per-node tracing — the runtime-enabled equivalent of the reference's
compile-time ``-DLOG_DIR`` instrumentation (map.hpp:85-91,116-176,
win_seq.hpp:128-138,479-501, win_seq_gpu.hpp:175-185,598-611): every node
keeps received-batch/tuple counters, a running and EWMA service time, the
inter-departure time, and (window nodes) the triggering vs non-triggering
split; at ``svc_end`` the counters are written to
``<dir>/<node_name>.log`` as one JSON object.

Enabled at runtime (no recompilation): pass ``trace_dir=`` to
:class:`~windflow_tpu.runtime.engine.Dataflow` / ``MultiPipe``, or set the
``WF_LOG_DIR`` environment variable (the spiritual ``-DLOG_DIR``).

These counters also feed the *live* observability layer: when the
dataflow runs with ``metrics=`` / ``sample_period=`` the engine creates a
``NodeStats`` per node even without a trace dir, and the background
sampler (obs/sampler.py) reads ``snapshot()``-equivalent fields racily
while the graph runs — end-of-run files stay gated on ``trace_dir``
alone, so the seed tracing behavior is unchanged.
"""

from __future__ import annotations

import json
import os
import time

#: EWMA smoothing for service/inter-departure times (the reference keeps a
#: plain running average; we record both)
ALPHA = 0.1


def node_stats_name(dataflow_name: str, idx: int, node_name: str) -> str:
    """Canonical per-node id: the NodeStats name, the ``<trace_dir>/*.log``
    filename stem, and the ``id`` field of every metrics.jsonl node entry
    — one definition so the three can never drift apart."""
    return f"{dataflow_name}_{idx:02d}_{node_name}"


class NodeStats:
    """Counter block attached to a node when tracing is enabled."""

    __slots__ = ("name", "rcv_batches", "rcv_tuples", "svc_time_ns_total",
                 "avg_ts_us", "ewma_ts_us", "departures", "last_dep_ns",
                 "avg_td_us", "counters", "started_ns")

    def __init__(self, name: str):
        self.name = name
        self.rcv_batches = 0
        self.rcv_tuples = 0
        self.svc_time_ns_total = 0
        self.avg_ts_us = 0.0      # running mean service time per batch
        self.ewma_ts_us = 0.0     # EWMA service time per batch
        self.departures = 0
        self.last_dep_ns = None
        self.avg_td_us = 0.0      # running mean inter-departure time
        self.counters = {}        # node-specific extras (windows_fired, ...)
        self.started_ns = time.perf_counter_ns()

    # -- recording (hot path: branch-free beyond attribute math) -----------

    def record_svc(self, n_rows: int, dt_ns: int):
        self.rcv_batches += 1
        self.rcv_tuples += n_rows
        self.svc_time_ns_total += dt_ns
        us = dt_ns / 1e3
        n = self.rcv_batches
        self.avg_ts_us += (us - self.avg_ts_us) / n
        self.ewma_ts_us = (us if n == 1
                           else self.ewma_ts_us + ALPHA * (us - self.ewma_ts_us))

    def record_departure(self):
        now = time.perf_counter_ns()
        if self.last_dep_ns is not None:
            td_us = (now - self.last_dep_ns) / 1e3
            self.departures += 1
            self.avg_td_us += (td_us - self.avg_td_us) / self.departures
        self.last_dep_ns = now

    def bump(self, counter: str, n: int = 1):
        self.counters[counter] = self.counters.get(counter, 0) + n

    def record_shed(self, n: int = 1):
        """Items dropped from this node's inbox by a shedding
        OverloadPolicy (runtime/overload.py) — folded in once at node
        end by the engine, so the hot path stays counter-free."""
        self.bump("shed", n)

    def record_quarantined(self, n: int = 1):
        """Poison batches parked in the dead-letter queue instead of
        tearing the graph down (error-budget quarantine)."""
        self.bump("quarantined", n)

    # -- reporting ----------------------------------------------------------

    def snapshot(self) -> dict:
        alive_s = (time.perf_counter_ns() - self.started_ns) / 1e9
        return {
            "node": self.name,
            "rcv_batches": self.rcv_batches,
            "rcv_tuples": self.rcv_tuples,
            "svc_time_ms_total": round(self.svc_time_ns_total / 1e6, 3),
            "avg_service_us_per_batch": round(self.avg_ts_us, 3),
            "ewma_service_us_per_batch": round(self.ewma_ts_us, 3),
            "avg_interdeparture_us": round(self.avg_td_us, 3),
            "alive_sec": round(alive_s, 3),
            **self.counters,
        }

    def write(self, trace_dir: str):
        os.makedirs(trace_dir, exist_ok=True)
        safe = self.name.replace("/", "_")
        path = os.path.join(trace_dir, f"{safe}.log")
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
            f.write("\n")


def default_trace_dir() -> str | None:
    """The WF_LOG_DIR environment hook (the -DLOG_DIR analog)."""
    return os.environ.get("WF_LOG_DIR") or None


def default_sample_period() -> float | None:
    """The WF_SAMPLE_PERIOD environment hook: seconds between live
    metrics samples (obs/sampler.py).  Lets any existing program — the
    benchmarks, scripts/soak_overload.py — opt into in-flight telemetry
    with no code change, exactly like WF_LOG_DIR enables end-of-run
    tracing.  Unset/empty = no sampler thread (docs/OBSERVABILITY.md)."""
    raw = os.environ.get("WF_SAMPLE_PERIOD")
    if not raw:
        return None
    period = float(raw)
    if period <= 0:
        raise ValueError(
            f"WF_SAMPLE_PERIOD must be positive seconds, got {raw!r}")
    return period
