"""Shared per-result latency accounting for the benchmark sinks.

Every benchmark (pipe, ysb, spatial) reports the reference's headline
metric pair — throughput AND per-result latency (ysb_nodes.hpp:231-246)
— so the accumulate-then-percentile step lives here once: collect
per-batch latency arrays, summarize as avg/p50/p95/p99 + n.  Callers pick their
own field names/units at the edge (µs for ysb's reference-parity stdout,
ms elsewhere)."""

from __future__ import annotations

import numpy as np


def summarize(lat_arrays, scale: float = 1.0, ndigits: int = 2) -> dict:
    """avg/p50/p95/p99 plus ``n`` (result count) over the concatenation
    of ``lat_arrays`` (each a 1-d array of per-result latencies),
    multiplied by ``scale`` (e.g. 1e-3 for µs -> ms).  The median makes
    tail-vs-typical splits readable (a p95 triple the p50 is a tail
    problem; both high is a throughput problem) and ``n`` sizes the
    sample the percentiles stand on.  Empty input -> empty dict, so
    callers can splat the result without guarding."""
    arrays = [np.asarray(a, dtype=np.float64) for a in lat_arrays
              if a is not None and len(a)]
    if not arrays:
        return {}
    lat = np.concatenate(arrays) * scale
    p50, p95, p99 = np.percentile(lat, (50, 95, 99))
    return {"avg": round(float(lat.mean()), ndigits),
            "p50": round(float(p50), ndigits),
            "p95": round(float(p95), ndigits),
            "p99": round(float(p99), ndigits),
            "n": int(lat.size)}
