"""Env-gated phase timers for the device ship path (``WF_PROFILE=1``).

The wire — not the chip — is the budget on the tunneled TPU (BASELINE.md),
so the interesting split is host bookkeeping vs ``device_put`` staging vs
dispatch vs harvest blocking.  Timers are process-wide and near-free when
disabled; ``report()`` returns {phase: (seconds, calls)} and ``counters()``
plain accumulators (bytes shipped, launches, rows).

Enablement is *not* frozen at import: ``WF_PROFILE`` is re-read lazily at
every ``span`` entry (spans bracket ms-scale ship phases, so the environ
lookup is noise there), and the parsed value is cached so ``add()`` —
the per-block hot probe — pays only a bare global read.  A test that
monkeypatches the environment, or a live session toggling telemetry
alongside ``wf_top``, thus takes effect without re-importing the module
(for ``add()``: at the next span entry).  ``enable()`` / ``disable()``
pin the state explicitly (and stop the env reads entirely); ``auto()``
returns to env-driven behavior.  The module-level ``ENABLED`` mirror is
kept for introspection and refreshed by every span entry.
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict

_FORCED: bool | None = None   # enable()/disable() override; None = env


_env_raw = object()       # last seen WF_PROFILE string (sentinel: never)
_env_parsed = False


def _env_enabled() -> bool:
    # probe cost must stay near the old module-global read: one environ
    # lookup plus a short-string compare (os.environ.get decodes a fresh
    # str per call, so identity can't be used); the int() parse runs
    # only when the variable actually changed
    global _env_raw, _env_parsed
    raw = os.environ.get("WF_PROFILE")
    if raw != _env_raw:
        _env_parsed = bool(int(raw or "0"))
        _env_raw = raw
    return _env_parsed


#: introspection mirror of the last observed state (back-compat with the
#: historical import-time constant); the source of truth is _enabled()
ENABLED = _env_enabled()


def _enabled() -> bool:
    global ENABLED
    if _FORCED is None:
        ENABLED = _env_enabled()
    return ENABLED


def enable():
    """Pin profiling ON regardless of WF_PROFILE (until auto())."""
    global _FORCED, ENABLED
    _FORCED = ENABLED = True


def disable():
    """Pin profiling OFF regardless of WF_PROFILE (until auto())."""
    global _FORCED, ENABLED
    _FORCED = ENABLED = False


def auto():
    """Drop any enable()/disable() pin: follow WF_PROFILE again."""
    global _FORCED, ENABLED
    _FORCED = None
    ENABLED = _env_enabled()

_acc: dict[str, float] = defaultdict(float)
_cnt: dict[str, int] = defaultdict(int)
_val: dict[str, float] = defaultdict(float)
#: ship threads (one per shard) enter the same spans concurrently; the
#: read-add-store on the accumulators must not lose updates
_mu = threading.Lock()

#: per-exit observer hook (obs/trace.py): called as ``fn(name, dt_ns)``
#: after every completed span, INDEPENDENTLY of the WF_PROFILE
#: accumulators — the bridge that turns the ship-path phase spans
#: (device_put / dispatch / harvest_wait, ops/resident.py) into
#: child spans of a traced batch.  One recorder per process; None
#: (default) keeps the probe a bare global read.
_RECORDER = None


def set_recorder(fn):
    """Install the span-exit observer (``fn(name, dt_ns)``).  The
    recorder must be cheap and must not raise — it runs inside the
    device ship hot path.  Installing one makes every span stamp its
    clock even with profiling disabled; pass ``None`` to uninstall."""
    global _RECORDER
    _RECORDER = fn


class span:
    """``with span("device_put"): ...`` — accumulates wall time per phase."""

    __slots__ = ("name", "t0", "_acc_on")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        # the span brackets ONE decision per sink: __exit__ accumulates
        # iff _acc_on, and calls the recorder iff t0 was stamped while
        # one was installed — a mid-span toggle cannot read a stale t0
        self._acc_on = _enabled()
        self.t0 = (time.perf_counter_ns()
                   if (self._acc_on or _RECORDER is not None) else None)
        return self

    def __exit__(self, *exc):
        if self.t0 is not None:
            dt_ns = time.perf_counter_ns() - self.t0
            if self._acc_on:
                with _mu:
                    _acc[self.name] += dt_ns / 1e9
                    _cnt[self.name] += 1
            rec = _RECORDER
            if rec is not None:
                rec(self.name, dt_ns)
        return False


def add(name: str, value: float = 1.0):
    """Accumulate a plain counter (bytes, rows, launches).  Reads the
    cached ENABLED mirror — a bare global, the cheapest possible disabled
    path — so an env toggle reaches add() at the next span entry (spans
    and adds interleave per shipped block, so staleness is one block)."""
    if ENABLED:
        with _mu:
            _val[name] += value


def report() -> dict:
    # snapshot under the lock: ship threads mutate the defaultdicts
    # concurrently, and iterating a dict mid-resize raises "dictionary
    # changed size during iteration"
    with _mu:
        acc = dict(_acc)
        cnt = dict(_cnt)
    return {k: (round(acc[k], 4), cnt[k]) for k in sorted(acc)}


def counters() -> dict:
    with _mu:
        val = dict(_val)
    return {k: val[k] for k in sorted(val)}


def reset():
    with _mu:
        _acc.clear()
        _cnt.clear()
        _val.clear()


def dump() -> str:
    lines = ["phase                      seconds    calls"]
    for k, (s, c) in report().items():
        lines.append(f"{k:<25} {s:>9.3f} {c:>8d}")
    for k, v in counters().items():
        lines.append(f"{k:<25} {v:>14.0f}")
    return "\n".join(lines)
