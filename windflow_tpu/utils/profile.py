"""Env-gated phase timers for the device ship path (``WF_PROFILE=1``).

The wire — not the chip — is the budget on the tunneled TPU (BASELINE.md),
so the interesting split is host bookkeeping vs ``device_put`` staging vs
dispatch vs harvest blocking.  Timers are process-wide and near-free when
disabled; ``report()`` returns {phase: (seconds, calls)} and ``counters()``
plain accumulators (bytes shipped, launches, rows).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict

ENABLED = bool(int(os.environ.get("WF_PROFILE", "0") or "0"))

_acc: dict[str, float] = defaultdict(float)
_cnt: dict[str, int] = defaultdict(int)
_val: dict[str, float] = defaultdict(float)
#: ship threads (one per shard) enter the same spans concurrently; the
#: read-add-store on the accumulators must not lose updates
_mu = threading.Lock()


class span:
    """``with span("device_put"): ...`` — accumulates wall time per phase."""

    __slots__ = ("name", "t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        if ENABLED:
            self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if ENABLED:
            dt = time.perf_counter() - self.t0
            with _mu:
                _acc[self.name] += dt
                _cnt[self.name] += 1
        return False


def add(name: str, value: float = 1.0):
    """Accumulate a plain counter (bytes, rows, launches)."""
    if ENABLED:
        with _mu:
            _val[name] += value


def report() -> dict:
    return {k: (round(_acc[k], 4), _cnt[k]) for k in sorted(_acc)}


def counters() -> dict:
    return {k: _val[k] for k in sorted(_val)}


def reset():
    _acc.clear()
    _cnt.clear()
    _val.clear()


def dump() -> str:
    lines = ["phase                      seconds    calls"]
    for k, (s, c) in report().items():
        lines.append(f"{k:<25} {s:>9.3f} {c:>8d}")
    for k, v in counters().items():
        lines.append(f"{k:<25} {v:>14.0f}")
    return "\n".join(lines)
