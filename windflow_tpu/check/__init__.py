"""Pre-flight static analysis — the Python port's stand-in for the C++
reference's compile-time template checks (PAPER.md: WindFlow rejects
ill-formed graphs at template-instantiation time; a dynamic port must
recover that property with an explicit validation pass).

The subsystem is a catalog of ``WF###`` diagnostics (docs/CHECKS.md) plus
three passes over a *built but not yet running* graph:

* :mod:`.config` — knob-conflict checks on ``Dataflow``/``MultiPipe``
  configuration and on :class:`~windflow_tpu.parallel.channel.WireConfig`
  (WF2xx);
* :mod:`.graph` — a walk of the materialised node graph: recovery over
  non-snapshotable cores, keyed state behind non-keyed emitters, window
  geometry (WF1xx/WF2xx);
* :mod:`.closures` — the closure race analyzer: bytecode inspection of
  user functions shared by parallel replicas (WF3xx).

Entry points: :func:`validate` (returns a :class:`CheckReport`) and
:func:`enforce` (the ``check=`` knob's runtime hook — warn or raise).

Contract with the engine (ISSUE 11): ``check=`` unset means this package
is **never imported** — the engine's lazy import is the only coupling, so
the seed hot paths stay byte-identical.
"""

from __future__ import annotations

import warnings

from .diagnostics import (CATALOG, CheckError, CheckReport, CheckWarning,
                          Diagnostic)


def validate(target) -> CheckReport:
    """Run every applicable pass over ``target`` and return the report.

    ``target`` may be a :class:`~windflow_tpu.api.multipipe.MultiPipe`
    (built on demand — pre-build config conflicts that would make the
    build itself raise, e.g. WF208, are reported instead of raised), a
    built :class:`~windflow_tpu.runtime.engine.Dataflow`, a
    :class:`~windflow_tpu.parallel.channel.WireConfig`, or a
    :class:`~windflow_tpu.parallel.plane.PlanePolicy`.
    """
    from .config import check_pipe_config, check_plane, check_wire
    from .graph import check_dataflow

    report = CheckReport()
    kind = type(target).__name__
    if kind == "WireConfig":
        report.extend(check_wire(target))
        return report.finish()
    if kind == "PlanePolicy":
        # dispatched by type NAME, like WireConfig: the check package
        # must not import parallel.plane (the knob contract keeps that
        # module un-imported until a supervisor is actually built)
        report.extend(check_plane(target))
        return report.finish()
    if kind == "PlaneSpec":
        # declared multi-host topology (check/plane.py, WF22x)
        from .plane import check_plane_spec
        report.extend(check_plane_spec(target))
        return report.finish()
    if hasattr(target, "_build") and hasattr(target, "_stages"):
        # a MultiPipe: pre-build knob checks first — a fatal knob
        # conflict (WF208 at the Dataflow constructor, WF210/WF211 at
        # the control-plane wiring) means _build() itself would raise,
        # so the static report must not attempt it
        pre = check_pipe_config(target)
        report.extend(pre)
        if any(d.code in ("WF208", "WF210", "WF211") for d in pre):
            return report.finish()
        with warnings.catch_warnings():
            # the Dataflow constructor re-warns the WF207/WF209
            # conditions this report already carries as diagnostics —
            # a lint run must not double-fire them as live warnings
            warnings.simplefilter("ignore")
            df = target._build()
        report.extend(check_dataflow(df, skip_config=True))
        return report.finish()
    # a built Dataflow
    report.extend(check_dataflow(target))
    return report.finish()


def enforce(df):
    """The ``check=`` knob's hook, called by ``Dataflow.run()`` before
    any thread starts.  ``check='warn'`` reports every diagnostic as a
    :class:`CheckWarning`; ``check='error'`` additionally raises
    :class:`CheckError` when any error-severity diagnostic survives
    suppression.  Diagnostics are also mirrored into the dataflow's
    event log (kind ``check``) when observability is on."""
    from .graph import check_dataflow

    report = CheckReport()
    report.extend(check_dataflow(df))
    report.finish()
    for d in report.diagnostics:
        if df.events is not None:
            df.events.emit("check", dataflow=df.name, code=d.code,
                           severity=d.severity, node=d.node or "",
                           message=d.message)
        warnings.warn(str(d), CheckWarning, stacklevel=3)
    if df.check == "error" and report.has_errors:
        raise CheckError(report)
    return report


__all__ = ["CATALOG", "CheckError", "CheckReport", "CheckWarning",
           "Diagnostic", "validate", "enforce"]
