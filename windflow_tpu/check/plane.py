"""Plane-topology linter (WF22x): cross-process validation of a
declared multi-host row plane.

The per-process checks (WF205/206/214/216, check/config.py) each see ONE
side of a wire: a ``WireConfig`` carries both the sender knobs
(``heartbeat``, ``resume``) and the receiver knobs (``stall_timeout``,
``recovery``) because in a single process they ride the same bundle.
Across processes they do not — host A's sender faces host B's receiver,
and a topology where A heartbeats into a B that never arms
``stall_timeout`` is invisible to both hosts' local lint runs.  This
module lints the *declared deployment*: a :class:`PlaneSpec` naming
every process's address, wire, dtype and role, mirroring the kwargs each
process passes to :func:`~windflow_tpu.parallel.multihost.open_row_plane`.

A spec is plain declarative data — building one imports nothing from the
runtime (the ``check=``-unset contract: this package stays un-imported
unless lint runs), and ``scripts/wf_lint.py --plane my_spec.py`` drives
it from CI.  A spec module advertises its topology with a
``wf_plane_spec()`` callable returning one or more :class:`PlaneSpec`
objects, or with module-level instances.

The WF22x family (docs/CHECKS.md):

* **WF220** (error) — the topology itself is broken: a host ships to a
  pid with no spec/address, two hosts claim one ``(host, port)``, the
  address book and the host list disagree on the pid set.
* **WF221** (error) — dtype mismatch across an edge: the sender's row
  dtype is not what the receiver expects.
* **WF222** (error) — ``resume=`` on one end of an edge only: the
  resume handshake needs the sender journal AND the receiver epoch
  tracking; one-sided resume breaks reconnect.
* **WF223** (warning) — a PlaneSupervisor policy is declared but no
  host offers a ``ckpt_sink``/portable-spool replica target: a takeover
  has no portable checkpoint to restore from.
* **WF224** (error) — federation shipping misrouted: shippers with no
  aggregator, or two hosts claiming the aggregator role.

Plus the cross-host versions of the per-process pairings, reusing the
existing catalog ids: WF205 (sender heartbeat >= receiver stall
timeout), WF206 (heartbeat into a receiver with no stall timeout),
WF214 (sender journals but the receiver never acks sealed epochs),
WF216 (a supervised plane whose effective wire does not journal).
"""

from __future__ import annotations

import sys

from .diagnostics import Diagnostic


def _caller_anchor(depth: int = 2):
    """(filename, lineno) of the construction site, so WF22x
    diagnostics anchor at the spec line and ``# wf-lint: disable=``
    works on it."""
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    return (frame.f_code.co_filename, frame.f_lineno)


class HostSpec:
    """One process of the plane, mirroring its ``open_row_plane``
    call: ``pid`` and (via the owning :class:`PlaneSpec`) its address;
    ``wire`` the process's :class:`~windflow_tpu.parallel.channel.
    WireConfig` (None = the spec-level default); ``sends`` the row
    dtype this host ships and ``expects`` the dtype it decodes inbound
    (default: its own ``sends``); ``sends_to`` the pids it ships rows
    to (default: every other pid); ``resume``/``resume_epoch`` the
    journal/handshake opt-in; ``ckpt_sink`` truthy when the host
    replicates portable checkpoints (a PortableSpool target);
    ``plane`` the host's PlanePolicy (supervision/rolling restart),
    ``federate`` truthy when it ships telemetry snapshots and
    ``aggregator`` True when it runs the plane's TelemetryAggregator.
    """

    __slots__ = ("pid", "wire", "sends", "expects", "sends_to",
                 "resume", "resume_epoch", "ckpt_sink", "plane",
                 "federate", "aggregator", "anchor")

    def __init__(self, pid: int, wire=None, sends=None, expects=None,
                 sends_to=None, resume=None, resume_epoch=None,
                 ckpt_sink=None, plane=None, federate=None,
                 aggregator: bool = False):
        self.pid = int(pid)
        self.wire = wire
        self.sends = sends
        self.expects = expects if expects is not None else sends
        self.sends_to = (None if sends_to is None
                         else tuple(int(p) for p in sends_to))
        self.resume = resume
        self.resume_epoch = resume_epoch
        self.ckpt_sink = ckpt_sink
        self.plane = plane
        self.federate = federate
        self.aggregator = bool(aggregator)
        self.anchor = _caller_anchor()

    def __repr__(self):
        return f"<HostSpec pid={self.pid}>"


class PlaneSpec:
    """A declared multi-host deployment: the shared ``addresses`` book
    (pid -> ``(host, port)``, the same dict every process passes to
    ``open_row_plane``) plus one :class:`HostSpec` per process.
    ``wire`` is the plane-wide default WireConfig for hosts that do not
    set their own (``open_row_plane`` defaults to
    ``WireConfig.hardened()`` — mirror that in the spec if that is what
    the deployment runs)."""

    __slots__ = ("name", "addresses", "hosts", "wire", "anchor")

    def __init__(self, addresses: dict, hosts, name: str = "plane",
                 wire=None):
        self.name = str(name)
        self.addresses = {int(p): tuple(a) for p, a in addresses.items()}
        self.hosts = list(hosts)
        self.wire = wire
        self.anchor = _caller_anchor()


def _wire_of(spec: PlaneSpec, host: HostSpec):
    return host.wire if host.wire is not None else spec.wire


def check_plane_spec(spec: PlaneSpec) -> list[Diagnostic]:
    """Every WF22x + cross-host WF205/206/214/216 finding of one
    declared plane."""
    diags: list[Diagnostic] = []
    name = spec.name

    def d(code, msg, anchor=None, node=None):
        diags.append(Diagnostic(code, msg, node=node or name,
                                anchor=anchor or spec.anchor))

    by_pid: dict[int, HostSpec] = {}
    for host in spec.hosts:
        if host.pid in by_pid:
            d("WF220",
              f"plane {name!r}: two HostSpecs claim pid {host.pid} — "
              f"the spec is ambiguous about which process runs there",
              anchor=host.anchor)
            continue
        by_pid[host.pid] = host

    # address book vs host list: the SAME dict must be handed to every
    # process, so a pid on one side only is a deployment that cannot
    # boot (open_row_plane KeyErrors) or a silent never-wired host
    addr_pids = set(spec.addresses)
    host_pids = set(by_pid)
    for pid in sorted(host_pids - addr_pids):
        d("WF220",
          f"plane {name!r}: host pid {pid} has no entry in addresses= "
          f"— its receiver has nowhere to bind and every peer's "
          f"open_row_plane({pid}) raises at boot",
          anchor=by_pid[pid].anchor)
    for pid in sorted(addr_pids - host_pids):
        d("WF220",
          f"plane {name!r}: addresses= lists pid {pid} but no HostSpec "
          f"describes it — peers will connect-retry against an address "
          f"nothing ever binds")

    # two hosts on one (host, port): the second bind fails at boot
    seen_addr: dict[tuple, int] = {}
    for pid in sorted(addr_pids):
        addr = spec.addresses[pid]
        if addr in seen_addr:
            d("WF220",
              f"plane {name!r}: pids {seen_addr[addr]} and {pid} both "
              f"claim address {addr!r} — the second receiver's bind "
              f"fails at boot")
        else:
            seen_addr[addr] = pid

    # ---- per-edge checks -------------------------------------------
    for pid in sorted(host_pids):
        src = by_pid[pid]
        dests = (src.sends_to if src.sends_to is not None
                 else tuple(p for p in sorted(host_pids) if p != pid))
        for dpid in dests:
            if dpid not in by_pid:
                d("WF220",
                  f"plane {name!r}: host {pid} ships rows to pid "
                  f"{dpid}, which no HostSpec/address describes",
                  anchor=src.anchor)
                continue
            dst = by_pid[dpid]
            edge = f"edge {pid}->{dpid}"

            # dtype pairing: the receiver decodes with ITS dtype — a
            # disagreement is garbage rows (same itemsize) or a decoder
            # reject (different itemsize), never a usable stream
            if (src.sends is not None and dst.expects is not None
                    and src.sends != dst.expects):
                d("WF221",
                  f"plane {name!r} {edge}: sender ships dtype "
                  f"{src.sends!r} but the receiver decodes "
                  f"{dst.expects!r} — every batch is misdecoded",
                  anchor=src.anchor)

            # resume on both ends or neither: the reconnect handshake
            # pairs the sender journal with receiver epoch tracking
            if bool(src.resume) != bool(dst.resume):
                one, other = ((pid, dpid) if src.resume
                              else (dpid, pid))
                d("WF222",
                  f"plane {name!r} {edge}: resume= is set on host "
                  f"{one} but not host {other} — the resume handshake "
                  f"needs the sender journal AND the receiver's sealed-"
                  f"epoch tracking, so a reconnect on this edge fails "
                  f"(set resume on both, or neither)",
                  anchor=src.anchor)

            swire, dwire = _wire_of(spec, src), _wire_of(spec, dst)
            hb = getattr(swire, "heartbeat", None)
            stall = getattr(dwire, "stall_timeout", None)
            if hb is not None and stall is not None and hb >= stall:
                d("WF205",
                  f"plane {name!r} {edge}: sender heartbeat ({hb}s) >= "
                  f"receiver stall_timeout ({stall}s) — host {dpid} "
                  f"declares PeerStall before host {pid}'s next beat "
                  f"can arrive",
                  anchor=src.anchor)
            elif hb is not None and stall is None:
                d("WF206",
                  f"plane {name!r} {edge}: host {pid} heartbeats but "
                  f"host {dpid} has no stall_timeout — the beats buy "
                  f"nothing and a dead peer still hangs the read "
                  f"forever",
                  anchor=src.anchor)

            if src.resume and not getattr(dwire, "recovery", False):
                d("WF214",
                  f"plane {name!r} {edge}: host {pid} journals "
                  f"(resume=) but host {dpid}'s wire has no recovery= "
                  f"— no sealed-epoch acks ever flow back and the "
                  f"sender journal fills to its cap, then evicts",
                  anchor=src.anchor)

    # ---- plane-wide roles ------------------------------------------
    supervised = [h for h in spec.hosts if h.plane is not None]
    for host in supervised:
        pwire = getattr(host.plane, "wire", None) or _wire_of(spec, host)
        if not (getattr(pwire, "resume", None) or host.resume):
            d("WF216",
              f"plane {name!r}: host {host.pid} declares a PlanePolicy "
              f"but neither its plane wire nor the host journals "
              f"(resume=) — every handoff silently drops the frames in "
              f"flight at the death",
              anchor=host.anchor)
    if supervised and not any(h.ckpt_sink for h in spec.hosts):
        d("WF223",
          f"plane {name!r}: a PlanePolicy supervises the plane but no "
          f"host offers a ckpt_sink (portable-spool replica target) — "
          f"a cross-host takeover has no portable checkpoint to "
          f"restore from and silently degrades to an empty restart "
          f"(docs/ROBUSTNESS.md \"Cross-host recovery\")",
          anchor=supervised[0].anchor)

    shippers = [h for h in spec.hosts if h.federate]
    aggregators = [h for h in spec.hosts if h.aggregator]
    if shippers and not aggregators:
        d("WF224",
          f"plane {name!r}: hosts "
          f"{[h.pid for h in shippers]} federate telemetry but no "
          f"host runs the aggregator — every snapshot is shipped into "
          f"the void (mark one HostSpec aggregator=True; "
          f"docs/OBSERVABILITY.md \"Federation & SLOs\")",
          anchor=shippers[0].anchor)
    elif len(aggregators) > 1:
        d("WF224",
          f"plane {name!r}: hosts {[h.pid for h in aggregators]} all "
          f"claim the aggregator role — the federated view is split "
          f"across disagreeing aggregators (keep exactly one)",
          anchor=aggregators[1].anchor)
    return diags
