"""Configuration-conflict checks (WF2xx): knobs that are individually
valid but jointly inert or fatal — the misconfigurations that otherwise
surface only deep at runtime (a ``recovery=`` graph dying at its first
checkpoint, a sampler that never writes a file, a heartbeat nobody
listens to)."""

from __future__ import annotations

from .diagnostics import Diagnostic


def check_wire(cfg) -> list[Diagnostic]:
    """WF205/WF206/WF214 over one :class:`~windflow_tpu.parallel.
    channel.WireConfig` (sender heartbeat vs receiver stall timeout —
    and resume journal vs recovery acks — live on the same bundle, so
    the pairings are statically visible here)."""
    diags = []
    hb, stall = cfg.heartbeat, cfg.stall_timeout
    if hb is not None and stall is not None and hb >= stall:
        diags.append(Diagnostic(
            "WF205",
            f"heartbeat ({hb}s) must be < stall_timeout ({stall}s): the "
            f"receiver declares PeerStall before a healthy peer's next "
            f"beat can arrive (size stall_timeout to several heartbeat "
            f"intervals — WireConfig.hardened() uses 2s/10s)"))
    elif hb is not None and stall is None:
        diags.append(Diagnostic(
            "WF206",
            f"heartbeat={hb}s is sent but the receiving side has no "
            f"stall_timeout: beats buy nothing — a dead peer still "
            f"hangs the read forever (set stall_timeout on the paired "
            f"RowReceiver/WireConfig, docs/ROBUSTNESS.md)"))
    if getattr(cfg, "resume", None) and not getattr(cfg, "recovery",
                                                    False):
        diags.append(Diagnostic(
            "WF214",
            f"resume= is set but recovery= is not: the receiver never "
            f"acks sealed epochs back, so the sender journal can never "
            f"trim — it fills to journal_frames and then evicts, "
            f"breaking the replay guarantee for long streams (set "
            f"recovery=True, or ack sealed epochs yourself via "
            f"RowReceiver.ack_epoch; docs/ROBUSTNESS.md \"Wire "
            f"resume\")"))
    return diags


def check_plane(policy) -> list[Diagnostic]:
    """WF216 (plus the wire's own WF205/206/214) over one
    :class:`~windflow_tpu.parallel.plane.PlanePolicy`: a supervised
    plane promises handoff — the successor's takeover receiver resumes
    from the dead peer's last sealed epoch and expects every surviving
    sender to REPLAY its journaled tail.  Without ``resume=`` on the
    plane's wire there is no journal, so the frames in flight at the
    death are silently lost at every handoff."""
    wire = getattr(policy, "wire", None)
    diags = [] if wire is None else list(check_wire(wire))
    if wire is None or not getattr(wire, "resume", None):
        diags.append(Diagnostic(
            "WF216",
            f"PlanePolicy wire "
            f"{'is unset' if wire is None else 'has no resume='}: the "
            f"supervisor's handoff rebinds a dead peer's address with "
            f"resume_epoch=, but non-journaling senders cannot replay "
            f"their in-flight tail to the successor — every handoff "
            f"silently drops the frames in flight at the death (set "
            f"WireConfig(resume=True, recovery=True) on the plane; "
            f"docs/ROBUSTNESS.md \"Cross-host recovery\")"))
    return diags


def _obs_configured(metrics, sample_period) -> bool:
    # mirror the engine's truthiness rule: metrics=False/0 means OFF
    return bool(metrics) or sample_period is not None


def _native_state_abi() -> bool:
    """True when device-farm workers would route to the native C++ core
    AND that core can migrate keyed state (the loaded .so exports the
    state ABI)."""
    from ..native import enabled
    lib = enabled()
    return lib is not None and getattr(lib, "wf_has_state_abi", False)


def _iter_pipe_patterns(pipe):
    for branch in pipe._branches:
        yield from _iter_pipe_patterns(branch)
    for _kind, pattern in pipe._stages:
        yield pattern


def check_pipe_control(pipe) -> list[Diagnostic]:
    """WF209/210/211 over a MultiPipe's ``control=`` knob — the WF210/
    WF211 conflicts are refused outright at build/construction time
    (like WF208), so they must be *reportable* pre-build."""
    diags = []
    ctl = pipe.control
    if ctl is None:
        return diags
    if not _obs_configured(pipe._metrics_arg, pipe.sample_period):
        diags.append(_blind_control_diag(f"MultiPipe {pipe.name!r}"))
    if getattr(ctl, "has_rescale", False) and pipe.recovery is None:
        diags.append(Diagnostic(
            "WF211",
            f"MultiPipe {pipe.name!r}: control= has Rescale rules but "
            f"recovery= is unset — live rescale seals at epoch "
            f"barriers, which only a RecoveryPolicy's epoch triggers "
            f"inject (the Dataflow constructor refuses this pair; "
            f"docs/CONTROL.md)"))
    targeted = {r.pattern for r in getattr(ctl, "rules", ())
                if type(r).__name__ == "Rescale"}
    wired = {getattr(p, "name", None)
             for p in _iter_pipe_patterns(pipe)}
    for missing in sorted(targeted - wired):
        diags.append(Diagnostic(
            "WF212",
            f"Rescale rule targets {missing!r}, but no pattern of that "
            f"name is wired into MultiPipe {pipe.name!r} — the "
            f"controller will refuse to attach at run() (typo'd "
            f"pattern name?)", node=missing))
    for pattern in _iter_pipe_patterns(pipe):
        name = getattr(pattern, "name", None)
        rule = ctl.rescale_for(name)
        if rule is None:
            continue
        anchor = getattr(pattern, "anchor", None)
        width = getattr(pattern, "_ctl_width0", None)
        if width is None:
            width = getattr(pattern, "parallelism", 1)
        if getattr(pattern, "routing", None) is None:
            diags.append(Diagnostic(
                "WF210",
                f"Rescale rule targets {name!r}, which is not "
                f"key-partitioned (no keyed routing): live rescale "
                f"migrates per-key state between workers — wrap the "
                f"computation in a Key_Farm (docs/CONTROL.md)",
                node=name, anchor=anchor))
        elif getattr(pattern, "recoverable", None) is False:
            diags.append(Diagnostic(
                "WF210",
                f"Rescale rule targets {name!r}, whose recoverable "
                f"flag is opted out: a pattern that cannot snapshot "
                f"cannot seal the migration cut — drop the opt-out or "
                f"the rule (docs/CONTROL.md)",
                node=name, anchor=anchor))
        elif not rule.min_workers <= width <= rule.max_workers:
            # the wiring layer refuses this at build, so it must be
            # REPORTABLE pre-build like WF208 (the skip list below keeps
            # validate() from attempting the raising _build)
            diags.append(Diagnostic(
                "WF210",
                f"Rescale rule for {name!r}: declared parallelism "
                f"{width} is outside the rule's "
                f"[{rule.min_workers}, {rule.max_workers}] range — the "
                f"build refuses it (docs/CONTROL.md)",
                node=name, anchor=anchor))
        elif getattr(pattern, "n_emitters", 1) > 1:
            diags.append(Diagnostic(
                "WF210",
                f"Rescale rule targets multi-emitter farm {name!r}: "
                f"ordered multi-emitter merges pin the channel count "
                f"at build time and cannot rescale (docs/CONTROL.md)",
                node=name, anchor=anchor))
        elif (type(pattern).__name__.endswith("TPU")
                and not _native_state_abi()):
            # duck-typed like the WF215 native-core probe: device farm
            # workers mirror per-key rows into HBM rings the host
            # migration hooks cannot move, so their cores set
            # keyed_migratable=False and attach refuses.  When the
            # native library exports the state ABI the farm's workers
            # route to the migratable C++ core instead, so stay quiet
            # and let attach-time validation judge the actual cores
            # (a float reducer still lands on a device core and is
            # refused there with the precise ValueError).
            diags.append(Diagnostic(
                "WF210",
                f"Rescale rule targets device farm {name!r} "
                f"({type(pattern).__name__}): device cores decline "
                f"keyed-state migration (per-key rows live in device "
                f"rings) — target a host Key_Farm (docs/CONTROL.md)",
                node=name, anchor=anchor))
    return diags


def check_pipe_config(pipe) -> list[Diagnostic]:
    """Pre-build knob checks on a MultiPipe — including the conflicts
    the engine would refuse at ``Dataflow`` construction (WF208/WF210/
    WF211), which must be *reportable* here because the deferred build
    hides them until ``run()``."""
    diags = []
    overload = pipe.overload
    if (overload is not None and getattr(overload, "reshapes_put", False)
            and pipe.capacity <= 0):
        diags.append(Diagnostic(
            "WF208",
            f"MultiPipe {pipe.name!r}: OverloadPolicy "
            f"shed={overload.shed!r}/put_deadline="
            f"{overload.put_deadline} needs a bounded inbox (capacity > "
            f"0, got {pipe.capacity}): an unbounded queue never sheds "
            f"and never times out"))
    diags.extend(check_pipe_control(pipe))
    from ..utils.tracing import default_trace_dir
    # judged on the pipe's OWN (merged) knobs only: union_multipipes has
    # already hoisted the operands' trace_dir/metrics/overload onto the
    # merged pipe, so recursing into branches would re-judge them in
    # isolation and report a false WF207 on a union whose other branch
    # supplies the trace_dir
    if (_obs_configured(pipe._metrics_arg, pipe.sample_period)
            and not (pipe.trace_dir or default_trace_dir())):
        diags.append(_no_trace_dir_diag(pipe.name))
    # trace= is truthiness-gated exactly like metrics= (falsy = OFF), and
    # judged on the pipe's own merged knobs for the same union reason
    if (getattr(pipe, "trace", None)
            and not (pipe.trace_dir or default_trace_dir())):
        diags.append(_ring_only_trace_diag(pipe.name))
    if (getattr(pipe, "federate", None)
            and not _obs_configured(pipe._metrics_arg,
                                    pipe.sample_period)):
        diags.append(_blind_federation_diag(f"MultiPipe {pipe.name!r}"))
    return diags


def _blind_control_diag(owner: str) -> Diagnostic:
    return Diagnostic(
        "WF209",
        f"{owner}: control= is set but neither metrics= nor "
        f"sample_period= is — the controller never receives a sampler "
        f"snapshot, so no rule can fire (set metrics=True; "
        f"docs/CONTROL.md)")


def _no_trace_dir_diag(name: str) -> Diagnostic:
    return Diagnostic(
        "WF207",
        f"{name!r} runs with metrics=/sample_period= but no resolvable "
        f"trace_dir (trace_dir= or WF_LOG_DIR): the live registry works "
        f"but metrics.jsonl/events.jsonl are never written — set "
        f"trace_dir to keep the telemetry")


def _blind_federation_diag(owner: str) -> Diagnostic:
    return Diagnostic(
        "WF217",
        f"{owner}: federate= is set but neither metrics= nor "
        f"sample_period= is — the federation shipper's only source is "
        f"the sampler, so no telemetry snapshot is ever shipped and "
        f"federation is silently inert (set metrics=True; "
        f"docs/OBSERVABILITY.md \"Federation & SLOs\")")


def _ring_only_trace_diag(name: str) -> Diagnostic:
    return Diagnostic(
        "WF213",
        f"{name!r} runs with trace= but no resolvable trace_dir "
        f"(trace_dir= or WF_LOG_DIR): sampled spans stay in the bounded "
        f"in-memory ring — trace.jsonl is never written, so wf_trace / "
        f"Perfetto export has nothing to read; set trace_dir to keep "
        f"the spans (docs/OBSERVABILITY.md §tracing)")


def check_dataflow_config(df) -> list[Diagnostic]:
    """Knob checks on a built Dataflow (the WF208/WF210/WF211 conflicts
    cannot exist here — constructor and wiring refuse them)."""
    diags = []
    if (_obs_configured(df.metrics, df.sample_period)
            and not df.trace_dir):
        diags.append(_no_trace_dir_diag(df.name))
    if getattr(df, "trace", None) and not df.trace_dir:
        diags.append(_ring_only_trace_diag(df.name))
    if df.control is not None and df.metrics is None:
        diags.append(_blind_control_diag(f"Dataflow {df.name!r}"))
    if getattr(df, "federate", None) is not None and df.metrics is None:
        diags.append(_blind_federation_diag(f"Dataflow {df.name!r}"))
    return diags
