"""Configuration-conflict checks (WF2xx): knobs that are individually
valid but jointly inert or fatal — the misconfigurations that otherwise
surface only deep at runtime (a ``recovery=`` graph dying at its first
checkpoint, a sampler that never writes a file, a heartbeat nobody
listens to)."""

from __future__ import annotations

from .diagnostics import Diagnostic


def check_wire(cfg) -> list[Diagnostic]:
    """WF205/WF206 over one :class:`~windflow_tpu.parallel.channel.
    WireConfig` (sender heartbeat vs receiver stall timeout live on the
    same bundle, so the pairing is statically visible here)."""
    diags = []
    hb, stall = cfg.heartbeat, cfg.stall_timeout
    if hb is not None and stall is not None and hb >= stall:
        diags.append(Diagnostic(
            "WF205",
            f"heartbeat ({hb}s) must be < stall_timeout ({stall}s): the "
            f"receiver declares PeerStall before a healthy peer's next "
            f"beat can arrive (size stall_timeout to several heartbeat "
            f"intervals — WireConfig.hardened() uses 2s/10s)"))
    elif hb is not None and stall is None:
        diags.append(Diagnostic(
            "WF206",
            f"heartbeat={hb}s is sent but the receiving side has no "
            f"stall_timeout: beats buy nothing — a dead peer still "
            f"hangs the read forever (set stall_timeout on the paired "
            f"RowReceiver/WireConfig, docs/ROBUSTNESS.md)"))
    return diags


def _obs_configured(metrics, sample_period) -> bool:
    # mirror the engine's truthiness rule: metrics=False/0 means OFF
    return bool(metrics) or sample_period is not None


def check_pipe_config(pipe) -> list[Diagnostic]:
    """Pre-build knob checks on a MultiPipe — including the conflicts
    the engine would refuse at ``Dataflow`` construction (WF208), which
    must be *reportable* here because the deferred build hides them
    until ``run()``."""
    diags = []
    overload = pipe.overload
    if (overload is not None and getattr(overload, "reshapes_put", False)
            and pipe.capacity <= 0):
        diags.append(Diagnostic(
            "WF208",
            f"MultiPipe {pipe.name!r}: OverloadPolicy "
            f"shed={overload.shed!r}/put_deadline="
            f"{overload.put_deadline} needs a bounded inbox (capacity > "
            f"0, got {pipe.capacity}): an unbounded queue never sheds "
            f"and never times out"))
    from ..utils.tracing import default_trace_dir
    # judged on the pipe's OWN (merged) knobs only: union_multipipes has
    # already hoisted the operands' trace_dir/metrics/overload onto the
    # merged pipe, so recursing into branches would re-judge them in
    # isolation and report a false WF207 on a union whose other branch
    # supplies the trace_dir
    if (_obs_configured(pipe._metrics_arg, pipe.sample_period)
            and not (pipe.trace_dir or default_trace_dir())):
        diags.append(_no_trace_dir_diag(pipe.name))
    return diags


def _no_trace_dir_diag(name: str) -> Diagnostic:
    return Diagnostic(
        "WF207",
        f"{name!r} runs with metrics=/sample_period= but no resolvable "
        f"trace_dir (trace_dir= or WF_LOG_DIR): the live registry works "
        f"but metrics.jsonl/events.jsonl are never written — set "
        f"trace_dir to keep the telemetry")


def check_dataflow_config(df) -> list[Diagnostic]:
    """Knob checks on a built Dataflow (the WF208 conflict cannot exist
    here — the constructor refuses it)."""
    diags = []
    if (_obs_configured(df.metrics, df.sample_period)
            and not df.trace_dir):
        diags.append(_no_trace_dir_diag(df.name))
    return diags
