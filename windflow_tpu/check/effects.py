"""Effect analyzer (WF303-WF305): bytecode inspection of user functions
for calls whose *runtime effects* break a declared contract.

The closure analyzer (WF301/302, check/closures.py) asks "does this fn
race against its own replicas?".  This pass asks the complementary
question the recovery/control subsystems need answered: "is this fn
safe to RE-EXECUTE (replay) or to sit under a latency trigger?"

* **WF303 — replay nondeterminism.**  ``recovery=`` replays a crashed
  node's input from the journal and promises byte-identical re-emission
  (docs/ROBUSTNESS.md).  A recoverable fn calling ``time.time()``,
  ``random.random()``, ``os.urandom()``, ``uuid.uuid4()`` or the numpy
  *global* RNG produces different bytes on replay and diverges from the
  journal oracle.  A fn that CAPTURES a seeded generator
  (``np.random.default_rng(seed)``, ``random.Random(seed)``) is exempt:
  seeded-generator state is part of the snapshot, the blessed pattern.
* **WF304 — side effects under restart.**  A node opted into restart
  (``pattern.recoverable = True`` under ``recovery=``) re-fires
  file/socket/subprocess/HTTP calls on replay, and no downstream edge
  can deduplicate an external effect — PR 8's "sinks are not restartable
  by default" rationale, caught at lint time.
* **WF305 — blocking calls under latency control.**  ``sleep``, an
  untimed ``.acquire()``, a blocking ``.recv()`` inside the svc of a
  node governed by ``Rescale(up_q95_us=/up_slo_burn=)`` inflates the
  very tail-latency signal the rule watches: phantom rescales.

Mechanics: a conservative ``dis`` pass sharing the WF301/302 suppression
machinery (``# wf-lint: disable=`` on the call line or the ``def``
line).  Call targets are resolved through a small shadow stack —
``LOAD_GLOBAL``/``LOAD_ATTR`` chains are resolved against the live
module globals, everything unrecognised degrades to *opaque* (never
misattributed, so the pass under-reports rather than false-positives).
One level of same-module call following: a helper defined next to the
user fn is scanned too, anchored at the helper's offending line.
"""

from __future__ import annotations

import dis
import sys

from .diagnostics import Diagnostic
from .directives import suppressed_at

#: WF305 method-name heuristic: a method call of one of these names on
#: an UNRESOLVED receiver blocks the caller (``acquire`` only when
#: called with no arguments — a timeout argument bounds the wait)
_BLOCKING_METHODS = frozenset({
    "acquire", "recv", "recvfrom", "recv_into", "accept",
})

_tables = None


def _put(table, obj, code, label):
    if obj is None:
        return
    try:
        table[obj] = (code, label)
    except TypeError:        # unhashable callable: cannot be looked up
        pass


def _build_tables():
    """callable -> (WF###, printable name).  Keyed by the object itself
    (plain functions hash by identity; builtin bound methods hash/compare
    by ``__self__`` + slot, so a freshly resolved ``datetime.now`` still
    matches).  Built lazily on the first analyzed fn — the check package
    is only ever imported on the cold lint path."""
    import datetime
    import os
    import random
    import secrets
    import select
    import shutil
    import socket
    import subprocess
    import time
    import uuid

    t: dict[object, tuple[str, str]] = {}

    # -- WF303: replay nondeterminism ----------------------------------
    for name in ("time", "time_ns", "monotonic", "monotonic_ns",
                 "perf_counter", "perf_counter_ns", "clock_gettime",
                 "clock_gettime_ns", "process_time", "process_time_ns",
                 "thread_time", "thread_time_ns"):
        _put(t, getattr(time, name, None), "WF303", f"time.{name}")
    for name in ("random", "randint", "randrange", "uniform", "gauss",
                 "normalvariate", "lognormvariate", "expovariate",
                 "betavariate", "gammavariate", "triangular", "choice",
                 "choices", "sample", "shuffle", "getrandbits",
                 "randbytes", "vonmisesvariate", "paretovariate",
                 "weibullvariate", "seed"):
        _put(t, getattr(random, name, None), "WF303", f"random.{name}")
    _put(t, os.urandom, "WF303", "os.urandom")
    _put(t, getattr(os, "getrandom", None), "WF303", "os.getrandom")
    for name in ("uuid1", "uuid4"):
        _put(t, getattr(uuid, name, None), "WF303", f"uuid.{name}")
    for name in ("token_bytes", "token_hex", "token_urlsafe",
                 "randbelow", "choice", "randbits"):
        _put(t, getattr(secrets, name, None), "WF303", f"secrets.{name}")
    _put(t, datetime.datetime.now, "WF303", "datetime.datetime.now")
    _put(t, datetime.datetime.utcnow, "WF303", "datetime.datetime.utcnow")
    _put(t, datetime.date.today, "WF303", "datetime.date.today")
    try:
        import numpy as np
    except ImportError:
        np = None
    if np is not None:
        # the legacy GLOBAL RNG only — np.random.default_rng(seed) is
        # the blessed replay-safe pattern and must never flag
        for name in ("rand", "randn", "random", "randint", "normal",
                     "uniform", "choice", "shuffle", "permutation",
                     "standard_normal", "random_sample", "ranf",
                     "sample", "bytes", "exponential", "poisson",
                     "binomial", "beta", "gamma", "seed"):
            _put(t, getattr(np.random, name, None), "WF303",
                 f"numpy.random.{name}")

    # -- WF304: external side effects ----------------------------------
    import builtins
    _put(t, builtins.open, "WF304", "open")
    _put(t, getattr(os, "open", None), "WF304", "os.open")
    for name in ("remove", "unlink", "rename", "replace", "rmdir",
                 "mkdir", "makedirs", "removedirs", "truncate", "write",
                 "system", "popen", "symlink", "link"):
        _put(t, getattr(os, name, None), "WF304", f"os.{name}")
    for name in ("copy", "copy2", "copyfile", "copytree", "move",
                 "rmtree"):
        _put(t, getattr(shutil, name, None), "WF304", f"shutil.{name}")
    for name in ("run", "Popen", "call", "check_call", "check_output"):
        _put(t, getattr(subprocess, name, None), "WF304",
             f"subprocess.{name}")
    _put(t, socket.socket, "WF304", "socket.socket")
    _put(t, socket.create_connection, "WF304", "socket.create_connection")
    try:
        import urllib.request as _urlreq
    except ImportError:
        _urlreq = None
    if _urlreq is not None:
        _put(t, _urlreq.urlopen, "WF304", "urllib.request.urlopen")
    try:
        import http.client as _httpc
    except ImportError:
        _httpc = None
    if _httpc is not None:
        _put(t, _httpc.HTTPConnection, "WF304",
             "http.client.HTTPConnection")
        _put(t, getattr(_httpc, "HTTPSConnection", None), "WF304",
             "http.client.HTTPSConnection")
    if "requests" in sys.modules:    # never imported just for the table
        req = sys.modules["requests"]
        for name in ("get", "post", "put", "delete", "head", "patch",
                     "request"):
            _put(t, getattr(req, name, None), "WF304", f"requests.{name}")

    # -- WF305: blocking calls -----------------------------------------
    _put(t, time.sleep, "WF305", "time.sleep")
    _put(t, select.select, "WF305", "select.select")
    return t


def _flag_tables():
    global _tables
    if _tables is None:
        _tables = _build_tables()
    return _tables


# ------------------------------------------------------- shadow stack

class _Chain:
    """A resolvable global-attribute chain on the shadow stack."""

    __slots__ = ("names",)

    def __init__(self, names):
        self.names = names


class _Method:
    """A method loaded off an opaque receiver (WF305 name heuristic)."""

    __slots__ = ("name", "line")

    def __init__(self, name, line):
        self.name = name
        self.line = line


_OPAQUE = object()    # any value the scanner does not model

#: ops handled by the shadow stack as "push one opaque value"
_PUSH1 = frozenset({
    "LOAD_CONST", "LOAD_FAST", "LOAD_DEREF", "LOAD_CLOSURE",
    "LOAD_CLASSDEREF", "LOAD_FAST_AND_CLEAR", "LOAD_FAST_CHECK",
    "LOAD_BUILD_CLASS", "PUSH_NULL", "LOAD_LOCALS", "GET_LEN",
})
_POP1 = frozenset({
    "POP_TOP", "STORE_FAST", "STORE_DEREF", "STORE_GLOBAL",
    "STORE_NAME", "RETURN_VALUE", "LIST_APPEND", "SET_ADD",
    "LIST_EXTEND", "SET_UPDATE", "DICT_UPDATE", "DICT_MERGE",
    "MAP_ADD", "YIELD_VALUE", "POP_JUMP_IF_TRUE", "POP_JUMP_IF_FALSE",
    "POP_JUMP_FORWARD_IF_TRUE", "POP_JUMP_FORWARD_IF_FALSE",
})
#: binary ops: pop two, push one opaque
_POP2_PUSH1 = frozenset({
    "BINARY_SUBSCR", "BINARY_OP", "COMPARE_OP", "IS_OP", "CONTAINS_OP",
    "BINARY_ADD", "BINARY_SUBTRACT", "BINARY_MULTIPLY", "BINARY_POWER",
    "BINARY_TRUE_DIVIDE", "BINARY_FLOOR_DIVIDE", "BINARY_MODULO",
    "BINARY_LSHIFT", "BINARY_RSHIFT", "BINARY_AND", "BINARY_OR",
    "BINARY_XOR", "BINARY_MATRIX_MULTIPLY", "INPLACE_ADD",
    "INPLACE_SUBTRACT", "INPLACE_MULTIPLY", "INPLACE_TRUE_DIVIDE",
    "INPLACE_FLOOR_DIVIDE", "INPLACE_MODULO", "INPLACE_POWER",
    "INPLACE_LSHIFT", "INPLACE_RSHIFT", "INPLACE_AND", "INPLACE_OR",
    "INPLACE_XOR", "INPLACE_MATRIX_MULTIPLY",
})
_UNARY = frozenset({
    "UNARY_NEGATIVE", "UNARY_POSITIVE", "UNARY_NOT", "UNARY_INVERT",
    "GET_ITER", "UNARY_CALL_INTRINSIC_1", "CALL_INTRINSIC_1",
    "TO_BOOL", "CAST",
})


def _resolve(chain, globals_ns):
    """The live object a ``_Chain`` names, or None."""
    import builtins
    obj = globals_ns.get(chain.names[0], _OPAQUE)
    if obj is _OPAQUE:
        obj = getattr(builtins, chain.names[0], _OPAQUE)
        if obj is _OPAQUE:
            return None
    for name in chain.names[1:]:
        try:
            obj = getattr(obj, name)
        except Exception:
            return None
    return obj


def _scan_code(fn, depth, seen, findings):
    """Append raw findings ``(wfcode, label, filename, line, def_line,
    via)`` for ``fn`` — and, at depth 0, one level of same-module
    helpers."""
    code = fn.__code__
    if code in seen:
        return
    seen.add(code)
    tables = _flag_tables()
    globals_ns = getattr(fn, "__globals__", {}) or {}
    filename = code.co_filename
    def_line = code.co_firstlineno
    is311 = sys.version_info >= (3, 11)

    stack: list = []
    line = def_line

    def pop(n):
        del stack[max(0, len(stack) - n):]

    def callee_at(pos):
        """Stack entry ``pos`` slots below the top (1-based), or
        _OPAQUE on underflow."""
        return stack[-pos] if len(stack) >= pos else _OPAQUE

    def record(entry, argc, call_line):
        """Judge one call: ``entry`` is the shadow-stack callee."""
        if isinstance(entry, _Method):
            if entry.name in _BLOCKING_METHODS and (
                    entry.name != "acquire" or argc == 0):
                what = (f"untimed .{entry.name}()" if entry.name ==
                        "acquire" else f"blocking .{entry.name}(...)")
                findings.append(("WF305", what, filename, entry.line,
                                 def_line, None))
            return
        if not isinstance(entry, _Chain):
            return
        obj = _resolve(entry, globals_ns)
        if obj is None:
            # unresolvable attribute call: the name heuristic still
            # applies (x.acquire() blocks whoever x turns out to be)
            if (len(entry.names) > 1
                    and entry.names[-1] in _BLOCKING_METHODS
                    and (entry.names[-1] != "acquire" or argc == 0)):
                findings.append(("WF305",
                                 f".{entry.names[-1]}(...)", filename,
                                 call_line, def_line, None))
            return
        try:
            hit = tables.get(obj)
        except TypeError:
            hit = None
        if hit is not None:
            wfcode, label = hit
            findings.append((wfcode, f"{label}()", filename, call_line,
                             def_line, None))
            return
        if (getattr(obj, "__name__", None) in _BLOCKING_METHODS
                and (obj.__name__ != "acquire" or argc == 0)):
            findings.append(("WF305", f".{obj.__name__}(...)", filename,
                             call_line, def_line, None))
            return
        # one level of same-module call following: a helper defined in
        # the fn's own module is effectively part of the user function
        if (depth == 0 and getattr(obj, "__code__", None) is not None
                and getattr(obj, "__globals__", None) is globals_ns):
            pre = len(findings)
            _scan_code(obj, 1, seen, findings)
            via = (getattr(obj, "__qualname__", "<helper>"), call_line,
                   def_line)
            for i in range(pre, len(findings)):
                f = findings[i]
                if f[5] is None:
                    findings[i] = f[:5] + (via,)

    for ins in dis.get_instructions(code):
        if ins.starts_line:
            line = getattr(ins, "line_number", None) or int(ins.starts_line)
        op = ins.opname
        # control flow invalidates the linear shadow stack: reset (calls
        # spanning a jump degrade to opaque — under-report, never
        # misattribute)
        if ins.is_jump_target:
            stack.clear()
            continue
        if op in ("LOAD_GLOBAL", "LOAD_NAME"):
            if is311 and op == "LOAD_GLOBAL" and ins.arg is not None \
                    and ins.arg & 1:
                stack.append(_OPAQUE)    # the NULL the call protocol eats
            stack.append(_Chain([ins.argval]))
        elif op == "LOAD_ATTR":
            top = stack.pop() if stack else _OPAQUE
            pushes_self = is311 and ins.arg is not None and ins.arg & 1 \
                and sys.version_info >= (3, 12)
            if isinstance(top, _Chain):
                entry = _Chain(top.names + [ins.argval])
            elif ins.argval in _BLOCKING_METHODS:
                entry = _Method(ins.argval, line)
            else:
                entry = _OPAQUE
            stack.append(entry)
            if pushes_self:
                stack.append(_OPAQUE)
        elif op == "LOAD_METHOD":
            top = stack.pop() if stack else _OPAQUE
            if isinstance(top, _Chain):
                entry = _Chain(top.names + [ins.argval])
            elif ins.argval in _BLOCKING_METHODS:
                entry = _Method(ins.argval, line)
            else:
                entry = _OPAQUE
            # 3.10 layout: push method, then self-or-NULL
            stack.append(entry)
            stack.append(_OPAQUE)
        elif op == "CALL_METHOD":            # 3.10
            argc = ins.arg or 0
            record(callee_at(argc + 2), argc, line)
            pop(argc + 2)
            stack.append(_OPAQUE)
        elif op == "CALL_FUNCTION":          # 3.10
            argc = ins.arg or 0
            record(callee_at(argc + 1), argc, line)
            pop(argc + 1)
            stack.append(_OPAQUE)
        elif op == "CALL_FUNCTION_KW":       # 3.10
            argc = ins.arg or 0
            record(callee_at(argc + 2), argc + 1, line)
            pop(argc + 2)
            stack.append(_OPAQUE)
        elif op == "CALL_FUNCTION_EX":
            n = 3 if (ins.arg or 0) & 1 else 2
            record(callee_at(n), 1, line)
            pop(n)
            stack.append(_OPAQUE)
        elif op in ("CALL", "CALL_KW"):      # 3.11+
            argc = ins.arg or 0
            extra = 3 if op == "CALL_KW" else 2
            record(callee_at(argc + extra), argc, line)
            pop(argc + extra)
            stack.append(_OPAQUE)
        elif op == "PRECALL" or op == "KW_NAMES":
            pass
        elif op in _PUSH1:
            stack.append(_OPAQUE)
        elif op in _POP1:
            pop(1)
        elif op in _POP2_PUSH1:
            pop(2)
            stack.append(_OPAQUE)
        elif op in _UNARY:
            pop(1)
            stack.append(_OPAQUE)
        elif op in ("BUILD_LIST", "BUILD_TUPLE", "BUILD_SET",
                    "BUILD_STRING", "BUILD_SLICE"):
            pop(ins.arg or 0)
            stack.append(_OPAQUE)
        elif op == "BUILD_MAP":
            pop(2 * (ins.arg or 0))
            stack.append(_OPAQUE)
        elif op == "BUILD_CONST_KEY_MAP":
            pop((ins.arg or 0) + 1)
            stack.append(_OPAQUE)
        elif op == "STORE_SUBSCR":
            pop(3)
        elif op in ("STORE_ATTR", "DELETE_SUBSCR"):
            pop(2)
        elif op == "DUP_TOP":
            stack.append(stack[-1] if stack else _OPAQUE)
        elif op == "DUP_TOP_TWO":
            pair = stack[-2:] if len(stack) >= 2 else [_OPAQUE, _OPAQUE]
            stack.extend(pair)
        elif op == "COPY":
            i = ins.arg or 1
            stack.append(stack[-i] if len(stack) >= i else _OPAQUE)
        elif op in ("ROT_TWO", "ROT_THREE", "ROT_FOUR", "SWAP"):
            # depth-preserving, but the reordered entries could land a
            # chain in a callee slot it does not occupy: blank them
            n = {"ROT_TWO": 2, "ROT_THREE": 3, "ROT_FOUR": 4}.get(
                op, ins.arg or 2)
            for i in range(1, min(n, len(stack)) + 1):
                stack[-i] = _OPAQUE
        elif op in ("NOP", "RESUME", "CACHE", "EXTENDED_ARG",
                    "SETUP_LOOP", "MAKE_CELL", "COPY_FREE_VARS",
                    "DELETE_FAST", "DELETE_DEREF", "DELETE_GLOBAL",
                    "DELETE_NAME"):
            pass
        else:
            # unmodelled opcode: degrade the whole expression to opaque
            stack.clear()
    seen.discard(code)


_raw_cache: dict[object, list] = {}


def _raw_effects(fn) -> list:
    """All raw effect findings of ``fn`` (every WF30x family, ungated) —
    cached per code object, the gate filters per node."""
    code = fn.__code__
    cached = _raw_cache.get(code)
    if cached is None:
        cached = []
        _scan_code(fn, 0, set(), cached)
        _raw_cache[code] = cached
    return cached


def _captures_seeded_generator(fn) -> bool:
    """True when ``fn`` closes over (or defaults to) a seeded RNG —
    the replay-safe pattern WF303 must trust, like the closure
    analyzer trusts a captured lock."""
    import random as _random
    candidates = []
    closure = getattr(fn, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                candidates.append(cell.cell_contents)
            except ValueError:
                continue
    candidates.extend(getattr(fn, "__defaults__", None) or ())
    candidates.extend((getattr(fn, "__kwdefaults__", None) or {}).values())
    for v in candidates:
        tname = type(v).__name__
        tmod = type(v).__module__ or ""
        if tname in ("Generator", "RandomState") and \
                tmod.startswith("numpy"):
            return True
        if isinstance(v, _random.Random) and \
                not isinstance(v, _random.SystemRandom):
            return True
    return False


#: per-code gate context rendered into the message
_WHY = {
    "WF303": ("recovery= replays this node from the journal: the call "
              "returns different bytes on replay and the re-emission "
              "diverges from the journal oracle — capture a seeded "
              "generator (np.random.default_rng(seed)) instead"),
    "WF304": ("this node is opted into restart under recovery=: replay "
              "re-fires the external effect and no downstream edge can "
              "deduplicate it — drop the recoverable opt-in, or make "
              "the effect idempotent and suppress"),
    "WF305": ("a Rescale(up_q95_us=/up_slo_burn=) rule watches this "
              "node's tail latency: the block inflates q95/SLO burn and "
              "triggers phantom rescales — move the wait off the svc "
              "path, or gate scaling on depth instead"),
}


def analyze_effects(fn, active: set, owner: str) -> list[Diagnostic]:
    """Gated WF303/304/305 findings for user fn ``fn`` of node/pattern
    ``owner``; ``active`` is the subset of effect codes the node's
    declared contracts arm."""
    if getattr(fn, "__code__", None) is None or not active:
        return []
    wanted = set(active)
    if "WF303" in wanted and _captures_seeded_generator(fn):
        wanted.discard("WF303")
    if not wanted:
        return []
    fname = getattr(fn, "__qualname__", getattr(fn, "__name__", "<fn>"))
    caller_def = fn.__code__.co_firstlineno
    diags = []
    emitted = set()
    for wfcode, label, filename, line, def_line, via in _raw_effects(fn):
        if wfcode not in wanted:
            continue
        key = (wfcode, filename, line, label)
        if key in emitted:
            continue
        emitted.add(key)
        also = [def_line]
        detail = f"{fname!r} ({owner}) calls {label}"
        if via is not None:
            helper, call_line, _ = via
            detail = (f"{fname!r} ({owner}) calls {label} via helper "
                      f"{helper!r}")
            also.extend((call_line, caller_def))
        if suppressed_at(filename, line, wfcode, also_lines=tuple(also)):
            continue
        diags.append(Diagnostic(
            wfcode, f"{detail}: {_WHY[wfcode]}", node=owner,
            anchor=(filename, line)))
    return diags
