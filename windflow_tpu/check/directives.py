"""``# wf-lint:`` suppression directives.

A diagnostic anchored at ``file:line`` is suppressed when that source
line (or, for multi-line statements, the line the anchor points into)
carries a trailing directive:

    agg = PaneFarm(plq, wlq, 10, 3)   # wf-lint: disable=WF103
    counts[key] += 1                  # wf-lint: disable=WF301,WF302
    legacy_build()                    # wf-lint: disable

``disable`` with no code list suppresses every diagnostic anchored at
the line.  Codes are comma-separated, case-insensitive, and must look
like catalog ids (``WF`` + digits) — anything else is ignored rather
than silently suppressing the world.
"""

from __future__ import annotations

import linecache
import re

_DIRECTIVE = re.compile(r"#\s*wf-lint\s*:\s*disable(?:\s*=\s*([\w,\s]+))?",
                        re.IGNORECASE)


def parse_directive(line: str) -> set[str] | None:
    """Codes disabled by ``line``: a set of WF ids, the sentinel
    ``{"all"}`` for a bare ``disable``, or None when no directive."""
    m = _DIRECTIVE.search(line)
    if m is None:
        return None
    raw = m.group(1)
    if raw is None:
        return {"all"}
    codes = {c.strip().upper() for c in raw.split(",") if c.strip()}
    # drop anything that does not look like a catalog id: a typo'd code
    # must suppress NOTHING (an empty set), never widen to everything
    return {c for c in codes if re.fullmatch(r"WF\d+", c)}


def suppressed_at(filename: str, lineno: int, code: str,
                  also_lines=()) -> bool:
    """True when ``code`` is disabled at ``filename:lineno`` (or any of
    the extra candidate lines — e.g. a function's ``def`` line for a
    diagnostic anchored at a body instruction)."""
    # suppression is consulted only when a diagnostic fired (cold path):
    # pay the stat to never read a stale cached copy of an edited file
    linecache.checkcache(filename)
    for ln in (lineno, *also_lines):
        if not ln:
            continue
        disabled = parse_directive(linecache.getline(filename, ln))
        if disabled and ("all" in disabled or code in disabled):
            return True
    return False
