"""Graph validation: a walk over a built (not yet running)
:class:`~windflow_tpu.runtime.engine.Dataflow`.

Everything here works on the materialised node graph — ``df.nodes``,
``df._edges``, and per-node cores — so it covers manual wirings exactly
like MultiPipe-built ones.  When the graph came from a MultiPipe
(``df._check_pipe``, stamped by ``MultiPipe._build``), window-geometry
diagnostics anchor at the pattern's construction site instead of a bare
node name.

Detection is duck-typed by design (class names / attribute probes, no
pattern imports): the check package must stay import-light so the lazy
``check=`` hook costs nothing when off, and a stubbed core in a test is
as checkable as the real native one.
"""

from __future__ import annotations

from .closures import analyze_function
from .config import _iter_pipe_patterns as _iter_patterns
from .config import check_dataflow_config
from .diagnostics import Diagnostic


def _stats_name(df, node) -> str:
    from ..utils.tracing import node_stats_name
    try:
        idx = df.nodes.index(node)
    except ValueError:
        return node.name
    return node_stats_name(df.name, idx, node.name)


def _leaf_nodes(node):
    """A node and its fused members (Comb stages), flattened."""
    stages = getattr(node, "stages", None)
    if not stages:
        return [node]
    out = []
    for s in stages:
        out.extend(_leaf_nodes(s))
    return out


def _core_of(leaf):
    return getattr(leaf, "core", None)


def _is_async_core(core) -> bool:
    return core is not None and hasattr(core, "process_batches")


def _has_keyed_state(node) -> bool:
    """Per-key mutable stream state that keyed routing must protect:
    window cores (their substream arithmetic assumes one worker sees a
    key's whole slice) and accumulator folds."""
    for leaf in _leaf_nodes(node):
        if type(leaf).__name__ == "_AccumulatorNode":
            return True
        core = _core_of(leaf)
        if core is not None and hasattr(core, "spec"):
            return True
    return False


def _anchor_of(pattern):
    return getattr(pattern, "anchor", None)


# --------------------------------------------------------------- passes

def _check_recovery(df) -> list[Diagnostic]:
    """WF202-204 + WF215: recovery= over nodes whose configuration
    declines snapshots or restart — today these die at the FIRST
    checkpoint (SnapshotUnsupported) or silently degrade to
    fail-like-seed."""
    diags = []
    if df.recovery is None:
        return diags
    from ..runtime.node import SourceNode
    for node in df.nodes:
        name = _stats_name(df, node)
        leaves = _leaf_nodes(node)
        for leaf in leaves:
            core = _core_of(leaf)
            if core is None:
                continue
            if (type(core).__name__ == "NativeResidentCore"
                    and not getattr(core, "has_state_abi", False)):
                diags.append(Diagnostic(
                    "WF215",
                    f"recovery= over the native C++ resident core at "
                    f"{name}, but the loaded libwfnative.so predates "
                    f"the state ABI (no wf_core_state_export) — the "
                    f"first epoch checkpoint raises SnapshotUnsupported "
                    f"(patterns/native_core.py); rebuild with `make -C "
                    f"native`, or set WF_NO_NATIVE_CORE=1 to pin the "
                    f"snapshotable Python resident core",
                    node=name))
            elif (_is_async_core(core)
                    and getattr(core, "max_delay_s", None) is not None):
                diags.append(Diagnostic(
                    "WF202",
                    f"recovery= over a max_delay_ms device core at "
                    f"{name}: wall-clock flushes make replayed emission "
                    f"boundaries nondeterministic, so the core declines "
                    f"snapshots — drop max_delay_ms (count-triggered "
                    f"flushes recover exactly-once) or exclude this "
                    f"stage from recovery",
                    node=name))
        stages = getattr(node, "stages", None)
        if stages and any(_is_async_core(_core_of(s))
                          for s in stages[:-1]):
            diags.append(Diagnostic(
                "WF203",
                f"recovery= over fused chain {name}: a NON-TAIL stage "
                f"is an async device core, so the poll-timing of its "
                f"harvests shapes the tail's emission grouping and "
                f"replay cannot regenerate the seq numbering — use "
                f"add() instead of chain() to give the device stage "
                f"its own engine-driven thread",
                node=name))
        # terminal stage: judge the TAIL leaf, so a sink chained into a
        # fused group (SourceComb/Comb) is still seen as the sink it is
        tail = leaves[-1]
        if (not node._outputs and not isinstance(tail, SourceNode)
                and not getattr(tail, "recoverable", False)
                and not getattr(tail, "quarantine_exempt", False)):
            diags.append(Diagnostic(
                "WF204",
                f"recovery= with sink {name} not opted into restart: "
                f"sinks default to non-restartable (no downstream edge "
                f"can dedup replayed side effects), so a crash there "
                f"still tears the graph down — set "
                f"pattern.recoverable = True if the sink is idempotent",
                node=name))
    return diags


def _check_routing(df) -> list[Diagnostic]:
    """WF101: >= 2 keyed-state workers fed by a round-robin emitter —
    rows of one key land on different replicas and every per-key
    invariant (window content, fold state) silently corrupts."""
    diags = []
    dests: dict[int, list] = {}
    for src, dst in df._edges:
        if (type(src).__name__ == "StandardEmitter"
                and getattr(src, "routing", None) is None):
            dests.setdefault(id(src), [src]).append(dst)
    for _sid, group in dests.items():
        emitter, targets = group[0], group[1:]
        keyed = [t for t in {id(t): t for t in targets}.values()
                 if _has_keyed_state(t)]
        if len(keyed) >= 2:
            names = ", ".join(_stats_name(df, t) for t in keyed)
            diags.append(Diagnostic(
                "WF101",
                f"non-keyed emitter {_stats_name(df, emitter)} "
                f"round-robins batches across keyed-state workers "
                f"[{names}]: same-key rows split across replicas and "
                f"per-key state silently corrupts — route with "
                f"keyBy()/routing= (emitters.default_routing)",
                node=_stats_name(df, emitter)))
    return diags


def _check_windows(df) -> list[Diagnostic]:
    """WF102/WF103: window geometry.  Pattern-level when the graph came
    from a MultiPipe (anchored at the construction site, deduped per
    stage); node-core fallback for manual wirings."""
    diags = []
    pipe = getattr(df, "_check_pipe", None)
    if pipe is not None:
        for pattern in _iter_patterns(pipe):
            diags.extend(_check_pattern_window(pattern))
        return diags
    seen = set()
    for node in df.nodes:
        for leaf in _leaf_nodes(node):
            core = _core_of(leaf)
            spec = getattr(core, "spec", None)
            if spec is None:
                continue
            key = (leaf.name.rsplit(".", 1)[0], spec.win_len,
                   spec.slide_len)
            if key in seen:
                continue
            seen.add(key)
            if spec.slide_len > spec.win_len:
                diags.append(_hopping_diag(spec, _stats_name(df, node),
                                           None))
    return diags


def _hopping_diag(spec, where, anchor):
    return Diagnostic(
        "WF102",
        f"{where}: hopping window (slide {spec.slide_len} > win_len "
        f"{spec.win_len}) leaves gaps of {spec.slide_len - spec.win_len} "
        f"ids/ts between consecutive windows — rows landing there are "
        f"never aggregated; use slide <= win_len unless sampling is "
        f"intended", node=where, anchor=anchor)


def _check_pattern_window(pattern) -> list[Diagnostic]:
    diags = []
    spec = getattr(pattern, "spec", None)
    name = getattr(pattern, "name", type(pattern).__name__)
    anchor = _anchor_of(pattern)
    if spec is not None and spec.slide_len > spec.win_len:
        diags.append(_hopping_diag(spec, name, anchor))
    # pane decomposition (Pane_Farm family): panes are gcd(win, slide)
    # long, so a slide that does not divide the window degenerates the
    # decomposition (worst case gcd 1: every tuple its own pane)
    pane = getattr(pattern, "pane_len", None)
    if (pane is not None and spec is not None
            and spec.win_len % spec.slide_len != 0):
        diags.append(Diagnostic(
            "WF103",
            f"{name}: slide {spec.slide_len} does not divide win_len "
            f"{spec.win_len}, so the pane decomposition runs "
            f"gcd-sized panes of {pane} (win/pane={spec.win_len // pane} "
            f"partials per window) — pick win_len a multiple of "
            f"slide_len to keep panes slide-sized",
            node=name, anchor=anchor))
    return diags


def _check_closures(df) -> list[Diagnostic]:
    """WF301/WF302 over every user function object shared by >= 2
    runtime nodes (the replica-sharing that makes captured state a
    cross-thread race)."""
    fns: dict[int, list] = {}
    for node in df.nodes:
        for leaf in _leaf_nodes(node):
            fn = getattr(leaf, "fn", None)
            if fn is not None and hasattr(fn, "__code__"):
                fns.setdefault(id(fn), []).append((fn, leaf))
    diags = []
    for group in fns.values():
        if len(group) < 2:
            continue
        fn, leaf = group[0]
        owner = leaf.name.rsplit(".", 1)[0]
        diags.extend(analyze_function(fn, len(group), owner))
    return diags


def _check_effects(df) -> list[Diagnostic]:
    """WF303/304/305 (check/effects.py) over user functions whose node
    contracts arm an effect family: recovery+recoverable arms the
    replay checks, a latency-triggered Rescale rule arms the blocking
    check.  One finding per (pattern, call site) — farm replicas share
    the fn, so the walk dedups by pattern name."""
    from .effects import analyze_effects

    ctl = df.control
    diags = []
    seen: set[tuple] = set()
    for node in df.nodes:
        for leaf in _leaf_nodes(node):
            fns = []
            fn = getattr(leaf, "fn", None)
            if fn is not None and hasattr(fn, "__code__"):
                fns.append(fn)
            wfn = getattr(getattr(_core_of(leaf), "winfunc", None),
                          "fn", None)
            if wfn is not None and hasattr(wfn, "__code__"):
                fns.append(wfn)
            if not fns:
                continue
            owner = leaf.name.rsplit(".", 1)[0]
            active = set()
            if (df.recovery is not None
                    and getattr(leaf, "recoverable", False)):
                active |= {"WF303", "WF304"}
            if ctl is not None and hasattr(ctl, "rescale_for"):
                rule = ctl.rescale_for(owner)
                if rule is not None and (
                        getattr(rule, "up_q95_us", None) is not None
                        or getattr(rule, "up_slo_burn", None)
                        is not None):
                    active.add("WF305")
            if not active:
                continue
            for f in fns:
                for d in analyze_effects(f, active, owner):
                    key = (d.code, owner, d.anchor)
                    if key in seen:
                        continue
                    seen.add(key)
                    diags.append(d)
    return diags


def check_dataflow(df, skip_config: bool = False) -> list[Diagnostic]:
    """Every graph-level pass over a built Dataflow; ``skip_config``
    when the caller already ran the pipe-level knob checks (avoids
    duplicate WF207)."""
    diags = []
    if not skip_config:
        diags.extend(check_dataflow_config(df))
    diags.extend(_check_recovery(df))
    diags.extend(_check_routing(df))
    diags.extend(_check_windows(df))
    diags.extend(_check_closures(df))
    diags.extend(_check_effects(df))
    return diags
