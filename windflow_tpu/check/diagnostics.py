"""The ``WF###`` diagnostic catalog — the single source of truth for
code, severity, and one-line meaning.  docs/CHECKS.md documents each
entry and ``tests/test_docs.py`` drift-tests that the doc table and this
catalog list identical ids, the same contract ``obs.events.EVENT_KINDS``
has with the docs/OBSERVABILITY.md event table.

Codes are **append-only**: a released id never changes meaning or
severity family, because suppression directives (``# wf-lint:
disable=WF###``) embedded in user code reference them by id.

Numbering: WF1xx graph/topology, WF2xx configuration conflicts, WF3xx
closure/bytecode analysis.
"""

from __future__ import annotations

ERROR = "error"
WARNING = "warning"

#: code -> (severity, one-line title).  docs/CHECKS.md carries the long
#: form (example, fix, suppression); tests enforce id-set equality.
CATALOG: dict[str, tuple[str, str]] = {
    # -- WF1xx: graph / topology ----------------------------------------
    "WF101": (ERROR,
              "keyed-state workers fed by a non-keyed (round-robin) "
              "emitter: same-key rows split across replicas"),
    "WF102": (WARNING,
              "hopping window (slide > win_len): rows falling in the "
              "inter-window gaps are never aggregated"),
    "WF103": (WARNING,
              "pane factor does not divide the window: pane "
              "decomposition degenerates to gcd-sized panes"),
    # -- WF2xx: configuration conflicts ---------------------------------
    # WF201 retired (id never reused): the native core gained a state
    # ABI, so recovery= over it is supported whenever the loaded .so
    # exports the state symbols — WF215 warns on the stale-.so case.
    "WF202": (ERROR,
              "recovery= over a max_delay_ms device core: wall-clock "
              "flushes make replay emission boundaries nondeterministic"),
    "WF203": (ERROR,
              "recovery= over a fused chain with a non-tail async device "
              "stage: replay cannot regenerate the emission numbering"),
    "WF204": (WARNING,
              "recovery= with a sink not opted into restart: a sink "
              "crash still tears the graph down (side effects cannot be "
              "deduplicated)"),
    "WF205": (ERROR,
              "WireConfig heartbeat >= stall_timeout: a healthy peer's "
              "beats arrive too late and every read stall-times-out"),
    "WF206": (WARNING,
              "heartbeat sender paired with a receiver lacking "
              "stall_timeout: the beats are sent but nothing bounds the "
              "read, so a dead peer still hangs forever"),
    "WF207": (WARNING,
              "metrics=/sample_period= with no resolvable trace_dir: "
              "the sampler runs but metrics.jsonl/events.jsonl are "
              "never written"),
    "WF208": (ERROR,
              "shed/put_deadline overload knobs on unbounded inboxes "
              "(capacity <= 0): the queue never fills, so the knobs are "
              "inert while memory grows without bound"),
    "WF209": (WARNING,
              "control= set without metrics=/sample_period=: the "
              "controller's only sensor is the sampler, so every rule "
              "is silently inert"),
    "WF210": (ERROR,
              "Rescale rule targets a pattern that cannot migrate "
              "keyed state (recoverable opted out, or not "
              "key-partitioned): the migration cut can never seal"),
    "WF211": (ERROR,
              "control= has Rescale rules but recovery= is unset: live "
              "rescale seals at epoch barriers, which only a "
              "RecoveryPolicy's triggers inject"),
    "WF212": (ERROR,
              "Rescale rule targets a pattern name not wired into the "
              "graph: the controller refuses to attach at run()"),
    "WF213": (WARNING,
              "trace= with no resolvable trace_dir: sampled spans stay "
              "in the bounded in-memory ring and trace.jsonl is never "
              "written"),
    "WF214": (WARNING,
              "WireConfig resume= without recovery=: no sealed-epoch "
              "acks flow back, so the sender journal can never trim and "
              "fills to its cap"),
    "WF215": (WARNING,
              "recovery=/Rescale over a native core whose loaded .so "
              "lacks the state ABI: default execution runs, but the "
              "first snapshot or migration declines with "
              "SnapshotUnsupported"),
    "WF216": (WARNING,
              "plane supervisor/rolling restart over a wire without "
              "resume=: at handoff the dead process's in-flight frames "
              "have no journal to replay from and are silently lost"),
    "WF217": (WARNING,
              "federate= set without metrics=/sample_period=: the "
              "shipper's only source is the sampler, so no snapshot is "
              "ever shipped and federation is silently inert"),
    # -- WF22x: plane topology (cross-process, check/plane.py) ----------
    "WF220": (ERROR,
              "plane topology broken: a host ships rows to a pid with "
              "no declared address/spec, two hosts claim one address, "
              "or the address book and host specs disagree on the pid "
              "set"),
    "WF221": (ERROR,
              "row dtype mismatch across a plane edge: the sender's "
              "row dtype is not what the receiver expects, so every "
              "decoded batch is garbage (or the decoder rejects it)"),
    "WF222": (ERROR,
              "resume= on only one end of a plane edge: a journaling "
              "sender facing a non-resuming receiver (or vice versa) "
              "breaks the resume handshake at reconnect"),
    "WF223": (WARNING,
              "PlanePolicy supervision declared but no host offers a "
              "ckpt_sink/portable-spool replica target: a takeover has "
              "no portable checkpoint to restore from, so cross-host "
              "recovery silently degrades to an empty restart"),
    "WF224": (ERROR,
              "federation shipping misrouted: a host federates but no "
              "host aggregates the plane's telemetry, or two hosts "
              "claim the aggregator role for one plane"),
    # -- WF3xx: closure race analysis -----------------------------------
    "WF301": (WARNING,
              "user function shared by parallel replicas mutates "
              "closed-over mutable state: probable data race"),
    "WF302": (WARNING,
              "user function shared by parallel replicas rebinds a "
              "module global: probable data race"),
    # -- WF30x: effect analysis (check/effects.py) ----------------------
    "WF303": (WARNING,
              "nondeterministic call (time/random/uuid/os.urandom/"
              "numpy RNG) in a recovery=-recoverable node without a "
              "captured seeded generator: replay after a crash "
              "re-executes the fn and diverges from the journal"),
    "WF304": (WARNING,
              "external side effect (file/socket/subprocess/HTTP) in a "
              "node opted into restart: replay re-fires the effect — "
              "no downstream edge can deduplicate it"),
    "WF305": (WARNING,
              "blocking call (sleep/untimed acquire/blocking recv) in "
              "a node governed by a latency-triggered Rescale rule: "
              "self-inflicted q95/SLO-burn skew triggers phantom "
              "rescales"),
}


class CheckWarning(UserWarning):
    """Category for ``check='warn'`` diagnostics (and the engine's
    stand-alone WF207 silent-no-op warning)."""


class Diagnostic:
    """One finding: a catalog code plus the specific site."""

    __slots__ = ("code", "severity", "message", "node", "anchor",
                 "suppressed")

    def __init__(self, code: str, message: str, node: str = None,
                 anchor: tuple[str, int] = None):
        if code not in CATALOG:
            raise KeyError(f"unknown diagnostic code {code!r} "
                           f"(add it to check.diagnostics.CATALOG)")
        self.code = code
        self.severity = CATALOG[code][0]
        self.message = message
        #: canonical node id (tracing.node_stats_name) or node name,
        #: when the finding pins to one node
        self.node = node
        #: (filename, lineno) source anchor, when one is known — pattern
        #: construction sites and closure bytecode carry these
        self.anchor = anchor
        self.suppressed = False

    def where(self) -> str:
        if self.anchor:
            return f"{self.anchor[0]}:{self.anchor[1]}"
        return self.node or "<config>"

    def __str__(self):
        loc = f" [{self.where()}]" if (self.anchor or self.node) else ""
        return f"{self.code} {self.severity}: {self.message}{loc}"

    def __repr__(self):
        return f"<Diagnostic {self.code} {self.where()}>"


class CheckReport:
    """Ordered collection of diagnostics with suppression applied at
    :meth:`finish` (``# wf-lint: disable=WF###`` at the anchor line —
    check/directives.py)."""

    def __init__(self):
        self.diagnostics: list[Diagnostic] = []
        self.suppressed: list[Diagnostic] = []

    def add(self, diag: Diagnostic):
        self.diagnostics.append(diag)

    def extend(self, diags):
        self.diagnostics.extend(diags)

    def finish(self) -> "CheckReport":
        """Partition out anchor-line-suppressed diagnostics; idempotent."""
        from .directives import suppressed_at
        keep, drop = [], []
        for d in self.diagnostics:
            if d.anchor and suppressed_at(d.anchor[0], d.anchor[1], d.code):
                d.suppressed = True
                drop.append(d)
            else:
                keep.append(d)
        self.diagnostics = keep
        self.suppressed.extend(drop)
        return self

    @property
    def has_errors(self) -> bool:
        return any(d.severity == ERROR for d in self.diagnostics)

    def codes(self) -> set[str]:
        return {d.code for d in self.diagnostics}

    def __len__(self):
        return len(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def render(self) -> str:
        if not self.diagnostics:
            return "no diagnostics"
        return "\n".join(str(d) for d in self.diagnostics)


class CheckError(RuntimeError):
    """Raised by ``check='error'`` before any node thread starts; carries
    the full report on ``.report``."""

    def __init__(self, report: CheckReport):
        self.report = report
        errs = [d for d in report if d.severity == ERROR]
        head = (f"{len(errs)} error diagnostic"
                f"{'s' if len(errs) != 1 else ''} "
                f"(and {len(report) - len(errs)} warning(s)); "
                f"docs/CHECKS.md documents each code, `# wf-lint: "
                f"disable=<code>` at the anchor line suppresses one")
        super().__init__(head + "\n" + report.render())
