"""Closure race analyzer (WF3xx): bytecode inspection of user functions
shared by parallel replicas.

A pattern built with ``parallelism > 1`` hands the SAME function object
to every replica thread (patterns/basic.py ``_make_replica``).  Captured
state is therefore shared across threads, and a function that *mutates*
a closed-over list/dict — ``sent[0] += n``, ``counts.update(...)`` — or
rebinds a closed-over variable (``STORE_DEREF``) is a probable data
race: the classic "my benchmark counter loses increments at pardegree 4"
bug the C++ reference cannot even express (its functors are copied per
replica).

Heuristics, deliberately conservative:

* only functions actually shared by >= 2 runtime nodes are analyzed;
* only free variables whose **live cell contents** are mutable
  containers (list/dict/set/bytearray/ndarray) can trigger the
  mutation checks — captured ints, schemas, and callables never flag;
* a function that also captures a ``threading`` lock (Lock/RLock/
  Semaphore/Condition) is skipped entirely: the author synchronised,
  and the analyzer cannot see critical-section extents;
* ``# wf-lint: disable=WF301`` on the offending line or the ``def``
  line suppresses (check/directives.py).
"""

from __future__ import annotations

import dis

from .diagnostics import Diagnostic
from .directives import suppressed_at

#: method names that mutate their receiver — flagging a call of one on a
#: closed-over container
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "sort", "reverse",
    "appendleft", "extendleft", "popleft", "rotate", "fill", "put",
    "__setitem__",
})

import collections as _collections  # noqa: E402  (stdlib, import-light)

_MUTABLE_TYPES = (list, dict, set, bytearray, _collections.deque)

#: 3.10 spells augmented assignment as dedicated opcodes; 3.11+ folds
#: them into BINARY_OP whose argrepr carries the ``=`` (e.g. ``+=``)
_INPLACE_OPS = frozenset({
    "INPLACE_ADD", "INPLACE_SUBTRACT", "INPLACE_MULTIPLY",
    "INPLACE_TRUE_DIVIDE", "INPLACE_FLOOR_DIVIDE", "INPLACE_MODULO",
    "INPLACE_POWER", "INPLACE_LSHIFT", "INPLACE_RSHIFT", "INPLACE_AND",
    "INPLACE_OR", "INPLACE_XOR", "INPLACE_MATRIX_MULTIPLY",
})


def _is_inplace(ins) -> bool:
    if ins.opname in _INPLACE_OPS:
        return True
    return (ins.opname == "BINARY_OP"
            and "=" in (getattr(ins, "argrepr", "") or ""))


def _is_mutable_cell(value) -> bool:
    if isinstance(value, _MUTABLE_TYPES):
        return True
    # numpy arrays without importing numpy here
    return type(value).__name__ == "ndarray"


def _is_lock(value) -> bool:
    name = type(value).__name__
    mod = type(value).__module__
    return (mod in ("_thread", "threading")
            and name in ("lock", "LockType", "RLock", "_RLock", "Lock",
                         "Semaphore", "BoundedSemaphore", "Condition"))


def _cells(fn) -> dict[str, object]:
    """freevar name -> live cell content (unset cells are skipped)."""
    code = getattr(fn, "__code__", None)
    closure = getattr(fn, "__closure__", None)
    if code is None or not closure:
        return {}
    out = {}
    for name, cell in zip(code.co_freevars, closure):
        try:
            out[name] = cell.cell_contents
        except ValueError:       # cell not yet filled
            continue
    return out


def analyze_function(fn, shared_by: int, owner: str) -> list[Diagnostic]:
    """WF301/WF302 findings for ``fn`` running concurrently in
    ``shared_by`` replica threads of pattern/node ``owner``."""
    code = getattr(fn, "__code__", None)
    if code is None or shared_by < 2:
        return []
    cells = _cells(fn)
    if any(_is_lock(v) for v in cells.values()):
        return []        # author synchronised: trust the lock
    mutable = {n for n, v in cells.items() if _is_mutable_cell(v)}
    filename = code.co_filename
    def_line = code.co_firstlineno

    diags: list[Diagnostic] = []
    seen: set[tuple[str, str, int]] = set()

    def flag(codeid, msg, line):
        key = (codeid, msg, line or def_line)
        if key in seen:
            return
        seen.add(key)
        if suppressed_at(filename, line or def_line, codeid,
                         also_lines=(def_line,)):
            return
        diags.append(Diagnostic(codeid, msg, node=owner,
                                anchor=(filename, line or def_line)))

    fname = getattr(fn, "__qualname__", getattr(fn, "__name__", "<fn>"))
    line = def_line
    #: freevar names whose value is on the stack "recently" — a cheap
    #: window: a LOAD_DEREF of a mutable freevar arms the next
    #: subscript-store / mutator-call on the same source line
    armed: dict[str, int] = {}    # container itself on the stack
    derived: dict[str, int] = {}  # value read OUT of a closed container
    pending_method: tuple[str, int] | None = None
    #: (var, line) pairs already reported as in-place mutations — the
    #: compiler follows the INPLACE op with a STORE_DEREF rebind of the
    #: same name, which must not double-flag
    inplace_hit: set[tuple[str, int]] = set()
    prev = prev_val = ""
    for ins in dis.get_instructions(code):
        sl = ins.starts_line
        if sl:   # int on <= 3.12, True on 3.13+ (line_number carries it)
            line = getattr(ins, "line_number", None) or int(sl)
            armed.clear()
            derived.clear()
            pending_method = None
        op = ins.opname
        # 3.10 spells the augmented-subscript pair-duplication
        # DUP_TOP_TWO; 3.11+ spells it as two COPY instructions
        if (op == "BINARY_SUBSCR" and armed
                and prev not in ("DUP_TOP_TWO", "COPY")):
            # a plain read (`x = closed[k]`) consumed the container — a
            # later same-line STORE_SUBSCR targets something else, but a
            # mutating METHOD on the read-out value (`closed[k].append`)
            # still mutates shared state.  The augmented form
            # (`closed[k] += v`) duplicates the pair first
            # (DUP_TOP_TWO), so the container stays the store's target.
            derived.update(armed)
            armed.clear()
        if op in ("STORE_DEREF", "DELETE_DEREF") \
                and ins.argval in code.co_freevars:
            if (ins.argval, line) not in inplace_hit:
                flag("WF301",
                     f"{fname!r} ({owner}, parallelism {shared_by}) "
                     f"rebinds closed-over {ins.argval!r} from parallel "
                     f"replicas", line)
        elif op in ("STORE_GLOBAL", "DELETE_GLOBAL"):
            flag("WF302",
                 f"{fname!r} ({owner}, parallelism {shared_by}) rebinds "
                 f"module global {ins.argval!r} from parallel replicas",
                 line)
        elif op == "LOAD_DEREF" and ins.argval in mutable:
            armed[ins.argval] = line
        elif _is_inplace(ins) and armed:
            # `closed[k] += v` / `closed += [v]`: the in-place op runs
            # on the shared container (read-modify-write, the classic
            # lost-increment race).  Consume `armed` so the compiler's
            # trailing STORE_SUBSCR does not flag the same site twice.
            var, at = next(iter(armed.items()))
            flag("WF301",
                 f"{fname!r} ({owner}, parallelism {shared_by}) "
                 f"augments closed-over {type(cells[var]).__name__} "
                 f"{var!r} in place (read-modify-write) from parallel "
                 f"replicas", at)
            inplace_hit.add((var, at))
            armed.clear()
        elif op in ("STORE_SUBSCR", "DELETE_SUBSCR") and armed:
            var, at = next(iter(armed.items()))
            flag("WF301",
                 f"{fname!r} ({owner}, parallelism {shared_by}) writes "
                 f"into closed-over {type(cells[var]).__name__} "
                 f"{var!r} from parallel replicas", at)
            armed.clear()
        elif op in ("LOAD_METHOD", "LOAD_ATTR") and (armed or derived):
            # receiver-aware: the attribute is ON the shared container
            # only when the previous instruction put that container (or
            # a value read out of it) on top of the stack — an
            # unrelated receiver (`counts[b.x] += 1` loading `b.x`)
            # must not disarm the pending container
            on_container = ((prev == "LOAD_DEREF" and prev_val in armed)
                            or (prev == "BINARY_SUBSCR" and derived))
            if on_container:
                if ins.argval in _MUTATORS:
                    var, at = next(iter((armed or derived).items()))
                    pending_method = (var, at)
                armed.clear()
                derived.clear()
        elif op.startswith("CALL") and pending_method is not None:
            var, at = pending_method
            flag("WF301",
                 f"{fname!r} ({owner}, parallelism {shared_by}) calls a "
                 f"mutating method on closed-over "
                 f"{type(cells[var]).__name__} {var!r} from parallel "
                 f"replicas", at)
            pending_method = None
        prev, prev_val = op, ins.argval
    return diags
