"""windflow_tpu — a TPU-native stream-processing framework with the
capabilities of the reference WindFlow library (C++/CUDA, see SURVEY.md).

This umbrella module mirrors the reference's ``windflow.hpp`` +
``windflow_gpu.hpp`` include sets: everything a user application needs —
patterns, builders, MultiPipe — importable from the top level.  The
device-backed patterns (``*TPU``) are the ``windflow_gpu.hpp:33-38``
equivalents.
"""

from .api import (LEVEL0, LEVEL1, LEVEL2, Accumulator_Builder,
                  Filter_Builder, FlatMap_Builder, KeyFarm_Builder,
                  KeyFarmTPU_Builder, Map_Builder, MultiPipe,
                  PaneFarm_Builder, PaneFarmTPU_Builder, Sink_Builder,
                  Source_Builder, WinFarm_Builder, WinFarmTPU_Builder,
                  WinMapReduce_Builder, WinMapReduceTPU_Builder,
                  WinSeq_Builder, WinSeqTPU_Builder, union_multipipes)
from .core.tuples import Schema, batch_from_columns
from .core.windows import WinType
from .ops.functions import (FnWindowFunction, FnWindowUpdate, MultiReducer,
                            Reducer, WindowFunction, WindowUpdate)
from .patterns.basic import (Accumulator, Filter, FlatMap, Map, Shipper,
                             Sink, Source)
from .patterns.key_farm import KeyFarm
from .patterns.nesting import KeyFarmOf, WinFarmOf
from .patterns.pane_farm import PaneFarm
from .patterns.win_farm import WinFarm
from .patterns.win_mapreduce import WinMapReduce
from .patterns.win_seq import WinSeq
from .patterns.win_seq_tpu import (JaxWindowFunction, KeyFarmTPU,
                                   PaneFarmTPU, WinFarmTPU, WinMapReduceTPU,
                                   WinSeqTPU)
from .obs import EventLog, MetricsRegistry
from .recovery import CheckpointStore, EpochMarker, RecoveryPolicy
from .runtime.node import RuntimeContext
from .runtime.overload import DeadLetter, OverloadError, OverloadPolicy

__version__ = "0.1.0"

__all__ = [
    # core
    "Schema", "batch_from_columns", "WinType", "RuntimeContext",
    # window-function contracts
    "WindowFunction", "WindowUpdate", "FnWindowFunction", "FnWindowUpdate",
    "Reducer", "MultiReducer", "JaxWindowFunction",
    # patterns
    "Source", "Map", "Filter", "FlatMap", "Accumulator", "Sink", "Shipper",
    "WinSeq", "WinFarm", "KeyFarm", "PaneFarm", "WinMapReduce",
    "WinFarmOf", "KeyFarmOf",
    "WinSeqTPU", "WinFarmTPU", "KeyFarmTPU", "PaneFarmTPU",
    "WinMapReduceTPU",
    # composition
    "MultiPipe", "union_multipipes",
    "Source_Builder", "Filter_Builder", "Map_Builder", "FlatMap_Builder",
    "Accumulator_Builder", "Sink_Builder", "WinSeq_Builder",
    "WinFarm_Builder", "KeyFarm_Builder", "PaneFarm_Builder",
    "WinMapReduce_Builder", "WinSeqTPU_Builder", "WinFarmTPU_Builder",
    "KeyFarmTPU_Builder", "PaneFarmTPU_Builder", "WinMapReduceTPU_Builder",
    "LEVEL0", "LEVEL1", "LEVEL2",
    # robustness (docs/ROBUSTNESS.md)
    "OverloadPolicy", "OverloadError", "DeadLetter",
    "RecoveryPolicy", "CheckpointStore", "EpochMarker",
    # observability (docs/OBSERVABILITY.md)
    "MetricsRegistry", "EventLog",
]
