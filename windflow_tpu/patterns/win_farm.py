"""Win_Farm: window parallelism — windows are assigned round-robin to
workers, each worker running the sequential core with a private slide of
``slide * pardegree`` (reference win_farm.hpp:134-143).

The emitter multicasts each tuple to exactly the workers whose windows
contain it (wf_nodes.hpp:90-174); in the reference this uses a refcounted
shared wrapper to avoid copies — here batches are immutable arrays, so the
per-worker "copy" is a numpy boolean take of the batch (and the device-side
analog goes further: the archive slice is staged once, see ops/device).

At EOS the emitter replays each key's last tuple to ALL workers as an EOS
marker (wf_nodes.hpp:177-191) so every worker opens/fires the same trailing
windows Win_Seq would have.
"""

from __future__ import annotations

import numpy as np

from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..runtime.emitters import Collector, KeyedStreamState
from ..runtime.node import Node, RuntimeContext
from ..runtime.ordering import OrderingCore, OrderingMode
from .basic import _Pattern
from .win_seq import WinSeq, WinSeqNode

_NEG_INF = np.int64(-(2 ** 62))


class WFEmitterNode(Node):
    """Window-range multicast emitter (wf_nodes.hpp:40-195)."""

    quarantine_exempt = True    # framework shell: errors here fail fast
    shed_safe = True            # farm head: shedding drops raw stream rows
    #: recovery: the per-key last-tuple bookkeeping snapshots on the
    #: numpy path; the native keymap path raises SnapshotUnsupported
    #: (emitters.KeyedStreamState.state_snapshot)
    recoverable = True

    def state_snapshot(self):
        snap = self._state.state_snapshot()
        if snap is None:
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                f"{self.name}: native keymap state is not snapshotable")
        return snap

    def state_restore(self, snap):
        self._state.state_restore(snap)

    def __init__(self, spec: WindowSpec, pardegree: int, id_outer=0, n_outer=1,
                 slide_outer=None, role: Role = Role.SEQ, name="wf_emitter"):
        super().__init__(name)
        self.spec = spec
        self.pardegree = pardegree
        self.id_outer = id_outer
        self.n_outer = n_outer
        self.slide_outer = spec.slide_len if slide_outer is None else slide_outer
        self.role = role
        self.pos_field = "id" if spec.win_type is WinType.CB else "ts"
        self._state = KeyedStreamState(self.pos_field)

    def _initial_id(self, keys: np.ndarray) -> np.ndarray:
        first_gwid = (self.id_outer - (keys % self.n_outer) + self.n_outer) % self.n_outer
        init = first_gwid * self.slide_outer
        if self.role in (Role.WLQ, Role.REDUCE):
            init = np.zeros_like(init)
        return init

    def svc(self, batch, channel=0):
        spec = self.spec
        # marker absorption + out-of-order drop (wf_nodes.hpp:104-121)
        batch = self._state.filter(batch)
        if len(batch) == 0:
            return
        pos = self._state.pos_cache   # contiguous copy filter already made
        if pos is None:
            pos = batch[self.pos_field].astype(np.int64)
        keys = batch["key"]
        if self.n_outer == 1:
            rel = pos          # non-nested: _initial_id is identically 0
        else:
            rel = pos - self._initial_id(keys)
        keep = rel >= 0
        if spec.is_hopping:
            keep &= spec.in_any_window(np.maximum(rel, 0))
        if not np.all(keep):
            batch = batch[keep]
            rel = rel[keep]
            keys = keys[keep]
        if len(batch) == 0:
            return
        # window range per row (wf_nodes.hpp:134-157)
        first_w = spec.first_win_containing(rel)
        last_w = spec.last_win_containing(rel)
        count = last_w - first_w + 1
        n = self.pardegree
        # steady state of sliding windows (win > slide): every row belongs
        # to >= pardegree windows, so every worker gets every row — detect
        # it once and multicast the SAME array instead of gathering a full
        # copy per worker (workers only read; ~2x the stream size saved
        # per batch on the pipe benchmark)
        if count.min() >= n:
            for d in range(n):
                self.emit_to(d, batch)
            return
        start_dst = (keys & (n - 1)) if n & (n - 1) == 0 else keys % n
        for d in range(n):
            # worker d gets the row iff some w in [first, first+min(count,n))
            # satisfies (key%n + w) % n == d
            r = (d - start_dst - first_w) % n
            m = (count >= n) | (r < count)
            sub = batch[m]
            if len(sub):
                self.emit_to(d, sub)

    def eosnotify(self):
        # per-key EOS markers to every worker (wf_nodes.hpp:177-191)
        markers = self._state.marker_batch()
        if markers is None:
            return
        for d in range(self.pardegree):
            self.emit_to(d, markers)


class WFCollectorNode(Node):
    """Ordered collector: per-key reorder over dense result ids
    (wf_nodes.hpp:401-468), fully vectorised — pending rows of ALL keys are
    one buffer; the releasable contiguous id-run per key is a segmented
    prefix test over a (key, id) lexsort, and each svc emits at most ONE
    batch (per-key tiny emits would turn 10^5 keys into 10^5 downstream
    svc calls)."""

    quarantine_exempt = True    # framework shell: errors here fail fast
    recoverable = True          # reorder state is plain numpy data

    def __init__(self, name="wf_collector"):
        super().__init__(name)
        from ..core.slots import SlotMap
        self._slots = SlotMap(on_register=self._on_register)
        self._next = np.zeros(0, dtype=np.int64)   # slot -> next expected id
        self._pend_rows = None                     # structured array
        self._pend_slots = np.zeros(0, dtype=np.int64)

    def state_snapshot(self):
        return {
            "slots": self._slots.state_snapshot(),
            "next": self._next.copy(),
            "pend_rows": (None if self._pend_rows is None
                          else self._pend_rows.copy()),
            "pend_slots": self._pend_slots.copy(),
        }

    def state_restore(self, snap):
        self._slots.state_restore(snap["slots"])
        self._next = snap["next"].copy()
        self._pend_rows = (None if snap["pend_rows"] is None
                           else snap["pend_rows"].copy())
        self._pend_slots = snap["pend_slots"].copy()

    def _on_register(self, new_keys):
        self._next = np.concatenate(
            (self._next, np.zeros(len(new_keys), dtype=np.int64)))

    def svc(self, batch, channel=0):
        slots = self._slots.lookup(batch["key"].astype(np.int64, copy=False))
        if self._pend_rows is not None and len(self._pend_rows):
            # only slots present in this batch can make progress (release
            # needs new rows; _next only advances on release) — leave the
            # rest of the pending buffer untouched instead of re-sorting it
            touched = np.isin(self._pend_slots, slots)
            if touched.any():
                rows = np.concatenate((self._pend_rows[touched], batch))
                slots = np.concatenate((self._pend_slots[touched], slots))
                unt = ~touched
                self._pend_rows = self._pend_rows[unt] if unt.any() else None
                self._pend_slots = (self._pend_slots[unt] if unt.any()
                                    else np.zeros(0, dtype=np.int64))
            else:
                rows = batch
        else:
            rows = batch
        ids = rows["id"].astype(np.int64, copy=False)
        order = np.lexsort((ids, slots))
        s = slots[order]
        sid = ids[order]
        starts = np.concatenate(([0], np.flatnonzero(np.diff(s)) + 1))
        rank = np.arange(len(s), dtype=np.int64)
        rank -= np.repeat(starts, np.diff(np.concatenate((starts, [len(s)]))))
        ok = sid == self._next[s] + rank
        # release the per-segment all-ok prefix: rows before a segment's
        # first gap (segmented cumulative-bad == 0)
        bad_cum = np.cumsum(~ok)
        seg_base = np.repeat(bad_cum[starts] - (~ok[starts]),
                             np.diff(np.concatenate((starts, [len(s)]))))
        release = (bad_cum - seg_base) == 0
        if release.any():
            n_rel = np.add.reduceat(release, starts)
            u = s[starts]
            self._next[u] += n_rel
            out = rows[order[release]]
            keep = ~release
            held = rows[order[keep]] if keep.any() else None
            held_slots = slots[order[keep]] if keep.any() else None
            self._stash(held, held_slots)
            self.emit(out)
        else:
            self._stash(rows[order], s)

    def _stash(self, held, held_slots):
        """Park unreleased rows, joining any untouched pending buffer."""
        if held is None:
            return  # untouched pending (if any) already lives in _pend_rows
        if self._pend_rows is not None and len(self._pend_rows):
            self._pend_rows = np.concatenate((self._pend_rows, held))
            self._pend_slots = np.concatenate((self._pend_slots, held_slots))
        else:
            self._pend_rows = held
            self._pend_slots = held_slots


class _OrderedWorkerNode(WinSeqNode):
    """OrderingCore fused in front of a window core — the
    ff_comb(OrderingNode, Win_Seq) worker used behind multiple emitters
    (win_farm.hpp:157-162)."""

    def __init__(self, core, n_channels, mode, name, per_key=False):
        super().__init__(core, name)
        # per_key=True for merges of per-key-renumbered producer streams
        # (LEVEL2 fusion); plain multi-emitter splits are globally
        # monotone per channel and keep the liveness-preserving global
        # watermark (see OrderingCore)
        self.ordering = OrderingCore(n_channels, mode,
                                     per_key_watermarks=per_key)

    def state_snapshot(self):
        merge = self.ordering.state_snapshot()
        if merge is None:
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                f"{self.name}: native renumbering counters are not "
                "snapshotable")
        snap = super().state_snapshot()
        snap["ordering"] = merge
        return snap

    def state_restore(self, snap):
        self.ordering.state_restore(snap["ordering"])
        super().state_restore({k: v for k, v in snap.items()
                               if k != "ordering"})

    def svc_init(self):
        if self.n_input_channels != self.ordering.n_channels:
            raise RuntimeError(
                f"{self.name}: wired with {self.n_input_channels} input "
                f"channels but ordering expects {self.ordering.n_channels} "
                "(n_emitters mismatch — results would buffer until EOS)")

    def svc(self, batch, channel=0):
        for merged in self.ordering.push(batch, channel):
            super().svc(merged)

    def on_channel_eos(self, channel):
        for merged in self.ordering.channel_eos(channel):
            super().svc(merged)

    def eosnotify(self):
        for merged in self.ordering.flush():
            WinSeqNode.svc(self, merged)
        super().eosnotify()


class WinFarm(_Pattern):
    """Window-parallel farm of sequential cores (win_farm.hpp)."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, name="win_farm", incremental=None,
                 result_fields=None, ordered=True, n_emitters=1,
                 config: PatternConfig = None, role: Role = Role.SEQ):
        super().__init__(name, pardegree)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self.ordered = ordered
        self.n_emitters = n_emitters
        #: LEVEL2 fusion flips this: the fused upstreams emit per-key
        #: renumbered ids, so the workers' merge needs per-key watermarks
        self.ordering_per_key = False
        self.config = config or PatternConfig.plain(slide_len)
        self.role = role
        # worker template: private slide, nested PatternConfig
        # (win_farm.hpp:134-143)
        self._workers = []
        for i in range(pardegree):
            cfg = PatternConfig(
                id_outer=self.config.id_inner, n_outer=self.config.n_inner,
                slide_outer=self.config.slide_inner,
                id_inner=i, n_inner=pardegree, slide_inner=slide_len)
            self._workers.append(WinSeq(
                winfunc, win_len, slide_len * pardegree, win_type,
                name=f"{name}_wf.{i}", incremental=incremental,
                result_fields=result_fields, config=cfg, role=role,
                result_ts_slide=slide_len))

    @property
    def result_schema(self):
        return self._workers[0].result_schema

    def emitter(self):
        return WFEmitterNode(self.spec, self.parallelism,
                             id_outer=self.config.id_inner,
                             n_outer=self.config.n_inner,
                             slide_outer=self.config.slide_inner,
                             role=self.role, name=f"{self.name}.emitter")

    def collector(self):
        if self.ordered:
            return WFCollectorNode(name=f"{self.name}.collector")
        return Collector(name=f"{self.name}.collector")

    def _make_core(self, worker: WinSeq, i=0):
        """Core-factory hook: TPU farms override to build device cores
        (worker index `i` drives per-worker device placement)."""
        return worker.make_core()

    def _make_replica(self, i):
        core = self._make_core(self._workers[i], i)
        if self.n_emitters > 1:
            mode = OrderingMode.ID if self.spec.win_type is WinType.CB else OrderingMode.TS
            node = _OrderedWorkerNode(core, self.n_emitters, mode,
                                      f"{self.name}.{i}",
                                      per_key=self.ordering_per_key)
        else:
            node = WinSeqNode(core, f"{self.name}.{i}")
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node
