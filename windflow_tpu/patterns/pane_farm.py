"""Pane_Farm: pane decomposition of sliding windows — a two-stage pipeline
(reference pane_farm.hpp).

Stage 1 (PLQ, pane-level query) computes per-pane partials over *tumbling*
panes of length ``gcd(win, slide)`` (pane_farm.hpp:148-162); its results are
renumbered to a dense per-key pane index (the PLQ role renumbering,
win_seq.hpp:401-404).  Stage 2 (WLQ, window-level query) combines
``win/pane`` consecutive pane-results per window as a *count-based* window
of length ``win/pane`` sliding by ``slide/pane`` over the pane stream
(pane_farm.hpp:168-175).  Either stage can be a Win_Seq (degree 1) or an
ordered Win_Farm (degree > 1), and each stage independently accepts a
non-incremental or incremental user function (the reference's 4 constructor
families, pane_farm.hpp:105-418).

This is the streaming analog of a two-level blockwise reduction — on the
TPU it maps onto segmented partial reductions per core merged over ICI
(SURVEY.md §5 long-context note).
"""

from __future__ import annotations

from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from .win_farm import WinFarm
from .win_seq import WinSeq


class PaneFarm:
    """Composite two-stage pattern; wired by `instantiate` (used via
    add_farm / MultiPipe)."""

    def __init__(self, plq_func, wlq_func, win_len, slide_len,
                 win_type=WinType.CB, plq_degree=1, wlq_degree=1,
                 name="pane_farm", plq_incremental=None, wlq_incremental=None,
                 plq_result_fields=None, wlq_result_fields=None, ordered=True,
                 config: PatternConfig = None, opt_level: int = 0):
        if win_len <= slide_len:
            raise ValueError(
                "Pane_Farm requires sliding windows (slide < win), "
                "pane_farm.hpp:143")
        # keep construction parameters so nesting farms can replicate this
        # pattern with overridden slide/config (win_farm.hpp:376-389)
        self._proto = dict(
            plq_func=plq_func, wlq_func=wlq_func, win_len=win_len,
            slide_len=slide_len, win_type=win_type, plq_degree=plq_degree,
            wlq_degree=wlq_degree, plq_incremental=plq_incremental,
            wlq_incremental=wlq_incremental,
            plq_result_fields=plq_result_fields,
            wlq_result_fields=wlq_result_fields, opt_level=opt_level)
        self.opt_level = opt_level
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self.pane_len = self.spec.pane_len()
        self.win_type = win_type
        self.plq_degree = plq_degree
        self.wlq_degree = wlq_degree
        self.name = name
        self.ordered = ordered
        self.config = config or PatternConfig.plain(slide_len)
        from .basic import user_call_site
        #: construction-site anchor for check/ diagnostics (WF103)
        self.anchor = user_call_site()
        cfg = self.config
        pane = self.pane_len
        # --- PLQ stage: tumbling panes, role PLQ (pane_farm.hpp:152-162) ---
        self.plq = self._make_stage(
            "plq", plq_func, pane, pane, win_type, plq_degree,
            name=f"{name}_plq", incremental=plq_incremental,
            result_fields=plq_result_fields, ordered=True, role=Role.PLQ)
        # --- WLQ stage: CB window over the dense pane stream
        # --- (pane_farm.hpp:166-175) ---
        self.wlq = self._make_stage(
            "wlq", wlq_func, win_len // pane, slide_len // pane, WinType.CB,
            wlq_degree, name=f"{name}_wlq", incremental=wlq_incremental,
            result_fields=wlq_result_fields, ordered=ordered, role=Role.WLQ)

    def _make_stage(self, which, func, win, slide, wt, degree, name,
                    incremental, result_fields, ordered, role):
        """Build one stage as Win_Seq (degree 1) or ordered Win_Farm —
        overridable for device placement (Pane_Farm_GPU's 4 constructor
        families, pane_farm_gpu.hpp:176-480, become a per-stage override)."""
        cfg = self.config
        if degree > 1:
            return WinFarm(func, win, slide, wt, pardegree=degree, name=name,
                           incremental=incremental,
                           result_fields=result_fields, ordered=ordered,
                           config=cfg, role=role)
        seq_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, slide)
        return WinSeq(func, win, slide, wt, name=name,
                      incremental=incremental, result_fields=result_fields,
                      config=seq_cfg, role=role)

    @property
    def result_schema(self):
        return self.wlq.result_schema

    def instantiate(self, df, upstreams):
        from ..runtime.farm import add_farm, fuse_two_stage
        if self.opt_level >= 1:
            # optimize_PaneFarm (pane_farm.hpp:426-466): LEVEL1 fuses the
            # stage boundary into one thread, LEVEL2 removes the PLQ
            # collector and merges at OrderingCore-fronted WLQ workers
            return fuse_two_stage(df, self.plq, self.wlq, upstreams,
                                  self.opt_level)
        tails = add_farm(df, self.plq, upstreams)
        return add_farm(df, self.wlq, tails)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        """Replicate this pattern as a nested-farm worker (the reference
        rebuilds the Pane_Farm from its stored functions with a private
        slide and worker PatternConfig, win_farm.hpp:376-389)."""
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return PaneFarm(name=name, config=config, ordered=ordered, **kw)
