"""Win_Seq_TPU: the sequential window core with device-batched evaluation —
the TPU graft of the reference's Win_Seq_GPU (win_seq_gpu.hpp).

Same window bookkeeping as the host core (it *is* the host core: one
subclass hook), but fired NIC windows are not evaluated inline: their
(start, len) ranges plus the staged archive slice are queued, and at
``batch_len`` fired windows one XLA computation (or Pallas kernel)
evaluates them all.  Result headers (key, renumbered id, result ts) are
computed host-side at fire time, exactly like the reference pre-fills
``host_results[i].setInfo(...)`` before the kernel (win_seq_gpu.hpp:447-449).
Launches are asynchronous with bounded depth (vs the reference's per-batch
``cudaStreamSynchronize``, :481); results are emitted in launch order, so
per-key result order is preserved.

EOS leftovers run through the same device path padded to the smallest
bucket (the reference instead re-runs the functor on the CPU,
win_seq_gpu.hpp:533-581 — unnecessary here since the contract is a JAX
function, executable on any backend with identical semantics; that also
covers the reference's "host-callable device functor" testing trick).
"""

from __future__ import annotations

import numpy as np

from ..core.tuples import Schema
from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..core.winseq import WinSeqCore
from ..ops.device import DeviceWindowExecutor, builtin_batch_fn
from ..ops.functions import Reducer
from ..runtime.node import RuntimeContext
from .basic import _Pattern
from .key_farm import KeyFarm
from .pane_farm import PaneFarm
from .win_farm import WinFarm
from .win_mapreduce import WinMapReduce
from .win_seq import WinSeqNode


class JaxWindowFunction:
    """User window function for the device path: a JAX-traceable
    ``fn(keys, gwids, cols, mask) -> column(s)`` over a whole window batch
    — the TPU replacement for the reference's CUDA device functor
    ``F(key, gwid, data, res, size, scratch)`` (win_seq_gpu.hpp:54-67,
    deduced at meta_utils.hpp:173-180)."""

    def __init__(self, fn, fields=("value",), result_fields=None):
        self.fn = fn
        self.fields = tuple(fields)
        self.result_fields = dict(result_fields or {"value": np.int64})


def _host_standin(winfunc):
    """Host-side function object carrying the result schema for the
    core/farm template plumbing (the device path never calls it)."""
    if isinstance(winfunc, Reducer):
        return winfunc
    if isinstance(winfunc, JaxWindowFunction):
        r = Reducer("count")
        r.result_fields = dict(winfunc.result_fields)
        return r
    raise TypeError(
        "the device path needs a builtin Reducer or a JaxWindowFunction "
        "(host Python functions cannot be staged to the TPU — same "
        "restriction as the reference's __device__ functor contract)")


class DeviceWinSeqCore(WinSeqCore):
    """WinSeqCore whose fired-window evaluation is device-batched."""

    def __init__(self, spec: WindowSpec, winfunc, batch_len: int = 512,
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide=None, device=None,
                 depth: int = 4, use_pallas: bool = False,
                 compute_dtype=None):
        host_fn = _host_standin(winfunc)
        if isinstance(winfunc, Reducer):
            executor = DeviceWindowExecutor(
                builtin_batch_fn(winfunc.op, winfunc.field),
                fields=winfunc.required_fields,
                out_fields=tuple(winfunc.result_fields),
                device=device, depth=depth, use_pallas=use_pallas,
                op=winfunc.op, compute_dtype=compute_dtype,
                out_dtypes=winfunc.result_fields,
                # empty windows must produce the host-path identity even
                # though device compute may run in a narrower dtype
                empty_fill={winfunc.out_field: winfunc._identity()})
            self._stage_fields = tuple(winfunc.required_fields)
        else:
            executor = DeviceWindowExecutor(
                winfunc.fn, fields=winfunc.fields,
                out_fields=tuple(winfunc.result_fields),
                device=device, depth=depth, compute_dtype=compute_dtype,
                out_dtypes=winfunc.result_fields)
            self._stage_fields = winfunc.fields
        super().__init__(spec, host_fn, config=config, role=role,
                         map_indexes=map_indexes,
                         result_ts_slide=result_ts_slide)
        self.executor = executor
        self.batch_len = batch_len
        # pending windows: list of (segment_cols, starts, lens) + headers
        self._segs = []        # [(cols{f: np}, starts, lens)]
        self._pending = 0
        self._hdr = []         # [(key, ids, ts) per enqueue]

    # -- device-batched NIC evaluation ------------------------------------

    def _emit_windows(self, key, st, lwids, eos: bool):
        spec = self.spec
        gwids = st.first_gwid + lwids * self.config.gwid_stride()
        ts = self._result_ts(st, lwids, gwids)
        ids = self._renumber_ids(key, st, gwids)
        starts_abs = spec.win_start(lwids) + st.initial_id
        ends_abs = spec.win_end(lwids) + st.initial_id
        p = st.archive.positions
        lo = np.searchsorted(p, starts_abs, side="left")
        hi = (np.full(len(lwids), len(p), dtype=np.int64) if eos
              else np.searchsorted(p, ends_abs, side="left"))
        base = int(lo[0]) if len(lo) else 0
        top = int(hi[-1]) if len(hi) else 0
        rows = st.archive.rows[base:top]
        cols = {f: rows[f].copy() for f in self._stage_fields}
        self._segs.append((cols, (lo - base).astype(np.int64),
                           (hi - lo).astype(np.int64),
                           np.full(len(lwids), key, dtype=np.int64), gwids))
        self._hdr.append((key, ids, ts))
        self._pending += len(lwids)
        if not eos and len(lwids):
            st.archive.purge_below(int(starts_abs[-1]))
        if self._pending >= self.batch_len:
            self._flush_batch()
        return None

    def _flush_batch(self):
        if not self._segs:
            return
        flat = {f: [] for f in self._stage_fields}
        starts, lens, keys, gwids = [], [], [], []
        off = 0
        for cols, s, l, k, g in self._segs:
            for f in self._stage_fields:
                flat[f].append(cols[f])
            starts.append(s + off)
            lens.append(l)
            keys.append(k)
            gwids.append(g)
            off += len(next(iter(cols.values()))) if cols else 0
        flat = {f: np.concatenate(v) if v else np.zeros(0, dtype=np.int64)
                for f, v in flat.items()}
        self.executor.launch(
            list(self._hdr), flat,
            np.concatenate(starts), np.concatenate(lens),
            np.concatenate(keys), np.concatenate(gwids))
        self._segs, self._hdr, self._pending = [], [], 0

    # -- harvest ----------------------------------------------------------

    def _build_results(self, harvested):
        outs = []
        for hdr, cols in harvested:
            off = 0
            for key, ids, ts in hdr:
                n = len(ids)
                payload = {f: v[off:off + n] for f, v in cols.items()}
                outs.append(self._make_results(key, ids, ts, payload))
                off += n
        return outs

    def process(self, batch):
        super().process(batch)  # fired windows are enqueued, not returned
        outs = self._build_results(self.executor.poll())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    def flush(self):
        super().flush()         # enqueue EOS leftovers
        self._flush_batch()     # launch the partial batch
        outs = self._build_results(self.executor.drain())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    def use_incremental(self):
        raise TypeError("the device path is non-incremental only "
                        "(win_seq_gpu.hpp supports NIC device functors)")


def make_device_core(worker, fn, dev_kw) -> DeviceWinSeqCore:
    """Build the device-batched core for a prototype host worker (a WinSeq
    carrying the farm's per-worker spec/config/role plumbing)."""
    return DeviceWinSeqCore(worker.spec, fn, config=worker.config,
                            role=worker.role, map_indexes=worker.map_indexes,
                            result_ts_slide=worker.result_ts_slide, **dev_kw)


class _DeviceCoreFactory:
    """Mixin for farm variants whose workers are device-batched: the host
    farm builds its prototype workers, `_make_core` swaps in the device
    core (set `_raw_fn` and `_dev_kw` before calling the farm ctor)."""

    def _make_core(self, worker):
        return make_device_core(worker, self._raw_fn, self._dev_kw)


class WinSeqTPU(_Pattern):
    """Sequential TPU window pattern (reference Win_Seq_GPU builder shape:
    withBatch(batch_len) replaces withBatch(batch_len, n_thread_block))."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 batch_len=512, name="win_seq_tpu",
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide=None, device=None,
                 depth=4, use_pallas=False, compute_dtype=None):
        super().__init__(name, parallelism=1)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self._kw = dict(batch_len=batch_len, config=config, role=role,
                        map_indexes=map_indexes,
                        result_ts_slide=result_ts_slide, device=device,
                        depth=depth, use_pallas=use_pallas,
                        compute_dtype=compute_dtype)
        self.winfunc = winfunc

    def make_core(self):
        return DeviceWinSeqCore(self.spec, self.winfunc, **self._kw)

    @property
    def result_schema(self):
        return Schema(**self.winfunc.result_fields)

    def _make_replica(self, i):
        node = WinSeqNode(self.make_core(), f"{self.name}.{i}")
        node.ctx = RuntimeContext(1, 0, self.name)
        return node


class WinFarmTPU(_DeviceCoreFactory, WinFarm):
    """Win_Farm of device-batched window cores — the reference's
    Win_Farm_GPU (win_farm_gpu.hpp:132-168: same emitter/collector as the
    CPU farm, device workers). On one chip, workers share the device and
    their async launch queues interleave (replacing per-worker CUDA
    streams); multi-chip distribution is the mesh layer's job
    (parallel/)."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, batch_len=512, name="win_farm_tpu",
                 ordered=True, n_emitters=1, config=None, role=Role.SEQ,
                 device=None, depth=4, use_pallas=False, compute_dtype=None):
        self._raw_fn = winfunc
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype)
        super().__init__(_host_standin(winfunc), win_len, slide_len, win_type,
                         pardegree=pardegree, name=name, ordered=ordered,
                         n_emitters=n_emitters, config=config, role=role)


class KeyFarmTPU(_DeviceCoreFactory, KeyFarm):
    """Key_Farm of device-batched window cores (key_farm_gpu.hpp:151-161).
    Keys stay resident per worker; the mesh layer maps workers to cores
    over ICI with no collectives (SURVEY.md §7)."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, batch_len=512, name="key_farm_tpu",
                 routing=None, config=None, role=Role.SEQ, device=None,
                 depth=4, use_pallas=False, compute_dtype=None):
        self._raw_fn = winfunc
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype)
        super().__init__(_host_standin(winfunc), win_len, slide_len, win_type,
                         pardegree=pardegree, name=name, routing=routing,
                         config=config, role=role)


class PaneFarmTPU(PaneFarm):
    """Pane_Farm with per-stage device placement — the 4 constructor
    families of Pane_Farm_GPU (pane_farm_gpu.hpp:176-480) become two
    booleans; an incremental stage always runs on the host (the reference
    likewise pairs INC stages with host execution)."""

    def __init__(self, plq_func, wlq_func, win_len, slide_len,
                 win_type=WinType.CB, plq_degree=1, wlq_degree=1,
                 name="pane_farm_tpu", plq_on_device=True, wlq_on_device=True,
                 batch_len=512, device=None, depth=4, use_pallas=False,
                 compute_dtype=None, **kw):
        self._on_device = {"plq": plq_on_device, "wlq": wlq_on_device}
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype)
        super().__init__(plq_func, wlq_func, win_len, slide_len, win_type,
                         plq_degree=plq_degree, wlq_degree=wlq_degree,
                         name=name, **kw)

    def _make_stage(self, which, func, win, slide, wt, degree, name,
                    incremental, result_fields, ordered, role):
        if not self._on_device.get(which) or incremental:
            return super()._make_stage(which, func, win, slide, wt, degree,
                                       name, incremental, result_fields,
                                       ordered, role)
        cfg = self.config
        if degree > 1:
            return WinFarmTPU(func, win, slide, wt, pardegree=degree,
                              name=name, ordered=ordered, config=cfg,
                              role=role, **self._dev_kw)
        seq_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, slide)
        return WinSeqTPU(func, win, slide, wt, name=name, config=seq_cfg,
                         role=role, **self._dev_kw)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return PaneFarmTPU(name=name, config=config, ordered=ordered,
                           plq_on_device=self._on_device["plq"],
                           wlq_on_device=self._on_device["wlq"],
                           **self._dev_kw, **kw)


class WinMapReduceTPU(WinMapReduce):
    """Win_MapReduce with per-stage device placement
    (win_mapreduce_gpu.hpp:171-521)."""

    def __init__(self, map_func, reduce_func, win_len, slide_len,
                 win_type=WinType.CB, map_degree=2, reduce_degree=1,
                 name="win_mr_tpu", map_on_device=True,
                 reduce_on_device=False, batch_len=512, device=None, depth=4,
                 use_pallas=False, compute_dtype=None, **kw):
        self._on_device = {"map": map_on_device, "reduce": reduce_on_device}
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype)
        super().__init__(map_func, reduce_func, win_len, slide_len, win_type,
                         map_degree=map_degree, reduce_degree=reduce_degree,
                         name=name, **kw)

    def _make_map_stage(self, map_func, n, name, incremental, result_fields):
        from .win_mapreduce import _MapStage
        if not self._on_device["map"] or incremental:
            return super()._make_map_stage(map_func, n, name, incremental,
                                           result_fields)
        return _MapStage(_host_standin(map_func), self.spec, n, name, None,
                         result_fields, self.config, device_fn=map_func,
                         device_opts=self._dev_kw)

    def _make_reduce_stage(self, reduce_func, n, degree, name, incremental,
                           result_fields, ordered):
        if not self._on_device["reduce"] or incremental:
            return super()._make_reduce_stage(reduce_func, n, degree, name,
                                              incremental, result_fields,
                                              ordered)
        cfg = self.config
        if degree > 1:
            return WinFarmTPU(reduce_func, n, n, WinType.CB, pardegree=degree,
                              name=name, ordered=ordered, config=cfg,
                              role=Role.REDUCE, **self._dev_kw)
        red_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, n)
        return WinSeqTPU(reduce_func, n, n, WinType.CB, name=name,
                         config=red_cfg, role=Role.REDUCE, **self._dev_kw)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return WinMapReduceTPU(name=name, config=config, ordered=ordered,
                               map_on_device=self._on_device["map"],
                               reduce_on_device=self._on_device["reduce"],
                               **self._dev_kw, **kw)
