"""Win_Seq_TPU: the sequential window core with device-batched evaluation —
the TPU graft of the reference's Win_Seq_GPU (win_seq_gpu.hpp).

Same window bookkeeping as the host core (it *is* the host core: one
subclass hook), but fired NIC windows are not evaluated inline: their
(start, len) ranges plus the staged archive slice are queued, and at
``batch_len`` fired windows one XLA computation (or Pallas kernel)
evaluates them all.  Result headers (key, renumbered id, result ts) are
computed host-side at fire time, exactly like the reference pre-fills
``host_results[i].setInfo(...)`` before the kernel (win_seq_gpu.hpp:447-449).
Launches are asynchronous with bounded depth (vs the reference's per-batch
``cudaStreamSynchronize``, :481); results are emitted in launch order, so
per-key result order is preserved.

EOS leftovers run through the same device path padded to the smallest
bucket (the reference instead re-runs the functor on the CPU,
win_seq_gpu.hpp:533-581 — unnecessary here since the contract is a JAX
function, executable on any backend with identical semantics; that also
covers the reference's "host-callable device functor" testing trick).
"""

from __future__ import annotations

import numpy as np

from ..core.tuples import Schema
from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..core.winseq import WinSeqCore
from ..ops.device import DeviceWindowExecutor, builtin_batch_fn
from ..ops.functions import MultiReducer, Reducer
from ..runtime.node import RuntimeContext
from .basic import _Pattern
from .key_farm import KeyFarm
from .pane_farm import PaneFarm
from .win_farm import WinFarm
from .win_mapreduce import WinMapReduce
from .win_seq import WinSeqNode


def resolve_worker_device(device, i: int):
    """Per-worker device placement — farm worker *i* owns a chip the way
    each reference GPU worker owns a CUDA stream/device
    (win_farm_gpu.hpp:132-168, win_seq_gpu.hpp:271-306).

    ``None`` spreads workers round-robin over ``jax.devices()`` (on a
    single-chip host this degenerates to chip 0, unchanged); a list/tuple
    spreads over exactly those devices; a single device pins every worker
    to it."""
    if isinstance(device, (list, tuple)):
        return device[i % len(device)]
    if device is None:
        import jax
        devs = jax.devices()
        return devs[i % len(devs)]
    return device


class JaxWindowFunction:
    """User window function for the device path: a JAX-traceable
    ``fn(keys, gwids, cols, mask) -> column(s)`` over a whole window batch
    — the TPU replacement for the reference's CUDA device functor
    ``F(key, gwid, data, res, size, scratch)`` (win_seq_gpu.hpp:54-67,
    deduced at meta_utils.hpp:173-180)."""

    def __init__(self, fn, fields=("value",), result_fields=None,
                 field_dtypes=None):
        self.fn = fn
        self.fields = tuple(fields)
        self.result_fields = dict(result_fields or {"value": np.int64})
        #: ring dtype per input field on the resident path (default int32;
        #: float columns need an explicit float32 here — the ring is typed
        #: at allocation, unlike the restaging path which stages whatever
        #: dtype each launch carries)
        self.field_dtypes = dict(field_dtypes or {})


def _host_standin(winfunc):
    """Host-side function object carrying the result schema for the
    core/farm template plumbing (the device path never calls it)."""
    if isinstance(winfunc, (Reducer, MultiReducer)):
        return winfunc
    if isinstance(winfunc, JaxWindowFunction):
        r = Reducer("count")
        r.result_fields = dict(winfunc.result_fields)
        return r
    raise TypeError(
        "the device path needs a builtin Reducer or a JaxWindowFunction "
        "(host Python functions cannot be staged to the TPU — same "
        "restriction as the reference's __device__ functor contract)")


class _AsyncLaunchRecovery:
    """Recovery-mode hooks shared by the async device cores
    (docs/ROBUSTNESS.md "Recovery").  Emission granularity is ONE batch
    per completed launch, in launch order: launch boundaries are
    count-triggered (deterministic), while how many launches any one
    poll()/drain() harvests is wall-clock — per-launch emission keeps a
    replayed run's output seq numbering identical to the original's
    regardless of harvest timing."""

    def _pre_poll(self):
        """Hook before harvesting in process_batches (the resident core
        runs its latency-bound flush here)."""

    def _per_launch(self, harvested):
        outs = []
        for entry in harvested:
            built = self._build_results([entry])
            if built:
                outs.append(built[0] if len(built) == 1
                            else np.concatenate(built))
        return outs

    def process_batches(self, batch):
        """Recovery-mode process(): same work, per-launch outputs."""
        WinSeqCore.process(self, batch)
        self._pre_poll()
        return self._per_launch(self.executor.poll())

    def flush_batches(self):
        WinSeqCore.flush(self)
        self._flush_batch()
        return self._per_launch(self.executor.drain())

    def checkpoint_drain_batches(self):
        """Epoch-barrier drain: launch the partial batch and block out
        the in-flight results (they pre-date the snapshot cut and would
        otherwise be lost on restore) — per launch, like every other
        recovery-mode emission."""
        self._flush_batch()
        return self._per_launch(self.executor.drain())


class DeviceWinSeqCore(_AsyncLaunchRecovery, WinSeqCore):
    """WinSeqCore whose fired-window evaluation is device-batched."""

    #: control-plane live rescale declined (docs/CONTROL.md): the
    #: inherited keyed hooks would migrate only the host bookkeeping
    #: while launch queues / staged device work stay behind
    keyed_migratable = False

    def __init__(self, spec: WindowSpec, winfunc, batch_len: int = 512,
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide=None, device=None,
                 depth: int = 4, use_pallas: bool = False,
                 compute_dtype=None):
        host_fn = _host_standin(winfunc)
        if isinstance(winfunc, Reducer):
            executor = DeviceWindowExecutor(
                builtin_batch_fn(winfunc.op, winfunc.field),
                fields=winfunc.required_fields,
                out_fields=tuple(winfunc.result_fields),
                device=device, depth=depth, use_pallas=use_pallas,
                op=winfunc.op, compute_dtype=compute_dtype,
                out_dtypes=winfunc.result_fields,
                # empty windows must produce the host-path identity even
                # though device compute may run in a narrower dtype
                empty_fill={winfunc.out_field: winfunc._identity()})
            self._stage_fields = tuple(winfunc.required_fields)
        else:
            executor = DeviceWindowExecutor(
                winfunc.fn, fields=winfunc.fields,
                out_fields=tuple(winfunc.result_fields),
                device=device, depth=depth, compute_dtype=compute_dtype,
                out_dtypes=winfunc.result_fields)
            self._stage_fields = winfunc.fields
        super().__init__(spec, host_fn, config=config, role=role,
                         map_indexes=map_indexes,
                         result_ts_slide=result_ts_slide)
        self.executor = executor
        self.batch_len = batch_len
        # pending windows: list of (segment_cols, starts, lens) + headers
        self._segs = []        # [(cols{f: np}, starts, lens)]
        self._pending = 0
        self._hdr = []         # [(key, ids, ts) per enqueue]

    # -- device-batched NIC evaluation ------------------------------------

    def _emit_windows(self, key, st, lwids, eos: bool):
        spec = self.spec
        gwids = st.first_gwid + lwids * self.config.gwid_stride()
        ts = self._result_ts(st, lwids, gwids)
        ids = self._renumber_ids(key, st, gwids)
        starts_abs = spec.win_start(lwids) + st.initial_id
        ends_abs = spec.win_end(lwids) + st.initial_id
        p = st.archive.positions
        lo = np.searchsorted(p, starts_abs, side="left")
        hi = (np.full(len(lwids), len(p), dtype=np.int64) if eos
              else np.searchsorted(p, ends_abs, side="left"))
        base = int(lo[0]) if len(lo) else 0
        top = int(hi[-1]) if len(hi) else 0
        rows = st.archive.rows[base:top]
        cols = {f: rows[f].copy() for f in self._stage_fields}
        self._segs.append((cols, (lo - base).astype(np.int64),
                           (hi - lo).astype(np.int64),
                           np.full(len(lwids), key, dtype=np.int64), gwids))
        self._hdr.append((key, ids, ts))
        self._pending += len(lwids)
        if not eos and len(lwids):
            st.archive.purge_below(int(starts_abs[-1]))
        if self._pending >= self.batch_len:
            self._flush_batch()
        return None

    def _flush_batch(self):
        if not self._segs:
            return
        flat = {f: [] for f in self._stage_fields}
        starts, lens, keys, gwids = [], [], [], []
        off = 0
        for cols, s, l, k, g in self._segs:
            for f in self._stage_fields:
                flat[f].append(cols[f])
            starts.append(s + off)
            lens.append(l)
            keys.append(k)
            gwids.append(g)
            off += len(next(iter(cols.values()))) if cols else 0
        flat = {f: np.concatenate(v) if v else np.zeros(0, dtype=np.int64)
                for f, v in flat.items()}
        self.executor.launch(
            list(self._hdr), flat,
            np.concatenate(starts), np.concatenate(lens),
            np.concatenate(keys), np.concatenate(gwids))
        self._segs, self._hdr, self._pending = [], [], 0

    # -- harvest ----------------------------------------------------------

    def _build_results(self, harvested):
        outs = []
        for hdr, cols in harvested:
            off = 0
            for key, ids, ts in hdr:
                n = len(ids)
                payload = {f: v[off:off + n] for f, v in cols.items()}
                outs.append(self._make_results(key, ids, ts, payload))
                off += n
        return outs

    def process(self, batch):
        super().process(batch)  # fired windows are enqueued, not returned
        outs = self._build_results(self.executor.poll())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    def flush(self):
        super().flush()         # enqueue EOS leftovers
        self._flush_batch()     # launch the partial batch
        outs = self._build_results(self.executor.drain())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    # -- recovery (docs/ROBUSTNESS.md): emission hooks come from
    # _AsyncLaunchRecovery ------------------------------------------------

    def state_snapshot(self):
        """Post-drain snapshot: the restaging executor keeps no state
        across launches, so only the host Win_Seq bookkeeping (per-key
        archives + counters) needs capturing."""
        import copy
        return {"_keys": copy.deepcopy(self._keys),
                "_in_dtype": self._in_dtype}

    def state_restore(self, snap):
        import copy
        self._keys = copy.deepcopy(snap["_keys"])
        self._in_dtype = snap["_in_dtype"]
        self._segs, self._hdr, self._pending = [], [], 0
        self.executor._inflight.clear()
        self.executor._ready = []

    def use_incremental(self):
        raise TypeError("the device path is non-incremental only "
                        "(win_seq_gpu.hpp supports NIC device functors)")


#: (op, result-dtype, acc-dtype) combinations already warned about —
#: resident cores are built per farm worker / per run, and repeating the
#: same narrowing warning for each of them is noise (ADVICE r1)
_ACC_WARNED = set()


def _acc_range_safe(reducer: Reducer, acc: np.dtype, spec) -> bool:
    """True when the reducer's declared ``value_range`` proves its window
    results cannot exceed ``acc``'s range: min/max never leave the input
    range; a CB window's sum is bounded by win_len * max|value| (a TB
    window's row count is unbounded, so sums stay unprovable there)."""
    vr = getattr(reducer, "value_range", None)
    if vr is None or acc.kind == "f":
        return False
    m = max(abs(int(vr[0])), abs(int(vr[1])))
    if reducer.op in ("min", "max"):
        bound = m
    elif (reducer.op == "sum" and spec is not None
          and spec.win_type is WinType.CB):
        bound = m * int(spec.win_len)
    else:
        return False
    info = np.iinfo(acc)
    return -bound >= info.min and bound <= info.max


def select_acc_dtype(reducer: Reducer, compute_dtype,
                     spec: WindowSpec = None) -> np.dtype:
    """Accumulate dtype for the resident device path: int32/float32 by
    default (TPU-native widths), overridable via ``compute_dtype``.  Warns
    when the reducer's result dtype exceeds the accumulate range — unless
    the reducer's declared ``value_range`` plus the window shape prove the
    results fit; raises if a 64-bit accumulate dtype is requested without
    jax x64 enabled (jax would silently canonicalize the buffers back down
    to 32-bit)."""
    if compute_dtype is not None:
        acc = np.dtype(compute_dtype)
    elif np.issubdtype(reducer.dtype, np.floating):
        acc = np.dtype(np.float32)
    else:
        acc = np.dtype(np.int32)
    if acc.itemsize >= 8:
        import jax
        if not jax.config.jax_enable_x64:
            raise ValueError(
                f"compute_dtype={acc} needs jax x64 enabled "
                "(jax.config.update('jax_enable_x64', True)); without it "
                "jax silently truncates device buffers to 32 bits")
    elif (reducer.dtype.itemsize > acc.itemsize
          and not _acc_range_safe(reducer, acc, spec)):
        key = (reducer.op, reducer.dtype.str, acc.str)
        if key not in _ACC_WARNED:
            _ACC_WARNED.add(key)
            import warnings
            warnings.warn(
                f"resident device path accumulates in {acc}; {reducer.op} "
                "results beyond its range will wrap — pass compute_dtype "
                "for wide ranges, or declare the field's value_range on "
                "the Reducer to prove the fit (warned once per "
                "configuration)",
                stacklevel=4)
    return acc


def finalize_window_values(reducer: Reducer, vals: np.ndarray,
                           lens: np.ndarray) -> np.ndarray:
    """Shared harvest step: cast device outputs to the reducer's result
    dtype and write the host identity over empty windows (min/max/prod
    identities exceed narrow accumulate dtypes; sum's identity 0 is what
    the cumsum difference already yields)."""
    owned = vals.dtype != reducer.dtype
    if owned:
        vals = vals.astype(reducer.dtype)
    if (reducer.op in ("min", "max", "prod") and len(lens)
            and (lens == 0).any()):
        if not owned:
            vals = vals.copy()
        vals[lens == 0] = reducer._identity()
    return vals


class ResidentWinSeqCore(_AsyncLaunchRecovery, WinSeqCore):
    """Window core whose archive lives in device HBM (ops/resident.py).

    Host-side it is the same Win_Seq bookkeeping as every other core; the
    differences from :class:`DeviceWinSeqCore` (which restages each fired
    window's rows per batch, like the reference's per-batch H2D memcpy,
    win_seq_gpu.hpp:451-476) are:

    * appended rows are mirrored once into the device ring archive, in the
      narrowest dtype holding their range — each row crosses the wire once;
    * fired windows are described by (ring row, start, len) only; append and
      evaluation fuse into one dispatch per flush;
    * the host archive's purge is deferred to flush time so a rebase (ring
      compaction) can always rebuild the ring from host-live rows.
    """

    #: control-plane live rescale declined (docs/CONTROL.md): a key's
    #: rows are mirrored into THIS worker's HBM ring archive — the
    #: inherited host-dict hooks cannot move that half (extending the
    #: migration to device rings rides ROADMAP Open item 5's ABI work)
    keyed_migratable = False

    def __init__(self, spec: WindowSpec, reducer, batch_len: int = 8192,
                 flush_rows: int = 1 << 20, config: PatternConfig = None,
                 role: Role = Role.SEQ, map_indexes=(0, 1),
                 result_ts_slide=None, device=None, depth: int = 8,
                 compute_dtype=None, worker_index: int = 0, mesh=None,
                 max_delay_ms=None):
        from ..ops.resident import (MeshResidentExecutor,
                                    MultiFieldResidentExecutor,
                                    ResidentWindowExecutor)
        self._jax_fn = None
        self._pos_max_parts = []
        if isinstance(reducer, JaxWindowFunction):
            # arbitrary batched JAX window fn over device-resident rings —
            # one ring per input field (win_seq_gpu.hpp:54-67's arbitrary
            # functor over whole POD tuples, without per-fire restaging)
            self._device_parts = []
            self._count_parts = []
            self._jax_fn = reducer
            field = None
        elif isinstance(reducer, MultiReducer):
            # multi-stat: every DEVICE-WORTHY stat evaluates over its
            # field's resident ring in one fused dispatch; counts come
            # free from window lengths, and MAX over the POSITION field
            # (ts for TB, id for CB) is free from the position-ordered
            # host archive (stream_archive.hpp ordering) — splitting it
            # out here means e.g. YSB's COUNT + MAX(ts) + SUM(revenue)
            # ships ONLY the revenue column (narrowed to int8 on the
            # wire), not ts
            self._device_parts, self._pos_max_parts = \
                split_pos_max(spec, reducer)
            self._count_parts = reducer.count_parts
            if not self._device_parts:
                # an entirely host-free aggregate forced onto the device
                # (use_resident=True, wire benchmarking): ship the
                # position column after all — there is nothing else to
                # evaluate (make_core_for routes such aggregates to the
                # host core unless forced)
                self._device_parts, self._pos_max_parts = \
                    self._pos_max_parts, []
            fields = {p.field for p in self._device_parts}
            field = fields.pop() if len(fields) == 1 else None
            if not self._device_parts:
                raise ValueError(
                    "resident MultiReducer needs >=1 non-count stat "
                    "(use Reducer('count') for pure counts)")
        elif isinstance(reducer, Reducer):
            self._device_parts = [reducer]
            self._count_parts = []
            field = reducer.field
        else:
            raise TypeError("resident device path needs a builtin Reducer, "
                            "MultiReducer, or JaxWindowFunction")
        host_fn = _host_standin(reducer)
        super().__init__(spec, host_fn, config=config, role=role,
                         map_indexes=map_indexes,
                         result_ts_slide=result_ts_slide)
        self.reducer = reducer
        self.field = field
        if self._jax_fn is not None:
            self._ship_fields = tuple(self._jax_fn.fields)
        elif field is not None:
            self._ship_fields = (field,)
        else:
            self._ship_fields = tuple(dict.fromkeys(
                p.field for p in self._device_parts))
        multi = field is None
        if multi:
            # per-field ring dtypes: reducer parts pick theirs via
            # select_acc_dtype; fn-only fields use the fn's declared
            # field_dtypes (default int32)
            acc_by_field = {}
            for p in self._device_parts:
                a = select_acc_dtype(p, compute_dtype, spec)
                prev = acc_by_field.get(p.field)
                if prev is not None and prev.kind != a.kind:
                    raise ValueError(
                        f"stats over field {p.field!r} disagree on "
                        f"accumulate kind ({prev} vs {a})")
                if prev is None or a.itemsize > prev.itemsize:
                    acc_by_field[p.field] = a
            if self._jax_fn is not None:
                declared = getattr(self._jax_fn, "field_dtypes", None) or {}
                for f in self._ship_fields:
                    dt = np.dtype(declared.get(f, np.int32))
                    if dt.itemsize >= 8:
                        # same guard select_acc_dtype applies: without x64
                        # jax silently canonicalizes the ring to 32 bits
                        import jax
                        if not jax.config.jax_enable_x64:
                            raise ValueError(
                                f"field_dtypes[{f!r}]={dt} needs jax x64 "
                                "enabled (jax.config.update("
                                "'jax_enable_x64', True))")
                    acc_by_field.setdefault(f, dt)
            if mesh is not None:
                from ..ops.resident import MeshMultiFieldResidentExecutor
                self.executor = MeshMultiFieldResidentExecutor(
                    self._ship_fields,
                    stats=tuple((p.op, p.field)
                                for p in self._device_parts),
                    jax_fn=self._jax_fn, acc_dtypes=acc_by_field,
                    mesh=mesh, depth=depth)
            else:
                self.executor = MultiFieldResidentExecutor(
                    self._ship_fields,
                    stats=tuple((p.op, p.field) for p in self._device_parts),
                    jax_fn=self._jax_fn, acc_dtypes=acc_by_field,
                    device=resolve_worker_device(device, worker_index),
                    depth=depth)
        else:
            accs = [select_acc_dtype(p, compute_dtype, spec)
                    for p in self._device_parts]
            kinds = {d.kind for d in accs}
            if len(kinds) > 1:
                # one shared ring, one accumulate dtype: a float ring would
                # silently round sibling integer sums (float32 spacing > 1
                # above 2^24) — refuse instead
                raise ValueError(
                    "multi-stat parts disagree on accumulate kind "
                    f"({sorted(str(a) for a in accs)}): split the stats or "
                    "pass an explicit compute_dtype")
            acc = max(accs, key=lambda d: d.itemsize)
            ops = tuple(p.op for p in self._device_parts)
            op_arg = ops[0] if len(ops) == 1 else ops
            if mesh is not None:
                self.executor = MeshResidentExecutor(
                    op_arg, mesh, depth=depth, acc_dtype=acc)
            else:
                self.executor = ResidentWindowExecutor(
                    op_arg,
                    device=resolve_worker_device(device, worker_index),
                    depth=depth, acc_dtype=acc)
        self.batch_len = batch_len
        self.flush_rows = flush_rows
        # latency bound: ship pending windows/rows after this many ms even
        # when neither batch_len nor flush_rows is reached (checked per
        # process() call — the trigger cadence is the chunk cadence)
        self.max_delay_s = (None if max_delay_ms is None
                            else max_delay_ms / 1e3)
        self._last_flush_t = None
        self._rowmap = {}     # key -> dense ring row
        self._appended = {}   # key -> rows ever archived (abs row domain)
        self._launched = {}   # key -> rows already shipped to the ring
        self._base = {}       # key -> abs row index of ring column 0
        #: field -> key -> [column arrays not yet shipped]
        self._pend_cols = {f: {} for f in self._ship_fields}
        self._pend_rows = 0
        self._wdesc = []      # (key, abs_lo array, len array, gwids)
        self._hdr = []        # (key, ids, ts, lens) per fire
        self._n_wins = 0
        self._purge_pos = {}  # key -> purge threshold deferred to flush

    # ------------------------------------------------------------ bookkeeping

    def _on_append(self, key, st, rows):
        self._rowmap.setdefault(key, len(self._rowmap))
        for f in self._ship_fields:
            self._pend_cols[f].setdefault(key, []).append(
                np.asarray(rows[f]))
        self._appended[key] = self._appended.get(key, 0) + len(rows)
        self._pend_rows += len(rows)
        if self._pend_rows >= self.flush_rows:
            self._flush_batch()

    def _emit_windows(self, key, st, lwids, eos: bool):
        spec = self.spec
        self._rowmap.setdefault(key, len(self._rowmap))
        gwids = st.first_gwid + lwids * self.config.gwid_stride()
        ts = self._result_ts(st, lwids, gwids)
        ids = self._renumber_ids(key, st, gwids)
        starts_abs = spec.win_start(lwids) + st.initial_id
        ends_abs = spec.win_end(lwids) + st.initial_id
        p = st.archive.positions
        lo = np.searchsorted(p, starts_abs, side="left")
        hi = (np.full(len(lwids), len(p), dtype=np.int64) if eos
              else np.searchsorted(p, ends_abs, side="left"))
        live_start = self._appended.get(key, 0) - len(p)
        self._wdesc.append((key, lo + live_start, (hi - lo).astype(np.int64),
                            gwids))
        if self._pos_max_parts and len(p):
            # MAX/MIN over the position field, free from the ordered
            # archive: the window's last row holds the max and its first
            # row the min (empty windows fixed up to the identity at
            # harvest, finalize_window_values)
            pm = (p[np.minimum(np.maximum(hi - 1, 0), len(p) - 1)],
                  p[np.minimum(lo, len(p) - 1)])
        else:
            z = np.zeros(len(lwids), dtype=np.int64)
            pm = (z, z)
        self._hdr.append((key, ids, ts, (hi - lo).astype(np.int64), pm))
        self._n_wins += len(lwids)
        if not eos and len(lwids):
            # defer the purge so a flush-time rebase can rebuild the ring
            # from host-live rows (win_seq.hpp:390-392 purges at fire time)
            self._purge_pos[key] = max(self._purge_pos.get(key, -2 ** 62),
                                       int(starts_abs[-1]))
        if self._n_wins >= self.batch_len:
            self._flush_batch()
        return None

    # ------------------------------------------------------------------ flush

    def _flush_batch(self):
        if not self._wdesc and not self._pend_rows:
            return
        from ..ops.resident import _bucket
        ex = self.executor
        rowmap = self._rowmap
        K = len(rowmap)
        # --- decide append vs rebase ---
        # (KP < K, not KP < _bucket(K): the mesh executor's KP is a
        # multiple of its shard count rather than a power of two)
        rebase = ex.cap == 0 or ex.KP < max(K, 1)
        if not rebase:
            # the append rectangle is (K, Rb) with one global padded width,
            # so every key needs fill + Rb columns of room
            maxpend = max((self._appended.get(key, 0)
                           - self._launched.get(key, 0) for key in rowmap),
                          default=0)
            Rb = _bucket(max(maxpend, 1))
            for key in rowmap:
                fill = self._launched.get(key, 0) - self._base.get(key, 0)
                if fill + Rb > ex.cap:
                    rebase = True
                    break
        if rebase:
            counts = {}
            maxlive = 0
            for key in rowmap:
                st = self._keys.get(key)
                counts[key] = len(st.archive) if st is not None else 0
                maxlive = max(maxlive, counts[key])
            per_key_slack = max(self.flush_rows // max(K, 1), 64)
            ex.reset(K, _bucket(2 * maxlive + 2 * per_key_slack))
            R = maxlive
            srcs = {f: {key: ([np.asarray(self._keys[key].archive.rows[f])]
                              if key in self._keys else [])
                        for key in rowmap}
                    for f in self._ship_fields}
            for key in rowmap:
                self._base[key] = self._appended.get(key, 0) - counts[key]
                self._launched[key] = self._base[key]
            offs = np.zeros(ex.KP, dtype=np.int64)
        else:
            srcs = self._pend_cols
            counts = {key: self._appended.get(key, 0)
                      - self._launched.get(key, 0) for key in rowmap}
            R = max(counts.values(), default=0)
            offs = np.zeros(ex.KP, dtype=np.int64)
            for key, r in rowmap.items():
                offs[r] = self._launched.get(key, 0) - self._base.get(key, 0)
        # --- per-field rectangles in the narrowest wire dtype ---
        blks = {}
        for f in self._ship_fields:
            fsrcs = srcs[f]
            arrays = [a for key in rowmap for a in fsrcs.get(key, [])
                      if len(a)]
            if arrays:
                lo = min(a.min() for a in arrays)
                hi = max(a.max() for a in arrays)
                probe = np.array([lo, hi], dtype=arrays[0].dtype)
            else:
                probe = np.zeros(0, dtype=np.int64)
            wire = (ex.narrow_for(f, probe) if hasattr(ex, "narrow_for")
                    else ex.narrow(probe))
            blk = np.zeros((K, max(R, 1)), dtype=wire)
            for key, r in rowmap.items():
                c = 0
                for a in fsrcs.get(key, []):
                    blk[r, c:c + len(a)] = a
                    c += len(a)
            blks[f] = blk
        # --- window descriptors in ring coordinates ---
        if self._wdesc:
            wrows = np.concatenate([
                np.full(len(lens), rowmap[key], dtype=np.int64)
                for key, _, lens, _g in self._wdesc])
            wstarts = np.concatenate([
                abs_lo - self._base.get(key, 0)
                for key, abs_lo, _l, _g in self._wdesc])
            wlens = np.concatenate([lens for _k, _a, lens, _g in self._wdesc])
        else:
            wrows = wstarts = wlens = np.zeros(0, dtype=np.int64)
        from ..ops.resident import MultiFieldResidentExecutor
        if isinstance(ex, MultiFieldResidentExecutor):
            # multi-field executor: ships every ring's rectangle + the
            # (keys, gwids) header columns the JAX fn contract receives
            if self._jax_fn is not None and self._wdesc:
                wkeys = np.concatenate([
                    np.full(len(lens), key, dtype=np.int64)
                    for key, _a, lens, _g in self._wdesc])
                wgwids = np.concatenate(
                    [g for _k, _a, _l, g in self._wdesc]).astype(np.int64)
            else:
                wkeys = wgwids = np.zeros(0, dtype=np.int64)
            ex.launch(self._hdr, blks, offs[:K], wrows, wstarts, wlens,
                      wkeys=wkeys, wgwids=wgwids)
        else:
            ex.launch(self._hdr, blks[self.field], offs[:K], wrows,
                      wstarts, wlens)
        # --- advance cursors, apply deferred purges ---
        for key in rowmap:
            self._launched[key] = self._appended.get(key, 0)
        for key, pos in self._purge_pos.items():
            st = self._keys.get(key)
            if st is not None:
                st.archive.purge_below(pos)
        self._pend_cols = {f: {} for f in self._ship_fields}
        self._pend_rows = 0
        self._wdesc, self._hdr, self._n_wins = [], [], 0
        self._purge_pos = {}
        if self.max_delay_s is not None:
            # every flush (natural or forced) restarts the latency clock —
            # otherwise a saturated stream would fragment launches at
            # max_delay cadence despite fresh batch_len/flush_rows flushes
            import time as _time
            self._last_flush_t = _time.monotonic()

    # ---------------------------------------------------------------- harvest

    def _build_results(self, harvested):
        outs = []
        fn_fields = (tuple(self._jax_fn.result_fields.items())
                     if self._jax_fn is not None else ())
        for hdr, out in harvested:
            stat_arrs = out if isinstance(out, tuple) else (out,)
            off = 0
            for key, ids, ts, lens, pos_max in hdr:
                n = len(ids)
                payload = {}
                i = 0
                for p in self._device_parts:
                    payload[p.out_field] = finalize_window_values(
                        p, stat_arrs[i][off:off + n], lens)
                    i += 1
                for name, dt in fn_fields:
                    payload[name] = stat_arrs[i][off:off + n].astype(dt)
                    i += 1
                for p in self._count_parts:
                    payload[p.out_field] = lens.astype(p.dtype)
                for p in self._pos_max_parts:
                    payload[p.out_field] = finalize_window_values(
                        p, pos_max[0] if p.op == "max" else pos_max[1],
                        lens)
                outs.append(self._make_results(key, ids, ts, payload))
                off += n
        return outs

    def _maybe_delay_flush(self):
        if self.max_delay_s is not None and (self._wdesc or self._pend_rows):
            import time as _time
            now = _time.monotonic()
            if self._last_flush_t is None:
                self._last_flush_t = now
            elif now - self._last_flush_t >= self.max_delay_s:
                self._flush_batch()
                self._last_flush_t = now

    def process(self, batch):
        super().process(batch)  # fired windows are enqueued, not returned
        self._maybe_delay_flush()
        outs = self._build_results(self.executor.poll())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    def flush(self):
        super().flush()          # enqueue EOS leftovers
        self._flush_batch()      # launch the partial batch
        outs = self._build_results(self.executor.drain())
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)

    # -- recovery (docs/ROBUSTNESS.md): emission hooks come from
    # _AsyncLaunchRecovery ------------------------------------------------

    def _pre_poll(self):
        self._maybe_delay_flush()

    #: include the HBM ring contents in snapshots (a functional-array
    #: handle whose device→host copy overlaps the next batches' compute,
    #: ops/resident.RingSnapshot); the Supervisor mirrors
    #: RecoveryPolicy.snapshot_rings here.  False = restore by forcing a
    #: rebase from the host-live archive rows instead.
    snapshot_rings = True
    #: ring/cursor bookkeeping captured alongside the host archives
    _RES_ATTRS = ("_rowmap", "_appended", "_launched", "_base")

    def state_snapshot(self):
        if self.max_delay_s is not None:
            # the latency-bound flush is wall-clock-triggered: replayed
            # LAUNCH boundaries would diverge from the original run's,
            # and with them the emission seqs — decline rather than
            # risk duplicated/lost windows after a restart
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                "max_delay_ms wall-clock flushes make replay emission "
                "boundaries nondeterministic; recovery supports "
                "count-triggered flushes only")
        import copy
        snap = {
            "_keys": copy.deepcopy(self._keys),
            "_in_dtype": self._in_dtype,
            "resident": copy.deepcopy(
                {a: getattr(self, a) for a in self._RES_ATTRS}),
        }
        if self.snapshot_rings:
            snap["ring"] = self.executor.ring_snapshot()
        return snap

    def state_restore(self, snap):
        import copy
        self._keys = copy.deepcopy(snap["_keys"])
        self._in_dtype = snap["_in_dtype"]
        for a, v in snap["resident"].items():
            setattr(self, a, copy.deepcopy(v))
        self._pend_cols = {f: {} for f in self._ship_fields}
        self._pend_rows = 0
        self._wdesc, self._hdr, self._n_wins = [], [], 0
        self._purge_pos = {}
        self._last_flush_t = None
        ring = snap.get("ring")
        if ring is not None:
            self.executor.ring_restore(ring)
        else:
            # no ring in the snapshot: invalidate so the next flush
            # rebases — the deferred-purge invariant guarantees the
            # host archives still hold every ring-live row
            self.executor.invalidate()

    def use_incremental(self):
        raise TypeError("the device path is non-incremental only "
                        "(win_seq_gpu.hpp supports NIC device functors)")


#: reducer ops the resident path evaluates on device (count carries no
#: device work at all and routes to the HOST core via _host_free, as does
#: max over the position field; arbitrary JAX fns default to the
#: segment-restaging executor and opt into resident rings)
_RESIDENT_OPS = ("sum", "min", "max", "prod")


def split_pos_max(spec: WindowSpec, reducer: MultiReducer):
    """Partition a MultiReducer's non-count stats into (device_parts,
    pos_extremum_parts): MAX *and MIN* over the POSITION field (ts for
    TB, id for CB) are free from the position-ordered archive — the
    window's last row holds the max and its FIRST row the min — so
    neither ever ships (e.g. YSB's COUNT + MAX(ts) + SUM(revenue) ships
    only the revenue column, and a `firstUpdate` MIN(ts) costs nothing
    either).  Harvesters pick the per-window last/first-row array by
    each returned part's ``op``."""
    pos_field = "id" if spec.win_type is WinType.CB else "ts"
    dev = reducer.device_parts
    pos = [p for p in dev
           if p.op in ("max", "min") and p.field == pos_field]
    return [p for p in dev if p not in pos], pos


def _host_free(spec: WindowSpec, winfunc) -> bool:
    """True when every stat is free on the host: counts come from window
    lengths, and ``max``/``min`` over the POSITION field (ts for TB, id
    for CB) are the last/first archived row's values — archives are kept
    ordered by position (stream_archive.hpp), so the host bookkeeping
    already holds the answers.  Such aggregates have no device-worthy
    compute at all."""
    pos_field = "id" if spec.win_type is WinType.CB else "ts"
    parts = winfunc.parts if isinstance(winfunc, MultiReducer) else [winfunc]
    return all(p.op == "count"
               or (p.op in ("max", "min") and p.field == pos_field)
               for p in parts)


def _multi_resident_ok(winfunc: MultiReducer, use_pallas: bool) -> bool:
    """Whether a MultiReducer can run on the resident path: >=1 non-count
    stat, all ops resident-evaluable, no float-sum.  Stats over ONE field
    share a single ring; stats over several fields get one ring each
    (MultiFieldResidentExecutor)."""
    dev = winfunc.device_parts
    return (not use_pallas and bool(dev)
            and all(p.op in _RESIDENT_OPS for p in dev)
            and not any(p.op == "sum"
                        and np.issubdtype(p.dtype, np.floating)
                        for p in dev))


def _native_core_lib():
    """The native library handle for core routing, or None — also None
    under WF_NO_NATIVE_CORE=1, which pins the Python resident core
    (e.g. for recovery snapshots: the C++ core's archives live in
    native tables with no snapshot API, docs/ROBUSTNESS.md)."""
    import os
    if os.environ.get("WF_NO_NATIVE_CORE", "") == "1":
        return None
    from ..native import enabled
    return enabled()


def make_device_core(worker, fn, dev_kw, index=0):
    """Build the device-batched core for a prototype host worker (a WinSeq
    carrying the farm's per-worker spec/config/role plumbing); ``index`` is
    the farm worker index driving per-worker device placement."""
    return make_core_for(worker.spec, fn, config=worker.config,
                         role=worker.role, map_indexes=worker.map_indexes,
                         result_ts_slide=worker.result_ts_slide,
                         worker_index=index, **dev_kw)


def make_core_for(spec, winfunc, *, batch_len=512, config=None,
                  role=Role.SEQ, map_indexes=(0, 1), result_ts_slide=None,
                  device=None, depth=None, use_pallas=False,
                  compute_dtype=None, use_resident=None,
                  flush_rows=1 << 20, shards=1, worker_index=0, mesh=None,
                  max_delay_ms=None):
    """Choose the device core implementation: resident-archive (preferred —
    each row crosses the wire once) when the function is a built-in monoid
    the resident executor evaluates; segment-restaging otherwise.  With
    ``mesh`` the resident ring is sharded ``P('kf', None)`` across the mesh
    devices (one dispatch serves every key group over ICI)."""
    def _host_core():
        from .win_seq import WinSeq
        return WinSeq(winfunc, spec.win_len, spec.slide_len,
                      spec.win_type, config=config, role=role,
                      map_indexes=map_indexes,
                      result_ts_slide=result_ts_slide).make_core()

    if (max_delay_ms is not None and use_resident is None
            and mesh is None and not use_pallas
            and isinstance(winfunc, (Reducer, MultiReducer))
            # a MultiReducer invalid on EVERY device path must fall
            # through to the deterministic ValueError below — routing it
            # host only when some earlier run seeded the weather record
            # would make raise-vs-success depend on hidden global state
            and not (isinstance(winfunc, MultiReducer)
                     and not _multi_resident_ok(winfunc, use_pallas))):
        # budget-aware routing (VERDICT r4 item 4): every device result
        # pays at least one wire round-trip, so a latency budget under
        # ~2x the MEASURED per-launch service is unmeetable on the
        # device path by construction (the r4 YSB --max-delay-ms 250
        # run: force-flushing took avg 2.54 s -> 0.47 s but p95 stayed
        # 1.49 s against 700 ms launches).  The host core has no wire
        # in its path and meets double-digit-ms budgets today.  The
        # statistic is the recent-best service FLOOR, not the EMA: a
        # warmup run's compile launches inflate the mean (measured 915
        # ms EMA against a ~200 ms floor), and feasibility is about the
        # wire's best, not its average.  The record outlives executors
        # (ops/resident.py), so a warmup teaches the routing what this
        # session's tunnel can do; with no observation yet the device
        # keeps the benefit of the doubt.  ANY explicit path pin —
        # use_resident=True/False, use_pallas — outranks the heuristic.
        from ..ops.resident import wire_service_floor_ms
        floor = wire_service_floor_ms()
        if floor is not None and max_delay_ms < 2.0 * floor:
            return _host_core()
    if (isinstance(winfunc, (Reducer, MultiReducer))
            and use_resident is None and mesh is None
            and (isinstance(winfunc, MultiReducer) or not use_pallas)
            and _host_free(spec, winfunc)):
        # every stat is answerable from host bookkeeping (count from
        # window lengths; max over the position field from the
        # position-ordered archive) — shipping the column to the device
        # buys nothing but wire traffic (the r1 kf-tpu regression: YSB's
        # count+MAX(ts) lost to the host path for exactly this reason).
        # Route to the host core.  use_resident=True forces the device;
        # a Reducer with use_pallas=True keeps the Pallas/restaging path
        # (benchmarking) — MultiReducer has no Pallas path, so the flag
        # does not block its host routing.
        return _host_core()
    if isinstance(winfunc, MultiReducer):
        # multi-stat windows are resident-only (the restaging executor has
        # no multi-output contract); count-only MultiReducers should be a
        # plain Reducer("count")
        if use_resident is False or not _multi_resident_ok(winfunc,
                                                           use_pallas):
            raise ValueError(
                "MultiReducer runs on the resident device path only: "
                "needs >=1 non-count stat, ops in "
                f"{_RESIDENT_OPS}, no float sum (got {winfunc.parts})")
        dev_parts, _pos = split_pos_max(spec, winfunc)
        _nat = _native_core_lib()
        if (_nat is not None and dev_parts
                # dev_parts empty = a fully pos-free aggregate FORCED onto
                # the device (use_resident=True/mesh past the host route):
                # only the Python core has the ship-the-position-column
                # fallback for that shape
                and (len(dev_parts) == 1
                     or (len({p.field for p in dev_parts})
                         <= int(_nat.wf_max_fields())
                         and not any(np.issubdtype(p.dtype, np.floating)
                                     for p in dev_parts)))):
            # the C++ core carries the whole hot loop: counts and
            # max-over-position are answered host-side (window lengths /
            # the archive's per-window last row), and the remaining
            # device-worthy stats stage one narrowed int64 column per
            # distinct field — up to the C++ kMaxFields=4 — into
            # per-field device rings (rich multi-field aggregates
            # previously re-paid the Python hot loop; float stats still
            # do, by the Python core's design).  With a mesh the rings
            # shard P(kf, None) (Mesh[MultiField]ResidentExecutor) — the
            # pod shape keeps the C++ bookkeeping for every aggregate
            # form
            from .native_core import NativeResidentCore
            return NativeResidentCore(
                spec, winfunc, batch_len=batch_len, flush_rows=flush_rows,
                config=config, role=role, map_indexes=map_indexes,
                result_ts_slide=result_ts_slide, device=device,
                depth=depth if depth is not None else 8,
                compute_dtype=compute_dtype, shards=shards,
                worker_index=worker_index, max_delay_ms=max_delay_ms,
                mesh=mesh)
        return ResidentWinSeqCore(
            spec, winfunc, batch_len=batch_len, flush_rows=flush_rows,
            config=config, role=role, map_indexes=map_indexes,
            result_ts_slide=result_ts_slide, device=device,
            depth=depth if depth is not None else 8,
            compute_dtype=compute_dtype, worker_index=worker_index,
            mesh=mesh, max_delay_ms=max_delay_ms)
    if (isinstance(winfunc, JaxWindowFunction)
            and (use_resident or mesh is not None) and not use_pallas):
        # arbitrary JAX window fns evaluate over multi-field resident
        # rings on request (use_resident=True); the default stays the
        # segment-restaging executor, whose staged columns carry each
        # launch's exact dtypes (rings are typed at allocation —
        # JaxWindowFunction.field_dtypes declares them).  With a mesh the
        # rings shard P(kf, None) (MeshMultiFieldResidentExecutor) — the
        # resident path is the only one with a sharded-archive form, so
        # mesh implies it
        return ResidentWinSeqCore(
            spec, winfunc, batch_len=batch_len, flush_rows=flush_rows,
            config=config, role=role, map_indexes=map_indexes,
            result_ts_slide=result_ts_slide, device=device,
            depth=depth if depth is not None else 8,
            compute_dtype=compute_dtype, worker_index=worker_index,
            mesh=mesh, max_delay_ms=max_delay_ms)
    resident = use_resident
    if resident is None:
        resident = (not use_pallas and isinstance(winfunc, Reducer)
                    and winfunc.op in _RESIDENT_OPS
                    # a float cumsum accumulates rounding error the host
                    # path's per-window reduction does not; floats keep the
                    # segment-restaging path unless the user opts in
                    and not (winfunc.op == "sum"
                             and np.issubdtype(winfunc.dtype, np.floating)))
    if mesh is not None:
        if not (isinstance(winfunc, Reducer)
                and winfunc.op in _RESIDENT_OPS):
            raise ValueError(
                "mesh execution needs a resident-path Reducer "
                f"(one of {_RESIDENT_OPS}); got {winfunc!r}")
        if not resident:
            raise ValueError(
                "mesh execution requires the resident path: drop "
                "use_pallas, and for float sums opt in explicitly with "
                "use_resident=True (cumsum rounding differs from the "
                "host's per-window reduction)")
        kw = dict(batch_len=batch_len, flush_rows=flush_rows,
                  config=config, role=role, map_indexes=map_indexes,
                  result_ts_slide=result_ts_slide,
                  depth=depth if depth is not None else 8,
                  compute_dtype=compute_dtype, mesh=mesh,
                  max_delay_ms=max_delay_ms)
        if _native_core_lib() is not None:
            # the C++ bookkeeping feeds the sharded ring: a real pod's
            # multi-chip path must not re-pay the Python hot loop the
            # native core was built to kill (r2 weak #3); host key-shards
            # compose with it — each shard owns its own sharded ring
            # (r3 weak #5)
            from .native_core import NativeResidentCore
            return NativeResidentCore(spec, winfunc, shards=shards, **kw)
        return ResidentWinSeqCore(spec, winfunc, **kw)
    if resident:
        kw = dict(batch_len=batch_len, flush_rows=flush_rows, config=config,
                  role=role, map_indexes=map_indexes,
                  result_ts_slide=result_ts_slide, device=device,
                  depth=depth if depth is not None else 8,
                  compute_dtype=compute_dtype, worker_index=worker_index,
                  max_delay_ms=max_delay_ms)
        if _native_core_lib() is not None:
            from .native_core import NativeResidentCore
            return NativeResidentCore(spec, winfunc, shards=shards, **kw)
        return ResidentWinSeqCore(spec, winfunc, **kw)
    return DeviceWinSeqCore(
        spec, winfunc, batch_len=batch_len, config=config, role=role,
        map_indexes=map_indexes, result_ts_slide=result_ts_slide,
        device=resolve_worker_device(device, worker_index),
        depth=depth if depth is not None else 4,
        use_pallas=use_pallas, compute_dtype=compute_dtype)


class _DeviceCoreFactory:
    """Mixin for farm variants whose workers are device-batched: the host
    farm builds its prototype workers, `_make_core` swaps in the device
    core (set `_raw_fn` and `_dev_kw` before calling the farm ctor).
    Worker *i*'s executor lands on device ``i % n`` (resolve_worker_device)
    so a pardegree-n farm on an n-chip host owns one chip per worker."""

    def _make_core(self, worker, i=0):
        return make_device_core(worker, self._raw_fn, self._dev_kw, index=i)


class WinSeqTPU(_Pattern):
    """Sequential TPU window pattern (reference Win_Seq_GPU builder shape:
    withBatch(batch_len) replaces withBatch(batch_len, n_thread_block))."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 batch_len=512, name="win_seq_tpu",
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide=None, device=None,
                 depth=None, use_pallas=False, compute_dtype=None,
                 use_resident=None, flush_rows=1 << 20, shards=1,
                 mesh=None, max_delay_ms=None):
        super().__init__(name, parallelism=1)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self._kw = dict(batch_len=batch_len, config=config, role=role,
                        map_indexes=map_indexes,
                        result_ts_slide=result_ts_slide, device=device,
                        depth=depth, use_pallas=use_pallas,
                        compute_dtype=compute_dtype,
                        use_resident=use_resident, flush_rows=flush_rows,
                        shards=shards, mesh=mesh,
                        max_delay_ms=max_delay_ms)
        self.winfunc = winfunc

    def make_core(self):
        return make_core_for(self.spec, self.winfunc, **self._kw)

    @property
    def result_schema(self):
        return Schema(**self.winfunc.result_fields)

    def _make_replica(self, i):
        node = WinSeqNode(self.make_core(), f"{self.name}.{i}")
        node.ctx = RuntimeContext(1, 0, self.name)
        return node


class WinFarmTPU(_DeviceCoreFactory, WinFarm):
    """Win_Farm of device-batched window cores — the reference's
    Win_Farm_GPU (win_farm_gpu.hpp:132-168: same emitter/collector as the
    CPU farm, device workers). On one chip, workers share the device and
    their async launch queues interleave (replacing per-worker CUDA
    streams); multi-chip distribution is the mesh layer's job
    (parallel/)."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, batch_len=512, name="win_farm_tpu",
                 ordered=True, n_emitters=1, config=None, role=Role.SEQ,
                 device=None, depth=None, use_pallas=False,
                 compute_dtype=None, use_resident=None, flush_rows=1 << 20,
                 max_delay_ms=None):
        self._raw_fn = winfunc
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype,
                            use_resident=use_resident, flush_rows=flush_rows,
                            max_delay_ms=max_delay_ms)
        super().__init__(_host_standin(winfunc), win_len, slide_len, win_type,
                         pardegree=pardegree, name=name, ordered=ordered,
                         n_emitters=n_emitters, config=config, role=role)


class KeyFarmTPU(_DeviceCoreFactory, KeyFarm):
    """Key_Farm of device-batched window cores (key_farm_gpu.hpp:151-161).
    Keys stay resident per worker; the mesh layer maps workers to cores
    over ICI with no collectives (SURVEY.md §7)."""

    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, batch_len=512, name="key_farm_tpu",
                 routing=None, config=None, role=Role.SEQ, device=None,
                 depth=None, use_pallas=False, compute_dtype=None,
                 use_resident=None, flush_rows=1 << 20, max_delay_ms=None):
        self._raw_fn = winfunc
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype,
                            use_resident=use_resident, flush_rows=flush_rows,
                            max_delay_ms=max_delay_ms)
        super().__init__(_host_standin(winfunc), win_len, slide_len, win_type,
                         pardegree=pardegree, name=name, routing=routing,
                         config=config, role=role)


class PaneFarmTPU(PaneFarm):
    """Pane_Farm with per-stage device placement — the 4 constructor
    families of Pane_Farm_GPU (pane_farm_gpu.hpp:176-480) become two
    booleans; an incremental stage always runs on the host (the reference
    likewise pairs INC stages with host execution)."""

    def __init__(self, plq_func, wlq_func, win_len, slide_len,
                 win_type=WinType.CB, plq_degree=1, wlq_degree=1,
                 name="pane_farm_tpu", plq_on_device=True, wlq_on_device=True,
                 batch_len=512, device=None, depth=None, use_pallas=False,
                 compute_dtype=None, use_resident=None, flush_rows=1 << 20,
                 **kw):
        self._on_device = {"plq": plq_on_device, "wlq": wlq_on_device}
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype,
                            use_resident=use_resident, flush_rows=flush_rows)
        super().__init__(plq_func, wlq_func, win_len, slide_len, win_type,
                         plq_degree=plq_degree, wlq_degree=wlq_degree,
                         name=name, **kw)

    def _make_stage(self, which, func, win, slide, wt, degree, name,
                    incremental, result_fields, ordered, role):
        if not self._on_device.get(which) or incremental:
            return super()._make_stage(which, func, win, slide, wt, degree,
                                       name, incremental, result_fields,
                                       ordered, role)
        cfg = self.config
        if degree > 1:
            return WinFarmTPU(func, win, slide, wt, pardegree=degree,
                              name=name, ordered=ordered, config=cfg,
                              role=role, **self._dev_kw)
        seq_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, slide)
        return WinSeqTPU(func, win, slide, wt, name=name, config=seq_cfg,
                         role=role, **self._dev_kw)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return PaneFarmTPU(name=name, config=config, ordered=ordered,
                           plq_on_device=self._on_device["plq"],
                           wlq_on_device=self._on_device["wlq"],
                           **self._dev_kw, **kw)


class WinMapReduceTPU(WinMapReduce):
    """Win_MapReduce with per-stage device placement
    (win_mapreduce_gpu.hpp:171-521)."""

    def __init__(self, map_func, reduce_func, win_len, slide_len,
                 win_type=WinType.CB, map_degree=2, reduce_degree=1,
                 name="win_mr_tpu", map_on_device=True,
                 reduce_on_device=False, batch_len=512, device=None,
                 depth=None, use_pallas=False, compute_dtype=None,
                 use_resident=None, flush_rows=1 << 20, max_delay_ms=None,
                 **kw):
        self._on_device = {"map": map_on_device, "reduce": reduce_on_device}
        self._dev_kw = dict(batch_len=batch_len, device=device, depth=depth,
                            use_pallas=use_pallas,
                            compute_dtype=compute_dtype,
                            use_resident=use_resident, flush_rows=flush_rows,
                            max_delay_ms=max_delay_ms)
        super().__init__(map_func, reduce_func, win_len, slide_len, win_type,
                         map_degree=map_degree, reduce_degree=reduce_degree,
                         name=name, **kw)

    def _make_map_stage(self, map_func, n, name, incremental, result_fields):
        from .win_mapreduce import _MapStage
        if not self._on_device["map"] or incremental:
            return super()._make_map_stage(map_func, n, name, incremental,
                                           result_fields)
        return _MapStage(_host_standin(map_func), self.spec, n, name, None,
                         result_fields, self.config, device_fn=map_func,
                         device_opts=self._dev_kw)

    def _make_reduce_stage(self, reduce_func, n, degree, name, incremental,
                           result_fields, ordered):
        if not self._on_device["reduce"] or incremental:
            return super()._make_reduce_stage(reduce_func, n, degree, name,
                                              incremental, result_fields,
                                              ordered)
        cfg = self.config
        if degree > 1:
            return WinFarmTPU(reduce_func, n, n, WinType.CB, pardegree=degree,
                              name=name, ordered=ordered, config=cfg,
                              role=Role.REDUCE, **self._dev_kw)
        red_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, n)
        return WinSeqTPU(reduce_func, n, n, WinType.CB, name=name,
                         config=red_cfg, role=Role.REDUCE, **self._dev_kw)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return WinMapReduceTPU(name=name, config=config, ordered=ordered,
                               map_on_device=self._on_device["map"],
                               reduce_on_device=self._on_device["reduce"],
                               **self._dev_kw, **kw)
