"""Two-level nesting: Win_Farm / Key_Farm whose workers are whole Pane_Farm
or Win_MapReduce instances (reference Win_Farm/Key_Farm constructors III/IV,
win_farm.hpp:339-549, key_farm.hpp:210-334).

The reference fuses the two routing levels into dedicated nested emitters
(WF_NestedEmitter / KF_NestedEmitter, wf_nodes.hpp:199, kf_nodes.hpp:85);
here the same routing is obtained compositionally — the outer emitter feeds
each inner instance's own emitter — because the distribution math lives
entirely in the PatternConfig each nested instance is built with:

* WinFarmOf: instance i gets a private slide ``slide*pardegree`` and
  PatternConfig(0, 1, slide, i, pardegree, slide)  (win_farm.hpp:379);
* KeyFarmOf: instances keep the original slide with a plain config — keys,
  not windows, are partitioned (key_farm.hpp:252).

Inner instances are built unordered; the outer collector restores per-key
dense-id order (the KF_NestedCollector / WF_Collector role).
"""

from __future__ import annotations

from ..core.windows import PatternConfig, Role, WindowSpec
from ..runtime.emitters import Collector, StandardEmitter, default_routing
from .win_farm import WFCollectorNode, WFEmitterNode


class _NestedFarm:
    def __init__(self, name):
        self.name = name
        self.instances = []

    @property
    def result_schema(self):
        return self.instances[0].result_schema

    def _wire(self, df, upstreams, emitter, ordered):
        df.add(emitter)
        for up in upstreams:
            df.connect(up, emitter)
        tails = []
        for inst in self.instances:
            # each instantiate() call issues exactly one connect() from the
            # emitter, so output port i feeds instance i
            tails += inst.instantiate(df, [emitter])
        collector = (WFCollectorNode(name=f"{self.name}.collector") if ordered
                     else Collector(name=f"{self.name}.collector"))
        df.add(collector)
        for t in tails:
            df.connect(t, collector)
        return [collector]


class WinFarmOf(_NestedFarm):
    """Win_Farm of Pane_Farm / Win_MapReduce instances: windows are assigned
    round-robin to instances, each seeing a private slide."""

    def __init__(self, inner, pardegree=2, ordered=True, name="wf_nested"):
        super().__init__(name)
        self.pardegree = pardegree
        self.ordered = ordered
        spec = inner.spec
        self.spec = WindowSpec(spec.win_len, spec.slide_len, spec.win_type)
        slide = spec.slide_len
        self.instances = [
            inner.clone_with(
                name=f"{name}_wf_{i}", slide_len=slide * pardegree,
                config=PatternConfig(0, 1, slide, i, pardegree, slide),
                ordered=False)
            for i in range(pardegree)]

    def instantiate(self, df, upstreams):
        emitter = WFEmitterNode(self.spec, self.pardegree, 0, 1,
                                self.spec.slide_len, Role.SEQ,
                                name=f"{self.name}.emitter")
        return self._wire(df, upstreams, emitter, self.ordered)


class KeyFarmOf(_NestedFarm):
    """Key_Farm of Pane_Farm / Win_MapReduce instances: whole keys to
    instances."""

    def __init__(self, inner, pardegree=2, routing=None, ordered=True,
                 name="kf_nested"):
        super().__init__(name)
        self.pardegree = pardegree
        self.ordered = ordered
        self.routing = routing or default_routing
        spec = inner.spec
        self.spec = WindowSpec(spec.win_len, spec.slide_len, spec.win_type)
        self.instances = [
            inner.clone_with(
                name=f"{name}_kf_{i}",
                config=PatternConfig.plain(spec.slide_len), ordered=False)
            for i in range(pardegree)]

    def instantiate(self, df, upstreams):
        emitter = StandardEmitter(self.pardegree, self.routing,
                                  name=f"{self.name}.emitter")
        return self._wire(df, upstreams, emitter, self.ordered)
