"""Native-backed resident window core: C++ bookkeeping + device ring.

Same contract as ``ResidentWinSeqCore`` (process/flush producing result
batches), but the per-row window bookkeeping and staging-rectangle assembly
run in ``native/wf_native.cpp`` with the GIL released — the C++ hot loop the
reference runs per tuple (win_seq.hpp:268-474), feeding the same
``ResidentWindowExecutor`` device path.  Falls back to the pure-Python core
transparently when the payload field is not int64 (the native ABI ships one
int64 column) or the native library cannot be built.
"""

from __future__ import annotations

import ctypes
import os
import queue as _queue
import threading
import time
import weakref

import numpy as np

from ..core.tuples import MARKER_FIELD, Schema
from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..ops.functions import Reducer
from ..utils import profile

_ROLE_CODE = {Role.SEQ: 0, Role.PLQ: 1, Role.WLQ: 2, Role.MAP: 3,
              Role.REDUCE: 4}
_WIRE_DTYPES = (np.int8, np.int16, np.int32, np.int64)

#: per-natural-flush launch service (ms) below which dispatching at the
#: configured flush_rows keeps pace with the host loop (~26 ms of host
#: bookkeeping per 2^19-row flush at the measured ~20M rows/s; BASELINE.md
#: wire characterization).  Above it, each doubling of measured service
#: doubles the proactive flush multiple.
_FLUSH_SVC_MS = 30.0
_FLUSH_MULT_MAX = 16   # the prewarmed shape ladder's depth


def _pick_flush_mult(svc_ms) -> int:
    """Natural-dispatch size multiple for the measured per-natural-flush
    wire service: 1 while the wire keeps pace, doubling with service so a
    wire-stalled run issues ~flush_mult-times fewer, larger natural
    launches UP FRONT instead of discovering the stall one small launch
    at a time (the reactive coalescer only engages once the queue is
    already deep — VERDICT r3 item 1).  Power-of-2 multiples keep natural
    shapes on the exact bucket ladder prewarm_regular_ladder compiles."""
    if not svc_ms or svc_ms <= _FLUSH_SVC_MS:
        return 1
    mult = 1
    while mult < _FLUSH_MULT_MAX and svc_ms > _FLUSH_SVC_MS * mult:
        mult *= 2
    return mult


_U64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64, bit-identical to wf_native.cpp's mix64 — the key→shard
    hash, needed host-side to route a migrated key's blob to the shard
    sub-core that will process its future rows."""
    x = (x + 0x9E3779B97F4A7C15) & _U64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _U64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _U64
    return x ^ (x >> 31)


class NativeStateSnapshot:
    """Checkpoint handle over the native core's exported state blobs
    (recovery layer, docs/ROBUSTNESS.md "Native state ABI").

    Unlike the resident ring's RingSnapshot, the C++ tables are MUTABLE —
    the byte copy must happen at the barrier (wf_core_state_export runs on
    the node thread, under the drained cut) — so resolve(), on the
    supervisor's writer thread, only packages the already-captured bytes
    into the pickle-ready dict."""

    __slots__ = ("blobs", "abi")

    def __init__(self, blobs, abi: int):
        self.blobs = tuple(blobs)   # one bytes blob per key shard
        self.abi = int(abi)

    def resolve(self) -> dict:
        return {"kind": "native", "abi": self.abi, "blobs": self.blobs}


def _ship_loop(core_ref, ship_q, shard):
    """Ship-thread main: one thread per key shard, so the shards'
    device_put / dispatch / harvest overlap on the wire (a single thread
    would serialize all shards' transfers — the r1 bottleneck).  Resolves
    the core weakref per token so the thread never pins the core's
    lifetime (a dead core ends the loop)."""
    while True:
        tok = ship_q.get()
        if tok is None:
            return
        core = core_ref()
        if core is None:
            return
        core._ship_token(tok, shard)
        del core


class NativeResidentCore:
    """Drop-in for ResidentWinSeqCore with the hot loop in C++."""

    def __init__(self, spec: WindowSpec, reducer: Reducer,
                 batch_len: int = 8192, flush_rows: int = 1 << 20,
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide=None, device=None,
                 depth: int = 8, compute_dtype=None, shards: int = 1,
                 overlap: bool = True, worker_index: int = 0,
                 max_delay_ms=None, mesh=None):
        from ..native import load
        from ..ops.resident import (MeshResidentExecutor,
                                    ResidentWindowExecutor)
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        from ..ops.functions import MultiReducer
        if isinstance(reducer, MultiReducer):
            # counts come from window lengths and MAX over the position
            # field from the C++ archive's per-window last row (hpmax) —
            # e.g. YSB's COUNT + MAX(ts) + SUM(revenue) ships only revenue
            # while the whole hot loop stays in C++.  Remaining
            # device-worthy stats stage one int64 column per distinct
            # field (C++ kMaxFields = 4) into per-field device rings
            # (MultiFieldResidentExecutor) — the rich-aggregate form that
            # previously re-paid the Python hot loop (BASELINE.md round 5:
            # --rich-stats ingested 5.4M vs the native base's 10.8M).
            from .win_seq_tpu import split_pos_max
            dev, pos = split_pos_max(spec, reducer)
            if not dev:
                raise TypeError(
                    "native resident core needs >=1 device-worthy stat "
                    "after the pos-max split")
            self._dev_parts = dev
            self._pos_max_parts = pos
            self._count_parts = reducer.count_parts
        elif isinstance(reducer, Reducer):
            self._dev_parts = [reducer]
            self._pos_max_parts = []
            self._count_parts = []
        else:
            raise TypeError("native resident core needs a builtin "
                            "(Multi)Reducer")
        self._dev_part = self._dev_parts[0]
        self._ship_fields = tuple(dict.fromkeys(
            p.field for p in self._dev_parts))
        #: >1 device stat (several fields, or several ops over one field):
        #: per-field rings via MultiFieldResidentExecutor; the single-stat
        #: path keeps its regular-descriptor compression
        self._multi = len(self._dev_parts) > 1
        max_fields = int(self._lib.wf_max_fields())
        if len(self._ship_fields) > max_fields:
            raise TypeError(
                f"native resident core stages at most {max_fields} "
                f"payload columns (got fields {self._ship_fields})")
        if self._multi and any(np.issubdtype(p.dtype, np.floating)
                               for p in self._dev_parts):
            raise TypeError(
                "native multi-field staging ships int64 columns; float "
                "stats run on the Python resident core")
        self.spec = spec
        self.reducer = reducer
        self.field = self._dev_part.field
        self.out_field = self._dev_part.out_field
        self.config = config or PatternConfig.plain(spec.slide_len)
        self.role = role
        self.map_indexes = map_indexes
        self.result_ts_slide = (result_ts_slide if result_ts_slide is not None
                                else spec.slide_len)
        self.result_schema = Schema(**reducer.result_fields)
        self._result_dtype = self.result_schema.dtype()
        self._args = dict(batch_len=batch_len, flush_rows=flush_rows,
                          config=config, role=role, map_indexes=map_indexes,
                          result_ts_slide=result_ts_slide, device=device,
                          depth=depth, compute_dtype=compute_dtype,
                          worker_index=worker_index,
                          max_delay_ms=max_delay_ms, mesh=mesh)
        # latency bound (checked per process() call, chunk cadence)
        self.max_delay_s = (None if max_delay_ms is None
                            else max_delay_ms / 1e3)
        self._last_flush_t = None
        from .win_seq_tpu import resolve_worker_device, select_acc_dtype
        acc = select_acc_dtype(self._dev_part, compute_dtype, spec)
        #: per-field ring dtypes for the multi path (same rules as
        #: ResidentWinSeqCore: widest acc per field, consistent kind)
        self._acc_by_field = {}
        for p in self._dev_parts:
            a = select_acc_dtype(p, compute_dtype, spec)
            prev = self._acc_by_field.get(p.field)
            if prev is not None and prev.kind != a.kind:
                raise ValueError(
                    f"stats over field {p.field!r} disagree on "
                    f"accumulate kind ({prev} vs {a})")
            if prev is None or a.itemsize > prev.itemsize:
                self._acc_by_field[p.field] = a
        # key-sharded multithreading: shard t owns keys with
        # mix64(key) %% S == t (a hash decorrelated from the farm routing
        # modulus — see wf_native.cpp), each with an independent sub-core,
        # device ring, and launch queue; one GIL-released MT call
        # processes a chunk on S pool threads.  Shard rings spread over the
        # visible chips (worker_index * S + t round-robin) so a sharded
        # core on a multi-chip host keeps each shard's archive on its own
        # device, like the farms' per-worker device ownership.
        # cap at 256: the C++ MT path routes rows via a per-row shard-id
        # *byte* array (wf_native.cpp:wf_cores_process_mt), so ids beyond
        # u8 would alias and double-process rows
        self.shards = max(min(int(shards), 256), 1)
        if self._multi:
            stats = tuple((p.op, p.field) for p in self._dev_parts)
            if mesh is not None:
                # mesh-sharded per-field rings (P(kf, None)): the pod
                # deployment shape keeps the C++ hot loop for rich
                # aggregates too — same composition rule as the
                # single-stat mesh path (r2 weak #3 / r3 weak #5)
                from ..ops.resident import MeshMultiFieldResidentExecutor
                self.executors = [
                    MeshMultiFieldResidentExecutor(
                        self._ship_fields, stats=stats,
                        acc_dtypes=self._acc_by_field, mesh=mesh,
                        depth=depth)
                    for _t in range(self.shards)]
            else:
                from ..ops.resident import MultiFieldResidentExecutor
                self.executors = [
                    MultiFieldResidentExecutor(
                        self._ship_fields, stats=stats,
                        acc_dtypes=self._acc_by_field,
                        device=resolve_worker_device(
                            device, worker_index * self.shards + t),
                        depth=depth)
                    for t in range(self.shards)]
        elif mesh is not None:
            # mesh execution composes with host key-sharding: shard t's
            # sub-core keeps its own C++ bookkeeping AND its own
            # mesh-sharded ring (each P(kf, None) over every chip), so a
            # multicore host spreads the hot loop over its cores while
            # every shard's dispatches still serve all key groups in one
            # SPMD program (r3 weak #5: the pin to shards=1 re-paid the
            # single-threaded bookkeeping on exactly the pod config)
            self.executors = [
                MeshResidentExecutor(self._dev_part.op, mesh, depth=depth,
                                     acc_dtype=acc)
                for _t in range(self.shards)]
        else:
            self.executors = [
                ResidentWindowExecutor(
                    self._dev_part.op,
                    device=resolve_worker_device(
                        device, worker_index * self.shards + t),
                    depth=depth, acc_dtype=acc)
                for t in range(self.shards)]
        self.executor = self.executors[0]
        self._batch_len = int(batch_len)
        self._acc_wire = 3 if acc.itemsize >= 8 else 2
        self._flush_base = int(flush_rows)
        self._flush_mult = 1
        self._new_handles()
        #: recovery/rescale support requires the state-ABI symbols in the
        #: loaded .so (stale-library detection: snapshots decline loudly,
        #: check/graph.py's WF215 warns, rescale validate() refuses)
        self.has_state_abi = bool(getattr(self._lib, "wf_has_state_abi",
                                          False))
        #: control-plane keyed migration (control/rescale.py) — an
        #: instance attr, not a class attr: it follows the loaded library
        self.keyed_migratable = self.has_state_abi
        #: dataflow metrics sink, mirrored by Supervisor.attach_all (the
        #: core itself has no dataflow reference)
        self._obs_metrics = None
        #: recovery-mode latch (process_batches and friends): pins
        #: deterministic launch boundaries — no reactive coalescing, no
        #: proactive flush resizing — so a replayed run's per-launch
        #: emission regroups exactly like the original's
        self._recovery_mode = False
        # proactive dispatch sizing: seed the natural flush size from the
        # process-global wire weather (a warmup run's harvests populate
        # it), then retune per chunk from this core's own measured
        # service.  Latency-bounded cores keep their configured cadence —
        # growing flushes there would spend the max_delay budget on
        # purpose-built queueing.
        from ..ops import resident as _res
        # proactive sizing is OPT-IN (WF_PROACTIVE=1): the interleaved A/B
        # of 2026-07-31 (scripts/ab_proactive.py, BASELINE.md) measured it
        # LOSING to reactive coalescing — mult-8 naturals drove per-
        # dispatch service from 126-147 ms to 160-542 ms (the transfer
        # component is not negligible at 4M-row dispatches) and median
        # tps from 17.3M down to 14.6M.  The machinery stays: a wire
        # whose RTT dominates at these sizes (a real pod NIC, not the
        # dev tunnel) flips the trade the other way.
        self._proactive = (self.max_delay_s is None
                           and os.environ.get("WF_PROACTIVE", "")
                           not in ("", "0"))
        if self._proactive:
            self._flush_mult = _pick_flush_mult(_res.wire_weather_ms())
            if self._flush_mult > 1:
                for h in self._hs:
                    self._lib.wf_core_set_flush_rows(
                        h, self._flush_base * self._flush_mult)
        _res.stats_max("flush_mult_max", self._flush_mult)
        self._delegate = None
        self._offsets = None
        self._salvaged = []  # results drained during a raise, returned to
                             # a caller that catches and keeps going
        # overlap mode: a dedicated ship thread owns the executors —
        # device_put/dispatch/harvest run concurrently with the next
        # chunk's C++ bookkeeping (the C++ launch queue is mutex-guarded
        # for this producer/consumer split).  WF_NO_OVERLAP disables for
        # sweeps (a 1-core host pays GIL contention for the overlap).
        self._overlap = bool(overlap) and os.environ.get(
            "WF_NO_OVERLAP", "") in ("", "0")
        self._ship_exc = None
        #: launches allowed to pile up in the C++ queue before process()
        #: throttles — restores the backpressure the synchronous ship loop
        #: provided (each queued Launch holds a staged K*R block)
        self._max_pending = 2 * depth
        #: adaptive launch coalescing (wf_launch_coalesce): keep at most
        #: this many dispatches in flight un-serviced; beyond it, hold so
        #: the C++ queue deepens and queued launches fuse into fewer,
        #: larger dispatches (each dispatch costs an amortized wire RTT —
        #: BASELINE.md — so under stall fewer round trips win).
        #: Default 8 from the 2026-07-31 interleaved sweeps
        #: (scripts/sweep_window.py): 8 beat 4 on median in both weather
        #: bands (+~2M tps with depth 48); 32 collapses (queue thrash).
        #: WF_DISPATCH_WINDOW overrides for sweeps.
        self._dispatch_window = int(
            os.environ.get("WF_DISPATCH_WINDOW", "8"))
        #: absolute merged-rectangle area guard (cells = K * bucket(R)):
        #: stops pathological padded rectangles (one hot key at huge
        #: flush_rows) from blowing host memory; must admit a full
        #: ladder-deep merge of benchmark-shaped launches (16x of a
        #: 2^19-row flush = 2^23 cells)
        self._coalesce_cells = (1 << 24) // max(len(self._ship_fields), 1)
        if self._overlap:
            self._start_ship_threads()

    def _new_handles(self):
        """(Re)create the per-shard C++ cores with the constructor's
        config — shared by __init__ and state_restore (restore imports
        into FRESH handles rather than scrubbing live ones)."""
        spec, cfg = self.spec, self.config
        self._hs = [self._lib.wf_core_new(
            int(spec.win_len), int(spec.slide_len),
            0 if spec.win_type is WinType.CB else 1, _ROLE_CODE[self.role],
            int(cfg.id_outer), int(cfg.n_outer), int(cfg.slide_outer),
            int(cfg.id_inner), int(cfg.n_inner), int(cfg.slide_inner),
            int(self.map_indexes[0]), int(self.map_indexes[1]),
            int(self.result_ts_slide), self._batch_len, self._flush_base,
            self._acc_wire) for _ in range(self.shards)]
        if self._multi:
            # per-field widest wire dtype (ship_fields order): the C++
            # flush narrows each column independently against its ring
            mw = (ctypes.c_int * len(self._ship_fields))(*[
                3 if self._acc_by_field[f].itemsize >= 8 else 2
                for f in self._ship_fields])
            for h in self._hs:
                got = self._lib.wf_core_set_fields(
                    h, len(self._ship_fields), mw)
                if got != len(self._ship_fields):
                    # a short accept would leave the missing columns'
                    # rectangles uninitialized at take time — refuse
                    raise TypeError(
                        f"native core accepted {got} fields, "
                        f"need {len(self._ship_fields)}")
        if self._flush_mult > 1:
            for h in self._hs:
                self._lib.wf_core_set_flush_rows(
                    h, self._flush_base * self._flush_mult)
        self._harr = (ctypes.c_void_p * self.shards)(*self._hs)

    def _start_ship_threads(self):
        # one ship thread per shard: each owns its executor, so the
        # shards' wire traffic overlaps; threads hold only a weakref
        # (a live ship thread must not keep the core and its C++ heap
        # + device rings alive)
        self._out_q = _queue.SimpleQueue()
        self._ship_qs = [_queue.SimpleQueue()
                         for _ in range(self.shards)]
        self._ship_threads = [
            threading.Thread(
                target=_ship_loop,
                args=(weakref.ref(self), self._ship_qs[t], t),
                daemon=True, name=f"wf-ship.{t}")
            for t in range(self.shards)]
        for th in self._ship_threads:
            th.start()

    def _stop_worker(self):
        for t, th in enumerate(getattr(self, "_ship_threads", ()) or ()):
            if th is not None and th.is_alive():
                self._ship_qs[t].put(None)
                th.join(timeout=10)
        self._ship_threads = []

    def __del__(self):
        if getattr(self, "_overlap", False):
            self._stop_worker()
        for h in getattr(self, "_hs", None) or ():
            self._lib.wf_core_free(h)
        self._hs = []

    # ------------------------------------------------------------ ship thread

    def _ship_token(self, tok, shard):
        kind, ev = tok
        try:
            while self._ship_launch(shard, force=(kind == "drain")):
                pass
            got = (self.executors[shard].drain() if kind == "drain"
                   else self.executors[shard].poll())
            for item in got:
                self._out_q.put(item)
        except BaseException as e:  # surfaced on the node thread
            self._ship_exc = e
        finally:
            if ev is not None:
                ev.set()

    def _raise_ship_exc(self, drained):
        """Surface a ship-thread failure; results already drained are
        stashed and returned by the next successful call, so a caller that
        catches the error and keeps streaming does not lose windows.
        Clears the stored exception so it is raised once."""
        self._salvaged.extend(drained)
        exc, self._ship_exc = self._ship_exc, None
        raise exc

    def _drain_out_q(self):
        items = []
        while True:
            try:
                items.append(self._out_q.get_nowait())
            except _queue.Empty:
                break
        return items

    # ------------------------------------------------------------- delegate

    def _fall_back(self):
        """Switch to the pure-Python resident core (non-int64 payloads)."""
        from .win_seq_tpu import ResidentWinSeqCore
        self._delegate = ResidentWinSeqCore(self.spec, self.reducer,
                                            **self._args)
        if self._overlap:
            self._stop_worker()
        for h in self._hs:
            self._lib.wf_core_free(h)
        self._hs = []
        return self._delegate

    def _field_offsets(self, batch):
        if self._offsets is None:
            f = batch.dtype.fields
            if (any(fl not in f or f[fl][0] != np.int64
                    for fl in self._ship_fields)
                    or batch.dtype[MARKER_FIELD] != np.bool_):
                return None
            self._offsets = (batch.dtype.itemsize, f["key"][1], f["id"][1],
                             f["ts"][1], f[MARKER_FIELD][1],
                             f[self._ship_fields[0]][1])
            #: payload-column offsets, ship_fields order (the _f ABI)
            self._voffs = np.array([f[fl][1] for fl in self._ship_fields],
                                   dtype=np.int64)
        return self._offsets

    # ------------------------------------------------------------ streaming

    # -- recovery (docs/ROBUSTNESS.md "Native state ABI") ------------------

    def _obs_count(self, name, n=1):
        m = self._obs_metrics
        if m is not None:
            m.counter(name).inc(n)

    def _obs_hist(self, name, v):
        m = self._obs_metrics
        if m is not None:
            m.histogram(name).observe(v)

    def _require_state_abi(self, what: str):
        """Loud decline when the loaded .so predates the state ABI — the
        same degradation as before the ABI existed (check WF215 warns at
        build time about exactly this)."""
        if not self.has_state_abi:
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                f"the loaded native library lacks the state ABI "
                f"(wf_core_state_export): {what} unsupported — rebuild "
                f"native/libwfnative.so (make -C native) or set "
                f"WF_NO_NATIVE_CORE=1 to run the Python resident core")

    def _enter_recovery_mode(self):
        """Pin deterministic launch boundaries for recovery-mode runs:
        reactive coalescing fuses queued launches by measured wire
        service and proactive sizing rescales flush_rows by wire weather
        — both wall-clock-driven, so a replayed run's launch boundaries
        (and with them the per-launch emission seqs) would diverge from
        the original's.  Natural flushes alone are count-triggered."""
        if self._recovery_mode:
            return
        self._recovery_mode = True
        self._proactive = False
        if self._flush_mult > 1:
            self._flush_mult = 1
            for h in self._hs:
                self._lib.wf_core_set_flush_rows(h, self._flush_base)
        if self._overlap:
            # ship threads drain into ONE completion-ordered queue, so a
            # multi-shard core's emission interleaving is wall-clock —
            # recovery runs ship synchronously in shard-major order
            # instead (deterministic, at the cost of the wire overlap)
            self._stop_worker()
            self._salvaged.extend(self._drain_out_q())
            self._overlap = False

    def _drain_entries(self):
        """Ship every queued launch and block out in-flight results;
        returns the raw per-launch harvest entries."""
        if self._overlap:
            evs = [threading.Event() for _ in self._ship_qs]
            for q, ev in zip(self._ship_qs, evs):
                q.put(("drain", ev))
            for ev in evs:
                ev.wait()
            drained = self._drain_out_q()
            if self._ship_exc is not None:
                self._raise_ship_exc(drained)
            out, self._salvaged = self._salvaged + drained, []
            return out
        harvested = []
        for t in range(self.shards):
            while self._ship_launch(t, force=True):
                pass
            harvested.extend(self.executors[t].drain())
        return harvested

    def process_batches(self, batch):
        """Recovery-mode process(): same work, ONE output batch per
        completed launch, in launch order (the _AsyncLaunchRecovery
        contract, win_seq_tpu.py).  Unlike the single-executor resident
        core, the sharded native core has one launch FIFO per shard with
        wall-clock completion interleaving — so recovery mode drains all
        shards each call and emits entries in shard-major order, trading
        the wire/compute overlap for deterministic emission boundaries."""
        if self._delegate is not None:
            return self._delegate.process_batches(batch)
        self._enter_recovery_mode()
        if len(batch) and self._field_offsets(batch) is None:
            return self._fall_back().process_batches(batch)
        self._process_rows(batch)
        return [self._harvest([e]) for e in self._drain_entries()]

    def flush_batches(self):
        if self._delegate is not None:
            return self._delegate.flush_batches()
        self._enter_recovery_mode()
        return [self._harvest([e]) for e in self._eos_and_drain()]

    def checkpoint_drain_batches(self):
        """Epoch-barrier drain (WinSeqNode.checkpoint_prepare): force-
        flush pending rows/windows into launches — NOT eos, unfired
        windows stay pending — and block out the in-flight results (they
        pre-date the snapshot cut and would otherwise be lost on
        restore).  Afterwards the C++ cores are drained, which is exactly
        the precondition wf_core_state_export checks."""
        if self._delegate is not None:
            return self._delegate.checkpoint_drain_batches()
        self._enter_recovery_mode()
        for h in self._hs:
            self._lib.wf_core_force_flush(h)
        return [self._harvest([e]) for e in self._drain_entries()]

    def state_snapshot(self):
        """Export the drained C++ state (per-key archives + window/
        ordering counters) into per-shard blobs.  Must run at a barrier
        after checkpoint_drain_batches — an undrained core refuses.
        Device ring contents never cross: restore zeroes the ring
        geometry and the next flush rebases from the imported archives,
        the native analog of the resident core's no-ring-snapshot path."""
        if self._delegate is not None:
            return {"kind": "native_delegate",
                    "inner": self._delegate.state_snapshot()}
        self._require_state_abi("epoch snapshots")
        if self.max_delay_s is not None:
            # wall-clock flushes make replay launch boundaries (and so
            # emission seqs) nondeterministic — same decline as the
            # Python resident core's
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                "max_delay_ms wall-clock flushes make replay emission "
                "boundaries nondeterministic; recovery supports "
                "count-triggered flushes only")
        lib = self._lib
        blobs = []
        for h in self._hs:
            n = int(lib.wf_core_state_size(h))
            if n < 0:
                raise RuntimeError(
                    "native core not drained at the snapshot barrier "
                    "(checkpoint_prepare must flush + drain first)")
            buf = np.empty(max(n, 1), dtype=np.uint8)
            got = int(lib.wf_core_state_export(h, buf.ctypes.data, n))
            if got != n:
                raise RuntimeError(
                    f"native state export wrote {got} of {n} bytes")
            blobs.append(buf[:n].tobytes())
        nbytes = sum(len(b) for b in blobs)
        self._obs_count("native_state_exports")
        self._obs_count("native_state_export_bytes", nbytes)
        self._obs_hist("native_state_blob_bytes", nbytes)
        return NativeStateSnapshot(blobs, abi=int(lib.wf_abi_version()))

    def state_restore(self, snap):
        if isinstance(snap, NativeStateSnapshot):
            snap = snap.resolve()
        kind = snap.get("kind")
        if kind == "native_delegate":
            if self._delegate is None:
                self._fall_back()
            self._delegate.state_restore(snap["inner"])
            return
        if kind != "native":
            raise RuntimeError(
                f"NativeResidentCore cannot restore snapshot kind {kind!r}")
        self._require_state_abi("state restore")
        blobs = snap["blobs"]
        if len(blobs) != self.shards:
            raise RuntimeError(
                f"snapshot has {len(blobs)} shard blobs, core has "
                f"{self.shards} shards")
        # ship threads reach the C++ handles through queued tokens: join
        # them BEFORE freeing (use-after-free otherwise), rebuild after
        if self._overlap:
            self._stop_worker()
        for h in self._hs:
            self._lib.wf_core_free(h)
        self._hs = []
        self._new_handles()
        nbytes = 0
        for h, blob in zip(self._hs, blobs):
            buf = np.frombuffer(blob, dtype=np.uint8)
            rc = int(self._lib.wf_core_state_import(
                h, buf.ctypes.data, len(blob)))
            if rc != 0:
                raise RuntimeError(
                    f"native state import failed (code {rc})")
            nbytes += len(blob)
        # executors: drop in-flight work and rings from the crashed run;
        # the imported cores rebase at their next flush, re-shipping
        # every live row
        for ex in self.executors:
            inv = getattr(ex, "invalidate", None)
            if inv is not None:
                inv()
            else:
                ex._inflight.clear()
                ex._ready = []
        self._salvaged = []
        self._ship_exc = None
        self._last_flush_t = None
        if self._overlap:
            self._start_ship_threads()
        self._obs_count("native_state_imports")
        self._obs_count("native_state_import_bytes", nbytes)

    # -- control-plane keyed migration (control/rescale.py) ---------------

    def _shard_of(self, key: int) -> int:
        return int(_mix64(key & _U64) % self.shards) if self.shards > 1 \
            else 0

    def keyed_state_keys(self):
        """Keys with live native state, across all shards (sorted for a
        deterministic migration selection)."""
        self._require_state_abi("keyed-state migration")
        if self._delegate is not None:
            raise RuntimeError(
                "native core fell back to the Python delegate mid-stream; "
                "keyed migration state is no longer in the C++ tables")
        from ..native import p_i64
        lib = self._lib
        parts = []
        for h in self._hs:
            n = int(lib.wf_core_key_count(h))
            if n == 0:
                continue
            arr = np.empty(n, dtype=np.int64)
            got = int(lib.wf_core_key_list(
                h, arr.ctypes.data_as(p_i64), n))
            parts.append(arr[:min(got, n)])
        if not parts:
            return np.zeros(0, dtype=np.int64)
        return np.sort(np.concatenate(parts))

    def keyed_state_export(self, keys):
        """Export-and-neutralize the given keys (move semantics, like
        WinSeqCore's pop): the old owner never emits their windows again;
        the blobs re-import on the new owner inside the same barrier."""
        self._require_state_abi("keyed-state migration")
        lib = self._lib
        blobs = {}
        for k in np.asarray(keys, dtype=np.int64).tolist():
            k = int(k)
            for h in self._hs:
                n = int(lib.wf_core_key_state_size(h, k))
                if n != -2:     # -2 = key not on this shard
                    break
            if n < 0:
                raise RuntimeError(
                    f"native keyed export refused for key {k} "
                    f"(code {n}: core not drained or key unknown)")
            buf = np.empty(max(n, 1), dtype=np.uint8)
            got = int(lib.wf_core_key_export(h, k, buf.ctypes.data, n))
            if got != n:
                raise RuntimeError(
                    f"native keyed export wrote {got} of {n} bytes "
                    f"for key {k}")
            rc = int(lib.wf_core_key_neutralize(h, k))
            if rc != 0:
                raise RuntimeError(
                    f"native key neutralize failed for key {k} "
                    f"(code {rc})")
            blobs[k] = buf[:n].tobytes()
        nbytes = sum(len(b) for b in blobs.values())
        self._obs_count("native_state_exports")
        self._obs_count("native_state_export_bytes", nbytes)
        self._obs_hist("native_state_blob_bytes", nbytes)
        return {"kind": "native_keys",
                "abi": int(lib.wf_abi_version()), "blobs": blobs}

    def keyed_state_import(self, frag):
        self._require_state_abi("keyed-state migration")
        kind = frag.get("kind")
        if kind != "native_keys":
            raise TypeError(
                f"native core cannot import fragment kind {kind!r}")
        lib = self._lib
        nbytes = 0
        for k, blob in frag["blobs"].items():
            buf = np.frombuffer(blob, dtype=np.uint8)
            rc = int(lib.wf_core_key_import(
                self._hs[self._shard_of(int(k))],
                buf.ctypes.data, len(blob)))
            if rc != 0:
                raise RuntimeError(
                    f"native keyed import failed for key {k} (code {rc})")
            nbytes += len(blob)
        self._obs_count("native_state_imports")
        self._obs_count("native_state_import_bytes", nbytes)

    def process(self, batch: np.ndarray) -> np.ndarray:
        if self._delegate is not None:
            return self._delegate.process(batch)
        if len(batch) == 0 and self.max_delay_s is None:
            # keepalive harvesting only matters under a latency bound
            return np.zeros(0, dtype=self._result_dtype)
        if len(batch) and self._field_offsets(batch) is None:
            return self._fall_back().process(batch)
        self._process_rows(batch)
        if self._overlap:
            drained = self._drain_out_q()
            if self._ship_exc is not None:
                self._raise_ship_exc(drained)
            out, self._salvaged = self._salvaged + drained, []
            return self._harvest(out)
        harvested = []
        for t in range(self.shards):
            while self._ship_launch(t):
                pass
            harvested.extend(self.executors[t].poll())
        return self._harvest(harvested)

    def _process_rows(self, batch):
        """Feed one chunk through the C++ bookkeeping (flush cadence,
        proactive sizing, ship-thread pokes + backpressure included);
        harvest collection is the caller's (process vs process_batches)."""
        b = np.ascontiguousarray(batch) if len(batch) else None
        launched = 0
        if b is not None:
            itemsize, o_key, o_id, o_ts, o_mk, o_val = self._offsets
            with profile.span("native_bookkeeping"):
                if self._multi:
                    from ..native import p_i64
                    launched = self._lib.wf_cores_process_mt_f(
                        self._harr, self.shards, b.ctypes.data, len(b),
                        itemsize, o_key, o_id, o_ts, o_mk,
                        self._voffs.ctypes.data_as(p_i64))
                else:
                    launched = self._lib.wf_cores_process_mt(
                        self._harr, self.shards, b.ctypes.data, len(b),
                        itemsize, o_key, o_id, o_ts, o_mk, o_val)
        if self.max_delay_s is not None:
            now = time.monotonic()
            if self._last_flush_t is None or launched:
                # natural flushes restart the latency clock: a saturated
                # stream must not fragment launches at max_delay cadence
                self._last_flush_t = now
            elif now - self._last_flush_t >= self.max_delay_s:
                # ship pending windows/rows now (test_micro latency bound)
                for h in self._hs:
                    self._lib.wf_core_force_flush(h)
                self._last_flush_t = now
        elif self._proactive and self._hs:
            # proactive flush sizing, chunk cadence: fold this core's
            # measured launch service into the global weather and retune.
            # The service is NOT normalized by dispatch size: the tunnel
            # wire is latency-dominated (BASELINE.md: per-dispatch RTT
            # 50-250+ ms against single-digit-ms transfer at these sizes),
            # so a 165 ms launch at mult 4 argues for BIGGER dispatches,
            # not "41 ms each, downsize".  The residual size-dependent
            # component only kicks in at the deep multiples, where the
            # rule has already saturated at the ladder cap.
            from ..ops import resident as _res
            _res.stats_max("flush_mult_max", self._flush_mult)
            svc = max(ex.mean_service_s() for ex in self.executors)
            if svc > 0.0:
                # the global weather is fed per harvested launch
                # (resident._note_service, always-on) — folding the
                # chunk-cadence MEAN here again would both double-feed
                # the EMA and flood the 16-slot floor window with mean
                # values, evicting the genuine fast-launch minima the
                # budget routing keys on
                desired = _pick_flush_mult(_res.wire_weather_ms())
                if desired != self._flush_mult:
                    self._flush_mult = desired
                    _res.stats_max("flush_mult_max", desired)
                    for h in self._hs:
                        self._lib.wf_core_set_flush_rows(
                            h, self._flush_base * desired)
        if self._overlap:
            for q in self._ship_qs:
                q.put(("ship", None))
            # backpressure: if the device path is slower than ingestion,
            # wait for the ship threads to work the C++ queues down
            # (re-poking them each beat: a ship thread that held a launch
            # for coalescing has no other wake-up once tokens stop)
            with profile.span("backpressure_wait"):
                beats = 0
                while (self._ship_exc is None
                       and max(self._lib.wf_launch_pending(h)
                               for h in self._hs) > self._max_pending):
                    time.sleep(0.001)
                    beats += 1
                    if beats % 20 == 0:
                        for q in self._ship_qs:
                            q.put(("ship", None))

    def _eos_and_drain(self):
        """EOS every shard core, then ship + drain everything; returns
        the raw per-launch harvest entries (flush/flush_batches share
        this tail)."""
        from ..ops.resident import stats_add, stats_max
        t_eos = time.monotonic()
        backlog = 0
        for h in self._hs:
            self._lib.wf_core_eos(h)
            backlog += self._lib.wf_launch_pending(h)
        backlog += sum(len(ex._inflight) for ex in self.executors)
        out = self._drain_entries()
        # EOS drain accounting (VERDICT r4 #3): how long the finite-
        # run tail waits on the wire and how deep the backlog was —
        # the end-to-end-vs-ingest gap is exactly this number
        stats_add("drain_ms", 1e3 * (time.monotonic() - t_eos))
        stats_max("drain_backlog_max", backlog)
        return out

    def flush(self) -> np.ndarray:
        if self._delegate is not None:
            return self._delegate.flush()
        return self._harvest(self._eos_and_drain())

    def use_incremental(self):
        raise TypeError("the device path is non-incremental only "
                        "(win_seq_gpu.hpp supports NIC device functors)")

    # ------------------------------------------------------- launch plumbing

    def _ship_launch(self, shard: int = 0, force: bool = False) -> bool:
        lib = self._lib
        handle = self._hs[shard]
        ex = self.executors[shard]
        pending = lib.wf_launch_pending(handle)
        if pending == 0:
            return False
        # recovery mode never coalesces: merged launches would make the
        # per-launch emission boundaries wall-clock-dependent (replay
        # would regroup differently and break the per-edge seq dedup)
        coalesce = (not os.environ.get("WF_NO_COALESCE")
                    and not self._recovery_mode)
        if (coalesce and not force and pending <= self._max_pending
                and self.max_delay_s is None):
            # (beyond _max_pending the hold is skipped: the producer's
            # backpressure loop waits on this queue, so holding there
            # would livelock — and the memory bound outranks RTT savings.
            # A latency-bounded core never holds: a launch parked behind
            # a stalled wire would blow the max_delay budget by design.)
            if ex.unready_count() >= self._dispatch_window:
                # wire saturated: hold this launch so the queue deepens and
                # the next ship fuses the backlog into one dispatch
                return False
        if coalesce and pending > 1:
            # merge depth follows measured wire service: each dispatch
            # costs an amortized RTT, so when launches take >20 ms to come
            # back the buddy ladder is allowed deeper ({1x,2x,4x} -> up to
            # 16x), cutting a backlogged run's dispatch count ~4x further.
            # Shapes stay on the power-of-2 ladder either way; benchmarks
            # pre-compile the deep buckets via prewarm_regular_ladder().
            svc = ex.mean_service_s()
            max_mult = 16 if svc >= 0.05 else (8 if svc >= 0.02 else 4)
            # proactively upsized naturals are already flush_mult flushes
            # wide: cap the reactive ladder so total dispatch size stays
            # within the 16x of a BASE flush that prewarm compiled and the
            # ring was provisioned for
            max_mult = min(max_mult,
                           max(1, _FLUSH_MULT_MAX // self._flush_mult))
            merged = lib.wf_launch_coalesce(handle, self._coalesce_cells,
                                            16, max_mult)
            if merged:
                from ..ops.resident import stats_add
                stats_add("merges", merged)
        K = ctypes.c_longlong()
        R = ctypes.c_longlong()
        B = ctypes.c_longlong()
        KP = ctypes.c_longlong()
        cap = ctypes.c_longlong()
        wire = ctypes.c_int()
        rebase = ctypes.c_int()
        if not lib.wf_launch_peek(handle, ctypes.byref(K), ctypes.byref(R),
                                  ctypes.byref(B), ctypes.byref(wire),
                                  ctypes.byref(rebase), ctypes.byref(KP),
                                  ctypes.byref(cap)):
            return False
        K, R, B = K.value, R.value, B.value
        # allocate the device-ready zero-padded rectangle(s) and let the
        # C++ take fill them directly (no _pad2 re-copy on this thread)
        from ..ops.device import _bucket
        KPp, Rb = KP.value, _bucket(max(R, 1))
        blks = blk = None
        if self._multi:
            # one rectangle per ship field, each in the per-field wire
            # dtype the C++ flush narrowed that column to
            wires = (ctypes.c_int * len(self._ship_fields))()
            lib.wf_launch_peek_wires(handle, wires)
            blks = {f: np.empty((KPp, Rb), dtype=_WIRE_DTYPES[wires[i]])
                    for i, f in enumerate(self._ship_fields)}
        else:
            blk = np.empty((KPp, Rb), dtype=_WIRE_DTYPES[wire.value])
        offs = np.empty(K, dtype=np.int64)
        wrows = np.empty(max(B, 1), dtype=np.int32)
        hkey = np.empty(max(B, 1), dtype=np.int64)
        hid = np.empty(max(B, 1), dtype=np.int64)
        hts = np.empty(max(B, 1), dtype=np.int64)
        hlen = np.empty(max(B, 1), dtype=np.int64)
        hpm = (np.empty(max(B, 1), dtype=np.int64)
               if any(p.op == "max" for p in self._pos_max_parts)
               else None)
        hpmn = (np.empty(max(B, 1), dtype=np.int64)
                if any(p.op == "min" for p in self._pos_max_parts)
                else None)
        p32 = ctypes.POINTER(ctypes.c_int32)
        p64 = ctypes.POINTER(ctypes.c_longlong)
        regular = False
        cmax = ctypes.c_longlong()
        if (not self._multi and self._dev_part.op == "sum"
                and lib.wf_launch_peek_regular(handle, ctypes.byref(cmax))):
            regular = True
            rcount = np.empty(K, dtype=np.int32)
            rstart0 = np.empty(K, dtype=np.int32)
            rlen = np.empty(K, dtype=np.int32)
            widx = np.empty(max(B, 1), dtype=np.int32)
            lib.wf_launch_take_regular(
                handle, rcount.ctypes.data_as(p32),
                rstart0.ctypes.data_as(p32), rlen.ctypes.data_as(p32),
                widx.ctypes.data_as(p32))
        if regular:
            wstarts = wlens = None   # unread: skip the B*4-byte copies
            wstarts_p = wlens_p = None
        else:
            wstarts = np.empty(max(B, 1), dtype=np.int32)
            wlens = np.empty(max(B, 1), dtype=np.int32)
            wstarts_p = wstarts.ctypes.data_as(p32)
            wlens_p = wlens.ctypes.data_as(p32)
        with profile.span("launch_take"):
            if self._multi:
                ptrs = (ctypes.c_void_p * len(self._ship_fields))(
                    *[b.ctypes.data for b in blks.values()])
                lib.wf_launch_take_padded_f(
                    handle, ptrs, KPp, Rb,
                    offs.ctypes.data_as(p64), wrows.ctypes.data_as(p32),
                    wstarts_p, wlens_p,
                    hkey.ctypes.data_as(p64), hid.ctypes.data_as(p64),
                    hts.ctypes.data_as(p64), hlen.ctypes.data_as(p64),
                    hpm.ctypes.data_as(p64) if hpm is not None else None,
                    hpmn.ctypes.data_as(p64) if hpmn is not None else None)
            else:
                lib.wf_launch_take_padded(
                    handle, blk.ctypes.data_as(ctypes.c_void_p), KPp, Rb,
                    offs.ctypes.data_as(p64), wrows.ctypes.data_as(p32),
                    wstarts_p, wlens_p,
                    hkey.ctypes.data_as(p64), hid.ctypes.data_as(p64),
                    hts.ctypes.data_as(p64), hlen.ctypes.data_as(p64),
                    hpm.ctypes.data_as(p64) if hpm is not None else None,
                    hpmn.ctypes.data_as(p64) if hpmn is not None else None)
        if rebase.value:
            ex.reset(max(K, 1), cap.value)
        if getattr(ex, "mesh", None) is not None:
            # the mesh executors re-scatter rows onto their own (shard-
            # rounded) KP; hand them the live rows only, not the C++
            # padding
            if blk is not None:
                blk = blk[:K]
            if blks is not None:
                blks = {f: b[:K] for f, b in blks.items()}
        meta = (hkey[:B], hid[:B], hts[:B], hlen[:B],
                hpm[:B] if hpm is not None else None,
                hpmn[:B] if hpmn is not None else None)
        if self._multi:
            ex.launch(meta, blks, offs, wrows[:B], wstarts[:B], wlens[:B])
        elif regular:
            # per-key arithmetic descriptors instead of 3x B int32 arrays
            ex.launch_regular(meta, blk, offs, rcount, rstart0, rlen,
                              self.spec.slide_len, wrows[:B], widx[:B],
                              cmax=cmax.value)
        else:
            ex.launch(meta, blk, offs, wrows[:B], wstarts[:B], wlens[:B])
        return True

    def _harvest(self, harvested) -> np.ndarray:
        if not harvested:
            return np.zeros(0, dtype=self._result_dtype)
        from .win_seq_tpu import finalize_window_values
        outs = []
        for (hkey, hid, hts, hlen, hpm, hpmn), out in harvested:
            # multi executors return one array per stat (dev_parts
            # order); the single path returns the stat array itself
            arrs = out if isinstance(out, tuple) else (out,)
            res = np.zeros(len(arrs[0]), dtype=self._result_dtype)
            res["key"] = hkey
            res["id"] = hid
            res["ts"] = hts
            for part, a in zip(self._dev_parts, arrs):
                res[part.out_field] = finalize_window_values(part, a, hlen)
            for part in self._count_parts:
                res[part.out_field] = hlen.astype(part.dtype)
            for part in self._pos_max_parts:
                res[part.out_field] = finalize_window_values(
                    part, hpm if part.op == "max" else hpmn, hlen)
            outs.append(res)
        return outs[0] if len(outs) == 1 else np.concatenate(outs)
