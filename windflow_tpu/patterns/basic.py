"""Basic streaming patterns: Source, Map, Filter, FlatMap, Accumulator, Sink.

Functional parity with the reference L3a patterns (source.hpp, map.hpp,
filter.hpp, flatmap.hpp, accumulator.hpp, sink.hpp): every user-function
flavour — {itemized, loop} sources; {in-place, non-in-place} maps; plain and
"rich" (RuntimeContext-receiving) variants; optional keyed routing — plus a
`vectorized` flavour the reference cannot express: the user function operates
on the whole structure-of-arrays batch, which is the idiomatic form here and
the only one used on hot paths.

Each pattern class is a *node factory*: `replicas()` returns the worker
nodes, and `emitter()`/`collector()` the routing shell, which MultiPipe (or
a manual Dataflow) wires into a farm, mirroring the reference's
ff_farm(emitter, workers, collector) structure (map.hpp:196-209).
"""

from __future__ import annotations

import os
import sys

import numpy as np

from ..core.tuples import MARKER_FIELD, Schema
from ..runtime.emitters import Collector, StandardEmitter, default_routing
from ..runtime.node import Node, RuntimeContext, SourceNode


class Shipper:
    """Push-many output handle for loop-sources and flatmaps
    (shipper.hpp:52-105), buffering rows into batches."""

    def __init__(self, schema: Schema, emit_fn, chunk: int = 4096):
        self._schema = schema
        self._dtype = schema.dtype()
        self._emit = emit_fn
        self._chunk = chunk
        self._rows = []
        self.delivered = 0

    def push(self, key=0, id=0, ts=0, **payload):
        row = np.zeros((), dtype=self._dtype)
        row["key"], row["id"], row["ts"] = key, id, ts
        for k, v in payload.items():
            row[k] = v
        self._rows.append(row)
        self.delivered += 1
        if len(self._rows) >= self._chunk:
            self.flush()

    def push_batch(self, batch: np.ndarray):
        """Vectorised push of a whole pre-built batch."""
        self.flush()
        self.delivered += len(batch)
        self._emit(batch)

    def flush(self):
        if self._rows:
            self._emit(np.stack(self._rows))
            self._rows = []


_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def user_call_site() -> tuple[str, int] | None:
    """(filename, lineno) of the nearest stack frame OUTSIDE the
    windflow_tpu package — the line where user/app code constructed the
    pattern.  Static-analysis diagnostics (windflow_tpu/check/,
    docs/CHECKS.md) anchor there, and ``# wf-lint: disable=WF###`` on
    that line suppresses them.  Construction-time only — never on a hot
    path — and best-effort: None when everything on the stack is
    internal (e.g. tests driving patterns through framework helpers)."""
    pkg = _PKG_DIR + os.sep      # separator-guarded: a sibling dir whose
    apps = os.path.join(_PKG_DIR, "apps") + os.sep   # name merely shares
    f = sys._getframe(1)                             # the prefix is user code
    for _ in range(24):
        if f is None:
            return None
        fname = os.path.abspath(f.f_code.co_filename)
        # the bundled bench apps are *user* code for anchoring purposes
        if not fname.startswith(pkg) or fname.startswith(apps):
            return (f.f_code.co_filename, f.f_lineno)
        f = f.f_back
    return None


class _Pattern:
    """Common shell: parallelism + optional keyed routing."""

    def __init__(self, name, parallelism=1, routing=None):
        self.name = name
        self.parallelism = parallelism
        self.routing = routing  # vectorised fn(keys, n) -> dest
        #: construction-site anchor for check/ diagnostics
        self.anchor = user_call_site()

    def emitter(self):
        return StandardEmitter(self.parallelism, self.routing,
                               name=f"{self.name}.emitter")

    def collector(self):
        return Collector(name=f"{self.name}.collector")

    def replicas(self):
        return [self._make_replica(i) for i in range(self.parallelism)]

    def _make_replica(self, i) -> Node:
        raise NotImplementedError


# --------------------------------------------------------------------- Source

class _ItemizedSourceNode(SourceNode):
    """Itemized source: fn(shipper-row emit) -> bool continue
    (source.hpp:59-65, itemized flavour fn(tuple&)->bool)."""

    yields_fresh = True   # every emission is a fresh np.stack

    def __init__(self, fn, schema, name, rich, chunk=4096):
        super().__init__(name)
        self.fn = fn
        self.schema = schema
        self.rich = rich
        self.chunk = chunk

    def generate(self):
        dtype = self.schema.dtype()
        rows = []
        alive = True
        while alive:
            row = np.zeros((), dtype=dtype)
            alive = (self.fn(row, self.ctx) if self.rich else self.fn(row))
            rows.append(row)
            if len(rows) >= self.chunk or not alive:
                self.emit(np.stack(rows))
                rows = []


class _LoopSourceNode(SourceNode):
    """Loop source: fn(Shipper) called once (source.hpp:134-144)."""

    def __init__(self, fn, schema, name, rich, chunk=4096):
        super().__init__(name)
        self.fn = fn
        self.schema = schema
        self.rich = rich
        self.chunk = chunk

    def generate(self):
        shipper = Shipper(self.schema, self.emit, self.chunk)
        if self.rich:
            self.fn(shipper, self.ctx)
        else:
            self.fn(shipper)
        shipper.flush()


class _BatchSourceNode(SourceNode):
    """Vectorised source: an iterable of ready-made batches."""

    def __init__(self, batches, name):
        super().__init__(name)
        self.batches = batches

    def generate(self):
        for b in self.batches:
            self.emit(b)


class Source(_Pattern):
    def __init__(self, fn=None, schema: Schema = None, parallelism=1,
                 name="source", rich=False, itemized=False, batches=None,
                 chunk=4096, fresh=False):
        super().__init__(name, parallelism)
        self.fn = fn
        self.schema = schema
        self.rich = rich
        self.itemized = itemized
        self.batches = batches
        self.chunk = chunk
        #: app declaration (node.py ownership protocol): every batch the
        #: generator pushes / the iterable yields is transfer-owned — the
        #: app never touches it again, so fused downstream stages may
        #: mutate it in place instead of copying
        self.fresh = fresh

    def _make_replica(self, i):
        ctx = RuntimeContext(self.parallelism, i, self.name)
        if self.batches is not None:
            src = self.batches(i) if callable(self.batches) else self.batches
            node = _BatchSourceNode(src, f"{self.name}.{i}")
            node.yields_fresh = bool(self.fresh)
        elif self.itemized:
            node = _ItemizedSourceNode(self.fn, self.schema, f"{self.name}.{i}",
                                       self.rich, self.chunk)
        else:
            node = _LoopSourceNode(self.fn, self.schema, f"{self.name}.{i}",
                                   self.rich, self.chunk)
            node.yields_fresh = bool(self.fresh)
        node.ctx = ctx
        return node

    def emitter(self):
        return None  # sources have no input side


# ----------------------------------------------------------------------- Map

class _MapNode(Node):
    shed_safe = True   # stateless operator: shedding drops stream rows
    recoverable = True  # stateless: supervised restart needs no snapshot
    #: always true: emits either its private copy, a fresh out-schema
    #: array, or (elided path) an input batch that was itself handed off
    yields_fresh = True

    def __init__(self, fn, name, rich, vectorized, out_schema):
        super().__init__(name)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized
        self.out_schema = out_schema  # None => in-place

    def svc(self, batch, channel=0):
        args = (self.ctx,) if self.rich else ()
        if self.out_schema is None:
            # in-place semantics (map.hpp:141): on a handed-off batch the
            # runtime proved nobody else holds (input_fresh, node.py
            # ownership protocol) mutate directly; otherwise on a private
            # copy.  The copy was 0.26 s of the 8M-row pipe benchmark.
            out = batch if self.input_fresh else batch.copy()
            if self.vectorized:
                self.fn(out, *args)
            else:
                for row in out:
                    self.fn(row, *args)
        else:
            out = np.zeros(len(batch), dtype=self.out_schema.dtype())
            for f in ("key", "id", "ts", MARKER_FIELD):
                out[f] = batch[f]
            if self.vectorized:
                self.fn(batch, out, *args)
            else:
                for i in range(len(batch)):
                    self.fn(batch[i], out[i], *args)
        self.emit(out)


class Map(_Pattern):
    """Map: in-place fn(row) / non-in-place fn(in_row, out_row), plain or
    rich or vectorized (whole-batch), optional keyed routing
    (map.hpp:60-68)."""

    def __init__(self, fn, parallelism=1, name="map", rich=False,
                 vectorized=False, output_schema: Schema = None, routing=None,
                 keyed=False):
        if keyed and routing is None:
            routing = default_routing
        super().__init__(name, parallelism, routing)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized
        self.output_schema = output_schema

    def _make_replica(self, i):
        node = _MapNode(self.fn, f"{self.name}.{i}", self.rich,
                        self.vectorized, self.output_schema)
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node


# -------------------------------------------------------------------- Filter

class _FilterNode(Node):
    shed_safe = True   # stateless operator: shedding drops stream rows
    recoverable = True  # stateless: supervised restart needs no snapshot
    #: the surviving-rows gather is a fresh allocation every time
    yields_fresh = True

    def __init__(self, fn, name, rich, vectorized):
        super().__init__(name)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized

    def svc(self, batch, channel=0):
        args = (self.ctx,) if self.rich else ()
        if self.vectorized:
            mask = np.asarray(self.fn(batch, *args), dtype=bool)
        else:
            mask = np.fromiter((bool(self.fn(row, *args)) for row in batch),
                               dtype=bool, count=len(batch))
        out = batch[mask]
        if len(out):
            self.emit(out)


class Filter(_Pattern):
    """Filter: drop rows where fn is false (filter.hpp:59-61)."""

    def __init__(self, fn, parallelism=1, name="filter", rich=False,
                 vectorized=False, routing=None, keyed=False):
        if keyed and routing is None:
            routing = default_routing
        super().__init__(name, parallelism, routing)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized

    def _make_replica(self, i):
        node = _FilterNode(self.fn, f"{self.name}.{i}", self.rich,
                           self.vectorized)
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node


# ------------------------------------------------------------------- FlatMap

class _FlatMapNode(Node):
    shed_safe = True   # stateless operator: shedding drops stream rows
    #: the shipper flushes per input batch, so between svc calls (where
    #: epoch snapshots happen) there is no state to capture
    recoverable = True

    def __init__(self, fn, name, rich, vectorized, out_schema, chunk):
        super().__init__(name)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized
        self.out_schema = out_schema
        self.chunk = chunk
        self._shipper = None

    def svc_init(self):
        self._shipper = Shipper(self.out_schema, self.emit, self.chunk)

    def svc(self, batch, channel=0):
        args = (self.ctx,) if self.rich else ()
        if self.vectorized:
            self.fn(batch, self._shipper, *args)
        else:
            for row in batch:
                self.fn(row, self._shipper, *args)
        # flush per input batch to bound latency (one-to-any, flatmap.hpp:61)
        self._shipper.flush()


class FlatMap(_Pattern):
    """FlatMap: fn(row, shipper) pushing 0..n rows per input
    (flatmap.hpp:61-63)."""

    def __init__(self, fn, output_schema: Schema, parallelism=1,
                 name="flatmap", rich=False, vectorized=False, routing=None,
                 keyed=False, chunk=4096):
        if keyed and routing is None:
            routing = default_routing
        super().__init__(name, parallelism, routing)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized
        self.output_schema = output_schema
        self.chunk = chunk

    def _make_replica(self, i):
        node = _FlatMapNode(self.fn, f"{self.name}.{i}", self.rich,
                            self.vectorized, self.output_schema, self.chunk)
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node


# --------------------------------------------------------------- Accumulator

class _AccumulatorNode(Node):
    shed_safe = True   # keyed fold: shedding drops rows, no dense-id need
    recoverable = True          # per-key fold state deep-copies cleanly
    state_attrs = ("_keys",)    # key -> accumulator record

    def __init__(self, fn, init_value, result_schema, name, rich,
                 vectorized=False):
        super().__init__(name)
        self.fn = fn
        self.init_value = init_value
        self.result_schema = result_schema
        self.rich = rich
        self.vectorized = vectorized
        self._keys = {}

    def _acc(self, key: int):
        acc = self._keys.get(key)
        if acc is None:
            acc = np.zeros((), dtype=self.result_schema.dtype())
            acc["key"] = key
            for f, v in (self.init_value or {}).items():
                acc[f] = v
            self._keys[key] = acc
        return acc

    # keyed-state migration (control plane live rescale, docs/CONTROL.md):
    # the fold state is a plain key -> record dict, so fragments move
    # verbatim between sibling replicas of one keyed farm
    keyed_migratable = True

    def keyed_state_keys(self):
        if not self._keys:
            return np.zeros(0, dtype=np.int64)
        return np.fromiter(self._keys.keys(), dtype=np.int64,
                           count=len(self._keys))

    def keyed_state_export(self, keys):
        return {"kind": "accumulator",
                "keys": {int(k): self._keys.pop(int(k)) for k in keys}}

    def keyed_state_import(self, frag):
        if frag["kind"] != "accumulator":
            raise TypeError(f"cannot import {frag['kind']!r} state into "
                            f"{type(self).__name__}")
        self._keys.update(frag["keys"])

    def svc(self, batch, channel=0):
        if len(batch) == 0:
            return
        out = np.zeros(len(batch), dtype=self.result_schema.dtype())
        args = (self.ctx,) if self.rich else ()
        # group rows by key once (sorted contiguous slices): one state
        # lookup per distinct key per chunk instead of per row
        from ..core.tuples import group_by_key
        keys = batch["key"]
        order, starts, ends = group_by_key(keys)
        sk = keys[order]
        for s, e in zip(starts, ends):
            idx = order[s:e]
            acc = self._acc(int(sk[s]))
            rows = batch[idx]
            if self.vectorized:
                # vectorised fold: fn(rows, acc) -> per-row snapshots of
                # the result fields (len(rows) records)
                out[idx] = self.fn(rows, acc, *args)
            else:
                for j, row in zip(idx, rows):
                    self.fn(row, acc, *args)
                    out[j] = acc  # emit a copy of the running result
        # each snapshot carries the header of the row that triggered it
        # (per-key ts order is preserved for downstream consumers)
        for f in ("key", "id", "ts"):
            out[f] = batch[f]
        self.emit(out)


class Accumulator(_Pattern):
    """Keyed rolling reduce/fold: per-key state initialised to `init_value`,
    fn(row, acc) mutates it, a copy of the state is emitted per input row
    (accumulator.hpp:157-193). Always keyed (Accumulator_Emitter,
    accumulator.hpp:50-85)."""

    def __init__(self, fn, result_schema: Schema, init_value: dict = None,
                 parallelism=1, name="accumulator", rich=False, routing=None,
                 vectorized=False):
        super().__init__(name, parallelism, routing or default_routing)
        self.fn = fn
        self.result_schema = result_schema
        self.init_value = init_value
        self.rich = rich
        #: vectorised flavour: fn(rows, acc) folds one key's chunk rows
        #: into acc and returns len(rows) per-row result snapshots
        self.vectorized = vectorized

    def _make_replica(self, i):
        node = _AccumulatorNode(self.fn, self.init_value, self.result_schema,
                                f"{self.name}.{i}", self.rich,
                                vectorized=self.vectorized)
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node


# ---------------------------------------------------------------------- Sink

class _SinkNode(Node):
    shed_safe = True   # terminal: shedding drops deliveries only
    #: NOT restartable by default: a sink has no downstream to dedup the
    #: journal replay, so a restarted sink would re-fire already-
    #: delivered rows into the user's (possibly irreversible) side
    #: effects.  Idempotent sinks opt in per pattern
    #: (``sink_pattern.recoverable = True``, propagated by farm.py).
    recoverable = False

    def __init__(self, fn, name, rich, vectorized):
        super().__init__(name)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized

    def svc(self, batch, channel=0):
        args = (self.ctx,) if self.rich else ()
        if self.vectorized:
            self.fn(batch, *args)
        else:
            for row in batch:
                self.fn(row, *args)

    def eosnotify(self):
        # the reference signals stream end with an empty optional
        # (sink.hpp:118); here: one call with None (vectorized sinks get it
        # too — the fn must treat None as the end-of-stream signal)
        args = (self.ctx,) if self.rich else ()
        self.fn(None, *args)


class Sink(_Pattern):
    """Sink: fn(row) per tuple and fn(None) at EOS (sink.hpp:63-65)."""

    def __init__(self, fn, parallelism=1, name="sink", rich=False,
                 vectorized=False, routing=None, keyed=False):
        if keyed and routing is None:
            routing = default_routing
        super().__init__(name, parallelism, routing)
        self.fn = fn
        self.rich = rich
        self.vectorized = vectorized

    def _make_replica(self, i):
        node = _SinkNode(self.fn, f"{self.name}.{i}", self.rich,
                         self.vectorized)
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node

    def collector(self):
        return None  # sinks have no output side
