"""Win_MapReduce: window-partition parallelism — each window's tuples are
split round-robin across MAP workers computing partial results, merged per
window by a REDUCE stage (reference win_mapreduce.hpp, wm_nodes.hpp).

* MAP: ``map_degree`` sequential cores with the SAME win/slide, role MAP,
  ``map_indexes=(i, n)`` — worker i's k-th result gets the dense id
  ``i + k*n`` (win_seq.hpp:397-399), so the merged per-key MAP output ids
  are 0,1,2,... with n consecutive ids = the n partials of one window.
* The emitter assigns tuples per key round-robin starting at
  ``key % map_degree`` (wm_nodes.hpp:101-110) and broadcasts each key's last
  tuple to all workers as an EOS marker (wm_nodes.hpp:115-129).
* A reorder collector restores dense-id order per key (wm_nodes.hpp:218).
* REDUCE: a CB window of len = slide = ``map_degree`` over the partial
  stream, role REDUCE (win_mapreduce.hpp:173-183) — one firing = one
  window's n partials combined.

This is the streaming analog of tensor parallelism over one long window —
the TPU mesh version computes the partials per core and the REDUCE merge as
an on-device tree reduction over ICI (parallel/mesh.py).

The reference's ``WinMap_Dropper`` (wm_nodes.hpp:137-214) has no separate
equivalent here: it exists only to invert a ``broadcast_node`` in the
MultiPipe CB path (multipipe.hpp:766-777, broadcast-then-keep-my-turn);
this framework's MultiPipe composes the round-robin ``WinMapEmitter``
directly, so the broadcast+drop pair never arises while the tuple
assignment is identical.
"""

from __future__ import annotations

import numpy as np

from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..runtime.emitters import KeyedStreamState
from ..runtime.node import Node, RuntimeContext
from .basic import _Pattern
from .win_farm import WFCollectorNode, WinFarm
from .win_seq import WinSeq, WinSeqNode

_NEG_INF = np.int64(-(2 ** 62))


class WinMapEmitterNode(Node):
    """Per-key round-robin partitioner (wm_nodes.hpp:40-133)."""

    quarantine_exempt = True    # framework shell: errors here fail fast
    shed_safe = True            # farm head: shedding drops raw stream rows

    def __init__(self, map_degree: int, win_type: WinType, name="wm_emitter"):
        super().__init__(name)
        self.map_degree = map_degree
        self.pos_field = "id" if win_type is WinType.CB else "ts"
        self._state = KeyedStreamState(self.pos_field)
        self._next_dst = {}  # key -> next round-robin destination

    def svc(self, batch, channel=0):
        n = self.map_degree
        # marker absorption + ooo drop shared with WF emitter
        # (wm_nodes.hpp:87-104 mirrors wf_nodes.hpp:104-121)
        batch = self._state.filter(batch)
        if len(batch) == 0:
            return
        keys = batch["key"]
        # sort-by-key + segmented arange: O(n log n + K) instead of a
        # full-batch mask per distinct key (collapses at 1e5 keys)
        from ..core.tuples import group_by_key
        order, starts, ends = group_by_key(keys)
        sk = keys[order]
        counts = ends - starts
        base = np.empty(len(starts), dtype=np.int64)
        nd = self._next_dst
        for i, s in enumerate(starts):     # O(K) scalar dict ops
            k = int(sk[s])
            b = nd.get(k)
            if b is None:
                b = k % n
            base[i] = b
            nd[k] = (b + int(counts[i])) % n
        rank = np.arange(len(sk), dtype=np.int64) - np.repeat(starts, counts)
        dest = np.empty(len(batch), dtype=np.int64)
        dest[order] = (np.repeat(base, counts) + rank) % n
        for d in range(n):
            sub = batch[dest == d]
            if len(sub):
                self.emit_to(d, sub)

    def eosnotify(self):
        markers = self._state.marker_batch()
        if markers is None:
            return
        for d in range(self.map_degree):
            self.emit_to(d, markers)


class _MapStage(_Pattern):
    """The MAP farm: per-replica map_indexes, round-robin emitter, dense-id
    reorder collector (win_mapreduce.hpp:147-163)."""

    def __init__(self, map_func, spec: WindowSpec, map_degree, name,
                 incremental, result_fields, config: PatternConfig,
                 device_fn=None, device_opts=None):
        super().__init__(name, map_degree)
        cfg = PatternConfig(config.id_inner, config.n_inner, config.slide_inner,
                            0, 1, spec.slide_len)
        self._workers = [
            WinSeq(map_func, spec.win_len, spec.slide_len, spec.win_type,
                   name=f"{name}.{i}", incremental=incremental,
                   result_fields=result_fields, config=cfg, role=Role.MAP,
                   map_indexes=(i, map_degree))
            for i in range(map_degree)]
        self.spec = spec
        self._device_fn = device_fn       # raw Reducer/JaxWindowFunction
        self._device_opts = device_opts   # not None => device-batched MAP

    @property
    def result_schema(self):
        return self._workers[0].result_schema

    def emitter(self):
        return WinMapEmitterNode(self.parallelism, self.spec.win_type,
                                 name=f"{self.name}.emitter")

    def collector(self):
        return WFCollectorNode(name=f"{self.name}.collector")

    def _make_replica(self, i):
        w = self._workers[i]
        if self._device_opts is not None:
            from .win_seq_tpu import make_device_core
            core = make_device_core(w, self._device_fn, self._device_opts,
                                    index=i)
        else:
            core = w.make_core()
        node = WinSeqNode(core, f"{self.name}.{i}")
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node


class WinMapReduce:
    """Composite two-stage pattern (MAP farm + REDUCE)."""

    def __init__(self, map_func, reduce_func, win_len, slide_len,
                 win_type=WinType.CB, map_degree=2, reduce_degree=1,
                 name="win_mr", map_incremental=None, reduce_incremental=None,
                 map_result_fields=None, reduce_result_fields=None,
                 ordered=True, config: PatternConfig = None,
                 opt_level: int = 0):
        if map_degree < 2:
            raise ValueError("Win_MapReduce needs a parallel MAP stage "
                             "(win_mapreduce.hpp:135)")
        self.opt_level = opt_level
        self._proto = dict(
            map_func=map_func, reduce_func=reduce_func, win_len=win_len,
            slide_len=slide_len, win_type=win_type, map_degree=map_degree,
            reduce_degree=reduce_degree, map_incremental=map_incremental,
            reduce_incremental=reduce_incremental,
            map_result_fields=map_result_fields,
            reduce_result_fields=reduce_result_fields,
            opt_level=opt_level)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self.name = name
        self.config = config or PatternConfig.plain(slide_len)
        from .basic import user_call_site
        #: construction-site anchor for check/ diagnostics
        self.anchor = user_call_site()
        cfg = self.config
        n = map_degree
        self.map_stage = self._make_map_stage(
            map_func, n, f"{name}_map", map_incremental, map_result_fields)
        # REDUCE: CB window n/n over the dense partial stream
        # (win_mapreduce.hpp:173-183)
        self.reduce_stage = self._make_reduce_stage(
            reduce_func, n, reduce_degree, f"{name}_reduce",
            reduce_incremental, reduce_result_fields, ordered)

    def _make_map_stage(self, map_func, n, name, incremental, result_fields):
        return _MapStage(map_func, self.spec, n, name, incremental,
                         result_fields, self.config)

    def _make_reduce_stage(self, reduce_func, n, degree, name, incremental,
                           result_fields, ordered):
        cfg = self.config
        if degree > 1:
            return WinFarm(reduce_func, n, n, WinType.CB, pardegree=degree,
                           name=name, incremental=incremental,
                           result_fields=result_fields, ordered=ordered,
                           config=cfg, role=Role.REDUCE)
        red_cfg = PatternConfig(cfg.id_inner, cfg.n_inner, cfg.slide_inner,
                                0, 1, n)
        return WinSeq(reduce_func, n, n, WinType.CB, name=name,
                      incremental=incremental, result_fields=result_fields,
                      config=red_cfg, role=Role.REDUCE)

    @property
    def result_schema(self):
        return self.reduce_stage.result_schema

    def instantiate(self, df, upstreams):
        from ..runtime.farm import add_farm, fuse_two_stage
        if self.opt_level >= 1:
            # optimize_WinMapReduce (the Pane_Farm optimizer's mirror,
            # win_mapreduce.hpp): fuse the MAP-collector/REDUCE-emitter
            # boundary (LEVEL1) or merge at the REDUCE workers (LEVEL2)
            return fuse_two_stage(df, self.map_stage, self.reduce_stage,
                                  upstreams, self.opt_level)
        tails = add_farm(df, self.map_stage, upstreams)
        return add_farm(df, self.reduce_stage, tails)

    def clone_with(self, name, slide_len=None, config=None, ordered=False):
        """Replicate as a nested-farm worker (win_farm.hpp ctor IV)."""
        kw = dict(self._proto)
        if slide_len is not None:
            kw["slide_len"] = slide_len
        return WinMapReduce(name=name, config=config, ordered=ordered, **kw)
