"""Key_Farm: key parallelism — whole keys are routed to workers, each
running a full sequential window core over its keys' substreams
(reference key_farm.hpp:143-156, kf_nodes.hpp:38-82).

No reordering is needed downstream: every result of a key comes from the
same worker, so per-key order is preserved by construction — the property
the TPU mesh version exploits to keep keys resident per core with no
collectives (SURVEY.md §7).
"""

from __future__ import annotations

from ..core.windows import PatternConfig, Role, WinType
from ..runtime.emitters import StandardEmitter, default_routing
from ..runtime.node import RuntimeContext
from .basic import _Pattern
from .win_seq import WinSeq, WinSeqNode


class KeyFarm(_Pattern):
    def __init__(self, winfunc, win_len, slide_len, win_type=WinType.CB,
                 pardegree=2, name="key_farm", incremental=None,
                 result_fields=None, routing=None,
                 config: PatternConfig = None, role: Role = Role.SEQ):
        super().__init__(name, pardegree, routing or default_routing)
        self._seq_template = WinSeq(
            winfunc, win_len, slide_len, win_type, name=f"{name}_kf",
            incremental=incremental, result_fields=result_fields,
            config=config, role=role)

    @property
    def result_schema(self):
        return self._seq_template.result_schema

    def emitter(self):
        # pure key routing (kf_nodes.hpp:73)
        return StandardEmitter(self.parallelism, self.routing,
                               name=f"{self.name}.emitter")

    def _make_core(self, worker, i=0):
        """Core-factory hook: TPU farms override to build device cores
        (worker index `i` drives per-worker device placement)."""
        return worker.make_core()

    def _make_replica(self, i):
        node = WinSeqNode(self._make_core(self._seq_template, i),
                          f"{self.name}.{i}")
        node.ctx = RuntimeContext(self.parallelism, i, self.name)
        return node
