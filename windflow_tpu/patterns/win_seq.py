"""Win_Seq pattern: the sequential window core as a dataflow node
(reference win_seq.hpp — also the building block of every windowed farm).
"""

from __future__ import annotations

from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..core.winseq import WinSeqCore
from ..ops.functions import WindowFunction, WindowUpdate, as_window_function, as_window_update
from ..runtime.node import Node, RuntimeContext
from .basic import _Pattern


class WinSeqNode(Node):
    """Runtime node driving a WinSeqCore."""

    #: svc folds rows into per-key window/ordering state BEFORE any
    #: raise, so a quarantined batch would leave that state partially
    #: mutated (silently wrong windows) — never quarantine under the
    #: dataflow-wide error_budget; fail fast (runtime/overload.py)
    quarantine_exempt = True

    def __init__(self, core: WinSeqCore, name="win_seq"):
        super().__init__(name)
        self.core = core

    def svc(self, batch, channel=0):
        out = self.core.process(batch)
        if len(out):
            # triggering vs non-triggering split (win_seq.hpp:479-501)
            if self.stats is not None:
                self.stats.bump("windows_fired", len(out))
                self.stats.bump("triggering_batches")
            self.emit(out)
        elif self.stats is not None:
            self.stats.bump("non_triggering_batches")

    def eosnotify(self):
        out = self.core.flush()
        if len(out):
            if self.stats is not None:
                self.stats.bump("windows_fired", len(out))
            self.emit(out)


class WinSeq(_Pattern):
    """Sequential window pattern (parallelism is always 1; farms build
    parallelism around it, win_farm.hpp:134)."""

    def __init__(self, winfunc, win_len: int, slide_len: int,
                 win_type: WinType = WinType.CB, name="win_seq",
                 incremental: bool = None, result_fields=None,
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide: int = None):
        super().__init__(name, parallelism=1)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self.result_ts_slide = result_ts_slide
        # resolve the function flavour (meta_utils.hpp signature deduction
        # becomes an explicit `incremental` switch)
        if incremental is True:
            winfunc = as_window_update(winfunc, result_fields)
        elif incremental is False or isinstance(winfunc, WindowFunction):
            winfunc = as_window_function(winfunc, result_fields)
        elif isinstance(winfunc, WindowUpdate):
            incremental = True
        else:
            winfunc = as_window_function(winfunc, result_fields)
        self.winfunc = winfunc
        self.incremental = bool(incremental)
        self.config = config
        self.role = role
        self.map_indexes = map_indexes

    def make_core(self) -> WinSeqCore:
        # Tumbling/sliding windows over a monoid reducer take the
        # vectorised multi-key core: identical INC semantics (== NIC for a
        # monoid), O(rows log rows) per chunk regardless of key
        # cardinality.  WF_NO_VECCORE=1 forces the reference per-key core
        # (debugging / differential runs).
        import os
        from ..core.vecinc import make_vec_core, vec_core_supported
        if (vec_core_supported(self.spec, self.winfunc)
                and not os.environ.get("WF_NO_VECCORE")):
            return make_vec_core(
                self.spec, self.winfunc, config=self.config, role=self.role,
                map_indexes=self.map_indexes,
                result_ts_slide=self.result_ts_slide)
        core = WinSeqCore(self.spec, self.winfunc, config=self.config,
                          role=self.role, map_indexes=self.map_indexes,
                          result_ts_slide=self.result_ts_slide)
        if self.incremental:
            core.use_incremental()
        return core

    def _make_replica(self, i):
        node = WinSeqNode(self.make_core(), f"{self.name}.{i}")
        node.ctx = RuntimeContext(1, 0, self.name)
        return node

    @property
    def result_schema(self):
        from ..core.tuples import Schema
        return Schema(**self.winfunc.result_fields)
