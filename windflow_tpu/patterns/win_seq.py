"""Win_Seq pattern: the sequential window core as a dataflow node
(reference win_seq.hpp — also the building block of every windowed farm).
"""

from __future__ import annotations

from ..core.windows import PatternConfig, Role, WindowSpec, WinType
from ..core.winseq import WinSeqCore
from ..ops.functions import WindowFunction, WindowUpdate, as_window_function, as_window_update
from ..runtime.node import Node, RuntimeContext
from .basic import _Pattern


class WinSeqNode(Node):
    """Runtime node driving a WinSeqCore."""

    #: svc folds rows into per-key window/ordering state BEFORE any
    #: raise, so a quarantined batch would leave that state partially
    #: mutated (silently wrong windows) — never quarantine under the
    #: dataflow-wide error_budget; fail fast (runtime/overload.py)
    quarantine_exempt = True
    #: recovery (docs/ROBUSTNESS.md): window state restores from an
    #: epoch snapshot — host cores by whole-core deep copy (archives,
    #: vecinc lanes, ordering buffers are all plain numpy/dict state),
    #: device cores via their own snapshot hooks (ring archive handle +
    #: host bookkeeping) — and supervised restart replays the journal
    recoverable = True

    def __init__(self, core: WinSeqCore, name="win_seq"):
        super().__init__(name)
        self.core = core

    def checkpoint_prepare(self):
        """Device cores buffer fired windows in an async launch queue;
        at an epoch barrier their results pre-date the snapshot cut, so
        flush + drain them for emission first — per launch, keeping the
        emission seq numbering independent of harvest timing (host
        cores: no-op)."""
        drain = getattr(self.core, "checkpoint_drain_batches", None)
        return None if drain is None else drain()

    def state_snapshot(self):
        snap_fn = getattr(self.core, "state_snapshot", None)
        if snap_fn is not None:
            return snap_fn()
        import copy
        try:
            return {"core": copy.deepcopy(self.core)}
        except Exception as e:
            # a core holding native/device handles without its own
            # snapshot hooks cannot deep-copy — decline loudly so the
            # supervisor degrades to fail-like-seed for this node
            from ..runtime.node import SnapshotUnsupported
            raise SnapshotUnsupported(
                f"{self.name}: core {type(self.core).__name__} is not "
                f"deep-copyable ({type(e).__name__}: {e})") from e

    def state_restore(self, snap):
        # the native core's snapshot is a lazy handle object, not a
        # dict — anything that isn't the deep-copy form goes to the
        # core's own restore hook
        if isinstance(snap, dict) and "core" in snap:
            import copy
            self.core = copy.deepcopy(snap["core"])
        else:
            self.core.state_restore(snap)

    def svc(self, batch, channel=0):
        if self._recov is not None:
            # recovery mode + async device core: emit ONE batch per
            # completed launch, in launch order.  Launch boundaries are
            # count-triggered (deterministic); how many launches a given
            # poll() harvests is wall-clock — concatenating them per svc
            # (the seed path) would make replayed emission grouping
            # diverge from the original run's and break the per-edge
            # seq dedup (a split regroup would double-deliver windows).
            pb = getattr(self.core, "process_batches", None)
            if pb is not None:
                self._emit_each(pb(batch), triggering=True)
                return
        out = self.core.process(batch)
        if len(out):
            # triggering vs non-triggering split (win_seq.hpp:479-501)
            if self.stats is not None:
                self.stats.bump("windows_fired", len(out))
                self.stats.bump("triggering_batches")
            self.emit(out)
        elif self.stats is not None:
            self.stats.bump("non_triggering_batches")

    def _emit_each(self, outs, triggering=False):
        fired = 0
        for out in outs:
            if len(out):
                fired += len(out)
                self.emit(out)
        if self.stats is not None:
            if fired:
                self.stats.bump("windows_fired", fired)
                if triggering:
                    self.stats.bump("triggering_batches")
            elif triggering:
                self.stats.bump("non_triggering_batches")

    def eosnotify(self):
        if self._recov is not None:
            fb = getattr(self.core, "flush_batches", None)
            if fb is not None:
                self._emit_each(fb())
                return
        out = self.core.flush()
        if len(out):
            if self.stats is not None:
                self.stats.bump("windows_fired", len(out))
            self.emit(out)


class WinSeq(_Pattern):
    """Sequential window pattern (parallelism is always 1; farms build
    parallelism around it, win_farm.hpp:134)."""

    def __init__(self, winfunc, win_len: int, slide_len: int,
                 win_type: WinType = WinType.CB, name="win_seq",
                 incremental: bool = None, result_fields=None,
                 config: PatternConfig = None, role: Role = Role.SEQ,
                 map_indexes=(0, 1), result_ts_slide: int = None):
        super().__init__(name, parallelism=1)
        self.spec = WindowSpec(win_len, slide_len, win_type)
        self.result_ts_slide = result_ts_slide
        # resolve the function flavour (meta_utils.hpp signature deduction
        # becomes an explicit `incremental` switch)
        if incremental is True:
            winfunc = as_window_update(winfunc, result_fields)
        elif incremental is False or isinstance(winfunc, WindowFunction):
            winfunc = as_window_function(winfunc, result_fields)
        elif isinstance(winfunc, WindowUpdate):
            incremental = True
        else:
            winfunc = as_window_function(winfunc, result_fields)
        self.winfunc = winfunc
        self.incremental = bool(incremental)
        self.config = config
        self.role = role
        self.map_indexes = map_indexes

    def make_core(self) -> WinSeqCore:
        # Tumbling/sliding windows over a monoid reducer take the
        # vectorised multi-key core: identical INC semantics (== NIC for a
        # monoid), O(rows log rows) per chunk regardless of key
        # cardinality.  WF_NO_VECCORE=1 forces the reference per-key core
        # (debugging / differential runs).
        import os
        from ..core.vecinc import make_vec_core, vec_core_supported
        if (vec_core_supported(self.spec, self.winfunc)
                and not os.environ.get("WF_NO_VECCORE")):
            return make_vec_core(
                self.spec, self.winfunc, config=self.config, role=self.role,
                map_indexes=self.map_indexes,
                result_ts_slide=self.result_ts_slide)
        core = WinSeqCore(self.spec, self.winfunc, config=self.config,
                          role=self.role, map_indexes=self.map_indexes,
                          result_ts_slide=self.result_ts_slide)
        if self.incremental:
            core.use_incremental()
        return core

    def _make_replica(self, i):
        node = WinSeqNode(self.make_core(), f"{self.name}.{i}")
        node.ctx = RuntimeContext(1, 0, self.name)
        return node

    @property
    def result_schema(self):
        from ..core.tuples import Schema
        return Schema(**self.winfunc.result_fields)
