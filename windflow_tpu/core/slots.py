"""Shared key->dense-slot registry for vectorised per-key state.

The multi-key hot paths (VecIncTumblingCore, WFCollectorNode) keep per-key
state in parallel arrays indexed by a dense slot id.  This helper owns the
one subtle piece both need: a vectorised lookup that maps a chunk's key
column to slots, registering first-seen keys in first-appearance order and
maintaining a sorted view for ``np.searchsorted`` lookups.
"""

from __future__ import annotations

import numpy as np


def segments(sorted_vals: np.ndarray):
    """(starts, ends) of equal-value runs in a sorted array."""
    starts = np.concatenate(([0], np.flatnonzero(np.diff(sorted_vals)) + 1))
    ends = np.concatenate((starts[1:], [len(sorted_vals)]))
    return starts, ends


def segmented_excl_running_max(s: np.ndarray, p: np.ndarray,
                               starts: np.ndarray,
                               head_seed: np.ndarray) -> np.ndarray:
    """Per-segment EXCLUSIVE running max of `p` (segments = equal runs of
    the sorted `s`), seeded with `head_seed[i]` at segment i's head — the
    vectorised form of the reference's per-row running-max ordering check
    (win_seq.hpp:293-305), O(rows log rows) by Hillis-Steele doubling."""
    q = p.copy()
    q[starts] = np.maximum(q[starts], head_seed)
    sh = 1
    n = len(q)
    while sh < n:
        same = s[sh:] == s[:-sh]
        np.maximum(q[sh:], np.where(same, q[:-sh], q[sh:]), out=q[sh:])
        sh *= 2
    excl = np.empty(n, dtype=np.int64)
    excl[1:] = q[:-1]
    excl[starts] = head_seed
    return excl


class SlotMap:
    """Dense int slots for int64 keys; lookup is O(rows log keys)."""

    __slots__ = ("n", "keys", "_sorted_keys", "_sorted_slots", "_on_register")

    def __init__(self, on_register=None):
        self.n = 0
        self.keys = np.zeros(0, dtype=np.int64)      # slot -> key
        self._sorted_keys = np.zeros(0, dtype=np.int64)
        self._sorted_slots = np.zeros(0, dtype=np.int64)
        #: optional hook called with the (m,) array of newly registered keys
        #: (their slots are n-m .. n-1) — per-key init math goes here
        self._on_register = on_register

    def _register(self, new_keys: np.ndarray):
        uniq, first_idx = np.unique(new_keys, return_index=True)
        k = uniq[np.argsort(first_idx)]              # first-appearance order
        new_slots = np.arange(self.n, self.n + len(k), dtype=np.int64)
        self.keys = np.concatenate((self.keys[:self.n], k))
        self.n += len(k)
        # merge the m new keys into the sorted view (O(K + m log m)): a
        # full re-argsort here is O(K log K) *per registration*, quadratic
        # total when keys trickle in one-per-chunk (ADVICE r2)
        order = np.argsort(k, kind="stable")
        ks, ss = k[order], new_slots[order]
        pos = np.searchsorted(self._sorted_keys, ks)
        self._sorted_keys = np.insert(self._sorted_keys, pos, ks)
        self._sorted_slots = np.insert(self._sorted_slots, pos, ss)
        if self._on_register is not None:
            self._on_register(k)

    def state_snapshot(self) -> dict:
        """Data-only snapshot (recovery layer): the registered keys and
        the sorted lookup view — the ``on_register`` hook is identity,
        not state, and stays bound to the live owner on restore."""
        return {"n": self.n, "keys": self.keys[:self.n].copy(),
                "sorted_keys": self._sorted_keys.copy(),
                "sorted_slots": self._sorted_slots.copy()}

    def state_restore(self, snap: dict):
        self.n = snap["n"]
        self.keys = snap["keys"].copy()
        self._sorted_keys = snap["sorted_keys"].copy()
        self._sorted_slots = snap["sorted_slots"].copy()

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Slots for `keys` (int64 array), registering unseen keys."""
        if self.n:
            idx = np.searchsorted(self._sorted_keys, keys)
            idxc = np.minimum(idx, self.n - 1)
            found = self._sorted_keys[idxc] == keys
            if found.all():
                return self._sorted_slots[idxc]
            self._register(keys[~found])
        else:
            self._register(keys)
        idx = np.searchsorted(self._sorted_keys, keys)
        return self._sorted_slots[idx]
