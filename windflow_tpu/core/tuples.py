"""Structure-of-arrays tuple batches — the unit of data exchange.

The reference library moves one C++ struct at a time between threads
(``wrapper_tuple_t``, reference ``meta_utils.hpp:354``) and only forms
contiguous batches at the GPU boundary (``win_seq_gpu.hpp:96``).  A TPU-native
design inverts this: the *stream itself* is chunked into structure-of-arrays
batches from the source onward, so every operator is a vectorised array
transform and the device boundary needs no marshalling step — the batch
columns stage straight into device buffers.

The reference "tuple protocol" ``getInfo()/setInfo()`` returning
``(key, id, ts)`` (reference ``src/sum_test_cpu/sum_cb.hpp:31-88``) becomes
three mandatory int64 columns ``key``/``id``/``ts`` plus arbitrary payload
columns described by a :class:`Schema`.
"""

from __future__ import annotations

import numpy as np

# Mandatory columns implementing the (key, id, ts) tuple protocol.
INFO_FIELDS = ("key", "id", "ts")
# Internal column: EOS punctuation markers travel in-band like the reference's
# per-key EOS marker tuples (reference wf_nodes.hpp:177-191).  Marker rows
# advance window state but are never archived nor folded into results.
MARKER_FIELD = "marker"


class Schema:
    """Describes the payload columns of a stream (name -> numpy dtype)."""

    def __init__(self, **fields):
        self.fields = {name: np.dtype(dt) for name, dt in fields.items()}

    def dtype(self) -> np.dtype:
        base = [(f, np.int64) for f in INFO_FIELDS]
        base.append((MARKER_FIELD, np.bool_))
        base += [(name, dt) for name, dt in self.fields.items()]
        return np.dtype(base)

    def payload_names(self):
        return tuple(self.fields.keys())

    def __repr__(self):
        inner = ", ".join(f"{k}={v}" for k, v in self.fields.items())
        return f"Schema({inner})"

    def __eq__(self, other):
        return isinstance(other, Schema) and self.fields == other.fields


def make_batch(schema: Schema, n: int) -> np.ndarray:
    """Allocate an empty (zeroed) batch of `n` rows for `schema`."""
    return np.zeros(n, dtype=schema.dtype())


def batch_from_columns(schema: Schema, key, id, ts, **payload) -> np.ndarray:
    key = np.asarray(key, dtype=np.int64)
    out = make_batch(schema, key.shape[0])
    out["key"] = key
    out["id"] = np.asarray(id, dtype=np.int64)
    out["ts"] = np.asarray(ts, dtype=np.int64)
    for name, col in payload.items():
        out[name] = col
    return out


def concat(batches) -> np.ndarray:
    batches = [b for b in batches if b is not None and len(b)]
    if not batches:
        return None
    if len(batches) == 1:
        return batches[0]
    return np.concatenate(batches)


def schema_of(batch: np.ndarray) -> Schema:
    """Recover a Schema from a structured batch array."""
    skip = set(INFO_FIELDS) | {MARKER_FIELD}
    return Schema(**{n: batch.dtype[n] for n in batch.dtype.names if n not in skip})


def group_by_key(keys: np.ndarray):
    """Stable group-by: returns ``(order, starts, ends)`` where
    ``order[starts[i]:ends[i]]`` indexes group *i*'s rows in arrival order
    and ``keys[order[starts[i]]]`` is its key.  The one idiom behind every
    per-key hot path (emitters, accumulator, ordering, window cores);
    handles the empty batch (all three arrays empty)."""
    order = np.argsort(keys, kind="stable")
    if len(order) == 0:
        z = np.zeros(0, dtype=np.int64)
        return order, z, z
    sk = keys[order]
    starts = np.concatenate(([0], np.flatnonzero(np.diff(sk)) + 1))
    ends = np.concatenate((starts[1:], [len(sk)]))
    return order, starts, ends
