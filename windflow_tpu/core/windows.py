"""Window model: count/time windows, triggerer math, farm distribution math.

Re-derivation of the reference's window engine (reference ``window.hpp`` and
``basic.hpp:136``) in closed form so that it vectorises:

* Count-based (CB) window ``wid`` over a keyed substream whose first id is
  ``initial_id`` covers ids ``[initial_id + wid*slide, initial_id + wid*slide
  + win_len)`` and FIRES on the first id ``>= initial_id + wid*slide +
  win_len`` (reference ``window.hpp:63-66``).
* Time-based (TB) window ``wid`` covers ts ``[initial_ts + wid*slide,
  initial_ts + wid*slide + win_len)`` and fires on the first ts ``>=
  initial_ts + wid*slide + win_len`` (reference ``window.hpp:84-87``).

Instead of keeping one heap-allocated ``Window`` object with a closure per
open window, we keep *arithmetic*: for an in-order substream the set of open /
fired / created windows is a pure function of (next_lwid, max id seen), which
is what lets the bookkeeping run as array ops over whole batches.

``PatternConfig`` carries the two-level farm-distribution parameters
(outer x inner nesting) exactly as the reference does (``basic.hpp:136``,
consumed at ``win_seq.hpp:307-314``).
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass

import numpy as np


class WinType(enum.Enum):
    CB = "count"  # count-based: windows defined over tuple ids
    TB = "time"   # time-based: windows defined over tuple timestamps


class Role(enum.Enum):
    """Role of a window core inside a composed pattern (basic.hpp:84)."""

    SEQ = "seq"        # standalone sequential core
    PLQ = "plq"        # pane-level query stage of Pane_Farm
    WLQ = "wlq"        # window-level query stage of Pane_Farm
    MAP = "map"        # map stage of Win_MapReduce
    REDUCE = "reduce"  # reduce stage of Win_MapReduce


class OptLevel(enum.IntEnum):
    """Graph-optimisation level (basic.hpp:94). In this framework the
    runtime fuses nodes dynamically, so levels only gate fusion choices."""

    LEVEL0 = 0
    LEVEL1 = 1
    LEVEL2 = 2


@dataclass(frozen=True)
class PatternConfig:
    """Two-level distribution parameters for nested farm workers.

    ``id_outer/n_outer/slide_outer`` describe this worker's position in the
    outer farm, ``id_inner/n_inner/slide_inner`` in the inner pattern
    (reference basic.hpp:136-160).  A plain Win_Seq uses (0,1,slide,0,1,slide).
    """

    id_outer: int = 0
    n_outer: int = 1
    slide_outer: int = 0
    id_inner: int = 0
    n_inner: int = 1
    slide_inner: int = 0

    @staticmethod
    def plain(slide_len: int) -> "PatternConfig":
        return PatternConfig(0, 1, slide_len, 0, 1, slide_len)

    def first_gwid(self, key: int) -> int:
        """gwid of the first window of `key` assigned to this worker
        (win_seq.hpp:307)."""
        no, ni = self.n_outer, self.n_inner
        a = (self.id_inner - (key % ni) + ni) % ni
        b = (self.id_outer - (key % no) + no) % no
        return a * no + b

    def initial_id(self, key: int, role: Role) -> int:
        """First id/ts of the keyed substream reaching this worker
        (win_seq.hpp:309-314)."""
        no, ni = self.n_outer, self.n_inner
        initial_outer = ((self.id_outer - (key % no) + no) % no) * self.slide_outer
        initial_inner = ((self.id_inner - (key % ni) + ni) % ni) * self.slide_inner
        if role in (Role.WLQ, Role.REDUCE):
            return initial_inner
        return initial_outer + initial_inner

    def gwid_stride(self) -> int:
        """gwids assigned to one worker advance by n_outer*n_inner
        (win_seq.hpp:346)."""
        return self.n_outer * self.n_inner


@dataclass(frozen=True)
class WindowSpec:
    """A sliding/tumbling/hopping window definition."""

    win_len: int
    slide_len: int
    win_type: WinType

    def __post_init__(self):
        if self.win_len <= 0 or self.slide_len <= 0:
            raise ValueError("window length and slide must be positive")

    @property
    def is_tumbling(self) -> bool:
        return self.win_len == self.slide_len

    @property
    def is_hopping(self) -> bool:
        return self.slide_len > self.win_len

    def pane_len(self) -> int:
        """Pane decomposition length: gcd(win, slide) (pane_farm.hpp:148)."""
        return math.gcd(self.win_len, self.slide_len)

    # ---- closed-form window arithmetic (all positions relative to
    # ---- initial_id of the substream; works elementwise on numpy arrays) ----

    def _div_slide(self, x):
        """Floor-divide by slide_len; a power-of-two slide rides an
        arithmetic right shift (floor semantics for negatives too) —
        int64 division was the WF emitter's second-largest per-batch cost
        (~19 ms/M rows vs ~2 ms shifted)."""
        s = int(self.slide_len)   # numpy-int slide_lens lack bit_length
        if s & (s - 1) == 0:
            return x >> (s.bit_length() - 1)
        return x // s

    def last_win_containing(self, pos):
        """Local id of the last window containing position `pos` (>=0).

        Sliding/tumbling: ceil((pos+1)/slide) - 1  (win_seq.hpp:324)
        Hopping:          floor(pos/slide)         (win_seq.hpp:327)
        """
        pos = np.asarray(pos, dtype=np.int64)
        if self.is_hopping:
            return self._div_slide(pos)
        return np.maximum(self._div_slide(pos + self.slide_len) - 1, -1)

    def first_win_containing(self, pos):
        """Local id of the first window containing `pos`, i.e.
        max(0, ceil((pos - win + 1)/slide)) for sliding (wf_nodes.hpp:138-144);
        for hopping the only candidate is floor(pos/slide)."""
        pos = np.asarray(pos, dtype=np.int64)
        if self.is_hopping:
            return self._div_slide(pos)
        # floor division handles the pos < win_len operand range (the
        # quotient is <= 0 exactly there), so clamping replaces the
        # two-branch where — one fewer full-array pass
        return np.maximum(
            self._div_slide(pos - self.win_len + self.slide_len),
            np.int64(0))

    def in_any_window(self, pos):
        """Hopping streams have gaps: positions outside every window are
        dropped (win_seq.hpp:330). Always true for sliding windows."""
        pos = np.asarray(pos, dtype=np.int64)
        if not self.is_hopping:
            return np.ones(pos.shape, dtype=bool)
        off = pos % self.slide_len
        return off < self.win_len

    def fired_before(self, pos):
        """Number of windows already FIRED once position `pos` has been seen:
        window w fires on the first pos >= w*slide + win, so the count is
        floor((pos - win)/slide) + 1 for pos >= win, else 0."""
        pos = np.asarray(pos, dtype=np.int64)
        return np.where(
            pos >= self.win_len,
            (pos - self.win_len) // self.slide_len + 1,
            np.int64(0),
        )

    def win_start(self, lwid):
        return np.asarray(lwid, dtype=np.int64) * self.slide_len

    def win_end(self, lwid):
        """Exclusive end position of window `lwid`."""
        return np.asarray(lwid, dtype=np.int64) * self.slide_len + self.win_len
