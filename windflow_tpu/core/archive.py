"""Per-key stream archive: ordered buffer of in-flight tuples.

Equivalent of the reference ``stream_archive.hpp`` (binary-search insert,
range query, purge) redesigned for batch appends: streams arrive as sorted
chunks, so the common case is an O(chunk) tail append into a contiguous
growable buffer, keeping the window content contiguous for device staging
(the property the reference's GPU path gets from its vector-backed archive,
``win_seq_gpu.hpp:96``).  Purge advances a start offset instead of erasing
(compaction is amortised).
"""

from __future__ import annotations

import numpy as np


class KeyArchive:
    """Ordered (by `pos_field`) buffer of tuples for one key."""

    __slots__ = ("pos_field", "_buf", "_start", "_end")

    def __init__(self, dtype: np.dtype, pos_field: str, capacity: int = 64):
        self.pos_field = pos_field
        self._buf = np.empty(capacity, dtype=dtype)
        self._start = 0
        self._end = 0

    def __len__(self):
        return self._end - self._start

    @property
    def rows(self) -> np.ndarray:
        """Live contents, ordered by pos (view, do not mutate)."""
        return self._buf[self._start:self._end]

    @property
    def positions(self) -> np.ndarray:
        return self._buf[self.pos_field][self._start:self._end]

    def _reserve(self, extra: int):
        n = len(self)
        if self._end + extra <= len(self._buf):
            return
        cap = max(len(self._buf) * 2, n + extra, 64)
        newbuf = np.empty(cap, dtype=self._buf.dtype)
        newbuf[:n] = self._buf[self._start:self._end]
        self._buf = newbuf
        self._start, self._end = 0, n

    def append(self, rows: np.ndarray):
        """Append a chunk already sorted by pos, all >= current max pos
        (the in-order fast path; out-of-order rows were dropped upstream)."""
        if len(rows) == 0:
            return
        self._reserve(len(rows))
        self._buf[self._end:self._end + len(rows)] = rows
        self._end += len(rows)

    def insert_sorted(self, rows: np.ndarray):
        """General insert preserving order (used for equal-pos duplicates
        arriving interleaved); O(n + chunk)."""
        if len(rows) == 0:
            return
        live = self.rows
        merged = np.concatenate([live, rows])
        order = np.argsort(merged[self.pos_field], kind="stable")
        merged = merged[order]
        self._buf = merged
        self._start, self._end = 0, len(merged)

    def lower_bound(self, pos: int) -> int:
        """Index (relative to .rows) of the first row with pos >= `pos`."""
        return int(np.searchsorted(self.positions, pos, side="left"))

    def range(self, lo_pos: int, hi_pos: int) -> np.ndarray:
        """Rows with pos in [lo_pos, hi_pos) — one window's content
        (reference stream_archive.hpp:104)."""
        p = self.positions
        lo = np.searchsorted(p, lo_pos, side="left")
        hi = np.searchsorted(p, hi_pos, side="left")
        return self.rows[lo:hi]

    def tail_from(self, lo_pos: int) -> np.ndarray:
        """Rows with pos >= lo_pos (EOS flush range, win_seq.hpp:452)."""
        lo = np.searchsorted(self.positions, lo_pos, side="left")
        return self.rows[lo:]

    def purge_below(self, pos: int):
        """Drop rows with pos < `pos` (reference stream_archive.hpp:71)."""
        self._start += self.lower_bound(pos)
        # amortised compaction so the buffer doesn't grow without bound
        if self._start > 4096 and self._start > (self._end - self._start):
            n = len(self)
            self._buf[:n] = self._buf[self._start:self._end]
            self._start, self._end = 0, n
