"""Vectorised multi-key incremental cores for tumbling AND sliding windows.

``WinSeqCore`` (core/winseq.py) groups each chunk by key and runs ~20 numpy
ops per key group — exact, but at 10^5 distinct keys a chunk dissolves into
10^5 tiny-array calls (~100µs each; the reference pays the same shape of
cost per tuple, win_seq.hpp:268-474).  For windows over a **monoid
reducer** (YSB's per-campaign aggregate, the Pane_Farm PLQ stage,
Win_MapReduce's MAP/REDUCE stages, every sum_test config) the whole chunk
reduces to segment arithmetic.  Tumbling (``VecIncTumblingCore``):

* a row at relative position ``r`` belongs to exactly window ``r // L``;
* windows ``[n_fired, max_r // L)`` fire, window ``max_r // L`` stays
  pending with a partial accumulator (O(1) state per key, like INC mode);
* per-(key, window) partials are one ``ufunc.reduceat`` over the chunk
  sorted by key.

Sliding (``VecIncSlidingCore``) generalises this to ``W = ceil(L/S)``
concurrently open windows per key via accumulator *lanes* — see its
docstring.

Semantics are differentially identical to ``WinSeqCore`` in INC mode (which
for a monoid equals NIC mode): out-of-order drops against the per-key
running max (win_seq.hpp:293-305), rows below the worker's ``initial_id``
dropped (win_seq.hpp:307-314), empty skipped windows fire with the monoid
identity, EOS markers advance creation/firing and overwrite result
timestamps without being folded (window.hpp:149-154), PLQ/MAP result-id
renumbering (win_seq.hpp:396-405).  Per-key state is laid out as parallel
arrays indexed by a key->slot map instead of per-key objects, so a chunk's
bookkeeping is O(rows log rows) regardless of key cardinality.
"""

from __future__ import annotations

import threading

import numpy as np

from .slots import segments as _segments
from .tuples import MARKER_FIELD, Schema
from .windows import PatternConfig, Role, WindowSpec, WinType
from ..ops.functions import MultiReducer, Reducer
from ..ops.monoid import NP_UFUNCS, identity as monoid_identity

_NEG_INF = np.int64(-(2 ** 62))


def vec_core_supported(spec: WindowSpec, winfunc) -> bool:
    """The fast path handles tumbling AND sliding windows + (Multi)Reducer,
    any role.  Sliding is bounded to ceil(win/slide) <= 64 open windows per
    key (per-key pending state is a (keys, W) lane array and each row folds
    into <= W windows; beyond that the general core's per-key-group path is
    the better trade).  Hopping (slide > win) stays on the general core."""
    if isinstance(winfunc, MultiReducer):
        parts = winfunc.parts
    elif isinstance(winfunc, Reducer):
        parts = [winfunc]
    else:
        return False
    if not all(p.op == "count" or p.op in NP_UFUNCS for p in parts):
        return False
    if spec.is_tumbling:
        return True
    return (spec.slide_len < spec.win_len
            and -(-spec.win_len // spec.slide_len) <= 64)


def make_vec_core(spec: WindowSpec, winfunc, **kw):
    """The vectorised core for `spec` (vec_core_supported must hold):
    tumbling always vectorises; sliding defers to the first chunk's key
    cardinality (LazySlidingCore)."""
    if spec.is_tumbling:
        return VecIncTumblingCore(spec, winfunc, **kw)
    return LazySlidingCore(spec, winfunc, **kw)




class VecIncTumblingCore:
    """Drop-in for WinSeqCore (process/flush/use_incremental contract)."""

    def __init__(self, spec: WindowSpec, winfunc, config: PatternConfig = None,
                 role: Role = Role.SEQ, map_indexes=(0, 1),
                 result_ts_slide: int = None):
        assert vec_core_supported(spec, winfunc)
        self.spec = spec
        self.winfunc = winfunc
        self.config = config or PatternConfig.plain(spec.slide_len)
        self.role = role
        self.map_indexes = map_indexes
        self.result_ts_slide = (result_ts_slide if result_ts_slide is not None
                                else spec.slide_len)
        self.is_nic = False
        self.result_schema = Schema(**winfunc.result_fields)
        self._result_dtype = self.result_schema.dtype()
        self.pos_field = "id" if spec.win_type is WinType.CB else "ts"
        self._L = int(spec.win_len)
        self._S = int(spec.slide_len)
        parts = winfunc.parts if isinstance(winfunc, MultiReducer) else [winfunc]
        # (out_field, in_field, ufunc-or-None(=count), dtype, identity)
        self._parts = [(p.out_field, p.field, None if p.op == "count"
                        else NP_UFUNCS[p.op], p.dtype,
                        p.dtype.type(monoid_identity(p.op, p.dtype)))
                       for p in parts]
        # --- per-key state as parallel arrays (slot-indexed) ---
        from .slots import SlotMap
        self._slotmap = SlotMap(on_register=self._init_new_keys)
        self._n = 0
        self._cap = 0
        self._key = np.zeros(0, dtype=np.int64)
        self._last_pos = np.zeros(0, dtype=np.int64)
        self._initial = np.zeros(0, dtype=np.int64)
        self._fgwid = np.zeros(0, dtype=np.int64)
        self._inner_off = np.zeros(0, dtype=np.int64)   # PLQ renumbering
        self._nfired = np.zeros(0, dtype=np.int64)      # == pending lwid
        self._seen = np.zeros(0, dtype=bool)
        self._emit_ctr = np.zeros(0, dtype=np.int64)    # MAP/PLQ renumbering
        self._marker_pos = np.zeros(0, dtype=np.int64)
        self._marker_ts = np.zeros(0, dtype=np.int64)
        self._acc_ts = np.zeros(0, dtype=np.int64)      # last folded ts, pending
        self._acc = {of: np.zeros(0, dtype=dt)
                     for of, _f, _u, dt, _i in self._parts}

    def use_incremental(self):
        return self  # inherently incremental

    # ------------------------------------------------------------- key slots

    def _grow(self, need: int):
        cap = max(self._cap * 2, need, 1024)

        def g(a, fill=0):
            b = np.full(cap, fill, dtype=a.dtype)
            b[:self._n] = a[:self._n]
            return b

        self._key = g(self._key)
        self._last_pos = g(self._last_pos, _NEG_INF)
        self._initial = g(self._initial)
        self._fgwid = g(self._fgwid)
        self._inner_off = g(self._inner_off)
        self._nfired = g(self._nfired)
        self._seen = g(self._seen, False)
        self._emit_ctr = g(self._emit_ctr)
        self._marker_pos = g(self._marker_pos, _NEG_INF)
        self._marker_ts = g(self._marker_ts)
        self._grow_acc(cap)
        self._cap = cap

    def _grow_acc(self, cap: int):
        """Grow the pending-accumulator state (1D here; the sliding core
        overrides with (cap, W) lane arrays)."""
        n = self._n
        ts = np.zeros(cap, dtype=np.int64)
        ts[:n] = self._acc_ts[:n]
        self._acc_ts = ts
        for (of, _f, _u, dt, ident) in self._parts:
            b = np.full(cap, ident, dtype=dt)
            b[:n] = self._acc[of][:n]
            self._acc[of] = b

    def _init_new_keys(self, k: np.ndarray):
        """SlotMap registration hook: per-key distribution math vectorised
        (PatternConfig.first_gwid / initial_id, basic.hpp:136,
        win_seq.hpp:307-314); new slots are self._n .. self._n+len(k)-1."""
        m = len(k)
        if self._n + m > self._cap:
            self._grow(self._n + m)
        c = self.config
        sl = slice(self._n, self._n + m)
        no, ni = c.n_outer, c.n_inner
        a = (c.id_inner - (k % ni) + ni) % ni
        b = (c.id_outer - (k % no) + no) % no
        self._key[sl] = k
        self._fgwid[sl] = a * no + b
        self._inner_off[sl] = a
        if self.role in (Role.WLQ, Role.REDUCE):
            self._initial[sl] = a * c.slide_inner
        else:
            self._initial[sl] = b * c.slide_outer + a * c.slide_inner
        if self.role is Role.MAP:
            self._emit_ctr[sl] = self.map_indexes[0]
        self._n += m

    def _slots_for(self, keys: np.ndarray) -> np.ndarray:
        return self._slotmap.lookup(keys)

    # ------------------------------------------------------------- processing

    def _ingest(self, batch: np.ndarray):
        """Shared chunk intake: slot mapping, out-of-order drop against the
        per-key running max, drop of rows below the worker's initial
        position, marker-pos/ts absorption.  Returns
        ``(s, p, sorted_rows, starts, ends, mk, any_mk)`` for the kept rows
        in slot-grouped arrival order, or None when nothing survives."""
        keys = batch["key"].astype(np.int64, copy=False)
        pos = batch[self.pos_field].astype(np.int64, copy=False)
        slots = self._slots_for(keys)
        order = np.argsort(slots, kind="stable")
        s = slots[order]
        p = pos[order]
        starts, ends = _segments(s)
        # --- out-of-order drop against the per-key running max ---
        seg_first = np.zeros(len(s), dtype=bool)
        seg_first[starts] = True
        within_bad = np.zeros(len(s), dtype=bool)
        within_bad[1:] = (np.diff(p) < 0) & ~seg_first[1:]
        head_bad = p[starts] < self._last_pos[s[starts]]
        keep_s = None
        if within_bad.any() or head_bad.any():
            # the shared segmented exclusive running max (core/slots.py):
            # the reference's per-row runmax drop (win_seq.hpp:293-305)
            # with no per-key Python even when every segment is disordered
            from .slots import segmented_excl_running_max
            excl = segmented_excl_running_max(s, p, starts,
                                              self._last_pos[s[starts]])
            keep_s = p >= excl
        # update last_pos from surviving rows (win_seq.hpp updates it before
        # the initial_id filter)
        if keep_s is None:
            self._last_pos[s[starts]] = np.maximum(
                self._last_pos[s[starts]], p[ends - 1])
        else:
            liv = np.flatnonzero(keep_s)
            if len(liv) == 0:
                return None
            ls, le = _segments(s[liv])
            self._last_pos[s[liv[ls]]] = np.maximum(
                self._last_pos[s[liv[ls]]], p[liv[le - 1]])
        # --- drop rows below the worker's initial position ---
        below = p < self._initial[s]
        if below.any():
            keep_s = ~below if keep_s is None else keep_s & ~below
        if keep_s is not None:
            sub = np.flatnonzero(keep_s)
            if len(sub) == 0:
                return None
            order = order[sub]
            s = s[sub]
            p = p[sub]
            starts, ends = _segments(s)
        sorted_rows = batch[order]
        mk = sorted_rows[MARKER_FIELD]
        # --- markers: remember the last marker's pos/ts per key ---
        any_mk = bool(mk.any())
        if any_mk:
            mi = np.flatnonzero(mk)
            msl = s[mi]
            last = np.ones(len(mi), dtype=bool)
            last[:-1] = msl[1:] != msl[:-1]
            self._marker_pos[msl[last]] = p[mi[last]]
            self._marker_ts[msl[last]] = \
                sorted_rows["ts"][mi[last]].astype(np.int64)
        return s, p, sorted_rows, starts, ends, mk, any_mk

    def process(self, batch: np.ndarray) -> np.ndarray:
        if len(batch) == 0:
            return np.zeros(0, dtype=self._result_dtype)
        ing = self._ingest(batch)
        if ing is None:
            return np.zeros(0, dtype=self._result_dtype)
        s, p, sorted_rows, starts, ends, mk, any_mk = ing
        rel = p - self._initial[s]
        w = rel // self._L
        # --- per-(slot, window) fold segments over real (non-marker) rows ---
        if any_mk:
            ri = np.flatnonzero(~mk)
            r_s, r_w, r_rows = s[ri], w[ri], sorted_rows[ri]
        else:
            r_s, r_w, r_rows = s, w, sorted_rows
        if len(r_s):
            bnd = np.concatenate(([0], np.flatnonzero(
                (np.diff(r_s) != 0) | (np.diff(r_w) != 0)) + 1))
            bnd_end = np.concatenate((bnd[1:], [len(r_s)]))
            seg_slot = r_s[bnd]
            seg_w = r_w[bnd]
            seg_len = bnd_end - bnd
            seg_ts = r_rows["ts"][bnd_end - 1].astype(np.int64)
            seg_vals = {}
            for (of, field, ufunc, dt, _ident) in self._parts:
                if ufunc is None:
                    seg_vals[of] = seg_len.astype(dt)
                else:
                    seg_vals[of] = ufunc.reduceat(
                        r_rows[field].astype(dt, copy=False), bnd)
        else:
            seg_slot = seg_w = np.zeros(0, dtype=np.int64)
            seg_ts = np.zeros(0, dtype=np.int64)
            seg_vals = {of: np.zeros(0, dtype=dt)
                        for (of, _f, _u, dt, _i) in self._parts}
        # --- firing: windows [n_fired, w_max) fire; w_max stays pending ---
        u = s[starts]                       # unique slots, ascending
        w_max = w[ends - 1]                 # fired_before(max_rel), tumbling
        fired_lo = self._nfired[u]
        m = w_max - fired_lo                # >= 0: kept rows are in-order
        self._seen[u] = True
        total = int(m.sum())
        offs = np.concatenate(([0], np.cumsum(m)))
        out_slot = np.repeat(u, m)
        ar = np.arange(total, dtype=np.int64) - np.repeat(offs[:-1], m)
        out_lwid = np.repeat(fired_lo, m) + ar
        out_vals = {of: np.full(total, ident, dtype=dt)
                    for (of, _f, _u, dt, ident) in self._parts}
        out_ts = np.zeros(total, dtype=np.int64)
        # the old pending accumulator lands in each slot's first fired window
        moved = m > 0
        if moved.any():
            pp = offs[:-1][moved]
            mu = u[moved]
            for (of, _f, ufunc, dt, ident) in self._parts:
                accv = self._acc[of][mu]
                if ufunc is None:           # count: partials add
                    out_vals[of][pp] = out_vals[of][pp] + accv
                else:
                    out_vals[of][pp] = ufunc(out_vals[of][pp], accv)
                self._acc[of][mu] = ident
            out_ts[pp] = self._acc_ts[mu]
            self._acc_ts[mu] = 0
        # fold chunk segments into fired outputs / the pending accumulator
        if len(seg_slot):
            spos = np.searchsorted(u, seg_slot)
            fired_seg = seg_w < w_max[spos]
            if fired_seg.any():
                fs = np.flatnonzero(fired_seg)
                op = offs[:-1][spos[fs]] + (seg_w[fs] - fired_lo[spos[fs]])
                for (of, _f, ufunc, dt, _ident) in self._parts:
                    sv = seg_vals[of][fs]
                    if ufunc is None:
                        out_vals[of][op] = out_vals[of][op] + sv
                    else:
                        out_vals[of][op] = ufunc(out_vals[of][op], sv)
                out_ts[op] = seg_ts[fs]
            pend = ~fired_seg
            if pend.any():
                ps = np.flatnonzero(pend)
                psl = seg_slot[ps]
                for (of, _f, ufunc, dt, _ident) in self._parts:
                    sv = seg_vals[of][ps]
                    if ufunc is None:
                        self._acc[of][psl] = self._acc[of][psl] + sv
                    else:
                        self._acc[of][psl] = ufunc(self._acc[of][psl], sv)
                self._acc_ts[psl] = seg_ts[ps]
        self._nfired[u] = w_max
        if total == 0:
            return np.zeros(0, dtype=self._result_dtype)
        return self._make_results(out_slot, out_lwid, out_ts, out_vals)

    # ------------------------------------------------------------------- emit

    def _make_results(self, out_slot, out_lwid, out_ts, vals) -> np.ndarray:
        """Assemble a result batch: gwids, role renumbering
        (win_seq.hpp:396-405), CB marker ts overwrite (window.hpp:149-154),
        TB closed-form ts.  ``out_slot`` must be grouped (all of a slot's
        windows contiguous, lwids ascending)."""
        gwids = self._fgwid[out_slot] + out_lwid * self.config.gwid_stride()
        if self.spec.win_type is WinType.TB:
            ts = gwids * self.result_ts_slide + self.spec.win_len - 1
        else:
            ends_abs = (out_lwid * self._S + self._L
                        + self._initial[out_slot])
            mpos = self._marker_pos[out_slot]
            ts = np.where((mpos > _NEG_INF) & (mpos < ends_abs),
                          self._marker_ts[out_slot], out_ts)
        if self.role in (Role.MAP, Role.PLQ):
            first = np.ones(len(out_slot), dtype=bool)
            first[1:] = out_slot[1:] != out_slot[:-1]
            fidx = np.flatnonzero(first)
            cnt = np.diff(np.concatenate((fidx, [len(out_slot)])))
            rank = out_lwid - np.repeat(out_lwid[fidx], cnt)
            if self.role is Role.MAP:
                n = self.map_indexes[1]
                ids = self._emit_ctr[out_slot] + rank * n
                self._emit_ctr[out_slot[fidx]] += cnt * n
            else:
                ni = self.config.n_inner
                ids = (self._inner_off[out_slot]
                       + (self._emit_ctr[out_slot] + rank) * ni)
                self._emit_ctr[out_slot[fidx]] += cnt
        else:
            ids = gwids
        out = np.zeros(len(out_slot), dtype=self._result_dtype)
        out["key"] = self._key[out_slot]
        out["id"] = ids
        out["ts"] = ts
        for name in self.winfunc.result_fields:
            out[name] = vals[name]
        return out

    # -------------------------------------------------- keyed state migration
    # The control plane's live rescale (docs/CONTROL.md) moves per-key
    # state between sibling farm workers at an epoch barrier.  Slots are
    # never removed from the SlotMap: export NEUTRALIZES the source
    # slot (last_pos back to -inf marks it dead — a registered key
    # always has last_pos set by its first chunk), and import overwrites
    # whatever the destination slot holds.  Derived per-key fields
    # (initial, fgwid, inner_off) are recomputed by slot registration —
    # sibling workers share one PatternConfig, so they are identical.

    _FRAG_KIND = "vec_tumbling"
    #: all per-key state is in the host slot arrays — migratable
    keyed_migratable = True

    def keyed_state_keys(self) -> np.ndarray:
        live = self._last_pos[:self._n] > _NEG_INF
        return self._key[:self._n][live].copy()

    def _export_acc(self, slots) -> dict:
        out = {"acc_ts": self._acc_ts[slots].copy(),
               "acc": {of: self._acc[of][slots].copy()
                       for (of, _f, _u, _dt, _i) in self._parts}}
        self._acc_ts[slots] = 0
        for (of, _f, _u, _dt, ident) in self._parts:
            self._acc[of][slots] = ident
        return out

    def _import_acc(self, slots, frag):
        self._acc_ts[slots] = frag["acc_ts"]
        for of, v in frag["acc"].items():
            self._acc[of][slots] = v

    def keyed_state_export(self, keys: np.ndarray) -> dict:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        slots = self._slots_for(keys)
        frag = {
            "kind": self._FRAG_KIND,
            "keys": keys,
            "last_pos": self._last_pos[slots].copy(),
            "nfired": self._nfired[slots].copy(),
            "seen": self._seen[slots].copy(),
            "emit_ctr": self._emit_ctr[slots].copy(),
            "marker_pos": self._marker_pos[slots].copy(),
            "marker_ts": self._marker_ts[slots].copy(),
        }
        frag.update(self._export_acc(slots))
        self._last_pos[slots] = _NEG_INF
        self._nfired[slots] = 0
        self._seen[slots] = False
        self._emit_ctr[slots] = (self.map_indexes[0]
                                 if self.role is Role.MAP else 0)
        self._marker_pos[slots] = _NEG_INF
        self._marker_ts[slots] = 0
        return frag

    def keyed_state_import(self, frag: dict):
        if frag["kind"] != self._FRAG_KIND:
            raise TypeError(f"cannot import {frag['kind']!r} state into "
                            f"{type(self).__name__}")
        slots = self._slots_for(frag["keys"])
        self._last_pos[slots] = frag["last_pos"]
        self._nfired[slots] = frag["nfired"]
        self._seen[slots] = frag["seen"]
        self._emit_ctr[slots] = frag["emit_ctr"]
        self._marker_pos[slots] = frag["marker_pos"]
        self._marker_ts[slots] = frag["marker_ts"]
        self._import_acc(slots, frag)

    # -------------------------------------------------------------------- EOS

    def flush(self) -> np.ndarray:
        """Emit the pending window of every key that saw rows
        (win_seq.hpp:433-474); tumbling INC mode has exactly one open
        window per key."""
        slots = np.flatnonzero(self._seen[:self._n])
        if len(slots) == 0:
            return np.zeros(0, dtype=self._result_dtype)
        out_lwid = self._nfired[slots].copy()
        out_ts = self._acc_ts[slots].copy()
        vals = {of: self._acc[of][slots].copy()
                for (of, _f, _u, _dt, _i) in self._parts}
        out = self._make_results(slots, out_lwid, out_ts, vals)
        self._nfired[slots] += 1
        self._seen[slots] = False
        for (of, _f, _u, dt, ident) in self._parts:
            self._acc[of][slots] = ident
        self._acc_ts[slots] = 0
        return out


class VecIncSlidingCore(VecIncTumblingCore):
    """Vectorised multi-key incremental core for SLIDING windows
    (slide < win): the tumbling core's segment arithmetic generalised to
    ``W = ceil(win/slide)`` concurrently open windows per key.

    A row at relative position ``r`` belongs to windows
    ``[max(0, (r-L)//S + 1), r//S]`` (win_seq.hpp:324's last-window formula
    inverted); window ``w`` fires when a row with ``rel >= w*S + L``
    arrives.  Per-key pending state is a ring of W accumulator *lanes*
    (lane = w % W) in slot-indexed 2D parallel arrays: at any moment the
    windows holding data are exactly ``[n_fired, n_fired + W)``, so lanes
    never collide.  Each chunk expands rows into their (slot, window)
    memberships (<= W per row), sorts once, and folds one ``reduceat`` per
    stat — O(W * rows log rows) at any key cardinality, replacing the
    per-key-group collapse VERDICT r2 weak #2 names.
    """

    def __init__(self, spec: WindowSpec, winfunc, config: PatternConfig = None,
                 role: Role = Role.SEQ, map_indexes=(0, 1),
                 result_ts_slide: int = None):
        assert spec.slide_len < spec.win_len, "sliding only (see tumbling)"
        super().__init__(spec, winfunc, config=config, role=role,
                         map_indexes=map_indexes,
                         result_ts_slide=result_ts_slide)
        self._W = -(-self._L // self._S)
        # reshape the pending state to (cap, W) lanes + created-window count
        self._ncreated = np.zeros(self._cap, dtype=np.int64)
        self._acc_ts = np.zeros((self._cap, self._W), dtype=np.int64)
        self._acc = {of: np.full((self._cap, self._W), ident, dtype=dt)
                     for (of, _f, _u, dt, ident) in self._parts}

    def _grow_acc(self, cap: int):
        n, W = self._n, self._W
        nc = np.zeros(cap, dtype=np.int64)
        nc[:n] = self._ncreated[:n]
        self._ncreated = nc
        ts = np.zeros((cap, W), dtype=np.int64)
        ts[:n] = self._acc_ts[:n]
        self._acc_ts = ts
        for (of, _f, _u, dt, ident) in self._parts:
            b = np.full((cap, W), ident, dtype=dt)
            b[:n] = self._acc[of][:n]
            self._acc[of] = b

    def process(self, batch: np.ndarray) -> np.ndarray:
        if len(batch) == 0:
            return np.zeros(0, dtype=self._result_dtype)
        ing = self._ingest(batch)
        if ing is None:
            return np.zeros(0, dtype=self._result_dtype)
        s, p, sorted_rows, starts, ends, mk, any_mk = ing
        L, S, W = self._L, self._S, self._W
        rel = p - self._initial[s]
        if any_mk:
            ri = np.flatnonzero(~mk)
            r_s, r_rel, r_rows = s[ri], rel[ri], sorted_rows[ri]
        else:
            r_s, r_rel, r_rows = s, rel, sorted_rows
        # --- expand real rows into their (slot, window) memberships ---
        hi = r_rel // S
        lo = np.maximum((r_rel - L) // S + 1, 0)
        c = hi - lo + 1                      # >= 1: sliding covers every rel
        tot = int(c.sum())
        coffs = np.concatenate(([0], np.cumsum(c)))
        e_row = np.repeat(np.arange(len(r_s), dtype=np.int64), c)
        e_w = (np.repeat(lo, c)
               + np.arange(tot, dtype=np.int64) - np.repeat(coffs[:-1], c))
        e_s = r_s[e_row]
        # one stable sort groups (slot, window) pairs, preserving arrival
        # order within each (slot stays grouped; windows interleave by row)
        span = int(e_w.max()) + 2 if tot else 1
        sidx = np.argsort(e_s * span + e_w, kind="stable")
        g_s, g_w, g_row = e_s[sidx], e_w[sidx], e_row[sidx]
        if tot:
            bnd = np.concatenate(([0], np.flatnonzero(
                (np.diff(g_s) != 0) | (np.diff(g_w) != 0)) + 1))
            bnd_end = np.concatenate((bnd[1:], [tot]))
            seg_slot = g_s[bnd]
            seg_w = g_w[bnd]
            seg_len = bnd_end - bnd
            seg_ts = r_rows["ts"][g_row[bnd_end - 1]].astype(np.int64)
            seg_vals = {}
            for (of, field, ufunc, dt, _ident) in self._parts:
                if ufunc is None:
                    seg_vals[of] = seg_len.astype(dt)
                else:
                    seg_vals[of] = ufunc.reduceat(
                        r_rows[field].astype(dt, copy=False)[g_row], bnd)
        else:
            seg_slot = seg_w = np.zeros(0, dtype=np.int64)
            seg_ts = np.zeros(0, dtype=np.int64)
            seg_vals = {of: np.zeros(0, dtype=dt)
                        for (of, _f, _u, dt, _i) in self._parts}
        # --- firing: windows [n_fired, new_fired) fire, in window order ---
        u = s[starts]                        # unique slots, ascending
        max_rel = rel[ends - 1]              # kept rows are in-order per key
        new_fired = np.maximum(self._nfired[u],
                               np.maximum((max_rel - L) // S + 1, 0))
        self._ncreated[u] = np.maximum(self._ncreated[u], max_rel // S + 1)
        fired_lo = self._nfired[u]
        m = new_fired - fired_lo
        self._seen[u] = True
        total = int(m.sum())
        offs = np.concatenate(([0], np.cumsum(m)))
        out_slot = np.repeat(u, m)
        ar = np.arange(total, dtype=np.int64) - np.repeat(offs[:-1], m)
        out_lwid = np.repeat(fired_lo, m) + ar
        out_vals = {of: np.full(total, ident, dtype=dt)
                    for (of, _f, _u, dt, ident) in self._parts}
        out_ts = np.zeros(total, dtype=np.int64)
        # pending lanes land in their windows: only the first W fired per
        # slot can hold lane state (open windows live in [n_fired,
        # n_fired+W) — a row touching n_fired+W would have fired n_fired)
        take = ar < W
        if take.any():
            tsl = out_slot[take]
            tln = out_lwid[take] % W
            for (of, _f, _u, dt, ident) in self._parts:
                out_vals[of][take] = self._acc[of][tsl, tln]
                self._acc[of][tsl, tln] = ident
            out_ts[take] = self._acc_ts[tsl, tln]
            self._acc_ts[tsl, tln] = 0
        # fold chunk segments into fired outputs / the pending lanes
        if len(seg_slot):
            spos = np.searchsorted(u, seg_slot)
            fired_seg = seg_w < new_fired[spos]
            if fired_seg.any():
                fs = np.flatnonzero(fired_seg)
                op = offs[:-1][spos[fs]] + (seg_w[fs] - fired_lo[spos[fs]])
                for (of, _f, ufunc, dt, _ident) in self._parts:
                    sv = seg_vals[of][fs]
                    if ufunc is None:
                        out_vals[of][op] = out_vals[of][op] + sv
                    else:
                        out_vals[of][op] = ufunc(out_vals[of][op], sv)
                out_ts[op] = seg_ts[fs]
            pend = ~fired_seg
            if pend.any():
                ps = np.flatnonzero(pend)
                psl = seg_slot[ps]
                pln = seg_w[ps] % W          # distinct pending w => distinct
                for (of, _f, ufunc, dt, _ident) in self._parts:  # lanes
                    sv = seg_vals[of][ps]
                    if ufunc is None:
                        self._acc[of][psl, pln] = self._acc[of][psl, pln] + sv
                    else:
                        self._acc[of][psl, pln] = ufunc(
                            self._acc[of][psl, pln], sv)
                self._acc_ts[psl, pln] = seg_ts[ps]
        self._nfired[u] = new_fired
        if total == 0:
            return np.zeros(0, dtype=self._result_dtype)
        return self._make_results(out_slot, out_lwid, out_ts, out_vals)

    # keyed migration: the tumbling fragment plus the created-window
    # count; the 1D acc copies generalise to (m, W) lane rows untouched
    _FRAG_KIND = "vec_sliding"

    def keyed_state_export(self, keys: np.ndarray) -> dict:
        frag = super().keyed_state_export(keys)
        slots = self._slots_for(frag["keys"])
        frag["ncreated"] = self._ncreated[slots].copy()
        self._ncreated[slots] = 0
        return frag

    def keyed_state_import(self, frag: dict):
        super().keyed_state_import(frag)
        self._ncreated[self._slots_for(frag["keys"])] = frag["ncreated"]

    def flush(self) -> np.ndarray:
        """EOS: every created-but-unfired window fires, oldest first
        (win_seq.hpp:433-474) — at most W per key, all lane-resident."""
        W = self._W
        slots = np.flatnonzero(self._seen[:self._n])
        if len(slots) == 0:
            return np.zeros(0, dtype=self._result_dtype)
        fired_lo = self._nfired[slots]
        m = self._ncreated[slots] - fired_lo
        keep = m > 0
        slots, fired_lo, m = slots[keep], fired_lo[keep], m[keep]
        total = int(m.sum())
        if total == 0:
            self._seen[:self._n] = False
            return np.zeros(0, dtype=self._result_dtype)
        offs = np.concatenate(([0], np.cumsum(m)))
        out_slot = np.repeat(slots, m)
        ar = np.arange(total, dtype=np.int64) - np.repeat(offs[:-1], m)
        out_lwid = np.repeat(fired_lo, m) + ar
        lanes = out_lwid % W
        vals = {}
        for (of, _f, _u, dt, ident) in self._parts:
            vals[of] = self._acc[of][out_slot, lanes].copy()
            self._acc[of][out_slot, lanes] = ident
        out_ts = self._acc_ts[out_slot, lanes].copy()
        self._acc_ts[out_slot, lanes] = 0
        out = self._make_results(out_slot, out_lwid, out_ts, vals)
        self._nfired[slots] = self._ncreated[slots]
        self._seen[:self._n] = False
        return out


#: derived crossover cache, keyed by window shape — measured on THIS host
_SLIDING_THRESHOLD = {}
#: serialises the calibration benchmark: several farm workers
#: constructing LazySlidingCores concurrently would otherwise each run
#: the measurement under mutual contention and fit a skewed crossover
#: (ADVICE r4); the winner publishes the cached value the rest reuse
_THRESHOLD_LOCK = threading.Lock()


def derived_sliding_threshold(spec: WindowSpec = None,
                              force: bool = False) -> int:
    """Measure the per-key-core vs lane-core crossover cardinality on
    THIS host for this window SHAPE (r3 weak #4: the old hard-coded 512
    encoded the 1-core bench host; a multicore or faster host — or a
    denser window cadence, which multiplies the per-key core's
    per-window Python overhead — shifts the economics in an unmeasured
    direction).  Times both cores on a small synthetic stream of the
    given (win, slide) at two cardinalities, fits each as linear in key
    count, and solves for the intersection.  Cached per shape per
    process (~0.3-0.6 s once); mispredictions cost only throughput —
    LazySlidingCore migrates state if the stream later crosses whatever
    threshold this returns."""
    if spec is None:
        spec = WindowSpec(8, 2, WinType.CB)
    ck = (int(spec.win_len), int(spec.slide_len))
    if ck in _SLIDING_THRESHOLD and not force:
        return _SLIDING_THRESHOLD[ck]
    with _THRESHOLD_LOCK:
        if ck in _SLIDING_THRESHOLD and not force:
            return _SLIDING_THRESHOLD[ck]
        return _measure_sliding_threshold(ck)


def _measure_sliding_threshold(ck) -> int:
    import time as _t

    from .tuples import Schema, batch_from_columns
    from .winseq import WinSeqCore
    cal_spec = WindowSpec(ck[0], ck[1], WinType.CB)
    schema = Schema(value=np.int64)
    red = Reducer("sum")
    # enough rows that windows actually fire at the instance's cadence
    # for every probed cardinality, capped so wide-slide shapes keep the
    # one-off calibration under ~a second
    lo_k, hi_k = 64, 2048
    rows = max(4096, min(hi_k * 4 * ck[1], 1 << 17))

    def once(cls, nk):
        per = rows // nk
        ids = np.tile(np.arange(per, dtype=np.int64), nk)
        keys = np.repeat(np.arange(nk, dtype=np.int64), per)
        order = np.argsort(ids, kind="stable")   # interleave keys
        b = batch_from_columns(schema, key=keys[order], id=ids[order],
                               ts=ids[order], value=ids[order] % 97)
        best = None
        for _ in range(2):        # best-of: least interference
            core = cls(cal_spec, red)
            t0 = _t.perf_counter()
            core.process(b)
            core.flush()
            dt = _t.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    pk_lo, pk_hi = once(WinSeqCore, lo_k), once(WinSeqCore, hi_k)
    vec_lo, vec_hi = (once(VecIncSlidingCore, lo_k),
                      once(VecIncSlidingCore, hi_k))
    # t(nk) = t_lo + b*(nk - lo_k) per core; the lines meet at
    # nk* = lo_k + (vec_lo - pk_lo) / (pk_b - vec_b)
    pk_b = (pk_hi - pk_lo) / (hi_k - lo_k)
    vec_b = (vec_hi - vec_lo) / (hi_k - lo_k)
    if pk_b <= vec_b:
        # per-key never loses ground with cardinality here (e.g. a many-
        # core host whose dict path scales): keep a high threshold so the
        # migration path still covers extreme cardinalities
        nk_star = hi_k
    else:
        nk_star = lo_k + (vec_lo - pk_lo) / (pk_b - vec_b)
    th = int(min(max(nk_star, 64), 8192))
    _SLIDING_THRESHOLD[ck] = th
    return th


class LazySlidingCore:
    """Defers the sliding-core choice to observed key cardinality: the
    per-key-group ``WinSeqCore`` wins at low key counts, the
    lane-vectorised ``VecIncSlidingCore`` above a crossover MEASURED on
    the running host (derived_sliding_threshold — on the 1-core bench
    host it lands between 256 and 1024 keys: 64 keys 2.9M vs 1.6M tps,
    16k keys 0.24M vs 4.0M).  The first chunk picks the initial core; if
    a key-clustered stream later crosses the threshold (e.g. per-key-
    partitioned replay whose first chunk carries few keys), the per-key
    core's state MIGRATES into the lane core — its NIC archives hold
    exactly the live rows the open-window lanes need — so the choice is
    never locked in.  Mispredictions cost only throughput, never
    correctness: both cores are differentially identical."""

    def __init__(self, spec: WindowSpec, winfunc, threshold: int = None,
                 **kw):
        self.spec = spec
        self.winfunc = winfunc
        self._kw = kw
        self._threshold = (int(threshold) if threshold is not None
                           else derived_sliding_threshold(spec))
        self._core = None
        self._perkey = False
        self.result_schema = Schema(**winfunc.result_fields)
        self._result_dtype = self.result_schema.dtype()
        self.is_nic = False

    def _pick(self, batch):
        nk = len(np.unique(batch["key"]))
        if nk >= self._threshold:
            self._core = VecIncSlidingCore(self.spec, self.winfunc,
                                           **self._kw)
        else:
            from .winseq import WinSeqCore
            self._core = WinSeqCore(self.spec, self.winfunc, **self._kw)
            self._perkey = True
        return self._core

    def _escalate(self):
        """Move the per-key core's live state into a fresh lane core:
        per-key scalars copy across (the slot registration recomputes the
        identical distribution math), and each open window's accumulator
        folds from the archive range the NIC core kept live (purge only
        ever runs below the last FIRED window's start, so open windows'
        rows are all present)."""
        old = self._core
        vec = VecIncSlidingCore(self.spec, self.winfunc, **self._kw)
        W = vec._W
        spec = self.spec
        if old._keys:
            keys = np.fromiter(old._keys.keys(), dtype=np.int64,
                               count=len(old._keys))
            slots = vec._slots_for(keys)
            for key, slot in zip(keys.tolist(), slots.tolist()):
                st = old._keys[key]
                vec._last_pos[slot] = st.last_pos
                vec._nfired[slot] = st.n_fired
                vec._ncreated[slot] = st.next_lwid
                vec._seen[slot] = st.next_lwid > st.n_fired
                vec._emit_ctr[slot] = st.emit_counter
                vec._marker_pos[slot] = st.marker_pos
                vec._marker_ts[slot] = st.marker_ts
                p = st.archive.positions
                rows = st.archive.rows
                for lw in range(st.n_fired, st.next_lwid):
                    lo = np.searchsorted(p, spec.win_start(lw)
                                         + st.initial_id, side="left")
                    hi = np.searchsorted(p, spec.win_end(lw)
                                         + st.initial_id, side="left")
                    if hi <= lo:
                        continue
                    lane = lw % W
                    seg = rows[lo:hi]
                    for (of, field, ufunc, dt, _ident) in vec._parts:
                        if ufunc is None:
                            vec._acc[of][slot, lane] = hi - lo
                        else:
                            vec._acc[of][slot, lane] = ufunc.reduce(
                                seg[field].astype(dt, copy=False))
                    vec._acc_ts[slot, lane] = int(seg["ts"][-1])
        self._core = vec
        self._perkey = False

    def process(self, batch):
        core = self._core
        if core is None:
            if len(batch) == 0:
                return np.zeros(0, dtype=self._result_dtype)
            core = self._pick(batch)
        out = core.process(batch)
        if self._perkey and len(core._keys) >= self._threshold:
            self._escalate()
        return out

    def flush(self):
        if self._core is None:
            return np.zeros(0, dtype=self._result_dtype)
        return self._core.flush()

    def use_incremental(self):
        return self  # both backing cores compute the monoid INC == NIC

    # -------------------------------------------------- keyed state migration
    # Sibling workers may have picked DIFFERENT backings (each decides on
    # its own first chunk): before migrating, control/rescale.py
    # harmonizes every involved LazySlidingCore onto one backing class
    # via ensure_backing — escalation is lossless (the per-key core's
    # archives rebuild the lane accumulators, see _escalate), the
    # reverse direction is not, so vec wins whenever any sibling runs it.

    #: both possible backings are host cores
    keyed_migratable = True

    def ensure_backing(self, vec: bool):
        if self._core is None:
            if vec:
                self._core = VecIncSlidingCore(self.spec, self.winfunc,
                                               **self._kw)
            else:
                from .winseq import WinSeqCore
                self._core = WinSeqCore(self.spec, self.winfunc,
                                        **self._kw)
                self._perkey = True
        elif vec and self._perkey:
            self._escalate()

    @property
    def backing_is_vec(self):
        """None before the first chunk, else whether the lane core runs."""
        return None if self._core is None else not self._perkey

    def keyed_state_keys(self):
        if self._core is None:
            return np.zeros(0, dtype=np.int64)
        return self._core.keyed_state_keys()

    def keyed_state_export(self, keys):
        return self._core.keyed_state_export(keys)

    def keyed_state_import(self, frag):
        return self._core.keyed_state_import(frag)
