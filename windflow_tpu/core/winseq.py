"""The sequential window core: batch-vectorised re-derivation of Win_Seq.

This is the engine at the centre of every windowed pattern (the reference's
``Win_Seq``, ``win_seq.hpp:268-474``, is the worker of every farm).  The
reference processes one tuple at a time, keeping a vector of live ``Window``
objects per key and evaluating a triggerer closure per tuple per window.
Here the same semantics are derived in closed form over *chunks*:

* the set of windows created by a chunk is ``[next_lwid, last_w(max_pos)]``
  (lazy creation, win_seq.hpp:344-352);
* the set of windows fired is ``[n_fired, fired_before(max_pos)) ∩ created``
  (triggerer, window.hpp:63-66);
* a fired window's content is the archive range ``[start, end)`` by
  position — equal to the reference's ``[firstTuple, firingTuple)`` range
  for in-order streams (win_seq.hpp:366-384);
* out-of-order tuples are dropped (win_seq.hpp:293-305), hopping-gap tuples
  are dropped (win_seq.hpp:326-338), EOS markers participate in window
  creation/firing but are never archived nor folded (win_seq.hpp:340,357);
* fired NIC windows purge the archive below their start (win_seq.hpp:390-392);
* PLQ/MAP roles renumber emitted result ids (win_seq.hpp:396-405);
* at EOS every still-open window is flushed over the archive tail
  (win_seq.hpp:433-474).

All per-chunk work is numpy array arithmetic; the per-window evaluation
either loops (arbitrary host functions) or batches (monoid reducers / JAX
functions via ``apply_batch``) — the batched form is exactly what the TPU
pattern stages to the device.
"""

from __future__ import annotations

import numpy as np

from .tuples import MARKER_FIELD, Schema
from .windows import PatternConfig, Role, WindowSpec, WinType
from ..ops.functions import WindowFunction, WindowUpdate

_NEG_INF = np.int64(-(2 ** 62))


class _KeyState:
    __slots__ = (
        "archive", "next_lwid", "n_fired", "rcv_counter", "last_pos",
        "emit_counter", "inc_accs", "inc_last_ts", "first_gwid", "initial_id",
        "marker_pos", "marker_ts",
    )

    def __init__(self, dtype, pos_field, first_gwid, initial_id, emit_counter0):
        from .archive import KeyArchive
        self.archive = KeyArchive(dtype, pos_field)
        self.next_lwid = 0
        self.n_fired = 0
        self.rcv_counter = 0
        self.last_pos = _NEG_INF
        self.emit_counter = emit_counter0
        self.inc_accs = {}      # lwid -> accumulator record (INC mode)
        self.inc_last_ts = {}   # lwid -> ts of last folded/continue row (CB)
        self.first_gwid = first_gwid
        self.initial_id = initial_id
        self.marker_pos = _NEG_INF
        self.marker_ts = 0


class WinSeqCore:
    """Role-aware sequential window engine over one keyed stream partition."""

    def __init__(self, spec: WindowSpec, winfunc, config: PatternConfig = None,
                 role: Role = Role.SEQ, map_indexes=(0, 1),
                 result_ts_slide: int = None):
        self.spec = spec
        # TB result ts uses the *global* slide of the logical window, which
        # differs from spec.slide_len inside a farm worker (private slide =
        # slide*pardegree). The reference quirkily uses the private slide
        # (window.hpp:124 with win_farm.hpp:134's slide), making farm output
        # ts diverge from Win_Seq's on the same stream; we normalise to the
        # sequential semantics so all compositions agree.
        self.result_ts_slide = (result_ts_slide if result_ts_slide is not None
                                else spec.slide_len)
        self.config = config or PatternConfig.plain(spec.slide_len)
        self.role = role
        self.map_indexes = map_indexes
        if isinstance(winfunc, WindowUpdate) and not isinstance(winfunc, WindowFunction):
            self.is_nic = False
        elif isinstance(winfunc, WindowFunction) and not isinstance(winfunc, WindowUpdate):
            self.is_nic = True
        else:
            # dual-mode (e.g. Reducer): default to NIC unless told otherwise
            self.is_nic = True
        self.winfunc = winfunc
        self.result_schema = Schema(**winfunc.result_fields)
        self._result_dtype = self.result_schema.dtype()
        self._payload_names = tuple(winfunc.result_fields.keys())
        self.pos_field = "id" if spec.win_type is WinType.CB else "ts"
        self._keys = {}           # key -> _KeyState, insertion ordered
        self._in_dtype = None

    def use_incremental(self):
        """Force INC mode for a dual-mode function (monoid reducer)."""
        self.is_nic = False
        return self

    # ------------------------------------------------------------------ utils

    def _state(self, key: int) -> _KeyState:
        st = self._keys.get(key)
        if st is None:
            emit0 = self.map_indexes[0] if self.role is Role.MAP else 0
            st = _KeyState(
                self._in_dtype, self.pos_field,
                self.config.first_gwid(key),
                self.config.initial_id(key, self.role),
                emit0,
            )
            self._keys[key] = st
        return st

    def _renumber_ids(self, key: int, st: _KeyState, gwids: np.ndarray) -> np.ndarray:
        """Result-id assignment incl. PLQ/MAP renumbering (win_seq.hpp:396-405)."""
        n = len(gwids)
        if self.role is Role.MAP:
            ids = st.emit_counter + np.arange(n, dtype=np.int64) * self.map_indexes[1]
            st.emit_counter += n * self.map_indexes[1]
            return ids
        if self.role is Role.PLQ:
            ni = self.config.n_inner
            inner_off = (self.config.id_inner - (key % ni) + ni) % ni
            ids = inner_off + (st.emit_counter + np.arange(n, dtype=np.int64)) * ni
            st.emit_counter += n
            return ids
        return gwids

    def _result_ts(self, st: _KeyState, lwids: np.ndarray, gwids: np.ndarray) -> np.ndarray:
        """CB: ts of the last CONTINUE row per window; TB: closed form
        (window.hpp:121-124,154)."""
        if self.spec.win_type is WinType.TB:
            return gwids * self.result_ts_slide + self.spec.win_len - 1
        ends_abs = self.spec.win_end(lwids) + st.initial_id
        starts_abs = self.spec.win_start(lwids) + st.initial_id
        out = np.zeros(len(lwids), dtype=np.int64)
        if self.is_nic:
            p = st.archive.positions
            ts = st.archive.rows["ts"]
            if len(p):
                idx = np.searchsorted(p, ends_abs, side="left") - 1
                # only rows inside [start, end) ever raised CONTINUE on this
                # window (rows archived before the window was created must
                # not contribute a timestamp; empty windows keep ts=0)
                valid = (idx >= 0) & (p[np.maximum(idx, 0)] >= starts_abs)
                out[valid] = ts[idx[valid]]
        else:
            for i, lw in enumerate(lwids):
                if int(lw) in st.inc_last_ts:
                    out[i] = st.inc_last_ts[int(lw)]
        # an EOS marker arrives after every real row and also raises CONTINUE,
        # so it overwrites the result ts of any window it falls below
        # (window.hpp:149-154 runs for marker tuples too)
        if st.marker_pos > _NEG_INF:
            out = np.where(st.marker_pos < ends_abs, st.marker_ts, out)
        return out

    def _make_results(self, key, ids, ts, payload_cols) -> np.ndarray:
        out = np.zeros(len(ids), dtype=self._result_dtype)
        out["key"] = key
        out["id"] = ids
        out["ts"] = ts
        for name in self._payload_names:
            out[name] = payload_cols[name]
        return out

    # ------------------------------------------------------------- processing

    def process(self, batch: np.ndarray) -> np.ndarray:
        """Consume one chunk (any mix of keys, in arrival order); return the
        chunk of window results emitted."""
        if self._in_dtype is None:
            self._in_dtype = batch.dtype
        if len(batch) == 0:
            return np.zeros(0, dtype=self._result_dtype)
        outs = []
        keys = batch["key"]
        if keys[0] == keys[-1] and not np.any(keys != keys[0]):
            r = self._process_key(int(keys[0]), batch)
            if r is not None:
                outs.append(r)
        else:
            # stable group-by key preserving arrival order within key
            order = np.argsort(keys, kind="stable")
            sorted_keys = keys[order]
            bounds = np.flatnonzero(np.diff(sorted_keys)) + 1
            for grp in np.split(order, bounds):
                r = self._process_key(int(keys[grp[0]]), batch[grp])
                if r is not None:
                    outs.append(r)
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def _process_key(self, key: int, rows: np.ndarray):
        spec = self.spec
        st = self._state(key)
        pos = rows[self.pos_field].astype(np.int64)
        marker = rows[MARKER_FIELD]
        # --- drop out-of-order rows (strictly decreasing pos) ---
        runmax = np.maximum.accumulate(np.concatenate(([st.last_pos], pos)))[:-1]
        keep = pos >= runmax
        # --- drop rows before this worker's initial position ---
        keep &= pos >= st.initial_id
        rel = pos - st.initial_id
        # --- hopping gaps: drop non-marker rows outside every window ---
        if spec.is_hopping:
            keep &= spec.in_any_window(rel) | marker
        n_seen = int(np.count_nonzero(pos >= runmax))
        if n_seen:
            st.rcv_counter += n_seen
            st.last_pos = max(st.last_pos, int(pos.max()))
        if not np.all(keep):
            rows = rows[keep]
            pos = pos[keep]
            rel = rel[keep]
            marker = marker[keep]
        if len(rows) == 0:
            return None
        # --- track markers (they participate in firing & result-ts) ---
        if np.any(marker):
            mrows = rows[marker]
            st.marker_pos = int(mrows[self.pos_field][-1])
            st.marker_ts = int(mrows["ts"][-1])
            real = rows[~marker]
            real_pos = pos[~marker]
        else:
            real = rows
            real_pos = pos
        # --- archive (NIC only, non-marker rows; win_seq.hpp:340) ---
        if self.is_nic and len(real):
            st.archive.append(real)
            self._on_append(key, st, real)
        # --- window creation ---
        max_rel = int(rel.max())
        last_w = int(spec.last_win_containing(max_rel))
        new_next = max(st.next_lwid, last_w + 1)
        created = range(st.next_lwid, new_next)
        st.next_lwid = new_next
        # --- INC: fold chunk rows into every open window ---
        if not self.is_nic:
            for lw in created:
                gw = st.first_gwid + lw * self.config.gwid_stride()
                st.inc_accs[lw] = self.winfunc.init(key, gw)
            if len(real):
                rel_real = real_pos - st.initial_id
                for lw in list(st.inc_accs.keys()):
                    s, e = spec.win_start(lw), spec.win_end(lw)
                    lo = np.searchsorted(rel_real, s, side="left")
                    hi = np.searchsorted(rel_real, e, side="left")
                    if hi > lo:
                        gw = st.first_gwid + lw * self.config.gwid_stride()
                        self.winfunc.update_many(key, gw, real[lo:hi], st.inc_accs[lw])
                        st.inc_last_ts[lw] = int(real["ts"][hi - 1])
        # --- firing ---
        n_fireable = int(spec.fired_before(max_rel))
        n_fire_to = min(max(n_fireable, st.n_fired), st.next_lwid)
        if n_fire_to <= st.n_fired:
            return None
        lwids = np.arange(st.n_fired, n_fire_to, dtype=np.int64)
        st.n_fired = n_fire_to
        return self._emit_windows(key, st, lwids, eos=False)

    def _on_append(self, key, st: _KeyState, rows: np.ndarray):
        """Hook: called after `rows` are appended to `key`'s archive (the
        device-resident core mirrors appends into the HBM archive here)."""

    def _emit_windows(self, key, st: _KeyState, lwids: np.ndarray, eos: bool):
        spec = self.spec
        gwids = st.first_gwid + lwids * self.config.gwid_stride()
        ts = self._result_ts(st, lwids, gwids)
        if self.is_nic:
            starts_abs = spec.win_start(lwids) + st.initial_id
            ends_abs = spec.win_end(lwids) + st.initial_id
            cols = self._eval_nic(key, st, gwids, starts_abs, ends_abs, eos)
            if not eos and len(lwids):
                # purge below the start of the last fired window
                st.archive.purge_below(int(starts_abs[-1]))
        else:
            cols = {n: np.zeros(len(lwids), dtype=dt)
                    for n, dt in self.winfunc.result_fields.items()}
            for i, lw in enumerate(lwids):
                acc = st.inc_accs.pop(int(lw))
                st.inc_last_ts.pop(int(lw), None)
                for n in self._payload_names:
                    cols[n][i] = acc[n]
        ids = self._renumber_ids(key, st, gwids)
        return self._make_results(key, ids, ts, cols)

    def _eval_nic(self, key, st: _KeyState, gwids, starts_abs, ends_abs, eos: bool):
        """Evaluate NIC windows; batched when the function supports it."""
        p = st.archive.positions
        lo = np.searchsorted(p, starts_abs, side="left")
        hi = (np.full(len(starts_abs), len(p), dtype=np.int64) if eos
              else np.searchsorted(p, ends_abs, side="left"))
        lens = (hi - lo).astype(np.int64)
        if getattr(self.winfunc, "supports_batch", False) and len(gwids) > 1:
            pad = int(lens.max()) if len(lens) else 0
            arch = st.archive.rows
            idx = np.minimum(lo[:, None] + np.arange(max(pad, 1))[None, :],
                             max(len(arch) - 1, 0))
            pad_mask = np.arange(max(pad, 1))[None, :] >= lens[:, None]
            cols_in = {}
            req = getattr(self.winfunc, "required_fields", None)
            names = (tuple(req) if req is not None
                     else tuple(n for n in arch.dtype.names if n != MARKER_FIELD))
            for name in names:
                if len(arch):
                    col = arch[name][idx]
                    # honour the apply_batch contract: padding slots are zeros
                    col[pad_mask] = 0
                else:
                    col = np.zeros((len(gwids), max(pad, 1)),
                                   dtype=arch.dtype[name])
                cols_in[name] = col
            return self.winfunc.apply_batch(
                np.full(len(gwids), key, dtype=np.int64), gwids, cols_in, lens)
        cols = {n: np.zeros(len(gwids), dtype=dt)
                for n, dt in self.winfunc.result_fields.items()}
        arch = st.archive.rows
        for i in range(len(gwids)):
            vals = self.winfunc.apply(key, int(gwids[i]), arch[lo[i]:hi[i]])
            for n, v in zip(self._payload_names, vals):
                cols[n][i] = v
        return cols

    # -------------------------------------------------- keyed state migration

    #: explicit opt-in for the control plane's live rescale
    #: (control/rescale.py): the hooks below move the HOST per-key
    #: state only, so subclasses that mirror state elsewhere (device
    #: HBM ring archives, native C tables) MUST override this to False
    #: or a rescale would migrate half a key's state
    keyed_migratable = True

    def keyed_state_keys(self) -> np.ndarray:
        """Keys holding live state — the unit the control plane's live
        rescale repartitions (docs/CONTROL.md).  Key-partitioned farm
        workers share one PatternConfig, so a key's ``_KeyState`` is
        meaningful verbatim on any sibling worker."""
        if not self._keys:
            return np.zeros(0, dtype=np.int64)
        return np.fromiter(self._keys.keys(), dtype=np.int64,
                           count=len(self._keys))

    def keyed_state_export(self, keys: np.ndarray) -> dict:
        """Remove and return the per-key state of ``keys`` (a fragment
        ``keyed_state_import`` absorbs on a same-class, same-config
        sibling core).  Only called while both cores are quiescent (the
        rescale barrier parks every worker thread)."""
        return {"kind": "winseq",
                "keys": {int(k): self._keys.pop(int(k)) for k in keys},
                "in_dtype": self._in_dtype}

    def keyed_state_import(self, frag: dict):
        if frag["kind"] != "winseq":  # harmonized by control/rescale.py
            raise TypeError(f"cannot import {frag['kind']!r} state into "
                            f"WinSeqCore")
        if self._in_dtype is None:
            self._in_dtype = frag["in_dtype"]
        self._keys.update(frag["keys"])

    # ------------------------------------------------------------------- EOS

    def flush(self) -> np.ndarray:
        """Flush every still-open window (eosnotify, win_seq.hpp:433-474)."""
        outs = []
        for key, st in self._keys.items():
            if st.n_fired >= st.next_lwid:
                continue
            lwids = np.arange(st.n_fired, st.next_lwid, dtype=np.int64)
            st.n_fired = st.next_lwid
            r = self._emit_windows(key, st, lwids, eos=True)
            if r is not None:  # device cores enqueue instead of returning
                outs.append(r)
        if not outs:
            return np.zeros(0, dtype=self._result_dtype)
        return np.concatenate(outs)
