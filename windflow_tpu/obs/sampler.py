"""Background metrics sampler — the thread that makes a *running* graph
visible: every ``period`` seconds it snapshots per-node inbox depth /
high-water mark, shed and quarantine counters, the live
``tracing.NodeStats`` counters, the dead-letter count, and the attached
:class:`~windflow_tpu.obs.registry.MetricsRegistry` (wire counters, user
metrics) into one JSON line of ``<trace_dir>/metrics.jsonl``.

The sampler is owned by the :class:`~windflow_tpu.runtime.engine.Dataflow`
that configured ``sample_period=``: started in ``run()``, stopped (with a
final flush sample) in ``wait()``.  Without ``sample_period`` no thread
exists at all, and node hot paths carry only the inbox high-water-mark
branch (docs/OBSERVABILITY.md §overhead).

Everything here reads engine state *racily on purpose*: the sampled
values are ints/floats written under the GIL by the node threads, so a
sample is internally slightly torn but each field is a real observed
value — the standard monitoring trade.  A node mid-mutation (counter
dict resize) is skipped for that one sample rather than crashing the
sampler.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..utils.tracing import node_stats_name


class Sampler:
    """Periodic snapshotter for one Dataflow (see module docstring)."""

    def __init__(self, dataflow, period: float,
                 max_bytes: int = 64 << 20, keep: int = 2):
        self.df = dataflow
        self.period = float(period)
        if self.period <= 0:
            raise ValueError(f"sample_period must be positive, "
                             f"got {period}")
        #: size bound on metrics.jsonl (ISSUE 19): past it the file
        #: rolls to ``metrics.jsonl.1`` (older generations shift up,
        #: ``keep`` of them retained) — long soaks must not grow the
        #: file without limit.  ``max_bytes=None`` = unbounded.
        #: Rotation happens between whole lines, so tailing readers
        #: (``wf_top.read_samples``) detect the roll by file shrink and
        #: never see a torn record.
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        if self.max_bytes is not None and self.max_bytes < 1:
            raise ValueError("Sampler max_bytes must be positive")
        self.keep = int(keep)
        if self.keep < 1:
            raise ValueError("Sampler keep must retain at least one "
                             "rotated file")
        self._written = 0
        self._path = None
        self._stop = threading.Event()
        self._last_shed: dict[str, int] = {}
        self._subs: list = []
        #: last exception a subscriber raised (diagnostics; the sampler
        #: itself never dies on a bad subscriber)
        self.sub_error = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"{dataflow.name}/sampler")
        #: samples taken (monotone; the "seq" field of the next line)
        self.seq = 0

    # ------------------------------------------------------------ lifecycle

    def subscribe(self, fn):
        """Register an in-process snapshot consumer: ``fn(rec)`` is
        called on the sampler thread with every sample dict (the
        pre-serialisation ``metrics.jsonl`` record) — the control
        plane's sensor bus (docs/CONTROL.md), and the way any in-process
        supervisor reads live telemetry without tailing files.

        Contract: treat ``rec`` as read-only (the same dict is
        serialised to disk afterwards), return fast (the callback runs
        between samples), and raise nothing you care about — a
        subscriber exception is recorded on ``sub_error`` and swallowed
        so one bad consumer cannot kill everyone's telemetry.
        ``sample()`` itself stays a pure read; only the thread-owned
        ``_write_sample`` fans out to subscribers."""
        self._subs.append(fn)

    def start(self):
        self._thread.start()

    def stop(self, timeout: float = 5.0):
        """Request shutdown and wait for the final flush sample."""
        self._stop.set()
        self._thread.join(timeout=timeout)

    def _run(self):
        f = None
        if self.df.trace_dir:
            os.makedirs(self.df.trace_dir, exist_ok=True)
            self._path = os.path.join(self.df.trace_dir, "metrics.jsonl")
            f = open(self._path, "a")
            self._written = os.path.getsize(self._path)
        try:
            while True:
                f = self._write_sample(f)
                if self._stop.wait(self.period):
                    break
            f = self._write_sample(f)   # final: the end-state snapshot
        finally:
            if f is not None:
                f.close()

    def _rotate(self, f):
        """Roll metrics.jsonl -> .1 (older generations shift up, keep-N
        bounded) and return a fresh handle.  Runs on the sampler thread
        between whole lines."""
        f.close()
        last = f"{self._path}.{self.keep}"
        if os.path.exists(last):
            os.remove(last)
        for i in range(self.keep - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i + 1}")
        os.replace(self._path, f"{self._path}.1")
        self._written = 0
        return open(self._path, "a")

    # ------------------------------------------------------------- sampling

    def _node_entry(self, idx: int, node) -> dict:
        inbox = self.df._inboxes.get(id(node))
        node_id = node_stats_name(self.df.name, idx, node.name)
        entry = {
            "node": node.name,
            "id": node_id,
            "depth": int(inbox.depth()) if inbox is not None else 0,
            "hwm": int(getattr(inbox, "hwm", 0)),
            "shed": int(getattr(inbox, "shed", 0)),
            "quarantined": 0,
        }
        stats = node.stats
        if stats is not None:
            entry["quarantined"] = int(stats.counters.get("quarantined", 0))
            entry["rcv_batches"] = stats.rcv_batches
            entry["rcv_tuples"] = stats.rcv_tuples
            entry["ewma_service_us_per_batch"] = round(stats.ewma_ts_us, 3)
            entry["avg_service_us_per_batch"] = round(stats.avg_ts_us, 3)
        tracer = getattr(self.df, "tracer", None)
        if tracer is not None:
            # span-tracing latency sensors (obs/trace.py): per-node
            # queue-wait/service p50/p95/p99 (µs) read off the tracer's
            # fixed-bucket histograms — the fields ControlPolicy rules
            # threshold on (Rescale(up_q95_us=), docs/CONTROL.md).
            # Absent until the node saw a traced batch, so consumers of
            # pre-trace metrics.jsonl lines see no new keys.
            lat = tracer.latency_snapshot(node_id)
            if lat:
                entry.update(lat)
        return entry

    def sample(self) -> dict:
        """One observation of the whole graph (the metrics.jsonl line,
        pre-serialisation) — a pure read, safe to call synchronously
        (wf_top --expo, tests) while the background thread runs; only
        the thread-owned ``_write_sample`` advances seq and emits shed
        events."""
        df = self.df
        nodes = []
        for idx, node in enumerate(df.nodes):
            try:
                nodes.append(self._node_entry(idx, node))
            except Exception:   # noqa: BLE001 — torn read during a node's
                continue        # dict resize: skip it for this sample
        rec = {
            "t": time.time(),
            "seq": self.seq,
            "dataflow": df.name,
            "nodes": nodes,
            "dead_letters": len(df.dead_letters),
        }
        if df.metrics is not None:
            rec.update(df.metrics.snapshot())
        return rec

    def _emit_shed_events(self, nodes):
        """Transition-based shed events: one per node per period at most
        (per-item events would melt the log under sustained overload),
        carrying the delta since the last sample."""
        ev = self.df.events
        if ev is None:
            return
        for n in nodes:
            prev = self._last_shed.get(n["id"], 0)
            if n["shed"] > prev:
                ev.emit("shed", dataflow=self.df.name, node=n["node"],
                        n=n["shed"] - prev, total=n["shed"])
            self._last_shed[n["id"]] = n["shed"]

    def _write_sample(self, f):
        rec = self.sample()
        self.seq += 1
        self._emit_shed_events(rec["nodes"])
        for fn in self._subs:
            try:
                fn(rec)
            except Exception as e:  # noqa: BLE001 — see subscribe()
                first = self.sub_error is None
                self.sub_error = e
                # a silently-dead subscriber (e.g. the control plane's
                # controller) must still be observable: count every
                # failure, warn once on the first
                m = self.df.metrics
                if m is not None:
                    m.counter("sampler_subscriber_errors").inc()
                if first:
                    import warnings
                    warnings.warn(
                        f"sampler subscriber {getattr(fn, '__qualname__', fn)!r} "
                        f"raised {type(e).__name__}: {e} (further "
                        f"failures only count sampler_subscriber_errors)",
                        stacklevel=2)
        if f is not None:
            line = json.dumps(rec) + "\n"
            if (self.max_bytes is not None and self._written
                    and self._written + len(line) > self.max_bytes):
                f = self._rotate(f)
            f.write(line)
            f.flush()
            self._written += len(line)
        return f
