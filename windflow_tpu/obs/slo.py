"""SLO objectives with multi-window burn-rate alerting over local and
federated telemetry (docs/OBSERVABILITY.md "Federation & SLOs").

An :class:`SloObjective` names a *signal* (a key of the view dict the
caller assembles from sampler records or the federated plane view), a
*bad* condition on it (``bad_above=`` / ``bad_below=``), and an error
*budget* — the fraction of observations allowed to be bad.  The
:class:`SloEvaluator` keeps one observation ring per objective and
computes the classic SRE pair of burn rates,

    burn(window) = bad_fraction(window) / budget

over a FAST window (catches a cliff within seconds) and a SLOW window
(filters blips: a single bad sample in a quiet hour must not page).
The objective *burns* only while BOTH windows exceed
``burn_threshold`` — the standard multi-window guard against flapping.

On every observation the evaluator writes ``slo_burn_fast{objective=}``
/ ``slo_burn_slow{objective=}`` gauges plus the scalar
``slo_burn_max`` (the control plane's rule signal,
``Rescale(up_slo_burn=)``, docs/CONTROL.md) into the attached registry,
and emits one ``slo_burn`` event per state *transition* (``state:
"burn"`` / ``"ok"``) — never per observation, the same
transitions-only discipline the sampler's shed events follow.

Knob contract (ISSUE 19): this module is only ever imported by
``obs/federation.py`` under a set ``federate=`` knob — unset, it is
never imported.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class SloObjective:
    """One objective over one signal (see module docstring).  Exactly
    one of ``bad_above`` / ``bad_below`` defines the bad condition
    (latency-style signals burn high, availability-style signals burn
    low)."""

    __slots__ = ("name", "signal", "bad_above", "bad_below", "budget",
                 "fast_window", "slow_window", "burn_threshold")

    def __init__(self, name: str, signal: str, bad_above: float = None,
                 bad_below: float = None, budget: float = 0.05,
                 fast_window: float = 30.0, slow_window: float = 300.0,
                 burn_threshold: float = 1.0):
        if not name or not str(name).strip():
            raise ValueError("SloObjective needs a non-empty name")
        if (bad_above is None) == (bad_below is None):
            raise ValueError(
                f"SloObjective {name!r}: set exactly one of bad_above= / "
                f"bad_below= (the bad condition must have one direction)")
        if not 0.0 < float(budget) < 1.0:
            raise ValueError(
                f"SloObjective {name!r}: budget must be a fraction in "
                f"(0, 1), got {budget}")
        if float(fast_window) <= 0:
            raise ValueError(
                f"SloObjective {name!r}: fast_window must be positive")
        if float(slow_window) <= float(fast_window):
            raise ValueError(
                f"SloObjective {name!r}: slow_window must exceed "
                f"fast_window (multi-window burn needs two scales)")
        if float(burn_threshold) <= 0:
            raise ValueError(
                f"SloObjective {name!r}: burn_threshold must be positive")
        self.name = str(name)
        self.signal = str(signal)
        self.bad_above = None if bad_above is None else float(bad_above)
        self.bad_below = None if bad_below is None else float(bad_below)
        self.budget = float(budget)
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.burn_threshold = float(burn_threshold)

    def bad(self, value: float) -> bool:
        if self.bad_above is not None:
            return float(value) > self.bad_above
        return float(value) < self.bad_below

    def __repr__(self):
        cond = (f"> {self.bad_above}" if self.bad_above is not None
                else f"< {self.bad_below}")
        return (f"SloObjective({self.name!r}, {self.signal!r} {cond}, "
                f"budget={self.budget}, windows={self.fast_window}/"
                f"{self.slow_window}s)")


class SloPolicy:
    """The set of objectives one plane (or one process) promises."""

    __slots__ = ("objectives",)

    def __init__(self, objectives):
        objectives = list(objectives)
        if not objectives:
            raise ValueError("SloPolicy needs at least one objective")
        names = set()
        for o in objectives:
            if not isinstance(o, SloObjective):
                raise TypeError(f"SloPolicy objectives must be "
                                f"SloObjective, got {o!r}")
            if o.name in names:
                raise ValueError(f"duplicate SloObjective name {o.name!r}")
            names.add(o.name)
        self.objectives = objectives

    def __repr__(self):
        return f"SloPolicy({[o.name for o in self.objectives]})"


class _Ring:
    """Per-objective observation ring: (t, bad) pairs pruned to the slow
    window; burn rates are bad-fractions over each window divided by the
    budget."""

    __slots__ = ("obj", "obs")

    def __init__(self, obj: SloObjective):
        self.obj = obj
        self.obs = deque()

    def observe(self, now: float, bad: bool):
        self.obs.append((now, bool(bad)))
        horizon = now - self.obj.slow_window
        while self.obs and self.obs[0][0] < horizon:
            self.obs.popleft()
        return (self._burn(now, self.obj.fast_window),
                self._burn(now, self.obj.slow_window))

    def _burn(self, now: float, window: float) -> float:
        lo = now - window
        total = n_bad = 0
        for t, bad in reversed(self.obs):
            if t < lo:
                break
            total += 1
            n_bad += bad
        if total == 0:
            return 0.0
        return (n_bad / total) / self.obj.budget


class SloEvaluator:
    """Feed views in, get burning objectives out (see module
    docstring).  ``observe()`` is called from a single driver thread
    (the sampler's subscriber fan-out, or the aggregator's poll
    thread); ``burning()`` may be read from anywhere."""

    def __init__(self, policy: SloPolicy, metrics=None, events=None,
                 scope: str = "local"):
        if not isinstance(policy, SloPolicy):
            raise TypeError(f"SloEvaluator needs an SloPolicy, "
                            f"got {policy!r}")
        self.policy = policy
        self.scope = str(scope)
        self._metrics = metrics
        self._events = events
        self._rings = {o.name: _Ring(o) for o in policy.objectives}
        self._burning: set[str] = set()
        self._mu = threading.Lock()

    def burning(self) -> list:
        """Names of currently-burning objectives, sorted."""
        with self._mu:
            return sorted(self._burning)

    def observe(self, view: dict, now: float = None) -> list:
        """One evaluation pass over ``view`` (signal name -> value).
        Objectives whose signal is absent from the view are skipped —
        a local evaluator simply never sees plane-scope signals like
        ``availability``.  Returns the burning objective names."""
        if now is None:
            now = time.monotonic()
        burn_max = 0.0
        for obj in self.policy.objectives:
            value = view.get(obj.signal)
            if value is None:
                continue
            fast, slow = self._rings[obj.name].observe(now, obj.bad(value))
            burn_max = max(burn_max, min(fast, slow))
            self._gauge(f'slo_burn_fast{{objective="{obj.name}"}}', fast)
            self._gauge(f'slo_burn_slow{{objective="{obj.name}"}}', slow)
            burns = (fast >= obj.burn_threshold
                     and slow >= obj.burn_threshold)
            with self._mu:
                was = obj.name in self._burning
                if burns:
                    self._burning.add(obj.name)
                else:
                    self._burning.discard(obj.name)
            if burns and not was:
                self._event("slo_burn", objective=obj.name,
                            state="burn", signal=obj.signal,
                            value=round(float(value), 6),
                            burn_fast=round(fast, 3),
                            burn_slow=round(slow, 3),
                            threshold=obj.burn_threshold)
            elif was and not burns:
                self._event("slo_burn", objective=obj.name, state="ok",
                            signal=obj.signal,
                            value=round(float(value), 6),
                            burn_fast=round(fast, 3),
                            burn_slow=round(slow, 3))
        self._gauge("slo_burn_max", burn_max)
        return self.burning()

    # -------------------------------------------------------------- plumbing

    def _gauge(self, name: str, v: float):
        if self._metrics is not None:
            self._metrics.gauge(name).set(round(float(v), 6))

    def _event(self, kind: str, **fields):
        if self._events is not None:
            self._events.emit(kind, scope=self.scope, **fields)


def local_view(rec: dict, prev: dict = None) -> dict:
    """Assemble the local-process signal view from one sampler record
    (and optionally the previous one, for rate signals):

    * ``q95_us`` — worst per-node queue-wait p95 (µs; needs ``trace=``)
    * ``svc95_us`` — worst per-node service p95 (µs; needs ``trace=``)
    * ``depth`` — deepest inbox
    * ``shed_rate`` / ``quarantine_rate`` — items per second since the
      previous record (0.0 on the first)
    * ``dead_letters`` — current dead-letter count
    """
    nodes = rec.get("nodes", [])
    view = {
        "q95_us": max((n.get("q_p95_us", 0.0) for n in nodes),
                      default=0.0),
        "svc95_us": max((n.get("svc_p95_us", 0.0) for n in nodes),
                        default=0.0),
        "depth": max((n.get("depth", 0) for n in nodes), default=0),
        "shed_rate": 0.0,
        "quarantine_rate": 0.0,
        "dead_letters": rec.get("dead_letters", 0),
    }
    if prev is not None:
        dt = rec.get("t", 0.0) - prev.get("t", 0.0)
        if dt > 0:
            for key, field in (("shed_rate", "shed"),
                               ("quarantine_rate", "quarantined")):
                cur = sum(n.get(field, 0) for n in nodes)
                old = sum(n.get(field, 0)
                          for n in prev.get("nodes", []))
                view[key] = max(0.0, (cur - old) / dt)
    return view
