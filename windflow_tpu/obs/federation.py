"""Telemetry federation over the row plane, and the crash black-box
(docs/OBSERVABILITY.md "Federation & SLOs").

PRs 16–18 made this reproduction a multi-host plane; the obs layer
still only saw one process.  This module closes that gap with three
pieces riding infrastructure that already exists:

* :class:`FederationShipper` — subscribes to the process's
  :class:`~windflow_tpu.obs.sampler.Sampler` (the same sensor bus the
  control plane rides) and periodically ships a compact snapshot —
  sampler sample, cumulative registry counters/gauges, event-ring tail
  — over the plane's existing :class:`~windflow_tpu.parallel.channel.
  RowSender` links as ``-8`` TELEMETRY control frames.  Not journaled:
  the next snapshot supersedes a lost one.
* :class:`TelemetryAggregator` — the receiving side
  (``RowReceiver(telemetry_sink=...)``): merges per-host snapshot
  rings into host-labelled metric families
  (``obs/expo.py`` renders them: ``wf_fed_*{host="w1"}``), marks a
  peer *stale* when its snapshots stop arriving, spools a stale/dead
  peer's last snapshots to disk (the black box survives the host),
  and optionally evaluates plane-scope SLOs
  (:mod:`~windflow_tpu.obs.slo`) over the federated view.
* :class:`BlackBox` — the flight recorder: on node_error, recovery
  give-up, or plane death declaration, dumps the bounded in-memory
  rings (event ring, ``tracer.recent`` spans, the shipper's last K
  samples) to ``<trace_dir>/blackbox-<node>-<ts>.json`` —
  ``scripts/wf_blackbox.py`` renders the post-mortem timeline.

Knob contract (ISSUE 19, same as ``trace=``/``control=``): the
``federate=`` knob unset ⇒ this module (and :mod:`obs.slo`) is never
imported, no ``-8`` frame is ever sent, and the wire stays
byte-identical to the seed.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from .slo import SloEvaluator, SloPolicy, local_view

#: snapshot schema version (the ``"v"`` field); an aggregator refuses
#: snapshots from a version-skewed peer loudly, like the portable spool
SNAP_VERSION = 1


def _safe_host(host) -> str:
    """Filesystem- and label-safe host id (the spool filename and the
    ``host=`` label value)."""
    s = str(host)
    return "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in s) or "_"


class FederationPolicy:
    """Knobs of the federation tier (the ``federate=`` value).

    ``host`` labels this process's series in the federated view
    (default: the owning dataflow's name).  ``period`` is the ship
    cadence in seconds — snapshots also ride the sampler, so the
    effective cadence is ``max(period, sample_period)``.  ``keep``
    bounds the in-memory sample ring (the black box's K), and
    ``event_tail`` how many recent events each snapshot carries.
    ``stale_after`` (default ``3 * period``) is the aggregator's
    staleness deadline; ``slo`` an optional :class:`~windflow_tpu.obs.
    slo.SloPolicy` evaluated locally by the shipper and plane-wide by
    the aggregator.  ``blackbox`` enables the crash flight recorder
    (on by default — it costs nothing until a dump trigger fires)."""

    __slots__ = ("host", "period", "keep", "event_tail", "stale_after",
                 "slo", "blackbox")

    def __init__(self, host: str = None, period: float = 1.0,
                 keep: int = 8, event_tail: int = 64,
                 stale_after: float = None, slo=None,
                 blackbox: bool = True):
        if float(period) <= 0:
            raise ValueError("FederationPolicy: period must be positive "
                             "seconds")
        if int(keep) < 1:
            raise ValueError("FederationPolicy: keep must retain at "
                             "least 1 snapshot")
        if int(event_tail) < 0:
            raise ValueError("FederationPolicy: event_tail must be >= 0")
        if slo is not None and not isinstance(slo, SloPolicy):
            raise TypeError(f"FederationPolicy: slo= must be an "
                            f"SloPolicy, got {slo!r}")
        self.host = None if host is None else str(host)
        self.period = float(period)
        self.keep = int(keep)
        self.event_tail = int(event_tail)
        self.stale_after = (3.0 * self.period if stale_after is None
                            else float(stale_after))
        if self.stale_after <= 0:
            raise ValueError("FederationPolicy: stale_after must be "
                             "positive seconds")
        self.slo = slo
        self.blackbox = bool(blackbox)

    def agrees_with(self, other: "FederationPolicy") -> bool:
        """Knob-level equality, for ``union_multipipes`` conflict
        detection (one process runs one shipper)."""
        return (self.host == other.host
                and self.period == other.period
                and self.keep == other.keep
                and self.event_tail == other.event_tail
                and self.stale_after == other.stale_after
                and self.blackbox == other.blackbox
                and self.slo is other.slo)

    def __repr__(self):
        return (f"FederationPolicy(host={self.host!r}, "
                f"period={self.period}, keep={self.keep}, "
                f"slo={self.slo!r})")


def as_policy(value) -> FederationPolicy:
    """Normalise the ``federate=`` knob: ``True`` = defaults, an
    instance passes through.  (Falsy never reaches here — the engine's
    lazy import is the off switch.)"""
    if value is True:
        return FederationPolicy()
    if isinstance(value, FederationPolicy):
        return value
    raise TypeError(f"federate= must be True or a FederationPolicy, "
                    f"got {value!r}")


class FederationShipper:
    """Per-process sender side (see module docstring).  Created by the
    engine under ``federate=``; the application binds the plane's
    senders with :meth:`bind` once the row plane is open — unbound, the
    shipper still feeds the local sample ring (the black box's source)
    and the local SLO evaluator, it just ships nothing."""

    def __init__(self, policy: FederationPolicy, host: str,
                 dataflow_name: str = "", metrics=None, events=None):
        self.policy = policy
        self.host = _safe_host(host)
        self.dataflow_name = str(dataflow_name)
        self._metrics = metrics
        self._events = events
        #: bounded ring of the last K raw sampler records — the black
        #: box's "last K sampler snapshots"
        self.recent = deque(maxlen=policy.keep)
        self._senders: dict = {}
        self._last_ship = 0.0
        self._prev_rec = None
        self.slo = (SloEvaluator(policy.slo, metrics=metrics,
                                 events=events, scope=self.host)
                    if policy.slo is not None else None)

    def bind(self, senders: dict) -> "FederationShipper":
        """Point the shipper at the plane: ``senders`` maps peer pid ->
        :class:`~windflow_tpu.parallel.channel.RowSender` (the dict
        ``open_row_plane`` returns).  May be re-bound after a plane
        reopen."""
        self._senders = dict(senders)
        return self

    # ------------------------------------------------------------- sampling

    def on_sample(self, rec: dict):
        """Sampler subscriber (``Sampler.subscribe``): ring the sample,
        evaluate local SLOs, ship when the period elapsed.  Runs on the
        sampler thread; per-peer wire failures are swallowed (the next
        period re-ships), exactly like ``PlaneSupervisor.replicate``."""
        self.recent.append(rec)
        if self.slo is not None:
            self.slo.observe(local_view(rec, self._prev_rec))
        self._prev_rec = rec
        now = time.monotonic()
        if self._senders and now - self._last_ship >= self.policy.period:
            self._last_ship = now
            self.ship(rec)

    def snapshot(self, rec: dict = None) -> dict:
        """The compact wire snapshot (docs/OBSERVABILITY.md schema)."""
        if rec is None:
            rec = self.recent[-1] if self.recent else {}
        nodes = [{k: n[k] for k in ("node", "depth", "shed",
                                    "quarantined", "rcv_tuples",
                                    "q_p95_us", "svc_p95_us") if k in n}
                 for n in rec.get("nodes", [])]
        snap = {
            "v": SNAP_VERSION,
            "host": self.host,
            "t": rec.get("t", time.time()),
            "seq": rec.get("seq", 0),
            "dataflow": rec.get("dataflow", self.dataflow_name),
            "nodes": nodes,
            "dead_letters": rec.get("dead_letters", 0),
            # cumulative, not deltas: idempotent under snapshot loss
            # (the aggregator rates them against its own arrival clock)
            "counters": dict(rec.get("counters", {})),
            "gauges": dict(rec.get("gauges", {})),
        }
        if self._events is not None and self.policy.event_tail:
            snap["events"] = list(self._events.recent)[
                -self.policy.event_tail:]
        return snap

    def ship(self, rec: dict = None) -> int:
        """Ship one snapshot to every bound peer; returns how many
        peers took it."""
        snap = self.snapshot(rec)
        shipped = 0
        for pid in sorted(self._senders):
            snd = self._senders[pid]
            if (getattr(snd, "_link_down", False)
                    or getattr(snd, "_hb_error", None) is not None):
                # a down link must not stall the sampler thread for a
                # resume cycle: skip now, the next period re-ships
                continue
            try:
                snd.send_telemetry(snap)
                shipped += 1
            except (OSError, ValueError):
                continue
        if self._metrics is not None and shipped:
            self._metrics.counter("fed_snapshots_shipped").inc(shipped)
        return shipped


class BlackBox:
    """Crash flight recorder (see module docstring).  ``dump()`` writes
    everything the bounded in-memory rings know — cheap enough to call
    from failure paths, bounded by ``max_dumps`` so a crash-looping
    node cannot fill the disk."""

    def __init__(self, trace_dir: str, node: str, events=None,
                 tracer=None, shipper: FederationShipper = None,
                 max_dumps: int = 8):
        self.trace_dir = trace_dir
        self.node = _safe_host(node)
        self._events = events
        self._tracer = tracer
        self._shipper = shipper
        self._max_dumps = int(max_dumps)
        self._dumps = 0
        self._mu = threading.Lock()

    def dump(self, reason: str, **fields):
        """Write one black-box file; returns its path (None without a
        ``trace_dir`` or past the dump budget).  Never raises — a
        flight recorder that crashes the crash path is worse than
        none."""
        if not self.trace_dir:
            return None
        with self._mu:
            if self._dumps >= self._max_dumps:
                return None
            self._dumps += 1
        try:
            doc = {
                "v": SNAP_VERSION,
                "node": self.node,
                "t": time.time(),
                "reason": str(reason),
                **fields,
                "events": (list(self._events.recent)
                           if self._events is not None else []),
                "spans": (list(self._tracer.recent)
                          if self._tracer is not None else []),
                "samples": (list(self._shipper.recent)
                            if self._shipper is not None else []),
            }
            os.makedirs(self.trace_dir, exist_ok=True)
            ts = int(time.time() * 1000)
            path = os.path.join(self.trace_dir,
                                f"blackbox-{self.node}-{ts}.json")
            while os.path.exists(path):   # two dumps in the same ms
                ts += 1
                path = os.path.join(self.trace_dir,
                                    f"blackbox-{self.node}-{ts}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            if self._events is not None:
                self._events.emit("blackbox", node=self.node,
                                  reason=str(reason), path=path)
            return path
        except Exception:  # noqa: BLE001 — see docstring
            return None


class TelemetryAggregator:
    """Receiving side of the federation (see module docstring).  Pass
    it as ``telemetry_sink=`` to the plane's receiver; ``accept()``
    runs inline on the wire read threads and is thread-safe.  Staleness
    marking and plane-scope SLO evaluation run on :meth:`poll` — call
    it from your own loop, or :meth:`start` the built-in one."""

    def __init__(self, policy: FederationPolicy = None, metrics=None,
                 events=None, spool_dir: str = None,
                 state_path: str = None):
        self.policy = policy if policy is not None else FederationPolicy()
        self._metrics = metrics
        self._events = events
        self.spool_dir = spool_dir
        #: when set, every poll() atomically rewrites this JSON file
        #: with the cluster state — the out-of-process surface
        #: ``scripts/wf_top.py --plane`` renders
        self.state_path = state_path
        self._mu = threading.Lock()
        self._rings: dict[str, deque] = {}
        self._arrival: dict[str, float] = {}
        self._stale: set[str] = set()
        self._spooled: set[str] = set()
        self.slo = (SloEvaluator(self.policy.slo, metrics=metrics,
                                 events=events, scope="plane")
                    if self.policy.slo is not None else None)
        self._stop = threading.Event()
        self._thread = None

    # --------------------------------------------------------------- ingest

    def accept(self, snap: dict):
        """The ``telemetry_sink`` contract (wire ``-8`` family).  A
        version-skewed or malformed snapshot is REFUSED loudly (the
        read loop surfaces it like a torn frame), mirroring the
        portable spool's skew refusal."""
        if not isinstance(snap, dict) or snap.get("v") != SNAP_VERSION:
            raise ValueError(
                f"refusing telemetry snapshot with version "
                f"{snap.get('v') if isinstance(snap, dict) else snap!r} "
                f"(this aggregator speaks v{SNAP_VERSION})")
        host = _safe_host(snap.get("host", ""))
        if not snap.get("host"):
            raise ValueError("telemetry snapshot carries no host label")
        now = time.monotonic()
        with self._mu:
            ring = self._rings.get(host)
            if ring is None:
                ring = self._rings[host] = deque(maxlen=self.policy.keep)
            ring.append(snap)
            self._arrival[host] = now
            was_stale = host in self._stale
            self._stale.discard(host)
            if was_stale:
                self._spooled.discard(host)
            n_hosts = len(self._rings)
        if self._metrics is not None:
            self._metrics.counter("fed_snapshots").inc()
            self._metrics.gauge("fed_hosts").set(n_hosts)
        if was_stale:
            self._event("fed_peer", host=host, state="fresh")

    # ------------------------------------------------------------ staleness

    def poll(self, now: float = None):
        """One staleness + SLO pass; returns currently-stale hosts."""
        if now is None:
            now = time.monotonic()
        newly_stale = []
        with self._mu:
            for host, seen in self._arrival.items():
                if (now - seen > self.policy.stale_after
                        and host not in self._stale):
                    self._stale.add(host)
                    newly_stale.append((host, now - seen))
        for host, age in newly_stale:
            self._event("fed_peer", host=host, state="stale",
                        age=round(age, 3))
            # the dead peer's last snapshots must survive it: spool
            # them beside our own black boxes
            self.spool_host(host, reason="stale")
        if self.slo is not None:
            self.slo.observe(self.view(now=now), now=now)
        if self.state_path:
            self.write_state(now=now)
        with self._mu:
            return sorted(self._stale)

    def start(self, period: float = None) -> "TelemetryAggregator":
        """Run :meth:`poll` on a daemon thread every ``period`` seconds
        (default: the policy's ship period)."""
        period = self.policy.period if period is None else float(period)

        def _loop():
            while not self._stop.wait(period):
                self.poll()

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="wf-fed-aggregator")
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -------------------------------------------------------------- reading

    def hosts(self, now: float = None) -> dict:
        """Per-host freshness: host -> {"fresh", "age", "seq", "t"}."""
        if now is None:
            now = time.monotonic()
        out = {}
        with self._mu:
            for host, ring in self._rings.items():
                last = ring[-1]
                out[host] = {
                    "fresh": host not in self._stale,
                    "age": round(now - self._arrival[host], 3),
                    "seq": last.get("seq", 0),
                    "t": last.get("t", 0.0),
                    "dataflow": last.get("dataflow", ""),
                }
        return out

    def latest(self, host) -> dict:
        """Newest snapshot of ``host`` (None if never seen)."""
        with self._mu:
            ring = self._rings.get(_safe_host(host))
            return ring[-1] if ring else None

    def snapshots(self, host) -> list:
        """The retained snapshot ring of ``host``, oldest first."""
        with self._mu:
            return list(self._rings.get(_safe_host(host), ()))

    def view(self, now: float = None) -> dict:
        """The plane-scope SLO signal view over the federated state:

        * ``availability`` — fraction of known hosts still fresh
        * ``q95_us`` — worst queue-wait p95 across all fresh hosts
        * ``shed_rate`` — summed per-host shed deltas per second
        * ``stale_seconds`` — age of the stalest host's last snapshot
        """
        if now is None:
            now = time.monotonic()
        with self._mu:
            hosts = list(self._rings)
            fresh = [h for h in hosts if h not in self._stale]
            rings = {h: list(self._rings[h]) for h in hosts}
            ages = [now - self._arrival[h] for h in hosts]
        view = {
            "availability": (len(fresh) / len(hosts)) if hosts else 1.0,
            "q95_us": 0.0,
            "shed_rate": 0.0,
            "stale_seconds": max(ages, default=0.0),
        }
        for h in fresh:
            ring = rings[h]
            last = ring[-1]
            view["q95_us"] = max(
                view["q95_us"],
                max((n.get("q_p95_us", 0.0) for n in last.get("nodes", [])),
                    default=0.0))
            if len(ring) >= 2:
                prev = ring[-2]
                dt = last.get("t", 0.0) - prev.get("t", 0.0)
                if dt > 0:
                    cur = sum(n.get("shed", 0)
                              for n in last.get("nodes", []))
                    old = sum(n.get("shed", 0)
                              for n in prev.get("nodes", []))
                    view["shed_rate"] += max(0.0, (cur - old) / dt)
        return view

    def federated(self, now: float = None) -> dict:
        """The merged host-labelled registry snapshot — feed it to
        ``obs.expo.render_registry`` (each embedded-label name renders
        as one series of its family; ``fed_fresh{host=}`` marks
        staleness, 1 fresh / 0 stale)."""
        if now is None:
            now = time.monotonic()
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._mu:
            items = [(h, self._rings[h][-1], h not in self._stale,
                      now - self._arrival[h])
                     for h in sorted(self._rings)]
        for host, snap, fresh, age in items:
            lab = f'host="{host}"'
            out["gauges"][f"fed_fresh{{{lab}}}"] = 1 if fresh else 0
            out["gauges"][f"fed_age_seconds{{{lab}}}"] = round(age, 3)
            out["gauges"][f"fed_dead_letters{{{lab}}}"] = snap.get(
                "dead_letters", 0)
            for name, v in snap.get("counters", {}).items():
                out["counters"][self._label(name, lab)] = v
            for name, v in snap.get("gauges", {}).items():
                out["gauges"][self._label(name, lab)] = v
            for n in snap.get("nodes", []):
                nlab = f'{lab},node="{n.get("node", "")}"'
                for key, metric in (("depth", "fed_node_depth"),
                                    ("q_p95_us", "fed_node_q_p95_us"),
                                    ("svc_p95_us", "fed_node_svc_p95_us")):
                    if key in n:
                        out["gauges"][f"{metric}{{{nlab}}}"] = n[key]
        return out

    @staticmethod
    def _label(name: str, lab: str) -> str:
        """Append the host label to a registry name that may already
        embed labels (``a{x="1"}`` -> ``a{x="1",host="w1"}``)."""
        if name.endswith("}") and "{" in name:
            return f"{name[:-1]},{lab}}}"
        return f"{name}{{{lab}}}"

    def render(self) -> str:
        """Federated Prometheus text exposition."""
        from . import expo
        return expo.render_registry(self.federated())

    def state(self, now: float = None) -> dict:
        """The cluster-state document ``wf_top --plane`` renders: per-
        host freshness + latest snapshot, the SLO signal view, and which
        objectives are burning."""
        if now is None:
            now = time.monotonic()
        doc = {
            "v": SNAP_VERSION,
            "t": time.time(),
            "hosts": self.hosts(now=now),
            "latest": {h: self.latest(h) for h in self.hosts(now=now)},
            "view": self.view(now=now),
            "slo_burning": (self.slo.burning()
                            if self.slo is not None else []),
        }
        if self._metrics is not None:
            doc["slo_gauges"] = {
                k: v for k, v in
                self._metrics.snapshot().get("gauges", {}).items()
                if k.startswith("slo_")}
        return doc

    def write_state(self, now: float = None):
        """Atomically rewrite :attr:`state_path` (never raises — a
        status file must not fail a poll)."""
        if not self.state_path:
            return None
        try:
            doc = self.state(now=now)
            tmp = self.state_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.state_path)
            return self.state_path
        except Exception:  # noqa: BLE001 — like spool_host
            return None

    # ------------------------------------------------------------ black box

    def spool_host(self, host, reason: str):
        """Write ``host``'s retained snapshots to
        ``<spool_dir>/blackbox-<host>-<ts>.json`` — the surviving half
        of the dead peer's black box.  Idempotent per staleness episode;
        returns the path (None without a spool_dir or unknown host)."""
        host = _safe_host(host)
        if self.spool_dir is None:
            return None
        with self._mu:
            ring = list(self._rings.get(host, ()))
            if not ring or host in self._spooled:
                return None
            self._spooled.add(host)
        try:
            doc = {"v": SNAP_VERSION, "host": host, "t": time.time(),
                   "reason": str(reason), "samples": ring,
                   "events": ring[-1].get("events", [])}
            os.makedirs(self.spool_dir, exist_ok=True)
            ts = int(time.time() * 1000)
            path = os.path.join(self.spool_dir,
                                f"blackbox-{host}-{ts}.json")
            while os.path.exists(path):   # two spools in the same ms
                ts += 1
                path = os.path.join(self.spool_dir,
                                    f"blackbox-{host}-{ts}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            if self._metrics is not None:
                self._metrics.counter("fed_spooled").inc()
            self._event("blackbox", node=host, reason=str(reason),
                        path=path)
            return path
        except Exception:  # noqa: BLE001 — like BlackBox.dump
            return None

    def on_death(self, pid, down_for: float = None):
        """Adapter for ``PlaneSupervisor(on_death=...)``: spool every
        host whose snapshots already stopped, plus any host label that
        matches the dead pid by convention (``"<pid>"``)."""
        self.spool_host(str(pid), reason="plane_death")
        for host in self.poll():
            self.spool_host(host, reason="plane_death")

    # -------------------------------------------------------------- plumbing

    def _event(self, kind: str, **fields):
        if self._events is not None:
            self._events.emit(kind, **fields)
