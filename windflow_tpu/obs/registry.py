"""Live metrics registry — counters, gauges, and fixed-bucket histograms
shared by the engine, the wire channels, and user code.

The reference has no runtime metrics at all: its only instrumentation is
the compile-time ``-DLOG_DIR`` counter dump at ``svc_end``
(map.hpp:85-176), reproduced by ``utils/tracing.py``.  This registry is
the *live* half of the observability layer (docs/OBSERVABILITY.md): a
process-wide or per-dataflow bag of named metrics that the background
sampler (obs/sampler.py) snapshots into ``metrics.jsonl`` and the text
exposition (obs/expo.py) renders Prometheus-style.

Contract (same as ``OverloadPolicy``): **knobs unset ⇒ seed-identical
behavior**.  Nothing in the runtime holds a registry unless one was
configured (``metrics=`` / ``sample_period=``), and every hot-path hook
is a single ``is not None`` branch on the consumer side.  The metric
objects themselves are cheap: one lock-guarded add per update (these are
per-batch / per-frame events, not per-row).
"""

from __future__ import annotations

import threading
from bisect import bisect_left

#: default histogram bucket upper bounds, in seconds — spanning the
#: sub-millisecond inbox hops to multi-second stalls the runtime sees
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: bucket bounds for the span tracer's queue-wait/service histograms
#: (obs/trace.py): finer at the bottom — inbox hops are routinely tens
#: of microseconds, and a p95 read off DEFAULT_BUCKETS would round every
#: healthy hop up to 0.5 ms
LATENCY_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                   0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


class Counter:
    """Monotonically increasing count (events, bytes, frames)."""

    __slots__ = ("name", "_v", "_mu")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._mu = threading.Lock()

    def inc(self, n: int = 1):
        with self._mu:
            self._v += n

    @property
    def value(self):
        return self._v


class Gauge:
    """Last-write-wins instantaneous value (queue depth, connections)."""

    __slots__ = ("name", "_v", "_mu")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._mu = threading.Lock()

    def set(self, v: float):
        self._v = v  # single store: atomic under the GIL

    def inc(self, n: float = 1.0):
        with self._mu:
            self._v += n

    def dec(self, n: float = 1.0):
        with self._mu:
            self._v -= n

    @property
    def value(self):
        return self._v


class Histogram:
    """Fixed-bucket latency/size histogram: cumulative bucket counts in
    the Prometheus style (each bucket counts observations ``<= bound``,
    with an implicit ``+Inf`` bucket equal to ``count``)."""

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_mu")

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._counts = [0] * len(self.bounds)
        self._sum = 0.0
        self._count = 0
        self._mu = threading.Lock()

    def observe(self, v: float):
        i = bisect_left(self.bounds, v)
        with self._mu:
            if i < len(self._counts):
                self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._mu:
            per_bucket = list(self._counts)
            total, s = self._count, self._sum
        cum = 0
        buckets = {}
        for bound, n in zip(self.bounds, per_bucket):
            cum += n
            buckets[repr(bound)] = cum
        return {"buckets": buckets, "sum": round(s, 9), "count": total}

    @property
    def count(self):
        return self._count

    def quantile(self, q: float):
        """Estimate the q-quantile (0..1) — see
        :func:`quantile_from_snapshot`; None on an empty histogram."""
        return quantile_from_snapshot(self.snapshot(), q)


def quantile_from_snapshot(h: dict, q: float):
    """Estimate the q-quantile (0..1) from a Histogram ``snapshot()``
    dict ({"buckets": {bound: cumulative}, "count": n}) by linear
    interpolation inside the containing bucket — the standard Prometheus
    ``histogram_quantile`` estimate, shared by the sampler's per-node
    latency fields, wf_top's columns, and wf_trace.  Returns None on an
    empty histogram; a quantile landing in the implicit +Inf bucket
    clamps to the top finite bound (the honest answer a bounded
    histogram can give)."""
    total = h.get("count", 0)
    if not total:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in h["buckets"].items():
        b = float(bound)
        if cum >= rank:
            if cum == prev_cum:
                return b
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (b - prev_bound)
        prev_bound, prev_cum = b, cum
    return prev_bound  # +Inf bucket: clamp to the top finite bound


class MetricsRegistry:
    """Get-or-create registry of named metrics.  Names are flat strings
    (``wire_bytes_sent``); creation is locked, updates lock only the one
    metric touched.  ``snapshot()`` returns plain JSON-ready dicts — the
    unit the sampler embeds in every ``metrics.jsonl`` line."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            with self._mu:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, *args)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, Histogram, buckets)

    def snapshot(self) -> dict:
        """{"counters": {name: int}, "gauges": {name: float},
        "histograms": {name: {"buckets", "sum", "count"}}} — stable JSON
        shape (docs/OBSERVABILITY.md schema)."""
        with self._mu:
            items = list(self._metrics.items())
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(items):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.snapshot()
        return out
