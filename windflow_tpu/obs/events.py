"""Structured runtime event log — what *happened* to the graph, not how
fast it ran: node start/stop, per-channel EOS, shed and quarantine,
wire reconnect attempts, heartbeat failures, peer stalls/aborts
(docs/OBSERVABILITY.md lists the full vocabulary).

Events are rare by construction (lifecycle transitions and failures, at
most one shed event per sampler period — never per item), so the log can
afford a JSON line per event.  When a file path is configured the log
appends to ``<trace_dir>/events.jsonl``; it always keeps a bounded
in-memory ring (``recent``) so in-process supervisors and tests can read
the tail without touching the filesystem.  The file is opened lazily on
the first emit — constructing an EventLog (e.g. for a preview graph that
never runs) creates nothing on disk.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

#: the event vocabulary (docs/OBSERVABILITY.md); emitters must use these
EVENT_KINDS = frozenset({
    # engine lifecycle
    "dataflow_start", "dataflow_stop", "node_start", "node_stop",
    "node_error", "eos",
    # overload / robustness (runtime/overload.py)
    "shed", "quarantine",
    # wire (parallel/channel.py)
    "reconnect_attempt", "heartbeat_miss", "peer_stall", "peer_abort",
    # wire resume (docs/ROBUSTNESS.md "Wire resume"): an established
    # edge went down / was re-established with its journal tail replayed
    "wire_down", "wire_resume",
    # recovery (windflow_tpu/recovery/, docs/ROBUSTNESS.md "Recovery")
    "epoch", "checkpoint", "checkpoint_commit", "checkpoint_skip",
    "restore", "node_restart", "recovery_giveup",
    # cross-host recovery (parallel/plane.py, recovery/portable.py,
    # docs/ROBUSTNESS.md "Cross-host recovery"): membership transitions
    # of a supervised plane, successor handoff phases, the drain
    # actuator's quiesce phases, and a checkpoint store skipping a
    # torn/corrupt epoch at latest_complete()
    "membership", "handoff", "drain", "checkpoint_fallback",
    # static analysis (windflow_tpu/check/, docs/CHECKS.md): one event
    # per pre-flight diagnostic when the check= knob runs on an
    # observed graph
    "check",
    # control plane (windflow_tpu/control/, docs/CONTROL.md): one
    # `control` event per controller decision (rescale request, shed
    # tighten/relax, admission rate move), one `rescale` event per
    # completed epoch-barrier migration
    "control", "rescale",
    # span tracing (obs/trace.py): spans discarded past the trace.jsonl
    # max_spans bound — rate-limited, carries the running drop total
    "trace_drop",
    # federation & SLOs (obs/federation.py, obs/slo.py,
    # docs/OBSERVABILITY.md "Federation & SLOs"): burn-rate state
    # transitions (state burn/ok), a peer's federated snapshots going
    # stale/fresh at the aggregator, and black-box dumps (the local
    # flight recorder AND the aggregator's spool of a dead peer)
    "slo_burn", "fed_peer", "blackbox",
})


class EventLog:
    """Thread-safe append-only event sink: bounded memory ring + optional
    JSONL file (one ``{"t": ..., "event": ..., ...}`` object per line,
    flushed per event — events are rare, and a crash must not lose the
    events explaining it)."""

    def __init__(self, path: str = None, keep: int = 512,
                 max_bytes: int = None):
        self.path = path
        self.recent = deque(maxlen=keep)
        #: optional size bound on the file (ISSUE 19): past it the file
        #: rolls to ``<path>.1`` (one rotated generation) and a fresh
        #: file opens.  None (default) = unbounded, the seed behavior.
        #: Rotation happens BETWEEN events, so the per-event flush
        #: contract holds: every emitted event is durable in either the
        #: live file or the rolled one before emit() returns.
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        if self.max_bytes is not None and self.max_bytes < 1:
            raise ValueError("EventLog max_bytes must be positive")
        self._mu = threading.Lock()
        self._f = None
        self._written = 0
        self._closed = False

    def emit(self, event: str, **fields):
        if event not in EVENT_KINDS:
            raise ValueError(f"unknown event kind {event!r} "
                             f"(add it to obs.events.EVENT_KINDS)")
        rec = {"t": time.time(), "event": event, **fields}
        with self._mu:
            self.recent.append(rec)
            # after close() the log drops to ring-only: a straggling wire
            # thread emitting during teardown must not reopen the file
            # (nothing would close it again) or write past dataflow_stop
            if self.path is not None and not self._closed:
                if self._f is None:
                    os.makedirs(os.path.dirname(self.path) or ".",
                                exist_ok=True)
                    self._f = open(self.path, "a")
                    self._written = os.path.getsize(self.path)
                line = json.dumps(rec) + "\n"
                if (self.max_bytes is not None and self._written
                        and self._written + len(line) > self.max_bytes):
                    # roll between events, never mid-line: a reader of
                    # .1 + live always sees whole JSON records
                    self._f.close()
                    os.replace(self.path, self.path + ".1")
                    self._f = open(self.path, "a")
                    self._written = 0
                self._f.write(line)
                self._f.flush()
                self._written += len(line)
        return rec

    def close(self):
        with self._mu:
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
