"""Prometheus-style text exposition of the live metrics.

Two sources render to the same format (text/plain; version=0.0.4):

* :func:`render_registry` — a :class:`~windflow_tpu.obs.registry.
  MetricsRegistry` (or its ``snapshot()`` dict): counters/gauges/
  histograms with flat names, prefixed ``wf_``;
* :func:`render_sample` — one ``metrics.jsonl`` line (the sampler's
  per-node view): per-node gauges labelled ``{dataflow=...,node=...}``
  plus the embedded registry snapshot.

No HTTP server is shipped on purpose: serving one string is trivial in
any deployment (``python -m http.server`` wrappers, a sidecar, or
``scripts/wf_top.py --expo`` for ad-hoc scrapes), while binding ports
from inside the engine would be policy the runtime has no business
setting.
"""

from __future__ import annotations

_PREFIX = "wf"

#: per-node sample fields exposed as labelled gauges: sample key ->
#: (metric suffix, TYPE, HELP)
_NODE_FIELDS = {
    "depth": ("inbox_depth", "gauge", "current inbox occupancy (items)"),
    "hwm": ("inbox_hwm", "gauge", "inbox occupancy high-water mark"),
    "shed": ("shed_total", "counter", "items shed from this inbox"),
    "quarantined": ("quarantined_total", "counter",
                    "poison batches quarantined by this node"),
    "rcv_batches": ("rcv_batches_total", "counter", "batches processed"),
    "rcv_tuples": ("rcv_tuples_total", "counter", "tuples processed"),
    "ewma_service_us_per_batch": ("service_ewma_us", "gauge",
                                  "EWMA service time per batch (us)"),
}


def _esc(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _line(name, labels, value):
    if labels:
        lab = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


def _header(name, mtype, help_text):
    return [f"# HELP {name} {help_text}", f"# TYPE {name} {mtype}"]


def render_registry(registry, prefix: str = _PREFIX) -> str:
    """Expose a MetricsRegistry (or its snapshot dict)."""
    snap = registry if isinstance(registry, dict) else registry.snapshot()
    out = []
    for name, v in snap.get("counters", {}).items():
        mn = f"{prefix}_{name}"
        out += _header(mn, "counter", f"counter {name}")
        out.append(_line(mn, None, v))
    for name, v in snap.get("gauges", {}).items():
        mn = f"{prefix}_{name}"
        out += _header(mn, "gauge", f"gauge {name}")
        out.append(_line(mn, None, v))
    for name, h in snap.get("histograms", {}).items():
        mn = f"{prefix}_{name}"
        out += _header(mn, "histogram", f"histogram {name}")
        for bound, cum in h["buckets"].items():
            out.append(_line(f"{mn}_bucket", {"le": bound}, cum))
        out.append(_line(f"{mn}_bucket", {"le": "+Inf"}, h["count"]))
        out.append(_line(f"{mn}_sum", None, h["sum"]))
        out.append(_line(f"{mn}_count", None, h["count"]))
    return "\n".join(out) + ("\n" if out else "")


def render_sample(sample: dict, prefix: str = _PREFIX) -> str:
    """Expose one sampler line (per-node gauges + embedded registry)."""
    out = []
    df = sample.get("dataflow", "")
    for key, (suffix, mtype, help_text) in _NODE_FIELDS.items():
        mn = f"{prefix}_node_{suffix}"
        lines = []
        for n in sample.get("nodes", []):
            if key in n:
                lines.append(_line(mn, {"dataflow": df, "node": n["node"]},
                                   n[key]))
        if lines:
            out += _header(mn, mtype, help_text)
            out += lines
    mn = f"{prefix}_dead_letters"
    out += _header(mn, "gauge", "quarantined batches in the dead-letter "
                                "queue")
    out.append(_line(mn, {"dataflow": df}, sample.get("dead_letters", 0)))
    reg = {k: sample[k] for k in ("counters", "gauges", "histograms")
           if k in sample}
    if reg:
        txt = render_registry(reg, prefix=prefix)
        if txt:
            out.append(txt.rstrip("\n"))
    return "\n".join(out) + "\n"
