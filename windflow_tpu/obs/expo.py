"""Prometheus-style text exposition of the live metrics.

Two sources render to the same format (text/plain; version=0.0.4):

* :func:`render_registry` — a :class:`~windflow_tpu.obs.registry.
  MetricsRegistry` (or its ``snapshot()`` dict), prefixed ``wf_``.
  Registry names may embed labels in the Prometheus form
  (``trace_service_seconds{node="pipe_03_sink.0"}``, the convention the
  span tracer uses, obs/trace.py): all series of one base name render
  as ONE metric family — a single ``# HELP``/``# TYPE`` pair, each
  series keeping its labels, histogram ``_bucket`` lines merging the
  series labels with ``le`` — which is what the exposition spec
  requires (a family re-declared per series is a scrape error);
* :func:`render_sample` — one ``metrics.jsonl`` line (the sampler's
  per-node view): per-node gauges labelled ``{dataflow=...,node=...}``
  plus the embedded registry snapshot.

No HTTP server is shipped on purpose: serving one string is trivial in
any deployment (``python -m http.server`` wrappers, a sidecar, or
``scripts/wf_top.py --expo`` for ad-hoc scrapes), while binding ports
from inside the engine would be policy the runtime has no business
setting.
"""

from __future__ import annotations

_PREFIX = "wf"

#: per-node sample fields exposed as labelled gauges: sample key ->
#: (metric suffix, TYPE, HELP)
_NODE_FIELDS = {
    "depth": ("inbox_depth", "gauge", "current inbox occupancy (items)"),
    "hwm": ("inbox_hwm", "gauge", "inbox occupancy high-water mark"),
    "shed": ("shed_total", "counter", "items shed from this inbox"),
    "quarantined": ("quarantined_total", "counter",
                    "poison batches quarantined by this node"),
    "rcv_batches": ("rcv_batches_total", "counter", "batches processed"),
    "rcv_tuples": ("rcv_tuples_total", "counter", "tuples processed"),
    "ewma_service_us_per_batch": ("service_ewma_us", "gauge",
                                  "EWMA service time per batch (us)"),
    # span-tracing latency fields (obs/trace.py; present only on traced,
    # observed graphs — absent keys render nothing, so pre-trace samples
    # expose exactly the historical series)
    "q_p50_us": ("queue_wait_p50_us", "gauge",
                 "sampled inbox queue wait p50 (us)"),
    "q_p95_us": ("queue_wait_p95_us", "gauge",
                 "sampled inbox queue wait p95 (us)"),
    "q_p99_us": ("queue_wait_p99_us", "gauge",
                 "sampled inbox queue wait p99 (us)"),
    "svc_p50_us": ("service_p50_us", "gauge",
                   "sampled service time p50 (us)"),
    "svc_p95_us": ("service_p95_us", "gauge",
                   "sampled service time p95 (us)"),
    "svc_p99_us": ("service_p99_us", "gauge",
                   "sampled service time p99 (us)"),
}


def _esc(v) -> str:
    # the three escapes of the Prometheus text format's label values:
    # backslash, double-quote, and line feed (a raw newline would tear
    # the series line in two and fail the scrape)
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _line(name, labels, value):
    if labels:
        lab = ",".join(f'{k}="{_esc(v)}"' for k, v in labels.items())
        return f"{name}{{{lab}}} {value}"
    return f"{name} {value}"


def _header(name, mtype, help_text):
    return [f"# HELP {name} {help_text}", f"# TYPE {name} {mtype}"]


def _family(name: str, prefix: str):
    """Split a registry name into its (prefixed) family name and the
    raw label string: ``a_b{x="1"}`` -> (``wf_a_b``, ``x="1"``).  Names
    already starting with the prefix are kept verbatim (so a metric can
    pin its exposition name exactly)."""
    labels = None
    if name.endswith("}") and "{" in name:
        name, _, labels = name.partition("{")
        labels = labels[:-1]
    if not name.startswith(f"{prefix}_"):
        name = f"{prefix}_{name}"
    return name, labels


def _series(name: str, labels, value, extra: str = None):
    lab = ",".join(p for p in (labels, extra) if p)
    return f"{name}{{{lab}}} {value}" if lab else f"{name} {value}"


def render_registry(registry, prefix: str = _PREFIX) -> str:
    """Expose a MetricsRegistry (or its snapshot dict)."""
    snap = registry if isinstance(registry, dict) else registry.snapshot()
    out = []
    declared = set()

    def head(mn, mtype):
        # one HELP/TYPE per family, however many labelled series it has
        if mn not in declared:
            declared.add(mn)
            out.extend(_header(mn, mtype,
                               f"{mtype} {mn[len(prefix) + 1:]}"))

    for name, v in snap.get("counters", {}).items():
        mn, labels = _family(name, prefix)
        head(mn, "counter")
        out.append(_series(mn, labels, v))
    for name, v in snap.get("gauges", {}).items():
        mn, labels = _family(name, prefix)
        head(mn, "gauge")
        out.append(_series(mn, labels, v))
    for name, h in snap.get("histograms", {}).items():
        mn, labels = _family(name, prefix)
        head(mn, "histogram")
        for bound, cum in h["buckets"].items():
            out.append(_series(f"{mn}_bucket", labels, cum,
                               extra=f'le="{_esc(bound)}"'))
        out.append(_series(f"{mn}_bucket", labels, h["count"],
                           extra='le="+Inf"'))
        out.append(_series(f"{mn}_sum", labels, h["sum"]))
        out.append(_series(f"{mn}_count", labels, h["count"]))
    return "\n".join(out) + ("\n" if out else "")


def render_sample(sample: dict, prefix: str = _PREFIX) -> str:
    """Expose one sampler line (per-node gauges + embedded registry)."""
    out = []
    df = sample.get("dataflow", "")
    for key, (suffix, mtype, help_text) in _NODE_FIELDS.items():
        mn = f"{prefix}_node_{suffix}"
        lines = []
        for n in sample.get("nodes", []):
            if key in n:
                lines.append(_line(mn, {"dataflow": df, "node": n["node"]},
                                   n[key]))
        if lines:
            out += _header(mn, mtype, help_text)
            out += lines
    mn = f"{prefix}_dead_letters"
    out += _header(mn, "gauge", "quarantined batches in the dead-letter "
                                "queue")
    out.append(_line(mn, {"dataflow": df}, sample.get("dead_letters", 0)))
    reg = {k: sample[k] for k in ("counters", "gauges", "histograms")
           if k in sample}
    if reg:
        txt = render_registry(reg, prefix=prefix)
        if txt:
            out.append(txt.rstrip("\n"))
    return "\n".join(out) + "\n"
