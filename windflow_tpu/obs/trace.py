"""End-to-end tracing & latency attribution — sampled per-batch spans
across threads, the wire, and device launches (docs/OBSERVABILITY.md
§tracing).

The aggregate sensors (obs/sampler.py) say how *fast* each node runs;
nothing decomposes *latency*: the bench sinks measure only end-to-end
avg/p50/p95/p99, so "p95 tripled" cannot be attributed to a stage.  This
module stamps a sampled fraction of source batches with a trace context
and records, at every node the batch traverses, a **queue-wait span**
(enqueue → dequeue) and a **service span** (the ``svc`` call), each with
an explicit parent — the emitting hop's span — so a trace stitches
source → sink across threads, across farm fan-out, and (via a wire
frame, parallel/channel.py) across hosts.  The device ship phases the
profile timers already bracket (``device_put`` / ``dispatch`` /
``harvest_wait``, ops/resident.py, patterns/native_core.py) become
*child spans* of the service span that ran them, via the
``utils/profile.py`` recorder hook — the T(L) launch-weather relation
per launch instead of in aggregate.  Checkpoint and rescale seals appear
as control-plane spans (kind ``ctrl``).

Mechanics (all engine-driven, see runtime/engine.py):

* the source's ``emit`` asks :meth:`Tracer.outgoing` — every
  ``sample_every``-th batch gets a fresh :class:`SpanCtx` (trace id +
  ``perf_counter_ns`` ingest anchor) and a root span record; the others
  clear the thread-local so stale contexts never leak onto later
  batches.  A batch arriving off the wire with a decoded trace frame
  (``RowReceiver(decode_trace=True)``) is *adopted* instead: same trace
  id, anchor back-dated by the upstream elapsed time, parent pointing at
  the remote span — multihost graphs stitch one trace;
* a traced batch crosses real inboxes wrapped in :class:`Stamped`
  (batch + ctx + parent span + enqueue timestamp); the engine unwraps it
  at ``get``, measures the queue wait, sets the thread-local ctx/span
  for the duration of ``svc`` (so every emission of that call inherits
  the trace — including emissions from stages fused into one thread by
  ``runtime/comb.py``, whose synchronous inner edges need no wrapping),
  times ``svc``, and appends one hop record;
* spans land in ``<trace_dir>/trace.jsonl`` (read by
  ``scripts/wf_trace.py``, which exports Chrome trace-event JSON for
  Perfetto) and ALWAYS in a bounded in-memory ring (``recent``) — a
  graph traced without a trace dir keeps the live percentile sensors
  and the ring, writes nothing;
* when a metrics registry is attached, per-node
  ``trace_queue_wait_seconds{node=...}`` /
  ``trace_service_seconds{node=...}`` histograms
  (:data:`~windflow_tpu.obs.registry.LATENCY_BUCKETS`) feed
  p50/p95/p99 into every sampler record, which is how a
  ``ControlPolicy`` rule thresholds on tail latency
  (``Rescale(up_q95_us=...)``, docs/CONTROL.md).

Contract (same as ``metrics=``/``control=``): ``trace=`` unset ⇒ this
module is **never imported**, no batch is ever wrapped, no file is
created, and the hot paths carry one dead ``is not None`` branch per
emitted batch; falsy ⇒ OFF.  The file is bounded (``max_spans``); spans
past the bound are *dropped and counted*, with a rate-limited
``trace_drop`` event, never allowed to grow the file without bound.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque
from time import perf_counter_ns as _pc_ns

from ..utils import profile as _profile
from .registry import LATENCY_BUCKETS, quantile_from_snapshot

#: spans buffered before a file write (spans are sampled, so a small
#: buffer amortises the write syscalls without risking much loss)
_FLUSH_EVERY = 128
#: rate limit for trace_drop events: first drop, then every this many
_DROP_EVENT_EVERY = 4096

#: process-wide thread-local carrying the ACTIVE span of the current
#: node thread (set by the engine around svc / by the sampling decision
#: at the source).  Module-level on purpose: helpers like ``current()``,
#: the wire-plane ``export()``, and the profile recorder work without a
#: Tracer handle in scope.
_TLS = threading.local()

#: process-wide id allocator shared by trace ids and span ids: ids must
#: stay unique across every Tracer of the process (repeated runs of
#: same-named dataflows APPEND to one trace.jsonl) and are salted with a
#: per-process random base so wire-adopted remote traces can never
#: collide with locally allocated ids.  The salt is 21 bits over a
#: 32-bit counter, keeping every id below 2**53: the Chrome trace-event
#: export writes ids into JSON consumed by JavaScript (Perfetto /
#: chrome://tracing), where larger ints lose low bits to double
#: rounding and distinct ids would silently merge.
_ID_MU = threading.Lock()
_NEXT_ID = (int.from_bytes(os.urandom(3), "big") >> 3) << 32


def _new_id() -> int:
    global _NEXT_ID
    with _ID_MU:
        _NEXT_ID += 1
        return _NEXT_ID


class TracePolicy:
    """The ``trace=`` knob bundle (``Dataflow``/``MultiPipe``).

    ``sample_rate`` is the sampled fraction of source batches in
    ``(0, 1]`` (internally 1-in-``sample_every``); ``max_spans`` bounds
    the per-Tracer trace.jsonl contribution (drops are counted and
    surface as ``trace_drop`` events); ``ring`` sizes the always-on
    in-memory span ring; ``launch``/``control`` gate the device-launch
    child spans and the checkpoint/rescale control-plane spans."""

    __slots__ = ("sample_rate", "sample_every", "max_spans", "ring",
                 "launch", "control")

    def __init__(self, sample_rate: float = 0.01, max_spans: int = 1 << 20,
                 ring: int = 4096, launch: bool = True,
                 control: bool = True):
        rate = float(sample_rate)
        if not 0.0 < rate <= 1.0:
            raise ValueError(f"sample_rate must be a fraction in (0, 1], "
                             f"got {sample_rate!r}")
        self.sample_rate = rate
        self.sample_every = max(1, round(1.0 / rate))
        if int(max_spans) < 1:
            raise ValueError(f"max_spans must be >= 1, got {max_spans}")
        self.max_spans = int(max_spans)
        if int(ring) < 1:
            raise ValueError(f"ring must be >= 1 span, got {ring}")
        self.ring = int(ring)
        self.launch = bool(launch)
        self.control = bool(control)

    def _key(self):
        return (self.sample_every, self.max_spans, self.ring,
                self.launch, self.control)

    def agrees_with(self, other: "TracePolicy") -> bool:
        """Structural equality — the union-merge conflict rule (one
        Dataflow runs one tracer, api/multipipe.py)."""
        return self._key() == other._key()

    def __repr__(self):
        return (f"TracePolicy(sample_rate={self.sample_rate}, "
                f"max_spans={self.max_spans}, ring={self.ring}, "
                f"launch={self.launch}, control={self.control})")


def as_policy(trace) -> TracePolicy:
    """Normalise a truthy ``trace=`` value: a :class:`TracePolicy` is
    used as-is, ``True`` means sample everything, any other number is
    the sample fraction."""
    if isinstance(trace, TracePolicy):
        return trace
    if trace is True:
        return TracePolicy(sample_rate=1.0)
    return TracePolicy(sample_rate=float(trace))


class SpanCtx:
    """One sampled batch's identity: trace id + ingest anchor + owning
    tracer.  Travels by reference (thread-local inside a thread,
    :class:`Stamped` across inboxes, :func:`export`/adoption across the
    wire)."""

    __slots__ = ("trace_id", "t0_ns", "tracer")

    def __init__(self, trace_id: int, t0_ns: int, tracer: "Tracer"):
        self.trace_id = trace_id
        self.t0_ns = t0_ns
        self.tracer = tracer


class Stamped:
    """A traced batch in flight between two node threads: the payload,
    its span context, the emitting hop's span id (the consumer's parent)
    and the enqueue timestamp the consumer subtracts to get the queue
    wait.  Only ever exists inside an engine inbox — the engine unwraps
    before ``svc`` sees the batch."""

    __slots__ = ("batch", "ctx", "parent", "t_enq_ns")

    def __init__(self, batch, ctx: SpanCtx, parent, t_enq_ns: int):
        self.batch = batch
        self.ctx = ctx
        self.parent = parent
        self.t_enq_ns = t_enq_ns

    def copy(self):
        """Copy with a private batch — the recovery journal's
        ``copy_inputs`` defense (recovery/epoch.py ``_journal_item``)
        duck-types on ``.copy()``: a node that mutates its input in
        place must not mutate the journaled replay copy through the
        wrapper's alias."""
        batch = self.batch
        return Stamped(batch.copy() if hasattr(batch, "copy") else batch,
                       self.ctx, self.parent, self.t_enq_ns)


def current() -> SpanCtx | None:
    """The span context of the batch the calling node thread is
    processing (None outside a traced ``svc`` call)."""
    return getattr(_TLS, "ctx", None)


def current_span() -> int | None:
    """The active hop's span id (None outside a traced ``svc``)."""
    return getattr(_TLS, "span", None)


def export() -> dict | None:
    """Portable form of the calling thread's active span, for handing a
    trace across the row plane (``RowSender.send(batch, trace=...)``).
    Carries the *elapsed* time since ingest instead of the raw anchor,
    so the adopting host needs no clock sync — only the (small, DCN
    round-trip sized) wire transit time is unattributed."""
    ctx = current()
    if ctx is None:
        return None
    return {"trace": ctx.trace_id, "span": current_span(),
            "elapsed_us": round((_pc_ns() - ctx.t0_ns) / 1e3, 1)}


def _profile_recorder(name: str, dt_ns: int):
    """utils/profile.py span-exit observer: when the calling thread is
    inside a traced ``svc``, the just-finished ship phase becomes a
    child span of the active hop.  Outside a traced batch it is two
    attribute reads and a return."""
    ctx = getattr(_TLS, "ctx", None)
    if ctx is None:
        return
    tr = ctx.tracer
    if tr is None or tr._closed or not tr.policy.launch:
        return
    tr.record_launch(ctx, getattr(_TLS, "span", None),
                     getattr(_TLS, "node", None), name, dt_ns)


#: live-Tracer refcount for the profile recorder: while any tracer is
#: open every profile span stamps its clock (that is the price of the
#: launch bridge), but once the LAST tracer closes the recorder is
#: uninstalled so untraced runs return to the bare-global disabled
#: probe — the "one dead branch" contract outlives the traced graph.
_RECORDER_REFS = 0
_RECORDER_MU = threading.Lock()


def _install_recorder():
    global _RECORDER_REFS
    with _RECORDER_MU:
        _RECORDER_REFS += 1
        if _RECORDER_REFS == 1:
            _profile.set_recorder(_profile_recorder)


def _uninstall_recorder():
    global _RECORDER_REFS
    with _RECORDER_MU:
        _RECORDER_REFS -= 1
        if _RECORDER_REFS == 0:
            _profile.set_recorder(None)


class Tracer:
    """Per-Dataflow span sampler and sink (see module docstring).

    ``trace_dir`` gates the trace.jsonl file (opened lazily on the first
    flush, like the event log); ``metrics`` gates the per-node latency
    histograms; ``events`` receives rate-limited ``trace_drop`` events.
    Any of the three sinks may be None — the bounded ``recent`` ring is
    always maintained."""

    def __init__(self, dataflow_name: str, policy: TracePolicy,
                 trace_dir: str = None, metrics=None, events=None):
        self.dataflow = dataflow_name
        self.policy = policy
        self.path = (os.path.join(trace_dir, "trace.jsonl")
                     if trace_dir else None)
        self.metrics = metrics
        self.events = events
        #: bounded in-memory span ring — the no-trace_dir sink, and what
        #: tests/stitching assertions read without touching the fs
        self.recent = deque(maxlen=policy.ring)
        #: spans recorded (ring) / file records written / dropped over
        #: the file bound (stable after close)
        self.spans = 0
        self.written = 0
        self.dropped = 0
        self._buf: list[dict] = []
        self._f = None
        self._closed = False
        self._mu = threading.Lock()
        self._hists: dict[str, tuple] = {}
        self._launch_hists: dict[str, object] = {}
        if metrics is not None:
            self._c_spans = metrics.counter("trace_spans_total")
            self._c_dropped = metrics.counter("trace_spans_dropped")
        else:
            self._c_spans = self._c_dropped = None
        # the ship-phase bridge costs nothing until a thread holds a
        # traced ctx, so it is installed process-wide exactly once
        _install_recorder()
        # a tracer that is never close()d — a built-but-never-run
        # preview graph, or run() raising before wait() — must still
        # release the process-wide recorder, or every later untraced
        # run keeps stamping clocks per profile span: a GC finalizer
        # backstops close() (the release box, not self, is captured —
        # the finalizer must not keep the tracer alive)
        released = [False]

        def _do_release(box=released):
            if not box[0]:
                box[0] = True
                _uninstall_recorder()

        self._release = _do_release
        weakref.finalize(self, _do_release)

    # ------------------------------------------------------------- sampling

    def _start(self, node, batch) -> SpanCtx | None:
        """Origin-side decision for the batch being emitted: adopt a
        wire-carried trace if the batch brought one, else sample
        1-in-``sample_every`` (counter is thread-local: no lock per
        batch; the id allocation — rare — takes one).  Sets the
        thread-local either way so a non-sampled batch can never inherit
        the previous batch's span."""
        parent = None
        ctx = None
        wf = getattr(batch, "wf_trace", None)
        if wf is not None:
            try:
                ctx = SpanCtx(int(wf["trace"]),
                              _pc_ns() - int(float(wf.get("elapsed_us", 0))
                                             * 1e3), self)
                parent = wf.get("span")
            except (KeyError, TypeError, ValueError):
                ctx = None      # malformed peer frame: sample locally
        if ctx is None:
            n = getattr(_TLS, "n", 0)
            _TLS.n = n + 1
            if n % self.policy.sample_every:
                self.set_current(None)
                return None
            ctx = SpanCtx(_new_id(), _pc_ns(), self)
        root = _new_id()
        self.set_current(ctx, root, getattr(node, "_hop_id", node.name))
        # the root hop record: zero queue/service, so wf_trace and the
        # parentage walk always find the source end of the chain (for an
        # adopted trace its end_us offset is the upstream elapsed time)
        self.record_hop(ctx, getattr(node, "_hop_id", node.name), root,
                        parent, 0, 0,
                        len(batch) if batch is not None else 0)
        return ctx

    # engine hooks: the thread-local IS the ctx of the running svc call
    @staticmethod
    def set_current(ctx: SpanCtx | None, span: int = None,
                    node_id: str = None):
        _TLS.ctx = ctx
        _TLS.span = span
        _TLS.node = node_id

    @staticmethod
    def incoming(item: "Stamped"):
        """Engine-side unwrap at inbox dequeue: returns ``(batch, ctx,
        parent, span, q_ns)`` — a fresh span id for this hop and the
        queue wait measured from the producer's enqueue stamp."""
        return (item.batch, item.ctx, item.parent, _new_id(),
                _pc_ns() - item.t_enq_ns)

    def outgoing(self, batch, node):
        """Called by ``Node.emit``/``emit_to`` when tracing is on: make
        the sampling/adoption decision at an origin (source) node, then
        wrap the batch iff this node's outputs are real inboxes
        (``_trace_wrap``; fused inner edges deliver synchronously
        in-thread, where the thread-local already carries the ctx)."""
        if node._trace_origin:
            ctx = self._start(node, batch)
        else:
            ctx = getattr(_TLS, "ctx", None)
        if ctx is None or not node._trace_wrap:
            return batch
        return Stamped(batch, ctx, getattr(_TLS, "span", None), _pc_ns())

    # ------------------------------------------------------------ recording

    def _hist_pair(self, node_id: str):
        pair = self._hists.get(node_id)
        if pair is None:
            with self._mu:
                pair = self._hists.get(node_id)
                if pair is None:
                    m = self.metrics
                    pair = (
                        m.histogram(
                            f'trace_queue_wait_seconds{{node="{node_id}"}}',
                            LATENCY_BUCKETS),
                        m.histogram(
                            f'trace_service_seconds{{node="{node_id}"}}',
                            LATENCY_BUCKETS))
                    self._hists[node_id] = pair
        return pair

    def record_hop(self, ctx: SpanCtx, node_id: str, span: int, parent,
                   q_ns: int, svc_ns: int, rows: int):
        """One traversed node for one traced batch: queue-wait span +
        service span (one record carrying both), parented on the
        emitting hop, plus the hop-completion offset from ingest
        (``end_us`` — the monotone coordinate wf_trace reconstructs
        end-to-end latency from)."""
        if self.metrics is not None:
            if q_ns or svc_ns:      # root records would bias the
                qh, sh = self._hist_pair(node_id)   # percentiles to 0
                qh.observe(q_ns / 1e9)
                sh.observe(svc_ns / 1e9)
            self._c_spans.inc()
        self._append({"t": time.time(), "kind": "hop",
                      "trace": ctx.trace_id, "span": span,
                      "parent": parent, "dataflow": self.dataflow,
                      "node": node_id, "q_us": round(q_ns / 1e3, 1),
                      "svc_us": round(svc_ns / 1e3, 1),
                      "end_us": round((_pc_ns() - ctx.t0_ns) / 1e3, 1),
                      "rows": int(rows)})

    def record_launch(self, ctx: SpanCtx, parent, node_id, phase: str,
                      dt_ns: int):
        """One device ship phase (profile span) that ran inside a traced
        ``svc`` call: a child span of that hop.  Attribution note: async
        cores dispatch/harvest launches while servicing LATER batches,
        so a launch child quantifies the launch weather the traced batch
        *experienced*, not necessarily its own rows' launch."""
        if self.metrics is not None:
            h = self._launch_hists.get(phase)
            if h is None:
                with self._mu:
                    h = self._launch_hists.get(phase)
                    if h is None:
                        h = self.metrics.histogram(
                            f'trace_launch_seconds{{phase="{phase}"}}',
                            LATENCY_BUCKETS)
                        self._launch_hists[phase] = h
            h.observe(dt_ns / 1e9)
            self._c_spans.inc()
        self._append({"t": time.time(), "kind": "launch",
                      "trace": ctx.trace_id, "span": _new_id(),
                      "parent": parent, "dataflow": self.dataflow,
                      "node": node_id, "phase": phase,
                      "dur_us": round(dt_ns / 1e3, 1),
                      "end_us": round((_pc_ns() - ctx.t0_ns) / 1e3, 1)})

    def record_ctrl(self, node_id: str, name: str, epoch: int,
                    dur_s: float, **extra):
        """A control-plane moment — a checkpoint commit or a rescale
        seal — as a span record (kind ``ctrl``), so wf_trace can place
        epoch/checkpoint/rescale instants on the Perfetto timeline next
        to the batches they stalled."""
        if not self.policy.control:
            return
        if self._c_spans is not None:
            self._c_spans.inc()
        self._append({"t": time.time(), "kind": "ctrl", "trace": None,
                      "span": _new_id(), "parent": None,
                      "dataflow": self.dataflow, "node": node_id,
                      "name": name, "epoch": int(epoch),
                      "dur_us": round(dur_s * 1e6, 1), **extra})

    # ------------------------------------------------------------ sinks

    def _append(self, rec: dict):
        with self._mu:
            self.recent.append(rec)
            self.spans += 1
            if self.path is None:
                return
            if self.written >= self.policy.max_spans:
                self._drop_locked()
                return
            self.written += 1
            self._buf.append(rec)
            if len(self._buf) >= _FLUSH_EVERY:
                self._flush_locked()

    def _drop_locked(self):
        self.dropped += 1
        if self._c_dropped is not None:
            self._c_dropped.inc()
        if self.events is not None and (
                self.dropped == 1
                or self.dropped % _DROP_EVENT_EVERY == 0):
            # rate-limited: under sustained overflow one event per 4096
            # drops, never per span (events are rare by construction)
            self.events.emit("trace_drop", dataflow=self.dataflow,
                             dropped=self.dropped,
                             max_spans=self.policy.max_spans)

    def _flush_locked(self):
        if self._closed:
            self._buf.clear()
            return
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "a")
        for rec in self._buf:
            json.dump(rec, self._f)
            self._f.write("\n")
        self._f.flush()
        self._buf.clear()

    def latency_snapshot(self, node_id: str) -> dict | None:
        """p50/p95/p99 (µs) of this node's queue-wait/service histograms
        — the per-node fields the sampler merges into every
        metrics.jsonl node entry (None before the node saw a traced
        batch, so pre-trace consumers never see the keys)."""
        pair = self._hists.get(node_id)
        if pair is None:
            return None
        out = {}
        for h, prefix in zip(pair, ("q", "svc")):
            snap = h.snapshot()
            if not snap["count"]:
                continue
            for q, tag in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                v = quantile_from_snapshot(snap, q)
                out[f"{prefix}_{tag}_us"] = round(v * 1e6, 1)
        return out or None

    def close(self):
        """Flush buffered spans and close the file (engine ``wait()``);
        the ring and counters stay readable.  Idempotent — the profile
        recorder refcount must drop exactly once per tracer."""
        with self._mu:
            if self._closed:
                return
            if self._buf:
                self._flush_locked()
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None
        self._release()
