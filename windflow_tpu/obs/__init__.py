"""Observability layer (docs/OBSERVABILITY.md): live metrics registry,
structured runtime event log, background sampler, and Prometheus-style
text exposition.

The reference's only instrumentation is the compile-time ``-DLOG_DIR``
end-of-run counter dump (reproduced by ``utils/tracing.py``); this
package adds the *in-flight* view — per-node occupancy, shed/quarantine
and wire counters sampled while the graph runs — under the same opt-in
contract as ``runtime/overload.py``: knobs unset ⇒ no threads, no
files, seed-identical behavior.
"""

from .events import EVENT_KINDS, EventLog
from .registry import (DEFAULT_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry)
from .sampler import Sampler

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "DEFAULT_BUCKETS",
    "EventLog", "EVENT_KINDS", "Sampler",
]
