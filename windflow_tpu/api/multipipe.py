"""MultiPipe — the linear pipeline composer (reference multipipe.hpp:
``add_source / add / chain / add_sink / chain_sink / unionMultiPipes /
run / run_and_wait_end``).

The reference builds nested ff_a2a "matrioskas" and splices emitters onto
producer pipelines at add time (multipipe.hpp:174-240).  Here composition is
*deferred*: ``add``/``chain`` record stages, and the graph is materialised
once at ``run()``:

* ``add(p)`` wires p as its own farm (emitter -> replicas -> collector)
  fed by the current tail — the Case-2 "shuffle" of add_operator.
* ``chain(p)`` fuses p's workers into the previous stage's worker threads
  (one :class:`~windflow_tpu.runtime.comb.Comb` per replica — the
  chain_operator / ff_comb path, multipipe.hpp:244-271).  Chaining requires
  a non-keyed pattern of equal width; otherwise it degrades to ``add``
  exactly like the reference's width checks force a shuffle.
* ``union`` merges several MultiPipes into one (multipipe.hpp:909-940);
  an OrderingNode is interposed before order-sensitive consumers (windowed
  or keyed patterns), with TS_RENUMBERING for count-windows — the mode table
  of MultiPipe::add (multipipe.hpp:494-537).
"""

from __future__ import annotations

from ..core.windows import WinType
from ..runtime.comb import make_comb
from ..runtime.engine import Dataflow
from ..runtime.farm import add_farm
from ..runtime.ordering import OrderingMode, OrderingNode


def _window_spec(pattern):
    return getattr(pattern, "spec", None)


def _is_keyed(pattern):
    return getattr(pattern, "routing", None) is not None


def _is_composite(pattern):
    return hasattr(pattern, "instantiate")


def _chainable(pattern, group):
    """chain_operator preconditions (multipipe.hpp:244-271): same width,
    non-keyed, and a simple (non-composite) pattern on both sides."""
    if _is_composite(pattern) or _is_keyed(pattern):
        return False
    head = group[0]
    if _is_composite(head):
        return False
    return pattern.parallelism == head.parallelism


class _FusedPattern:
    """A chain group presented as one pattern: replica i is the Comb of
    every member's replica i; the shell comes from the ends."""

    def __init__(self, group):
        self.group = group
        self.parallelism = group[0].parallelism
        self.name = "+".join(p.name for p in group)
        # a fused chain runs every member in ONE thread, so one svc error
        # quarantines the chain's whole input batch: honor the tightest
        # member budget rather than silently dropping withErrorBudget
        budgets = [p.error_budget for p in group
                   if getattr(p, "error_budget", None) is not None]
        if budgets:
            self.error_budget = min(budgets)

    def replicas(self):
        per = [p.replicas() for p in self.group]
        return [make_comb([per[s][i] for s in range(len(per))])
                for i in range(self.parallelism)]

    def emitter(self):
        return self.group[0].emitter()

    def collector(self):
        return self.group[-1].collector()


class MultiPipe:
    """Deferred-construction pipeline of patterns.  Instances are also the
    operands of :func:`union_multipipes`."""

    def __init__(self, name: str = "pipe", trace_dir: str = None,
                 capacity: int = 16, overload=None, metrics=None,
                 sample_period: float = None, recovery=None,
                 check: str = None, control=None, trace=None,
                 federate=None):
        self.name = name
        self.trace_dir = trace_dir  # None -> WF_LOG_DIR env (tracing.py)
        #: per-queue chunk capacity (engine Inbox bound): the
        #: latency/throughput knob — buffered tuples ~= stages x capacity
        #: x chunk, so end-to-end latency ~= that over the throughput
        self.capacity = capacity
        #: runtime/overload.OverloadPolicy — shedding / put deadlines /
        #: poison quarantine for the materialised graph; None (default)
        #: keeps seed-identical behavior (docs/ROBUSTNESS.md)
        self.overload = overload
        #: observability knobs (docs/OBSERVABILITY.md): `metrics` is an
        #: obs.MetricsRegistry (or truthy for a fresh one) exposed live
        #: via `.metrics`; `sample_period` (seconds; WF_SAMPLE_PERIOD
        #: env) runs the background sampler writing
        #: <trace_dir>/metrics.jsonl + events.jsonl.  Both unset =>
        #: no thread, no files, seed-identical hot paths.
        self._metrics_arg = metrics
        self.sample_period = sample_period
        #: recovery/policy.RecoveryPolicy — epoch checkpoints + supervised
        #: node restart for the materialised graph; None (default) keeps
        #: seed-identical behavior (docs/ROBUSTNESS.md "Recovery")
        self.recovery = recovery
        #: pre-flight static analysis (docs/CHECKS.md): 'off'/None = seed
        #: behavior (check/ never imported), 'warn' = report diagnostics
        #: as CheckWarnings at run(), 'error' = raise CheckError before
        #: any thread starts.  Validated eagerly — the deferred build
        #: would otherwise surface a typo'd mode only at run() (or as a
        #: bare KeyError from the union strictness merge).
        if check not in Dataflow.CHECK_MODES:
            raise ValueError(f"check= wants one of {Dataflow.CHECK_MODES}, "
                             f"got {check!r}")
        self.check = check
        #: control/policy.ControlPolicy — the closed-loop control plane
        #: (docs/CONTROL.md): elastic rescale at epoch barriers, adaptive
        #: shedding, source admission.  None (default) keeps seed-
        #: identical behavior and never imports windflow_tpu.control.
        self.control = control
        #: obs/trace.TracePolicy (or a sample-rate fraction) — end-to-end
        #: span tracing (docs/OBSERVABILITY.md §tracing): sampled source
        #: batches leave per-hop queue-wait/service spans (+ device
        #: launch child spans) in <trace_dir>/trace.jsonl.  Falsy
        #: (default) keeps seed-identical behavior and never imports
        #: windflow_tpu.obs.trace.
        self.trace = trace
        #: obs/federation.FederationPolicy (or True) — the plane-wide
        #: telemetry tier (docs/OBSERVABILITY.md "Federation & SLOs"):
        #: snapshot shipping over the row plane, local SLO burn rates,
        #: and the crash black-box.  Falsy (default) keeps seed-
        #: identical behavior and never imports windflow_tpu.obs
        #: .federation / .slo.
        self.federate = federate
        self._stages: list[tuple[str, object]] = []  # (kind, pattern)
        self._branches: list[MultiPipe] = []
        self._has_source = False
        self._has_sink = False
        self._df: Dataflow | None = None
        #: seal listeners registered before the deferred build; handed
        #: to the Dataflow at _build() (and registered directly once
        #: built) — see on_epoch_sealed
        self._seal_listeners: list = []

    # ------------------------------------------------------------- builders

    def _check_open(self):
        if self._has_sink:
            raise ValueError(f"MultiPipe {self.name!r} already has a sink")
        if self._df is not None:
            raise ValueError(f"MultiPipe {self.name!r} is already running")

    def add_source(self, source) -> "MultiPipe":
        self._check_open()
        if self._has_source or self._branches:
            raise ValueError("MultiPipe already has a source")
        self._has_source = True
        self._stages.append(("add", source))
        return self

    def add(self, pattern) -> "MultiPipe":
        self._check_open()
        self._require_input()
        self._stages.append(("add", pattern))
        return self

    def chain(self, pattern) -> "MultiPipe":
        self._check_open()
        self._require_input()
        self._stages.append(("chain", pattern))
        return self

    def add_sink(self, sink) -> "MultiPipe":
        self._check_open()
        self._require_input()
        self._stages.append(("add", sink))
        self._has_sink = True
        return self

    def chain_sink(self, sink) -> "MultiPipe":
        self._check_open()
        self._require_input()
        self._stages.append(("chain", sink))
        self._has_sink = True
        return self

    def _require_input(self):
        if not (self._has_source or self._branches):
            raise ValueError("add a source first (or union MultiPipes)")

    # ---------------------------------------------------------------- build

    def _group_stages(self):
        groups = []
        for kind, p in self._stages:
            if kind == "chain" and groups and _chainable(p, groups[-1]):
                groups[-1].append(p)
            else:
                groups.append([p])
        return groups

    def _maybe_order(self, df, tails, group, ordered, dense):
        """Interpose the right merge in front of an order-sensitive consumer
        — the OrderingNode mode table of MultiPipe::add
        (multipipe.hpp:377-537): count-windows over a stream whose per-key
        ids are no longer pristine (filtered/flat-mapped/unioned/unordered)
        get a TS_RENUMBERING front-end, so CB means "count of arriving
        tuples per key" exactly like the reference's broadcast+renumber CB
        path (:494-537); time-windows and keyed state get a TS merge when
        the stream is unordered or multi-tailed.

        Deliberate reference-faithful asymmetry: a Key_Farm exposes no
        window spec here and is added with its plain key-routing emitter
        (:547-589 — no broadcast, no renumbering), so ITS count windows
        run over RAW tuple ids, gaps and all.  Downstream of a Filter a
        KeyFarm and a WinFarm therefore legitimately disagree on CB
        window content — in the reference exactly as here (the KeyFarm
        raw-id half is pinned by tests/test_fuzz_differential.py's pipe
        fuzz; the WinFarm renumbered half by tests/test_multipipe.py's
        Filter->WinFarm CB case)."""
        specs = [s for s in (_window_spec(p) for p in group) if s is not None]
        cb = any(s.win_type is WinType.CB for s in specs)
        sensitive = bool(specs) or any(_is_keyed(p) for p in group)
        disordered = not ordered or len(tails) > 1
        if cb and (disordered or not dense):
            mode = OrderingMode.TS_RENUMBERING
        elif sensitive and disordered:
            mode = OrderingMode.TS
        elif len(tails) > 1 and not self._keeps_channels(group):
            # a non-sensitive consumer would still merge the channels
            # blindly at its (multi-in) emitter/replica inbox, destroying
            # the per-channel order for everything downstream — merge here
            # (the reference interposes OrderingNode at every Case-2
            # shuffle, multipipe.hpp:218-224)
            mode = OrderingMode.TS
        else:
            return tails, ordered, dense
        onode = OrderingNode(max(len(tails), 1), mode,
                             name=f"{self.name}.order_merge",
                             ordered_input=(ordered and len(tails) == 1),
                             # every producer hands its batches off =>
                             # the renumbering fast path may write ids in
                             # place (node.py ownership protocol)
                             owned_input=all(t.yields_fresh for t in tails))
        df.add(onode)
        for t in tails:
            df.connect(t, onode)
        return [onode], True, (dense or mode is OrderingMode.TS_RENUMBERING)

    @staticmethod
    def _stream_effect(group, ordered, dense):
        """How a wired group changes the stream's (ordered, dense-ids)
        invariants for what flows downstream of it."""
        for p in group:
            if _window_spec(p) is not None:
                # windowed results carry fresh per-key window ids; ordered
                # collectors (default) restore emission order
                ordered = getattr(p, "ordered", True)
                dense = True
                continue
            cls = type(p).__name__
            if cls in ("Filter", "FlatMap"):
                dense = False  # rows dropped / multiplied
            if cls == "Accumulator":
                # accumulator snapshots carry the triggering row's header,
                # but the fold makes ids non-window-meaningful downstream
                dense = False
        return ordered, dense

    @staticmethod
    def _keeps_channels(group):
        """True when the group's replica outputs must stay as separate
        tails instead of being funnelled through a blind Collector: each
        worker's output IS per-key ordered, but an interleaving collector
        would destroy that invariant for good.  Downstream consumers either
        don't care (stateless ops), or get a real k-way OrderingNode merge
        over the per-replica channels — the reference's fused
        OrderingNode∘worker combs (multipipe.hpp:218-224).

        Applies to non-keyed parallel stateless groups and to explicitly
        unordered window farms (whose plain Collector would interleave the
        per-worker result streams)."""
        if any(_is_composite(p) or _is_keyed(p) for p in group):
            return False
        if group[0].parallelism <= 1:
            return False
        if all(_window_spec(p) is None for p in group):
            return True
        # single unordered window farm: drop its interleaving Collector
        return (len(group) == 1
                and _window_spec(group[0]) is not None
                and not getattr(group[0], "ordered", True))

    def _build_into(self, df: Dataflow):
        tails = []
        ordered, dense = True, True
        for b in self._branches:
            tails.extend(b._build_into(df))
        if len(self._branches) > 1:
            ordered, dense = False, False  # cross-branch interleave, id clash
        for group in self._group_stages():
            pattern = group[0] if len(group) == 1 else _FusedPattern(group)
            tails, ordered, dense = self._maybe_order(
                df, tails, group, ordered, dense)
            if self._keeps_channels(group):
                tails = add_farm(df, pattern, tails, collector=None)
            else:
                tails = add_farm(df, pattern, tails)
            ordered, dense = self._stream_effect(group, ordered, dense)
        return tails

    def _build(self) -> Dataflow:
        if self._df is None:
            df = Dataflow(self.name, capacity=self.capacity,
                      trace_dir=self.trace_dir, overload=self.overload,
                      metrics=self._metrics_arg,
                      sample_period=self.sample_period,
                      recovery=self.recovery, check=self.check,
                      control=self.control, trace=self.trace,
                      federate=self.federate)
            #: the validator (check/graph.py) anchors window-geometry
            #: diagnostics at pattern construction sites via the
            #: declared stage list — only reachable through this stamp
            df._check_pipe = self
            self._build_into(df)
            for fn in self._seal_listeners:
                df.on_epoch_sealed(fn)
            self._df = df
        return self._df

    def on_epoch_sealed(self, fn) -> "MultiPipe":
        """Register ``fn(epoch)`` to fire when the recovery supervisor
        seals a checkpoint epoch — the sealed-ack hook for resumable
        wire planes: ``pipe.on_epoch_sealed(receiver.ack_epoch)`` lets
        remote RowSender journals trim at exactly the epochs this
        pipe's checkpoints made durable (docs/ROBUSTNESS.md "Wire
        resume").  Needs ``recovery=`` with a checkpoint_dir to ever
        fire.  Safe before or after run()."""
        self._seal_listeners.append(fn)
        if self._df is not None:
            self._df.on_epoch_sealed(fn)
        return self

    # ------------------------------------------------------------------ run

    def run(self) -> "MultiPipe":
        self._build().run()
        return self

    def wait(self, timeout: float = None):
        """Join the materialised graph; ``timeout`` (seconds) bounds a
        hung graph with a TimeoutError instead of waiting forever
        (engine.Dataflow.wait)."""
        if self._df is None:
            raise RuntimeError("run() first")
        self._df.wait(timeout=timeout)

    def run_and_wait_end(self, timeout: float = None):
        df = self._build()
        if df._threads:          # already started via run(): just wait
            df.wait(timeout=timeout)
        else:
            df.run_and_wait_end(timeout=timeout)

    @property
    def dead_letters(self):
        """Quarantined poison batches (engine DeadLetter records) — only
        populated when an error budget is set; inspect after wait()."""
        return self._df.dead_letters if self._df is not None else []

    def shed_counts(self) -> dict:
        """Per-node shed counters of the materialised graph (empty before
        run() and under the default blocking policy)."""
        return self._df.shed_counts() if self._df is not None else {}

    @property
    def metrics(self):
        """The materialised graph's live obs.MetricsRegistry (None before
        run() unless one was passed in, and always None when neither
        `metrics` nor `sample_period` was configured)."""
        if self._df is not None:
            return self._df.metrics
        from ..obs import MetricsRegistry
        return (self._metrics_arg
                if isinstance(self._metrics_arg, MetricsRegistry) else None)

    @property
    def events(self):
        """The materialised graph's obs.EventLog (None before run() or
        when observability is off); `.recent` holds the in-memory tail."""
        return self._df.events if self._df is not None else None

    @property
    def controller(self):
        """The materialised graph's control-plane Controller (None
        before run() or when ``control=`` is unset/blind) — the handle
        for scripted ``request_rescale`` calls (docs/CONTROL.md)."""
        return self._df._controller if self._df is not None else None

    def request_drain(self, timeout: float = None) -> bool:
        """Gate every source and wait for in-flight work to settle —
        the quiesce leg of a rolling restart (docs/ROBUSTNESS.md
        "Cross-host recovery").  Needs a running pipe whose ``control=``
        policy declares a :class:`~windflow_tpu.control.Drain` rule."""
        if self._df is None:
            raise RuntimeError("request_drain() needs a running pipe — "
                               "call after run()")
        return self._df.request_drain(timeout)

    def release_drain(self):
        """Reopen the source gate closed by :meth:`request_drain`."""
        if self._df is None:
            raise RuntimeError("release_drain() needs a running pipe — "
                               "call after run()")
        self._df.release_drain()

    def getNumThreads(self) -> int:
        """Thread count of the materialised graph (multipipe.hpp:973).
        Before run() this builds a throwaway preview graph, so the pipe
        stays open for further add()/chain() calls."""
        if self._df is not None:
            return self._df.cardinality()
        import warnings
        with warnings.catch_warnings():
            # a control= preview would re-fire the construction-time
            # WF209/WF207 warnings the real build already owns
            warnings.simplefilter("ignore")
            # control changes the materialised cardinality (farms
            # pre-provision to a Rescale rule's max_workers, but only
            # when the graph is observed — blind control provisions
            # nothing), so the preview graph must carry the control,
            # recovery AND observability knobs to match the real build
            df = Dataflow(self.name, capacity=self.capacity,
                          trace_dir=self.trace_dir,
                          metrics=self._metrics_arg,
                          sample_period=self.sample_period,
                          recovery=self.recovery, control=self.control)
        self._build_into(df)
        return df.cardinality()

    # ---------------------------------------------------------------- union

    @staticmethod
    def union(*pipes: "MultiPipe", name: str = "union") -> "MultiPipe":
        return union_multipipes(*pipes, name=name)


def union_multipipes(*pipes: MultiPipe, name: str = "union") -> MultiPipe:
    """Merge several source-bearing MultiPipes into one downstream pipe
    (multipipe.hpp:909-940).  The operands must not have sinks; the merged
    pipe continues with add/chain/add_sink."""
    if len(pipes) < 2:
        raise ValueError("union needs at least two MultiPipes")
    for p in pipes:
        if p._has_sink:
            raise ValueError(f"cannot union {p.name!r}: it has a sink")
        if not (p._has_source or p._branches):
            raise ValueError(f"cannot union {p.name!r}: it has no source")
        if p._df is not None:
            raise ValueError(f"cannot union {p.name!r}: already running")
    # the merged pipe builds ONE Dataflow for the whole graph, so the
    # tightest operand capacity wins (a per-branch latency tuning must not
    # be silently widened back to the default).  Overload policies have no
    # such merge rule: distinct configured policies would silently drop
    # one author's knobs, so they must agree (or all but one be unset)
    policies = [p.overload for p in pipes if p.overload is not None]
    overload = policies[0] if policies else None
    for pol in policies[1:]:
        if (pol.shed, pol.put_deadline, pol.error_budget,
                pol.soft_limit) != (
                overload.shed, overload.put_deadline,
                overload.error_budget, overload.soft_limit):
            raise ValueError(
                f"cannot union MultiPipes with conflicting overload "
                f"policies ({overload!r} vs {pol!r}): one Dataflow runs "
                f"one policy — configure it on the merged pipe")
    # one Dataflow runs one controller: configured control policies must
    # agree (or all but one be unset), like overload/recovery policies
    ctl_pols = [p.control for p in pipes if p.control is not None]
    control = ctl_pols[0] if ctl_pols else None
    for pol in ctl_pols[1:]:
        if not control.agrees_with(pol):
            raise ValueError(
                f"cannot union MultiPipes with conflicting control "
                f"policies ({control!r} vs {pol!r}): one Dataflow runs "
                f"one controller — configure it on the merged pipe")
    # one Dataflow runs one recovery policy: configured policies must
    # agree (or all but one be unset), like overload policies
    rec_pols = [p.recovery for p in pipes if p.recovery is not None]
    recovery = rec_pols[0] if rec_pols else None
    for pol in rec_pols[1:]:
        if not recovery.agrees_with(pol):
            raise ValueError(
                f"cannot union MultiPipes with conflicting recovery "
                f"policies ({recovery!r} vs {pol!r}): one Dataflow runs "
                f"one policy — configure it on the merged pipe")
    # one Dataflow runs one span tracer: configured trace policies must
    # agree (or all but one be unset) — normalised lazily, so a union of
    # untraced pipes still never imports obs.trace
    tr_pols = [p.trace for p in pipes if p.trace]
    trace = tr_pols[0] if tr_pols else None
    if len(tr_pols) > 1:
        from ..obs.trace import as_policy
        first = as_policy(trace)
        for pol in tr_pols[1:]:
            if not first.agrees_with(as_policy(pol)):
                raise ValueError(
                    f"cannot union MultiPipes with conflicting trace "
                    f"policies ({trace!r} vs {pol!r}): one Dataflow "
                    f"runs one tracer — configure it on the merged pipe")
    # one process runs one federation shipper: configured federate
    # policies must agree (or all but one be unset) — normalised
    # lazily, so a union of unfederated pipes never imports
    # obs.federation
    fed_pols = [p.federate for p in pipes if p.federate]
    federate = fed_pols[0] if fed_pols else None
    if len(fed_pols) > 1:
        from ..obs.federation import as_policy as _fed_as_policy
        first = _fed_as_policy(federate)
        for pol in fed_pols[1:]:
            if not first.agrees_with(_fed_as_policy(pol)):
                raise ValueError(
                    f"cannot union MultiPipes with conflicting federate "
                    f"policies ({federate!r} vs {pol!r}): one process "
                    f"runs one shipper — configure it on the merged "
                    f"pipe")
    # observability merges like capacity: the merged graph samples at the
    # finest requested cadence, and the first configured registry and
    # trace_dir win (these are additive sinks, not behavior — no conflict
    # rule needed the way overload policies need one)
    periods = [p.sample_period for p in pipes if p.sample_period is not None]
    registries = [p._metrics_arg for p in pipes if p._metrics_arg]
    trace_dirs = [p.trace_dir for p in pipes if p.trace_dir is not None]
    # static analysis merges by strictness: any operand asking for
    # 'error' makes the merged graph raise, any 'warn' at least warns —
    # loosening one author's check mode would silently drop their gate
    strictness = {"off": 0, "warn": 1, "error": 2}
    modes = [p.check for p in pipes if p.check is not None]
    check = max(modes, key=strictness.__getitem__) if modes else None
    merged = MultiPipe(name, capacity=min(p.capacity for p in pipes),
                       trace_dir=trace_dirs[0] if trace_dirs else None,
                       overload=overload,
                       metrics=registries[0] if registries else None,
                       sample_period=min(periods) if periods else None,
                       recovery=recovery, check=check, control=control,
                       trace=trace, federate=federate)
    merged._branches = list(pipes)
    # seal listeners are additive sinks like metrics registries: every
    # operand's hooks fire on the one merged supervisor
    for p in pipes:
        merged._seal_listeners.extend(p._seal_listeners)
    return merged
