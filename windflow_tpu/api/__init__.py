"""Composition layer (L4): MultiPipe + the 16 fluent builders — the
equivalents of the reference's multipipe.hpp and builders.hpp."""

from .builders import (LEVEL0, LEVEL1, LEVEL2, Accumulator_Builder,
                       Filter_Builder, FlatMap_Builder, KeyFarm_Builder,
                       KeyFarmTPU_Builder, Map_Builder, PaneFarm_Builder,
                       PaneFarmTPU_Builder, Sink_Builder, Source_Builder,
                       WinFarm_Builder, WinFarmTPU_Builder,
                       WinMapReduce_Builder, WinMapReduceTPU_Builder,
                       WinSeq_Builder, WinSeqTPU_Builder)
from .multipipe import MultiPipe, union_multipipes

__all__ = [
    "MultiPipe", "union_multipipes",
    "Source_Builder", "Filter_Builder", "Map_Builder", "FlatMap_Builder",
    "Accumulator_Builder", "Sink_Builder",
    "WinSeq_Builder", "WinFarm_Builder", "KeyFarm_Builder",
    "PaneFarm_Builder", "WinMapReduce_Builder",
    "WinSeqTPU_Builder", "WinFarmTPU_Builder", "KeyFarmTPU_Builder",
    "PaneFarmTPU_Builder", "WinMapReduceTPU_Builder",
    "LEVEL0", "LEVEL1", "LEVEL2",
]
