"""Fluent builders — the 16 builder classes of the reference's
``builders.hpp`` (Source_Builder:57 ... Sink_Builder:2186), with the five
``*GPU_Builder`` classes becoming ``*TPU_Builder``.

Differences forced by the platform, mirroring the pattern layer:

* the reference deduces functor flavour (plain/rich, NIC/INC) from the C++
  signature (meta_utils.hpp:47-259); Python has no signatures to deduce
  from, so flavour is explicit: ``withRich()``, ``incremental()``,
  ``vectorized()``;
* window result payloads need declared dtypes: ``withResultFields``
  (C++ gets this from the result template parameter);
* ``withBatch(batch_len, n_thread_block)``'s second argument was the CUDA
  thread-block size — accepted and ignored here (XLA picks its own tiling);
  ``withScratchpad`` likewise only matters to raw CUDA functors and is
  accepted for source compatibility with a warning;
* ``withOpt(level)`` drives real graph surgery on the two-stage patterns
  (Pane_Farm / Win_MapReduce): LEVEL1 fuses the internal
  collector/emitter boundary into one thread, LEVEL2 removes it entirely
  and merges at OrderingCore-fronted stage-2 workers
  (runtime/farm.py:fuse_two_stage — optimize_PaneFarm,
  pane_farm.hpp:426-466).  For single-farm patterns the engine already
  fuses pass-through shells automatically and ``chain()`` on MultiPipe is
  the explicit fusion path, so the level is advisory there.
"""

from __future__ import annotations

import warnings

from ..core.windows import WinType
from ..patterns.basic import (Accumulator, Filter, FlatMap, Map, Sink,
                              Source)
from ..patterns.key_farm import KeyFarm
from ..patterns.nesting import KeyFarmOf, WinFarmOf
from ..patterns.pane_farm import PaneFarm
from ..patterns.win_farm import WinFarm
from ..patterns.win_mapreduce import WinMapReduce
from ..patterns.win_seq import WinSeq
from ..patterns.win_seq_tpu import (KeyFarmTPU, PaneFarmTPU, WinFarmTPU,
                                    WinMapReduceTPU, WinSeqTPU)

LEVEL0, LEVEL1, LEVEL2 = 0, 1, 2  # opt_level_t (basic.hpp:94)


class _Builder:
    """Shared fluent machinery: every option mutates and returns self;
    ``build()`` constructs the pattern (build_ptr/build_unique are aliases
    of the reference API — Python has one object model)."""

    _pattern_cls = None

    def __init__(self):
        self._kw = {}

    def withName(self, name: str):
        self._kw["name"] = name
        return self

    def _build_kw(self) -> dict:
        return dict(self._kw)

    def build(self):
        return self._pattern_cls(**self._build_kw())

    build_ptr = build
    build_unique = build


class _ParallelMixin:
    def withParallelism(self, n: int):
        self._kw["parallelism"] = int(n)
        return self


class _RichMixin:
    def withRich(self):
        """Mark the functor as RuntimeContext-receiving (the reference's
        rich variants, e.g. map.hpp:64-68).  Beyond parallelism/index,
        the context carries the dataflow's live metrics registry when
        observability is on (``MultiPipe(metrics=…/sample_period=…)``,
        docs/OBSERVABILITY.md): a rich functor may record custom
        counters/histograms via ``ctx.metrics`` (None when off)."""
        self._kw["rich"] = True
        return self


class _KeyByMixin:
    def keyBy(self, routing=None):
        """Keyed routing (builders.hpp:190,299,408); default ``key % n``."""
        from ..runtime.emitters import default_routing
        self._kw["routing"] = routing or default_routing
        return self


class _VectorizedMixin:
    def vectorized(self, flag: bool = True):
        """Whole-batch user function — the TPU-idiomatic flavour the
        reference cannot express."""
        self._kw["vectorized"] = flag
        return self


class _ErrorBudgetMixin:
    """Poison-tuple quarantine knob (runtime/overload.py, no reference
    analog — FastFlow tears the farm down on any svc error).  Applies to
    the operator's worker replicas; stamped on the built pattern and
    propagated per node by runtime/farm.py."""

    def withErrorBudget(self, n: int):
        """Allow each replica to quarantine up to `n` failing batches to
        the dataflow's dead-letter queue before failing fast."""
        n = int(n)
        if n < 0:
            raise ValueError("error budget must be >= 0")
        self._error_budget = n
        return self

    def build(self):
        pattern = super().build()
        budget = getattr(self, "_error_budget", None)
        if budget is not None:
            pattern.error_budget = budget
        return pattern

    build_ptr = build
    build_unique = build


# ------------------------------------------------------------ basic patterns

class Source_Builder(_Builder, _ParallelMixin, _RichMixin):
    """builders.hpp:57."""
    _pattern_cls = Source

    def __init__(self, fn=None):
        super().__init__()
        self._kw["fn"] = fn

    def withSchema(self, schema):
        self._kw["schema"] = schema
        return self

    def withBatches(self, batches):
        """Pre-built structured-array batches (or replica-index -> batches
        callable) instead of a generator function."""
        self._kw["batches"] = batches
        return self

    def itemized(self):
        """bool(tuple&) flavour (source.hpp:59): fn fills one row dict and
        returns False at end-of-stream."""
        self._kw["itemized"] = True
        return self

    def withChunk(self, n: int):
        self._kw["chunk"] = int(n)
        return self


class Filter_Builder(_ErrorBudgetMixin, _Builder, _ParallelMixin,
                     _RichMixin, _KeyByMixin, _VectorizedMixin):
    """builders.hpp:139."""
    _pattern_cls = Filter

    def __init__(self, fn):
        super().__init__()
        self._kw["fn"] = fn


class Map_Builder(_ErrorBudgetMixin, _Builder, _ParallelMixin, _RichMixin,
                  _KeyByMixin, _VectorizedMixin):
    """builders.hpp:247."""
    _pattern_cls = Map

    def __init__(self, fn):
        super().__init__()
        self._kw["fn"] = fn

    def withOutputSchema(self, schema):
        """Non-in-place Map producing a different tuple type
        (map.hpp:63-68)."""
        self._kw["output_schema"] = schema
        return self


class FlatMap_Builder(_ErrorBudgetMixin, _Builder, _ParallelMixin,
                      _RichMixin, _KeyByMixin, _VectorizedMixin):
    """builders.hpp:356."""
    _pattern_cls = FlatMap

    def __init__(self, fn):
        super().__init__()
        self._kw["fn"] = fn

    def withOutputSchema(self, schema):
        self._kw["output_schema"] = schema
        return self


class Accumulator_Builder(_ErrorBudgetMixin, _Builder, _ParallelMixin,
                          _RichMixin):
    """builders.hpp:465."""
    _pattern_cls = Accumulator

    def __init__(self, fn):
        super().__init__()
        self._kw["fn"] = fn

    def withInitialValue(self, init: dict):
        self._kw["init_value"] = dict(init)
        return self

    def withResultSchema(self, schema):
        self._kw["result_schema"] = schema
        return self

    def withRouting(self, routing):
        self._kw["routing"] = routing
        return self


class Sink_Builder(_ErrorBudgetMixin, _Builder, _ParallelMixin, _RichMixin,
                   _KeyByMixin, _VectorizedMixin):
    """builders.hpp:2186."""
    _pattern_cls = Sink

    def __init__(self, fn):
        super().__init__()
        self._kw["fn"] = fn


# --------------------------------------------------------- windowed patterns

class _WindowMixin:
    def withCBWindow(self, win_len: int, slide_len: int):
        self._kw["win_len"] = int(win_len)
        self._kw["slide_len"] = int(slide_len)
        self._kw["win_type"] = WinType.CB
        return self

    def withTBWindow(self, win_us: int, slide_us: int):
        """Time-based window; extents in the stream's `ts` units (the
        reference takes std::chrono microseconds)."""
        self._kw["win_len"] = int(win_us)
        self._kw["slide_len"] = int(slide_us)
        self._kw["win_type"] = WinType.TB
        return self

    def incremental(self, flag: bool = True):
        """INC (per-tuple fold) flavour; default NIC (win_seq.hpp:116)."""
        self._kw["incremental"] = flag
        return self

    def withResultFields(self, fields: dict):
        self._kw["result_fields"] = dict(fields)
        return self

    def withOpt(self, level: int):
        """Graph-optimization level (opt_level_t, basic.hpp:94).  Two-stage
        patterns (Pane_Farm / Win_MapReduce) honour it: LEVEL1 fuses the
        stage boundary into one thread, LEVEL2 removes the internal
        collector and merges at OrderingCore-fronted stage-2 workers
        (optimize_PaneFarm, pane_farm.hpp:426-466).  For single-farm
        patterns the engine's chaining already provides the LEVEL1
        fusion, so the level is advisory there."""
        self._opt_level = level
        return self


class _WinParMixin:
    def withParallelism(self, n: int):
        self._kw["pardegree"] = int(n)
        return self


class WinSeq_Builder(_Builder, _WindowMixin):
    """builders.hpp:579."""
    _pattern_cls = WinSeq

    def __init__(self, winfunc):
        super().__init__()
        self._kw["winfunc"] = winfunc


class _NestingMixin:
    """Shared nesting acceptance of WinFarm/KeyFarm builders: the input may
    be a window function OR a Pane_Farm / Win_MapReduce instance
    (Constructor III/IV of win_farm.hpp; initWindowConf,
    builders.hpp:1210-1234).  Subclasses set `_nested_cls` and may override
    `_nested_kw` to add routing etc."""

    _nested_cls = None

    def __init__(self, input_):
        super().__init__()
        self._input = input_
        if not isinstance(input_, (PaneFarm, WinMapReduce)):
            self._kw["winfunc"] = input_

    def withOrdered(self, flag: bool = True):
        self._kw["ordered"] = flag
        return self

    def _nested_kw(self) -> dict:
        return dict(pardegree=self._kw.get("pardegree", 2),
                    ordered=self._kw.get("ordered", True),
                    name=self._kw.get("name",
                                      self._nested_cls.__name__.lower()))

    def build(self):
        if isinstance(self._input, (PaneFarm, WinMapReduce)):
            return self._nested_cls(self._input, **self._nested_kw())
        return _Builder.build(self)

    build_ptr = build
    build_unique = build


class WinFarm_Builder(_NestingMixin, _Builder, _WindowMixin, _WinParMixin):
    """builders.hpp:803."""
    _pattern_cls = WinFarm
    _nested_cls = WinFarmOf

    def withEmitters(self, n: int):
        self._kw["n_emitters"] = int(n)
        return self


class KeyFarm_Builder(_NestingMixin, _Builder, _WindowMixin, _WinParMixin):
    """builders.hpp:1193."""
    _pattern_cls = KeyFarm
    _nested_cls = KeyFarmOf

    def withRouting(self, routing):
        self._kw["routing"] = routing
        return self

    def _nested_kw(self):
        kw = super()._nested_kw()
        kw["routing"] = self._kw.get("routing")
        return kw

    def _build_kw(self):
        kw = dict(self._kw)
        kw.pop("ordered", None)  # plain Key_Farm workers are per-key-ordered
        return kw


class _TwoStageParMixin:
    def withParallelism(self, n1: int, n2: int):
        self._deg = (int(n1), int(n2))
        return self

    def withOrdered(self, flag: bool = True):
        self._kw["ordered"] = flag
        return self


class PaneFarm_Builder(_Builder, _WindowMixin, _TwoStageParMixin):
    """builders.hpp:1561."""
    _pattern_cls = PaneFarm

    def __init__(self, plq_func, wlq_func):
        super().__init__()
        self._kw["plq_func"] = plq_func
        self._kw["wlq_func"] = wlq_func
        self._deg = (1, 1)

    def incremental(self, plq: bool = None, wlq: bool = None):
        if plq is not None:
            self._kw["plq_incremental"] = plq
        if wlq is not None:
            self._kw["wlq_incremental"] = wlq
        return self

    def withResultFields(self, plq: dict = None, wlq: dict = None):
        if plq is not None:
            self._kw["plq_result_fields"] = dict(plq)
        if wlq is not None:
            self._kw["wlq_result_fields"] = dict(wlq)
        return self

    def _build_kw(self):
        kw = dict(self._kw)
        kw["plq_degree"], kw["wlq_degree"] = self._deg
        kw["opt_level"] = getattr(self, "_opt_level", 0)
        return kw


class WinMapReduce_Builder(_Builder, _WindowMixin, _TwoStageParMixin):
    """builders.hpp:1873."""
    _pattern_cls = WinMapReduce

    def __init__(self, map_func, reduce_func):
        super().__init__()
        self._kw["map_func"] = map_func
        self._kw["reduce_func"] = reduce_func
        self._deg = (2, 1)

    def incremental(self, map: bool = None, reduce: bool = None):
        if map is not None:
            self._kw["map_incremental"] = map
        if reduce is not None:
            self._kw["reduce_incremental"] = reduce
        return self

    def withResultFields(self, map: dict = None, reduce: dict = None):
        if map is not None:
            self._kw["map_result_fields"] = dict(map)
        if reduce is not None:
            self._kw["reduce_result_fields"] = dict(reduce)
        return self

    def _build_kw(self):
        kw = dict(self._kw)
        kw["map_degree"], kw["reduce_degree"] = self._deg
        kw["opt_level"] = getattr(self, "_opt_level", 0)
        return kw


# ------------------------------------------------------------- TPU builders

class _TPUMixin:
    """Device-path options shared by the five *TPU builders — the
    ``withBatch(batch_len, n_thread_block)`` family of the GPU builders
    (builders.hpp:987+) retargeted at XLA.

    Note on the native C++ hot loop: the resident device path runs its
    per-row bookkeeping in C++ (native/wf_native.cpp) only when the
    reduced payload field is **int64** (the native ABI ships one int64
    column); other payload dtypes transparently fall back to the pure
    -Python resident core — same results, slower host loop
    (patterns/native_core.py:_fall_back)."""

    def withBatch(self, batch_len: int, n_thread_block: int = None):
        self._kw["batch_len"] = int(batch_len)
        if n_thread_block is not None:
            warnings.warn("n_thread_block is a CUDA concept; XLA chooses "
                          "its own tiling — argument ignored", stacklevel=2)
        return self

    def withScratchpad(self, size: int):
        warnings.warn("withScratchpad applies to raw CUDA functors; the "
                      "JAX window-function contract passes columns instead "
                      "— argument ignored", stacklevel=2)
        return self

    def withDevice(self, device):
        self._kw["device"] = device
        return self

    def withDepth(self, depth: int):
        """Async launch pipeline depth (replaces per-batch stream sync)."""
        self._kw["depth"] = int(depth)
        return self

    def withPallas(self, flag: bool = True):
        self._kw["use_pallas"] = flag
        return self

    def withComputeDtype(self, dtype):
        self._kw["compute_dtype"] = dtype
        return self


class WinSeqTPU_Builder(WinSeq_Builder, _TPUMixin):
    """builders.hpp:682 (WinSeqGPU_Builder)."""
    _pattern_cls = WinSeqTPU


class WinFarmTPU_Builder(_Builder, _WindowMixin, _WinParMixin, _TPUMixin):
    """builders.hpp:987 (WinFarmGPU_Builder)."""
    _pattern_cls = WinFarmTPU

    def __init__(self, winfunc):
        super().__init__()
        self._kw["winfunc"] = winfunc

    def withOrdered(self, flag: bool = True):
        self._kw["ordered"] = flag
        return self


class KeyFarmTPU_Builder(_Builder, _WindowMixin, _WinParMixin, _TPUMixin):
    """builders.hpp:1366 (KeyFarmGPU_Builder)."""
    _pattern_cls = KeyFarmTPU

    def __init__(self, winfunc):
        super().__init__()
        self._kw["winfunc"] = winfunc

    def withRouting(self, routing):
        self._kw["routing"] = routing
        return self


class PaneFarmTPU_Builder(PaneFarm_Builder, _TPUMixin):
    """builders.hpp:1707 (PaneFarmGPU_Builder) — the 4 constructor families
    (GPU-PLQ/CPU-WLQ etc., pane_farm_gpu.hpp:176-480) become two placement
    flags."""
    _pattern_cls = PaneFarmTPU

    def plqOnDevice(self, flag: bool = True):
        self._kw["plq_on_device"] = flag
        return self

    def wlqOnDevice(self, flag: bool = True):
        self._kw["wlq_on_device"] = flag
        return self


class WinMapReduceTPU_Builder(WinMapReduce_Builder, _TPUMixin):
    """builders.hpp:2020 (WinMapReduceGPU_Builder)."""
    _pattern_cls = WinMapReduceTPU

    def mapOnDevice(self, flag: bool = True):
        self._kw["map_on_device"] = flag
        return self

    def reduceOnDevice(self, flag: bool = True):
        self._kw["reduce_on_device"] = flag
        return self
