"""ctypes bindings for the C++ native host runtime (native/wf_native.cpp).

The shared library is built on demand with ``make -C native`` (g++ only, no
third-party dependencies) and cached; if the toolchain is unavailable the
framework falls back to the pure-Python cores transparently.  Every call
into the library releases the GIL, so farm workers running native cores get
true multicore host parallelism — the FastFlow-pinned-threads property the
reference gets for free from being a C++ library.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO = os.path.join(_DIR, "libwfnative.so")

_lock = threading.Lock()
_lib = None
_tried = False

i64 = ctypes.c_longlong
p_i64 = ctypes.POINTER(i64)
p_i32 = ctypes.POINTER(ctypes.c_int32)
p_int = ctypes.POINTER(ctypes.c_int)


def _build() -> bool:
    src = os.path.join(_DIR, "wf_native.cpp")
    if not os.path.exists(src):
        return False
    # always invoke make: it no-ops when up to date and rebuilds when the
    # host fingerprint changed (host.tag — a -march=native .so cached on
    # another CPU would SIGILL; mtime alone cannot see that)
    try:
        subprocess.run(["make", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except Exception:
        # no toolchain: only trust an existing .so that is not stale
        # relative to the source (the pre-host.tag safety rule)
        return (os.path.exists(_SO)
                and os.path.getmtime(_SO) >= os.path.getmtime(src))


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            return _bind(ctypes.CDLL(_SO))
        except Exception:
            # dlopen failure or missing symbol (e.g. a truncated or
            # older-ABI .so that survived a failed rebuild): fall back to
            # the pure-Python cores instead of crashing the dataflow
            return None


def _bind(lib):
    global _lib
    lib.wf_core_new.restype = ctypes.c_void_p
    lib.wf_core_new.argtypes = ([i64] * 2 + [ctypes.c_int] * 2
                                + [i64] * 11 + [ctypes.c_int])
    lib.wf_core_free.argtypes = [ctypes.c_void_p]
    lib.wf_core_process.restype = i64
    lib.wf_core_process.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    i64, i64, i64, i64, i64, i64, i64]
    lib.wf_core_eos.restype = i64
    lib.wf_core_eos.argtypes = [ctypes.c_void_p]
    lib.wf_core_force_flush.restype = i64
    lib.wf_core_force_flush.argtypes = [ctypes.c_void_p]
    lib.wf_core_set_flush_rows.restype = None
    lib.wf_core_set_flush_rows.argtypes = [ctypes.c_void_p, i64]
    lib.wf_renum_new.restype = ctypes.c_void_p
    lib.wf_renum_new.argtypes = []
    lib.wf_renum_free.argtypes = [ctypes.c_void_p]
    lib.wf_renum_run.restype = None
    lib.wf_renum_run.argtypes = [ctypes.c_void_p, p_i64, i64, p_i64]
    lib.wf_renum_next.restype = i64
    lib.wf_renum_next.argtypes = [ctypes.c_void_p, i64]
    lib.wf_keymap_new.restype = ctypes.c_void_p
    lib.wf_keymap_new.argtypes = []
    lib.wf_keymap_free.argtypes = [ctypes.c_void_p]
    lib.wf_keymap_lookup.restype = i64
    lib.wf_keymap_lookup.argtypes = [ctypes.c_void_p, p_i64, i64, p_i64]
    lib.wf_keyscan_ordered.restype = i64
    lib.wf_keyscan_ordered.argtypes = [p_i64, p_i64, i64, p_i64, p_i64,
                                       p_i64, p_i64]
    lib.wf_cores_process_mt.restype = i64
    lib.wf_cores_process_mt.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), i64, ctypes.c_void_p,
        i64, i64, i64, i64, i64, i64, i64]
    lib.wf_max_fields.restype = i64
    lib.wf_max_fields.argtypes = []
    lib.wf_core_set_fields.restype = i64
    lib.wf_core_set_fields.argtypes = [ctypes.c_void_p, i64, p_int]
    lib.wf_cores_process_mt_f.restype = i64
    lib.wf_cores_process_mt_f.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), i64, ctypes.c_void_p,
        i64, i64, i64, i64, i64, i64, p_i64]
    lib.wf_launch_peek_wires.restype = ctypes.c_int
    lib.wf_launch_peek_wires.argtypes = [ctypes.c_void_p, p_int]
    lib.wf_launch_take_padded_f.restype = None
    lib.wf_launch_take_padded_f.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p), i64, i64,
        p_i64, p_i32, p_i32, p_i32, p_i64, p_i64, p_i64, p_i64, p_i64,
        p_i64]
    lib.wf_launch_pending.restype = i64
    lib.wf_launch_pending.argtypes = [ctypes.c_void_p]
    lib.wf_launch_peek.restype = ctypes.c_int
    lib.wf_launch_peek.argtypes = [ctypes.c_void_p, p_i64, p_i64, p_i64,
                                   p_int, p_int, p_i64, p_i64]
    lib.wf_launch_take.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   p_i64, p_i32, p_i32, p_i32,
                                   p_i64, p_i64, p_i64, p_i64]
    lib.wf_launch_take_padded.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, i64, i64,
        p_i64, p_i32, p_i32, p_i32, p_i64, p_i64, p_i64, p_i64, p_i64,
        p_i64]
    lib.wf_launch_peek_regular.restype = ctypes.c_int
    lib.wf_launch_peek_regular.argtypes = [ctypes.c_void_p, p_i64]
    lib.wf_launch_coalesce.restype = i64
    lib.wf_launch_coalesce.argtypes = [ctypes.c_void_p, i64, i64, i64]
    lib.wf_launch_take_regular.argtypes = [ctypes.c_void_p, p_i32,
                                           p_i32, p_i32, p_i32]
    lib.wf_queue_new.restype = ctypes.c_void_p
    lib.wf_queue_new.argtypes = [i64]
    lib.wf_queue_free.argtypes = [ctypes.c_void_p]
    lib.wf_queue_push.restype = ctypes.c_int
    lib.wf_queue_push.argtypes = [ctypes.c_void_p, i64, i64]
    lib.wf_queue_pop.restype = ctypes.c_int
    lib.wf_queue_pop.argtypes = [ctypes.c_void_p, p_i64, p_i64]
    lib.wf_queue_close.argtypes = [ctypes.c_void_p]
    # overload-policy entry points (runtime/overload.py) — absent from a
    # pre-robustness .so; bind tolerantly so an old library still serves
    # every default path and only the opt-in shed/deadline knobs fall back
    # to the Python queue (engine._make_inbox gates on this flag)
    try:
        lib.wf_queue_try_push.restype = ctypes.c_int
        lib.wf_queue_try_push.argtypes = [ctypes.c_void_p, i64, i64]
        lib.wf_queue_push_timed.restype = ctypes.c_int
        lib.wf_queue_push_timed.argtypes = [ctypes.c_void_p, i64, i64, i64]
        lib.wf_queue_try_pop.restype = ctypes.c_int
        lib.wf_queue_try_pop.argtypes = [ctypes.c_void_p, p_i64, p_i64]
        lib.wf_has_overload_queue = True
    except AttributeError:
        lib.wf_has_overload_queue = False
    # state-ABI entry points (checkpoints + keyed live rescale for the
    # native core, docs/ROBUSTNESS.md "Native state ABI") — absent from a
    # pre-ABI .so; bind tolerantly so an old library still serves every
    # default execution path while snapshot/migration requests decline
    # loudly (SnapshotUnsupported / check WF215 gate on this flag)
    try:
        lib.wf_abi_version.restype = i64
        lib.wf_abi_version.argtypes = []
        lib.wf_core_state_size.restype = i64
        lib.wf_core_state_size.argtypes = [ctypes.c_void_p]
        lib.wf_core_state_export.restype = i64
        lib.wf_core_state_export.argtypes = [ctypes.c_void_p,
                                             ctypes.c_void_p, i64]
        lib.wf_core_state_import.restype = i64
        lib.wf_core_state_import.argtypes = [ctypes.c_void_p,
                                             ctypes.c_void_p, i64]
        lib.wf_core_key_count.restype = i64
        lib.wf_core_key_count.argtypes = [ctypes.c_void_p]
        lib.wf_core_key_list.restype = i64
        lib.wf_core_key_list.argtypes = [ctypes.c_void_p, p_i64, i64]
        lib.wf_core_key_state_size.restype = i64
        lib.wf_core_key_state_size.argtypes = [ctypes.c_void_p, i64]
        lib.wf_core_key_export.restype = i64
        lib.wf_core_key_export.argtypes = [ctypes.c_void_p, i64,
                                           ctypes.c_void_p, i64]
        lib.wf_core_key_import.restype = i64
        lib.wf_core_key_import.argtypes = [ctypes.c_void_p,
                                           ctypes.c_void_p, i64]
        lib.wf_core_key_neutralize.restype = i64
        lib.wf_core_key_neutralize.argtypes = [ctypes.c_void_p, i64]
        lib.wf_has_state_abi = True
    except AttributeError:
        lib.wf_has_state_abi = False
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def enabled():
    """The native library, or None when unavailable or opted out via
    WF_NO_NATIVE=1 — the single selection gate for every native-vs-Python
    choice (cores, engine channels)."""
    if os.environ.get("WF_NO_NATIVE", "") == "1":
        return None
    return load()
