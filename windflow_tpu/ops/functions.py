"""Window-function contracts: non-incremental, incremental, and batched.

The reference supports two user-function shapes per window pattern
(``win_seq.hpp:116-117``):

* non-incremental (NIC): ``winFunction(key, gwid, Iterable<tuple>, result&)``
  evaluated over the whole window content on fire;
* incremental (INC): ``winUpdate(key, gwid, tuple, result&)`` folded per
  tuple as it arrives.

Its GPU path additionally requires a CUDA-compilable functor over flat arrays
(``win_seq_gpu.hpp:54-67``): ``F(key, gwid, data*, result*, size, scratch*)``.

A TPU cannot JIT arbitrary host C++/Python per window, so this framework
defines the device contract at the *batch* level: a window function may
provide ``apply_batch(keys, gwids, cols, lens)`` where ``cols`` maps each
payload field to a ``(n_windows, pad_len)`` array and ``lens`` gives the
valid prefix per window.  Built-in monoid reducers implement all three
shapes; arbitrary user JAX functions are wrapped by :class:`JaxWindowFunction`
which vmaps them over the window batch; arbitrary Python functions fall back
to the host path.
"""

from __future__ import annotations

import numpy as np


class WindowFunction:
    """Non-incremental window function (host contract).

    Subclasses implement :meth:`apply`; implementing :meth:`apply_batch`
    opts into the batched/device path.
    """

    #: name -> numpy dtype of the produced result payload
    result_fields: dict
    #: input columns apply_batch needs (None = all); declaring them lets the
    #: engine gather/stage only what the function reads
    required_fields = None

    def apply(self, key: int, gwid: int, rows: np.ndarray) -> tuple:
        """Evaluate one window. `rows` is a structured array of the tuples in
        the window (possibly empty). Returns the result payload values in
        `result_fields` order."""
        raise NotImplementedError

    def apply_batch(self, keys, gwids, cols, lens):
        """Optional vectorised evaluation of many windows at once.

        cols: {field: (n, pad)} padded columns; lens: (n,) valid lengths.
        Returns {field: (n,)} result payload columns. Padding rows are zeros.
        """
        raise NotImplementedError

    @property
    def supports_batch(self) -> bool:
        return type(self).apply_batch is not WindowFunction.apply_batch


class WindowUpdate:
    """Incremental per-tuple fold (host contract, O(1) state per window)."""

    result_fields: dict

    def init(self, key: int, gwid: int) -> np.void:
        """Fresh accumulator record (defaults to zeros)."""
        dt = np.dtype([(k, v) for k, v in self.result_fields.items()])
        return np.zeros((), dtype=dt)

    def update(self, key: int, gwid: int, row: np.void, acc: np.void) -> None:
        raise NotImplementedError

    def update_many(self, key: int, gwid: int, rows: np.ndarray, acc: np.void) -> None:
        """Fold a chunk of in-order rows; default is a per-row loop —
        monoid reducers override with a vectorised fold."""
        for row in rows:
            self.update(key, gwid, row, acc)


class FnWindowFunction(WindowFunction):
    """Adapts a plain Python callable ``fn(key, gwid, rows) -> value(s)``."""

    def __init__(self, fn, result_fields):
        self.fn = fn
        self.result_fields = dict(result_fields)

    def apply(self, key, gwid, rows):
        out = self.fn(key, gwid, rows)
        return out if isinstance(out, tuple) else (out,)


class FnWindowUpdate(WindowUpdate):
    """Adapts a plain Python callable ``fn(key, gwid, row, acc) -> None``."""

    def __init__(self, fn, result_fields):
        self.fn = fn
        self.result_fields = dict(result_fields)

    def update(self, key, gwid, row, acc):
        self.fn(key, gwid, row, acc)


from .monoid import NP_UFUNCS as _UFUNCS
from .monoid import identity as _monoid_identity


class Reducer(WindowFunction, WindowUpdate):
    """Built-in monoid reduction over one payload field.

    Serves as NIC function, INC update, *and* batched/device function —
    the three are algebraically identical for a monoid, which the
    differential tests rely on (mirroring the reference's NIC/INC parity
    in ``src/sum_test_cpu/test_all_cb.cpp``).
    """

    def __init__(self, op: str, field: str = "value", out_field: str = None,
                 dtype=np.int64, value_range=None):
        if op == "count":
            self.ufunc = None
        else:
            self.ufunc = _UFUNCS[op]
        self.op = op
        self.field = field
        self.out_field = out_field or field
        self.dtype = np.dtype(dtype)
        self.result_fields = {self.out_field: self.dtype}
        self.required_fields = () if op == "count" else (self.field,)
        #: optional (lo, hi) bound on the input field's values — lets the
        #: device path prove a narrow accumulate dtype cannot wrap (e.g.
        #: values in [0, 100) summed over a 256-row window fit int32) and
        #: skip the wrap warning that would otherwise fire on dtypes alone
        self.value_range = value_range

    # identity element for empty windows / fresh accumulators
    def _identity(self):
        return _monoid_identity(self.op, self.dtype)

    # --- NIC ---
    def apply(self, key, gwid, rows):
        if self.op == "count":
            return (len(rows),)
        if len(rows) == 0:
            return (self.dtype.type(self._identity()),)
        return (self.ufunc.reduce(rows[self.field].astype(self.dtype)),)

    def apply_batch(self, keys, gwids, cols, lens):
        n, pad = next(iter(cols.values())).shape if cols else (len(lens), 0)
        if self.op == "count":
            return {self.out_field: lens.astype(self.dtype)}
        vals = cols[self.field].astype(self.dtype)
        mask = np.arange(pad)[None, :] < lens[:, None]
        ident = self.dtype.type(self._identity())
        vals = np.where(mask, vals, ident)
        return {self.out_field: self.ufunc.reduce(vals, axis=1)}

    # --- INC ---
    def init(self, key, gwid):
        acc = np.zeros((), dtype=np.dtype([(self.out_field, self.dtype)]))
        acc[self.out_field] = self._identity()
        return acc

    def update(self, key, gwid, row, acc):
        if self.op == "count":
            acc[self.out_field] += 1
        else:
            acc[self.out_field] = self.ufunc(
                acc[self.out_field], self.dtype.type(row[self.field]))

    def update_many(self, key, gwid, rows, acc):
        if self.op == "count":
            acc[self.out_field] += len(rows)
        elif len(rows):
            acc[self.out_field] = self.ufunc(
                acc[self.out_field],
                self.ufunc.reduce(rows[self.field].astype(self.dtype)))

    @property
    def supports_batch(self):
        return True


class MultiReducer(WindowFunction, WindowUpdate):
    """Several monoid stats over the same windows in one evaluation — e.g.
    YSB's per-campaign COUNT(*) + MAX(ts) (yahoo_app.hpp:150-156), or
    count + sum + max of one value column.

    ``stats`` are (op, field, out_field) triples or ready Reducers.  Like
    :class:`Reducer` it serves as NIC function, INC update, and batched
    function; the resident device path evaluates every non-count stat over
    ONE shipped column set in one fused dispatch (count is answered
    host-side from the window lengths — no device work).
    """

    def __init__(self, *stats, dtype=np.int64):
        parts = []
        for s in stats:
            if isinstance(s, Reducer):
                parts.append(s)
            else:
                op, field, out_field = s
                parts.append(Reducer(op, field or "value", out_field,
                                     dtype=dtype))
        if not parts:
            raise ValueError("MultiReducer needs at least one stat")
        outs = [p.out_field for p in parts]
        if len(set(outs)) != len(outs):
            raise ValueError(f"duplicate out_fields: {outs}")
        self.parts = parts
        self.result_fields = {}
        for p in parts:
            self.result_fields.update(p.result_fields)
        self.required_fields = tuple(dict.fromkeys(
            f for p in parts for f in p.required_fields))

    @property
    def device_parts(self):
        """Stats needing device evaluation (count is free host-side)."""
        return [p for p in self.parts if p.op != "count"]

    @property
    def count_parts(self):
        return [p for p in self.parts if p.op == "count"]

    # --- NIC ---
    def apply(self, key, gwid, rows):
        return tuple(v for p in self.parts for v in p.apply(key, gwid, rows))

    def apply_batch(self, keys, gwids, cols, lens):
        out = {}
        for p in self.parts:
            out.update(p.apply_batch(keys, gwids, cols, lens))
        return out

    # --- INC ---
    def init(self, key, gwid):
        acc = np.zeros((), dtype=np.dtype(
            [(k, v) for k, v in self.result_fields.items()]))
        for p in self.parts:
            if p.op != "count":
                acc[p.out_field] = p._identity()
        return acc

    def update(self, key, gwid, row, acc):
        for p in self.parts:
            p.update(key, gwid, row, acc)

    def update_many(self, key, gwid, rows, acc):
        for p in self.parts:
            p.update_many(key, gwid, rows, acc)

    @property
    def supports_batch(self):
        return True


def as_window_function(f, result_fields=None) -> WindowFunction:
    if isinstance(f, WindowFunction):
        return f
    if callable(f):
        if result_fields is None:
            raise ValueError("result_fields required for a plain callable")
        return FnWindowFunction(f, result_fields)
    raise TypeError(f"cannot interpret {f!r} as a window function")


def as_window_update(f, result_fields=None) -> WindowUpdate:
    if isinstance(f, WindowUpdate):
        return f
    if callable(f):
        if result_fields is None:
            raise ValueError("result_fields required for a plain callable")
        return FnWindowUpdate(f, result_fields)
    raise TypeError(f"cannot interpret {f!r} as a window update")
