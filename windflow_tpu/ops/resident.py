"""Device-resident window archives: each stream row crosses the host→device
wire ONCE and window evaluation reads HBM.

This is the second-generation device path (the first, ``device.py``, restages
every fired window's archive segment per batch, mirroring the reference's
per-batch ``cudaMemcpyAsync`` of ``Bin`` — win_seq_gpu.hpp:451-476).  Measured
on the tunneled v5e (see BASELINE.md), the wire — not the chip — is the
budget: ~120 ms round-trip latency and ~50 MB/s host→device bandwidth, while
on-device work (cumsum over the whole ring, (B, pad) gathers) is effectively
free.  The design therefore:

* keeps a per-key **ring archive** resident on the device: a ``(KP, cap)``
  array whose row ``r`` holds the live tuples of dense-key ``r`` in arrival
  order (the device twin of ``core/archive.py``'s host ``KeyArchive``);
* appends each chunk's new rows as ONE rectangle in the **narrowest dtype**
  that holds the chunk's value range (int8/int16/int32/float32), widened to
  the accumulate dtype on device;
* fuses append + window evaluation into ONE dispatch per launch: a vmapped
  ``dynamic_update_slice`` writes the rectangle at per-key offsets, then
  either a ring-wide ``cumsum`` + two-point gather (sum/mean — O(B) gathered
  elements instead of O(B·win)) or a masked ``(B, pad)`` gather-reduce
  (min/max) evaluates every fired window;
* fetches results asynchronously (``copy_to_host_async``) with bounded
  depth, so steady state pipelines H2D, compute, and D2H over the tunnel.

The host side (``ResidentWinSeqCore`` in patterns/win_seq_tpu.py) owns all
bookkeeping — write offsets, ring rebase, window descriptors — so this
executor is a dumb, replayable launch queue, like the reference's per-worker
``cudaStream_t`` (win_seq_gpu.hpp:294).
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import profile
from .device import _bucket
from .monoid import identity as _identity

#: process-wide compiled-step cache (executors are per-pattern-instance,
#: the executables they compile should outlive them)
_STEP_CACHE = {}
#: step-cache keys added by prewarm_regular_ladder (never seed ladders)
_PREWARMED = set()

# -- wire diagnostics (always on: one lock round-trip per dispatch) ---------
# The bench's artifact of record must distinguish a weather-trashed capture
# from a regression (VERDICT r2), so every resident dispatch feeds these
# process-wide counters: dispatch count, merge count (launches fused by
# wf_launch_coalesce), and wall service time from dispatch to result-ready.

_STATS_MU = threading.Lock()
_STATS = {"dispatches": 0, "merges": 0, "svc_s_sum": 0.0, "svc_n": 0}


def stats_add(name: str, value=1):
    with _STATS_MU:
        _STATS[name] = _STATS.get(name, 0) + value


def stats_max(name: str, value):
    """High-water gauge (e.g. the deepest proactive flush multiple a run
    reached) — snapshot/reset like the counters."""
    with _STATS_MU:
        if value > _STATS.get(name, 0):
            _STATS[name] = value


def stats_snapshot(reset: bool = False) -> dict:
    """{"dispatches", "merges", "mean_launch_ms"} since the last reset."""
    with _STATS_MU:
        snap = dict(_STATS)
        if reset:
            for k in _STATS:
                _STATS[k] = 0
    n = snap.pop("svc_n")
    s = snap.pop("svc_s_sum")
    snap["mean_launch_ms"] = round(1e3 * s / n, 2) if n else 0.0
    return snap

_REDUCE_OPS = ("sum", "min", "max", "prod")

#: process-global wire-weather record: an EMA of RAW per-dispatch launch
#: service in ms, deliberately NOT normalized by dispatch size — the
#: sizing rule's thresholds (_pick_flush_mult) are calibrated for raw
#: values, and the 2026-07-31 A/B showed service is not size-linear on
#: this wire.  It outlives executors, so a timed run can size its first
#: dispatches from the warmup run's measured weather instead of
#: discovering the stall one small launch at a time — the proactive half
#: of dispatch sizing (VERDICT r3 item 1; the reactive half is
#: wf_launch_coalesce).
_WEATHER = {"ema_ms": None, "recent": deque(maxlen=16), "floor_ms": None}
_WEATHER_MU = threading.Lock()


def note_wire_service_ms(ms: float, weight: float = 0.2):
    """Fold one raw per-dispatch launch-service observation (ms) into the
    global wire-weather EMA and the recent-window floor.  Mutation and
    the floor recompute happen under one lock (harvests run on ship
    threads AND node threads concurrently); readers get atomic floats."""
    with _WEATHER_MU:
        prev = _WEATHER["ema_ms"]
        _WEATHER["ema_ms"] = ms if prev is None else (
            (1.0 - weight) * prev + weight * ms)
        _WEATHER["recent"].append(ms)
        _WEATHER["floor_ms"] = min(_WEATHER["recent"])


def wire_weather_ms():
    """Current wire-weather estimate (None before any observation)."""
    return _WEATHER["ema_ms"]


def wire_service_floor_ms():
    """BEST per-launch service among the recent observations (None before
    any) — the feasibility statistic for budget-aware routing: a latency
    budget the wire cannot meet even at its recent best is unmeetable by
    construction, while mean-based statistics get poisoned by the
    one-off compile launches a warmup run necessarily pays (a warmup EMA
    of 915 ms was measured against a ~200 ms steady-state floor)."""
    return _WEATHER["floor_ms"]


class RingSnapshot:
    """Checkpoint handle over a resident ring archive (recovery layer,
    docs/ROBUSTNESS.md "Recovery").

    Grabbing one is cheap: jax arrays are functional, so holding the
    current ring reference IS a consistent copy — each later launch
    produces a *new* ring array and never mutates this one.  The
    device→host transfer starts immediately (``copy_to_host_async``) but
    materialises only at :meth:`resolve` — on the checkpoint writer
    thread — so the copy overlaps the ring's ongoing compute instead of
    stalling it (the CTA-pipelining hide-latency-with-stages idiom
    applied to snapshots)."""

    __slots__ = ("rings", "KP", "cap")

    def __init__(self, rings, KP: int, cap: int):
        self.rings = rings      # tuple of device arrays, or None (lazy ring)
        self.KP = KP
        self.cap = cap
        if rings is not None:
            for r in rings:
                getattr(r, "copy_to_host_async", lambda: None)()

    def resolve(self) -> dict:
        """Materialise to host numpy (pickle-ready)."""
        rings = (None if self.rings is None
                 else tuple(np.asarray(r) for r in self.rings))
        return {"rings": rings, "KP": self.KP, "cap": self.cap}


def _pad2(a, rows, cols):
    out = np.zeros((rows, cols), dtype=a.dtype)
    out[:a.shape[0], :a.shape[1]] = a
    return out


def _pad1(a, size, dtype=np.int32):
    out = np.zeros(size, dtype=dtype)
    out[:len(a)] = a
    return out


def _check_ring_overflow(offs, Rb, cap):
    """dynamic_update_slice clamps the start, which would silently
    overwrite live cells near the ring end — the host core's rebase
    invariant must prevent ever getting here."""
    if len(offs) and int(offs.max()) + Rb > cap:
        raise ValueError(
            f"ring overflow: offset {int(offs.max())} + {Rb} > {cap}")


def _regular_body(cap, C, slide, acc_dt, ring, blk, offs, rstart0, rlen):
    """Fused append + regular-window sum over one ring (block): window i of
    ring row r starts at rstart0[r] + i*slide with length rlen[r] — the
    descriptors are expanded on the device from per-key scalars via an
    iota.  Returns (ring, (rows, C) sums)."""
    blk = blk.astype(acc_dt)
    ring = jax.vmap(
        lambda row, b, o: lax.dynamic_update_slice(row, b, (o,))
    )(ring, blk, offs)
    cs = jnp.cumsum(ring, axis=1)
    cs = jnp.pad(cs, ((0, 0), (1, 0)))
    iota = jnp.arange(C, dtype=jnp.int32)
    s2 = jnp.clip(rstart0[:, None] + iota[None, :] * slide, 0, cap)
    e2 = jnp.clip(s2 + rlen[:, None], 0, cap)
    rows = jnp.arange(ring.shape[0], dtype=jnp.int32)[:, None]
    out = cs[rows, e2] - cs[rows, s2]
    return ring, out


def _make_regular_step(key):
    (_, _op, cap, R, KP, C, blk_dt, acc_dt, slide) = key
    acc_dt = np.dtype(acc_dt)

    def step(ring, blk, offs, rcount, rstart0, rlen):
        return _regular_body(cap, C, slide, acc_dt, ring, blk, offs,
                             rstart0, rlen)

    return jax.jit(step)


def _make_mesh_regular_step(key):
    """Sharded regular step: shard_map of :func:`_regular_body` over the
    key-group axis — each device appends its row block and expands its own
    per-key arithmetic window sequences (no collectives, like the plain
    mesh step)."""
    (_tag, _op, cap, Rb, KP, C, blk_dt, acc_dt, slide, mesh, axis) = key
    acc_dt = np.dtype(acc_dt)
    from jax.sharding import PartitionSpec as P

    def local(ring, blk, offs, rcount, rstart0, rlen):
        return _regular_body(cap, C, slide, acc_dt, ring, blk, offs,
                             rstart0, rlen)

    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis), P(axis), P(axis),
                  P(axis)),
        out_specs=(P(axis, None), P(axis, None)))
    return jax.jit(mapped)


def _ring_append(ring, blk, offs, acc_dt):
    """Vmapped per-row append: write each key's new-row slice at its ring
    offset, widening the wire dtype to the accumulate dtype."""
    blk = blk.astype(acc_dt)
    return jax.vmap(
        lambda row, b, o: lax.dynamic_update_slice(row, b, (o,))
    )(ring, blk, offs)


def _ring_eval(op, cap, pad, acc_dt, ring, rows, starts, lens):
    """Evaluate one monoid over every described window: cumsum two-point
    gather (sum) or masked (B, pad) gather-reduce (min/max/prod)."""
    if op == "sum":
        cs = jnp.cumsum(ring, axis=1)
        cs = jnp.pad(cs, ((0, 0), (1, 0)))
        return cs[rows, starts + lens] - cs[rows, starts]
    idx = jnp.minimum(
        starts[:, None] + jnp.arange(pad, dtype=jnp.int32)[None, :],
        cap - 1)
    vals = ring[rows[:, None], idx]
    mask = jnp.arange(pad, dtype=jnp.int32)[None, :] < lens[:, None]
    ident = jnp.asarray(_identity(op, acc_dt), dtype=acc_dt)
    red = {"min": jnp.min, "max": jnp.max, "prod": jnp.prod}[op]
    return red(jnp.where(mask, vals, ident), axis=1)


def _append_eval(ops, cap, pad, acc_dt, ring, blk, offs, rows, starts,
                 lens):
    """The shared fused append + window-eval body — one append, then every
    stat of `ops` evaluated over the same ring (multi-stat: e.g. YSB's
    sum/max over one shipped column set in one dispatch).  Returns the ring
    and one output per op."""
    ring = _ring_append(ring, blk, offs, acc_dt)
    outs = tuple(_ring_eval(op, cap, pad, acc_dt, ring, rows, starts, lens)
                 for op in ops)
    return ring, outs


def _make_step(key):
    """Build + jit the fused append+eval step for one shape bucket."""
    (ops, cap, R, B, KP, blk_dt, acc_dt, pad) = key
    acc_dt = np.dtype(acc_dt)

    def step(ring, blk, offs, wrows, wstarts, wlens):
        ring, outs = _append_eval(ops, cap, pad, acc_dt, ring, blk, offs,
                                  wrows, wstarts, wlens)
        return ring, (outs[0] if len(outs) == 1 else outs)

    return jax.jit(step)


def _make_mesh_step(key):
    """Build + jit the sharded fused append+eval step: shard_map over the
    key-group axis — each device appends to and evaluates windows over its
    own row block of the ring (key groups are embarrassingly parallel, so
    the program has no collectives; the sharding just keeps each group's
    archive in its own chip's HBM)."""
    (_, ops, cap, Rb, Bs, KP, blk_dt, acc_dt, pad, mesh, axis) = key
    acc_dt = np.dtype(acc_dt)
    from jax.sharding import PartitionSpec as P

    def local(ring, blk, offs, lrows, lstarts, llens):
        # per-shard views: ring (rps, cap), blk (rps, Rb), offs (rps,),
        # descriptors (1, Bs) — local rows/starts/lens of this shard's
        # windows (host pre-grouped them per shard)
        ring, outs = _append_eval(ops, cap, pad, acc_dt, ring, blk, offs,
                                  lrows[0], lstarts[0], llens[0])
        outs = tuple(o[None, :] for o in outs)
        return ring, (outs[0] if len(outs) == 1 else outs)

    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis),
                  P(axis, None), P(axis, None), P(axis, None)),
        out_specs=(P(axis, None), P(axis, None)))
    return jax.jit(mapped)


class ResidentWindowExecutor:
    """Launch queue over a device-resident ring archive.

    The caller fully specifies each dispatch (rectangle, offsets, window
    descriptors in ring coordinates); this class handles shape bucketing,
    dtype narrowing/widening, the ring array's lifetime, and asynchronous
    result harvest.  ``op`` is one of sum/min/max/prod ("count" needs no
    device work — the host core answers it from window lengths; "mean" is
    answered by the segment-restaging path, ops/device.py).
    """

    def __init__(self, op, device=None, depth: int = 8,
                 acc_dtype=np.int32):
        # `op` is one reduce op or a tuple of them: every op evaluates over
        # the SAME ring in one fused dispatch (multi-stat windows — the
        # device side of ops.functions.MultiReducer)
        self.single = isinstance(op, str)
        self.ops = (op,) if self.single else tuple(op)
        for o in self.ops:
            if o not in _REDUCE_OPS:
                raise ValueError(f"unsupported resident op {o!r}")
        if not self.ops:
            raise ValueError("need at least one resident op")
        self.op = self.ops[0]
        self.device = device or jax.devices()[0]
        self.depth = depth
        self.acc_dtype = np.dtype(acc_dtype)
        self.cap = 0          # ring columns (set on first reset)
        self.KP = 0           # ring rows (padded key count)
        self._ring = None
        self._inflight = deque()   # (meta, sel, device_out, t_dispatch)
        self._ready = []
        self._svc = deque(maxlen=32)   # recent dispatch→ready seconds
        self._svc_mean = 0.0

    # ------------------------------------------------------------ lifecycle

    def reset(self, n_keys: int, cap: int):
        """(Re)allocate an empty ring of at least (n_keys, cap); contents
        are repopulated by the next launch's rectangle (host rebase)."""
        self.KP = _bucket(max(n_keys, 1))
        self.cap = _bucket(max(cap, 16))
        self._ring = None  # lazily zeros on next launch

    def _ring_arr(self):
        if self._ring is None:
            self._ring = jax.device_put(
                jnp.zeros((self.KP, self.cap), dtype=self.acc_dtype),
                self.device)
        return self._ring

    # ---------------------------------------------------- checkpoint/restore

    def _ring_placement(self):
        """Where restored rings land (mesh executors override with their
        NamedSharding)."""
        return self.device

    def _rings_tuple(self):
        """Current ring array(s) as a tuple, or None if lazily unbuilt
        (the multi-field executor overrides the pair of accessors; the
        checkpoint methods below are shared)."""
        return None if self._ring is None else (self._ring,)

    def _rings_assign(self, rings):
        self._ring = None if rings is None else rings[0]

    def ring_snapshot(self) -> RingSnapshot:
        """Consistent-copy handle of the ring(s) (caller must have
        drained in-flight launches first — their appends are already IN
        this ring version, but their undelivered results would be
        lost)."""
        if self._inflight:
            raise RuntimeError("ring_snapshot with launches in flight; "
                               "drain() first")
        return RingSnapshot(self._rings_tuple(), self.KP, self.cap)

    def ring_restore(self, snap):
        """Reinstate a snapshot (RingSnapshot or its resolved dict) and
        clear the launch queue."""
        data = snap.resolve() if isinstance(snap, RingSnapshot) else snap
        self._inflight.clear()
        self._ready = []
        self.KP = data["KP"]
        self.cap = data["cap"]
        rings = data["rings"]
        self._rings_assign(None if rings is None else tuple(
            jax.device_put(r, self._ring_placement()) for r in rings))

    def invalidate(self):
        """Drop the ring(s) and launch queue entirely: the owning
        core's next flush rebases, rebuilding the ring from host-live
        archive rows (the no-ring-snapshot restore path)."""
        self._inflight.clear()
        self._ready = []
        self._rings_assign(None)
        self.KP = 0
        self.cap = 0

    # ------------------------------------------------------------- dispatch

    def narrow(self, vals: np.ndarray) -> np.dtype:
        """Narrowest wire dtype holding `vals` exactly, capped by the
        accumulate dtype: ints narrow to int8/int16/int32 (int64 allowed
        when accumulating in a 64-bit dtype); floats ship in the
        accumulate precision."""
        wide = self.acc_dtype.itemsize >= 8
        if vals.dtype.kind == "f":
            return np.dtype(np.float64 if wide else np.float32)
        if not len(vals):
            return np.dtype(np.int8)
        lo, hi = int(vals.min()), int(vals.max())
        ladder = (np.int8, np.int16, np.int32, np.int64) if wide else \
                 (np.int8, np.int16, np.int32)
        for dt in ladder:
            info = np.iinfo(dt)
            if info.min <= lo and hi <= info.max:
                return np.dtype(dt)
        return np.dtype(ladder[-1])  # wraps; the core warned at
        # construction when the result dtype exceeds the accumulate dtype

    def launch(self, meta, blk: np.ndarray, offs: np.ndarray,
               wrows: np.ndarray, wstarts: np.ndarray, wlens: np.ndarray):
        """One fused append+eval dispatch.

        blk: (K, R) new rows per dense key (narrow dtype, zero-padded);
        offs: (K,) per-key ring write offsets; wrows/wstarts/wlens: (B,)
        fired-window descriptors in ring coordinates.  `meta` is returned
        with the results at harvest.  Caller guarantees offs + R <= cap.
        """
        K, R = blk.shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        B = len(wstarts)
        Rb = _bucket(max(R, 1))
        Bb = _bucket(max(B, 1))
        _check_ring_overflow(offs, Rb, self.cap)
        pad = (_bucket(int(wlens.max()) if B else 1)
               if any(o != "sum" for o in self.ops) else 0)
        key = (self.ops, self.cap, Rb, Bb, self.KP, blk.dtype.str,
               self.acc_dtype.str, pad)
        fn = _STEP_CACHE.get(key)
        if fn is None:
            fn = _STEP_CACHE[key] = _make_step(key)
        with profile.span("device_put"):
            blkp = (blk if blk.shape == (self.KP, Rb)
                    else _pad2(blk, self.KP, Rb))
            args = jax.device_put(
                (blkp, _pad1(offs, self.KP),
                 _pad1(wrows, Bb), _pad1(wstarts, Bb), _pad1(wlens, Bb)),
                self.device)
        profile.add("bytes_shipped", blk.nbytes)
        profile.add("rows_shipped", blk.size)
        profile.add("windows", B)
        with profile.span("dispatch"):
            self._ring, out = fn(self._ring_arr(), *args)
            for o in (out if isinstance(out, tuple) else (out,)):
                getattr(o, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        self._inflight.append((meta, B, out, time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    def launch_regular(self, meta, blk: np.ndarray, offs: np.ndarray,
                       rcount: np.ndarray, rstart0: np.ndarray,
                       rlen: np.ndarray, slide: int, wrows: np.ndarray,
                       widx: np.ndarray, cmax: int = 0):
        """Fused append+eval with *regular* window descriptors: per ring
        row, windows i in [0, rcount[r]) start at rstart0[r] + i*slide with
        length rlen[r] — only 3 per-key scalars cross the wire instead of
        3 arrays of B int32 (sum only; the host maps the (KP, C) result
        back to pending-window order via (wrows, widx))."""
        if not (self.single and self.op == "sum"):
            raise ValueError("regular descriptors implemented for "
                             "single-stat sum")
        K, R = blk.shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        Rb = _bucket(max(R, 1))
        C = _bucket(int(cmax) if cmax else
                    (int(rcount.max()) if len(rcount) else 1))
        _check_ring_overflow(offs, Rb, self.cap)
        key = ("reg", self.op, self.cap, Rb, self.KP, C, blk.dtype.str,
               self.acc_dtype.str, int(slide))
        fn = _STEP_CACHE.get(key)
        if fn is None:
            fn = _STEP_CACHE[key] = _make_regular_step(key)
        with profile.span("device_put"):
            blkp = (blk if blk.shape == (self.KP, Rb)
                    else _pad2(blk, self.KP, Rb))
            args = jax.device_put(
                (blkp, _pad1(offs, self.KP),
                 _pad1(rcount, self.KP), _pad1(rstart0, self.KP),
                 _pad1(rlen, self.KP)),
                self.device)
        profile.add("bytes_shipped", blk.nbytes)
        profile.add("rows_shipped", blk.size)
        profile.add("windows", len(wrows))
        with profile.span("dispatch"):
            self._ring, out = fn(self._ring_arr(), *args)
            getattr(out, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        self._inflight.append((meta, (np.asarray(wrows), np.asarray(widx)),
                               out, time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    # -------------------------------------------------------------- harvest

    def _note_service(self, t0: float):
        dt = time.perf_counter() - t0
        self._svc.append(dt)
        # fold the window mean here, on the harvesting thread: readers on
        # OTHER threads (the proactive flush sizer runs on the node
        # thread) then see one atomic float instead of iterating a deque
        # that a ship thread is appending to
        self._svc_mean = sum(self._svc) / len(self._svc)
        stats_add("svc_s_sum", dt)
        stats_add("svc_n", 1)
        # always-on wire weather: the budget-aware core routing
        # (win_seq_tpu.make_core_for) reads this EMA at construction
        # time, so a warmup run must seed it unconditionally — not only
        # when the opt-in proactive sizer is enabled
        note_wire_service_ms(1e3 * dt)

    def mean_service_s(self) -> float:
        """Mean dispatch→ready wall time of recent launches (slightly
        overestimates when results sit ready before the next harvest poll;
        the poll cadence is the chunk cadence, well under the ~20 ms
        threshold the adaptive coalescer keys on).  Safe to read from any
        thread."""
        return self._svc_mean

    def _harvest_one(self):
        meta, sel, out, t0 = self._inflight.popleft()
        multi = isinstance(out, tuple)
        with profile.span("harvest_wait"):
            arrs = ([np.asarray(o) for o in out] if multi
                    else [np.asarray(out)])
        self._note_service(t0)
        if isinstance(sel, tuple):   # regular/mesh: index map -> flat (B,)
            arrs = [a[sel[0], sel[1]] for a in arrs]
        else:
            arrs = [a[:sel] for a in arrs]
        self._ready.append((meta, tuple(arrs) if multi else arrs[0]))

    def poll(self):
        """Harvest completed launches without blocking on the rest."""
        while self._inflight and self._is_ready(self._inflight[0][2]):
            self._harvest_one()
        ready, self._ready = self._ready, []
        return ready

    def unready_count(self) -> int:
        """Dispatches still being serviced by the device/wire (the ship
        throttle's saturation signal)."""
        return sum(1 for entry in self._inflight
                   if not self._is_ready(entry[2]))

    @staticmethod
    def _is_ready(out) -> bool:
        try:
            if isinstance(out, tuple):
                return all(o.is_ready() for o in out)
            return out.is_ready()
        except AttributeError:
            return True

    def drain(self):
        # EOS drain taper, part 1 (VERDICT r4 #3): issue async D2H copies
        # for EVERY in-flight result before the serial harvest blocks on
        # the first — the remaining launches' compute and result copies
        # then overlap the waits instead of paying one wire round-trip
        # each, strictly in arrival order
        for entry in self._inflight:
            out = entry[2]
            for o in (out if isinstance(out, tuple) else (out,)):
                try:
                    o.copy_to_host_async()
                except AttributeError:
                    pass
        while self._inflight:
            self._harvest_one()
        ready, self._ready = self._ready, []
        return ready


def _make_multi_step(key, jax_fn):
    """Fused multi-field append + eval: one ring per field, reducer stats
    evaluate over their field's ring, and an optional batched JAX window
    function (JaxWindowFunction) reads (B, pad) gathers of every field —
    the device-resident form of the reference's arbitrary device functor
    over whole POD tuples (win_seq_gpu.hpp:54-67): every column crosses
    the wire once, the functor reads HBM."""
    (fields, stats, _fnid, cap, Rb, Bb, KP, wires, accs, pad) = key
    acc_dts = tuple(np.dtype(a) for a in accs)
    fidx = {f: i for i, f in enumerate(fields)}

    def step(rings, blks, offs, wrows, wstarts, wlens, wkeys, wgwids):
        rings = tuple(_ring_append(r, b, offs, dt)
                      for r, b, dt in zip(rings, blks, acc_dts))
        outs = []
        for op, f in stats:
            outs.append(_ring_eval(op, cap, pad, acc_dts[fidx[f]],
                                   rings[fidx[f]], wrows, wstarts, wlens))
        if jax_fn is not None:
            idx = jnp.minimum(
                wstarts[:, None] + jnp.arange(pad, dtype=jnp.int32)[None, :],
                cap - 1)
            mask = jnp.arange(pad, dtype=jnp.int32)[None, :] < wlens[:, None]
            cols = {}
            for f in jax_fn.fields:
                vals = rings[fidx[f]][wrows[:, None], idx]
                cols[f] = jnp.where(mask, vals, 0)
            res = jax_fn.fn(wkeys, wgwids, cols, mask)
            outs.extend(res if isinstance(res, tuple) else (res,))
        return rings, tuple(outs)

    return jax.jit(step)


class MultiFieldResidentExecutor(ResidentWindowExecutor):
    """Resident launch queue with one ring PER FIELD: multi-field
    reducer stats (e.g. sum(a) + max(b)) and arbitrary batched JAX window
    functions evaluate over device-resident archives — rows cross the
    wire once per field instead of once per fire (the restaging path,
    ops/device.py, which mirrors the reference's per-batch H2D memcpy).

    ``stats``: tuple of (op, field) reducer evaluations; ``jax_fn``: an
    optional JaxWindowFunction whose ``fn(keys, gwids, cols, mask)`` runs
    over (B, pad) gathers of its fields.  ``acc_dtypes`` maps each field
    to its ring dtype."""

    def __init__(self, fields, stats=(), jax_fn=None, acc_dtypes=None,
                 device=None, depth: int = 8):
        self.fields = tuple(fields)
        if not self.fields:
            raise ValueError("need at least one ring field")
        self.stats = tuple(stats)
        self.jax_fn = jax_fn
        for op, f in self.stats:
            if op not in _REDUCE_OPS:
                raise ValueError(f"unsupported resident op {op!r}")
            if f not in self.fields:
                raise ValueError(f"stat field {f!r} not in ring fields")
        if jax_fn is not None:
            for f in jax_fn.fields:
                if f not in self.fields:
                    raise ValueError(f"fn field {f!r} not in ring fields")
        if not self.stats and jax_fn is None:
            raise ValueError("nothing to evaluate")
        self.acc_dtypes = {f: np.dtype(acc_dtypes[f]) for f in self.fields}
        self.device = device or jax.devices()[0]
        self.depth = depth
        self.cap = 0
        self.KP = 0
        self._rings = None
        self._inflight = deque()
        self._ready = []
        self._svc = deque(maxlen=32)
        self._svc_mean = 0.0
        self._step_cache = {}   # per-executor cache for fn-bound steps

    # single-field plumbing from the base class that does not apply
    op = property(lambda self: tuple(op for op, _f in self.stats))
    single = False

    def reset(self, n_keys: int, cap: int):
        self.KP = _bucket(max(n_keys, 1))
        self.cap = _bucket(max(cap, 16))
        self._rings = None

    def _rings_arr(self):
        if self._rings is None:
            self._rings = tuple(
                jax.device_put(
                    jnp.zeros((self.KP, self.cap),
                              dtype=self.acc_dtypes[f]), self.device)
                for f in self.fields)
        return self._rings

    def _rings_tuple(self):
        return self._rings

    def _rings_assign(self, rings):
        self._rings = rings

    def narrow_for(self, field, vals: np.ndarray) -> np.dtype:
        """Per-field wire narrowing (same ladder as the base class but
        bounded by that field's ring dtype)."""
        acc = self.acc_dtypes[field]
        wide = acc.itemsize >= 8
        if len(vals) and vals.dtype.kind == "f" and acc.kind != "f":
            raise ValueError(
                f"float column {field!r} headed into a {acc} ring would "
                "silently truncate — declare a float ring dtype "
                f"(JaxWindowFunction(field_dtypes={{{field!r}: "
                "np.float32}}))")
        if acc.kind == "f":
            return np.dtype(np.float64 if wide else np.float32)
        if not len(vals):
            return np.dtype(np.int8)
        lo, hi = int(vals.min()), int(vals.max())
        ladder = (np.int8, np.int16, np.int32, np.int64) if wide else \
                 (np.int8, np.int16, np.int32)
        for dt in ladder:
            info = np.iinfo(dt)
            if info.min <= lo and hi <= info.max:
                return np.dtype(dt)
        return np.dtype(ladder[-1])

    def launch(self, meta, blks: dict, offs: np.ndarray,
               wrows: np.ndarray, wstarts: np.ndarray, wlens: np.ndarray,
               wkeys: np.ndarray = None, wgwids: np.ndarray = None):
        """One fused dispatch: per-field rectangles `blks[f]` (K, R) append
        at `offs`, then every stat / the JAX fn evaluates the described
        windows.  `wkeys`/`wgwids` are required when a JAX fn is bound."""
        K, R = next(iter(blks.values())).shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        B = len(wstarts)
        Rb = _bucket(max(R, 1))
        Bb = _bucket(max(B, 1))
        _check_ring_overflow(offs, Rb, self.cap)
        pad = (_bucket(int(wlens.max()) if B else 1)
               if (self.jax_fn is not None
                   or any(op != "sum" for op, _f in self.stats)) else 0)
        wires = tuple(blks[f].dtype.str for f in self.fields)
        key = (self.fields, self.stats, None, self.cap, Rb, Bb,
               self.KP, wires,
               tuple(self.acc_dtypes[f].str for f in self.fields), pad)
        # fn-bound steps cache per executor (the jitted closure pins the
        # fn; a process-wide cache keyed on fn identity would pin every
        # instance + compiled executable forever); stat-only steps share
        # the process-wide cache like the base class
        cache = _STEP_CACHE if self.jax_fn is None else self._step_cache
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _make_multi_step(key, self.jax_fn)
        with profile.span("device_put"):
            blkps = tuple(
                (blks[f] if blks[f].shape == (self.KP, Rb)
                 else _pad2(blks[f], self.KP, Rb)) for f in self.fields)
            args = jax.device_put(
                (blkps, _pad1(offs, self.KP), _pad1(wrows, Bb),
                 _pad1(wstarts, Bb), _pad1(wlens, Bb),
                 _pad1(wkeys if wkeys is not None else np.zeros(0), Bb,
                       dtype=np.int64),
                 _pad1(wgwids if wgwids is not None else np.zeros(0), Bb,
                       dtype=np.int64)),
                self.device)
        for f in self.fields:
            profile.add("bytes_shipped", blks[f].nbytes)
            profile.add("rows_shipped", blks[f].size)
        profile.add("windows", B)
        with profile.span("dispatch"):
            self._rings, out = fn(self._rings_arr(), *args)
            for o in out:
                getattr(o, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        self._inflight.append((meta, B, out, time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    def _harvest_one(self):
        meta, B, out, t0 = self._inflight.popleft()
        with profile.span("harvest_wait"):
            arrs = tuple(np.asarray(o)[:B] for o in out)
        self._note_service(t0)
        self._ready.append((meta, arrs))


def _make_mesh_multi_step(key, jax_fn):
    """Sharded fused multi-field append+eval: shard_map over the key-group
    axis of the per-field rings — each device appends its row block of
    EVERY field's ring and evaluates its own windows' stats/fn (windows
    are row-local, so the program has no collectives; the multi-chip form
    of the whole-tuple functor contract, win_seq_gpu.hpp:54-67 x SURVEY
    §2.8)."""
    (_tag, fields, stats, _fnid, cap, Rb, Bs, KP, wires, accs, pad, mesh,
     axis) = key
    acc_dts = tuple(np.dtype(a) for a in accs)
    fidx = {f: i for i, f in enumerate(fields)}
    from jax.sharding import PartitionSpec as P

    def local(rings, blks, offs, lrows, lstarts, llens, lkeys, lgwids):
        # per-shard views: rings/blks (rps, .) per field, offs (rps,),
        # descriptors (1, Bs) — this shard's windows, host pre-grouped
        rings = tuple(_ring_append(r, b, offs, dt)
                      for r, b, dt in zip(rings, blks, acc_dts))
        wrows, wstarts, wlens = lrows[0], lstarts[0], llens[0]
        outs = []
        for op, f in stats:
            outs.append(_ring_eval(op, cap, pad, acc_dts[fidx[f]],
                                   rings[fidx[f]], wrows, wstarts, wlens))
        if jax_fn is not None:
            idx = jnp.minimum(
                wstarts[:, None] + jnp.arange(pad, dtype=jnp.int32)[None, :],
                cap - 1)
            mask = jnp.arange(pad, dtype=jnp.int32)[None, :] < wlens[:, None]
            cols = {}
            for f in jax_fn.fields:
                vals = rings[fidx[f]][wrows[:, None], idx]
                cols[f] = jnp.where(mask, vals, 0)
            res = jax_fn.fn(lkeys[0], lgwids[0], cols, mask)
            outs.extend(res if isinstance(res, tuple) else (res,))
        outs = tuple(o[None, :] for o in outs)
        return rings, outs

    n_f = len(fields)
    mapped = jax.shard_map(
        local, mesh=mesh,
        in_specs=((P(axis, None),) * n_f, (P(axis, None),) * n_f,
                  P(axis), P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None), P(axis, None)),
        out_specs=((P(axis, None),) * n_f, P(axis, None)))
    return jax.jit(mapped)


class MeshMultiFieldResidentExecutor(MultiFieldResidentExecutor):
    """Multi-field resident rings sharded ``P(kf, None)`` over a mesh:
    the per-field-ring generalisation of :class:`MeshResidentExecutor` —
    arbitrary multi-stat reducers and batched JAX window functions run
    over key-group-sharded archives, one SPMD dispatch for every group
    (VERDICT r3 item 7: the general whole-tuple functor contract,
    win_seq_gpu.hpp:54-67, distributed over the ICI mesh)."""

    def __init__(self, fields, stats=(), jax_fn=None, acc_dtypes=None,
                 mesh=None, axis: str = "kf", depth: int = 8):
        if mesh is None or axis not in mesh.shape:
            raise ValueError(f"need a mesh with axis {axis!r}")
        super().__init__(fields, stats=stats, jax_fn=jax_fn,
                         acc_dtypes=acc_dtypes,
                         device=mesh.devices.flat[0], depth=depth)
        self.mesh = mesh
        self.axis = axis
        self.n_shards = int(mesh.shape[axis])

    def _sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(*spec))

    def _ring_placement(self):
        return self._sharding(self.axis, None)

    def reset(self, n_keys: int, cap: int):
        S = self.n_shards
        rows_per_shard = _bucket(max(-(-max(n_keys, 1) // S), 1))
        self.KP = S * rows_per_shard
        self.cap = _bucket(max(cap, 16))
        self._rings = None

    def _rings_arr(self):
        if self._rings is None:
            self._rings = tuple(
                jax.device_put(
                    jnp.zeros((self.KP, self.cap),
                              dtype=self.acc_dtypes[f]),
                    self._sharding(self.axis, None))
                for f in self.fields)
        return self._rings

    def launch(self, meta, blks: dict, offs: np.ndarray,
               wrows: np.ndarray, wstarts: np.ndarray, wlens: np.ndarray,
               wkeys: np.ndarray = None, wgwids: np.ndarray = None):
        S = self.n_shards
        K, R = next(iter(blks.values())).shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        rps = self.KP // S
        B = len(wstarts)
        wrows = np.asarray(wrows, dtype=np.int64)
        # stride dense key rows over shards (MeshResidentExecutor.launch)
        shard = wrows % S
        local = wrows // S
        slots = np.zeros(B, dtype=np.int64)
        maxc = 0
        for s in range(S):
            m = shard == s
            c = int(m.sum())
            slots[m] = np.arange(c)
            maxc = max(maxc, c)
        Bs = _bucket(max(maxc, 1))
        lrows = np.zeros((S, Bs), dtype=np.int32)
        lstarts = np.zeros((S, Bs), dtype=np.int32)
        llens = np.zeros((S, Bs), dtype=np.int32)
        lkeys = np.zeros((S, Bs), dtype=np.int64)
        lgwids = np.zeros((S, Bs), dtype=np.int64)
        if B:
            lrows[shard, slots] = local.astype(np.int32)
            lstarts[shard, slots] = wstarts
            llens[shard, slots] = wlens
            # the caller sends empty header columns when no fn is bound
            if wkeys is not None and len(wkeys) == B:
                lkeys[shard, slots] = wkeys
            if wgwids is not None and len(wgwids) == B:
                lgwids[shard, slots] = wgwids
        Rb = _bucket(max(R, 1))
        _check_ring_overflow(offs, Rb, self.cap)
        pad = (_bucket(int(wlens.max()) if B else 1)
               if (self.jax_fn is not None
                   or any(op != "sum" for op, _f in self.stats)) else 0)
        wires = tuple(blks[f].dtype.str for f in self.fields)
        key = ("mesh-multi", self.fields, self.stats, None, self.cap, Rb,
               Bs, self.KP, wires,
               tuple(self.acc_dtypes[f].str for f in self.fields), pad,
               self.mesh, self.axis)
        cache = _STEP_CACHE if self.jax_fn is None else self._step_cache
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = _make_mesh_multi_step(key, self.jax_fn)
        # shard-major physical scatter (MeshResidentExecutor.launch)
        rows = np.arange(K)
        prow = (rows % S) * rps + rows // S
        offsp = np.zeros(self.KP, dtype=np.int32)
        offsp[prow] = offs
        blkps = []
        for f in self.fields:
            bp = np.zeros((self.KP, Rb), dtype=blks[f].dtype)
            bp[prow, :R] = blks[f]
            blkps.append(jax.device_put(bp, self._sharding(self.axis,
                                                           None)))
            profile.add("bytes_shipped", blks[f].nbytes)
            profile.add("rows_shipped", blks[f].size)
        profile.add("windows", B)
        s2 = self._sharding(self.axis, None)
        args = (tuple(blkps),
                jax.device_put(offsp, self._sharding(self.axis)),
                jax.device_put(lrows, s2), jax.device_put(lstarts, s2),
                jax.device_put(llens, s2), jax.device_put(lkeys, s2),
                jax.device_put(lgwids, s2))
        with profile.span("dispatch"):
            self._rings, out = fn(self._rings_arr(), *args)
            for o in out:
                getattr(o, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        self._inflight.append((meta, (shard, slots), out,
                               time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    def _harvest_one(self):
        meta, sel, out, t0 = self._inflight.popleft()
        with profile.span("harvest_wait"):
            arrs = tuple(np.asarray(o)[sel[0], sel[1]] for o in out)
        self._note_service(t0)
        self._ready.append((meta, arrs))


class MeshResidentExecutor(ResidentWindowExecutor):
    """Resident ring sharded ``P(kf, None)`` over a ``jax.sharding.Mesh``:
    dense-key ring rows are block-distributed over the mesh's key-group
    axis, so ONE fused append+eval dispatch serves every key group — each
    chip holds its groups' archives in its own HBM and evaluates its own
    windows (no collectives; the kf axis is embarrassingly parallel,
    parallel/mesh.py).  This is the multi-chip form of the reference's
    per-worker GPU ownership (win_farm_gpu.hpp:132-168) with the farm
    collapsed into one SPMD program."""

    def __init__(self, op: str, mesh, axis: str = "kf", depth: int = 8,
                 acc_dtype=np.int32):
        if axis not in mesh.shape:
            raise ValueError(f"mesh has no axis {axis!r}: {mesh.shape}")
        super().__init__(op, device=mesh.devices.flat[0], depth=depth,
                         acc_dtype=acc_dtype)
        self.mesh = mesh
        self.axis = axis
        self.n_shards = int(mesh.shape[axis])

    def _sharding(self, *spec):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, P(*spec))

    def _ring_placement(self):
        return self._sharding(self.axis, None)

    def reset(self, n_keys: int, cap: int):
        S = self.n_shards
        rows_per_shard = _bucket(max(-(-max(n_keys, 1) // S), 1))
        self.KP = S * rows_per_shard
        self.cap = _bucket(max(cap, 16))
        self._ring = None

    def _ring_arr(self):
        if self._ring is None:
            self._ring = jax.device_put(
                jnp.zeros((self.KP, self.cap), dtype=self.acc_dtype),
                self._sharding(self.axis, None))
        return self._ring

    def launch(self, meta, blk: np.ndarray, offs: np.ndarray,
               wrows: np.ndarray, wstarts: np.ndarray, wlens: np.ndarray):
        S = self.n_shards
        K, R = blk.shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        rps = self.KP // S
        B = len(wstarts)
        wrows = np.asarray(wrows, dtype=np.int64)
        # STRIDE dense key rows over shards (row r -> shard r % S, local
        # slot r // S): the host assigns rows in key-arrival order, so a
        # block mapping would concentrate all live keys on the low shards
        # while the padded tail idles — striding balances any K
        shard = wrows % S
        local = wrows // S
        # per-shard slot assignment, preserving original order per shard
        slots = np.zeros(B, dtype=np.int64)
        maxc = 0
        for s in range(S):
            m = shard == s
            c = int(m.sum())
            slots[m] = np.arange(c)
            maxc = max(maxc, c)
        Bs = _bucket(max(maxc, 1))
        lrows = np.zeros((S, Bs), dtype=np.int32)
        lstarts = np.zeros((S, Bs), dtype=np.int32)
        llens = np.zeros((S, Bs), dtype=np.int32)
        if B:
            lrows[shard, slots] = local.astype(np.int32)
            lstarts[shard, slots] = wstarts
            llens[shard, slots] = wlens
        Rb = _bucket(max(R, 1))
        _check_ring_overflow(offs, Rb, self.cap)
        pad = (_bucket(int(wlens.max()) if B else 1)
               if any(o != "sum" for o in self.ops) else 0)
        key = ("mesh", self.ops, self.cap, Rb, Bs, self.KP, blk.dtype.str,
               self.acc_dtype.str, pad, self.mesh, self.axis)
        fn = _STEP_CACHE.get(key)
        if fn is None:
            fn = _STEP_CACHE[key] = _make_mesh_step(key)
        # scatter the rectangle so dense row r lands at physical ring row
        # (r % S) * rps + r // S — shard-major, matching the window mapping
        rows = np.arange(K)
        prow = (rows % S) * rps + rows // S
        blkp = np.zeros((self.KP, Rb), dtype=blk.dtype)
        blkp[prow, :R] = blk
        offsp = np.zeros(self.KP, dtype=np.int32)
        offsp[prow] = offs
        args = (jax.device_put(blkp, self._sharding(self.axis, None)),
                jax.device_put(offsp, self._sharding(self.axis)),
                jax.device_put(lrows, self._sharding(self.axis, None)),
                jax.device_put(lstarts, self._sharding(self.axis, None)),
                jax.device_put(llens, self._sharding(self.axis, None)))
        self._ring, out = fn(self._ring_arr(), *args)
        for o in (out if isinstance(out, tuple) else (out,)):
            getattr(o, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        # harvest indexes the (S, Bs) result back to flat window order
        self._inflight.append((meta, (shard, slots), out, time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    def launch_regular(self, meta, blk: np.ndarray, offs: np.ndarray,
                       rcount: np.ndarray, rstart0: np.ndarray,
                       rlen: np.ndarray, slide: int, wrows: np.ndarray,
                       widx: np.ndarray, cmax: int = 0):
        """Regular-descriptor dispatch on the sharded ring: the per-key
        (count, start0, len) scalars shard with their rows, and each device
        expands its own arithmetic window sequences — the native core's
        wire compression composes with mesh execution (r2 weak #3)."""
        if not (self.single and self.op == "sum"):
            raise ValueError("regular descriptors implemented for "
                             "single-stat sum")
        S = self.n_shards
        K, R = blk.shape
        if K > self.KP:
            raise ValueError("rectangle exceeds ring rows; reset() first")
        rps = self.KP // S
        Rb = _bucket(max(R, 1))
        C = _bucket(int(cmax) if cmax else
                    (int(rcount.max()) if len(rcount) else 1))
        _check_ring_overflow(offs, Rb, self.cap)
        key = ("mesh-reg", self.op, self.cap, Rb, self.KP, C, blk.dtype.str,
               self.acc_dtype.str, int(slide), self.mesh, self.axis)
        fn = _STEP_CACHE.get(key)
        if fn is None:
            fn = _STEP_CACHE[key] = _make_mesh_regular_step(key)
        # strided physical scatter, same mapping as launch()
        rows = np.arange(K)
        prow = (rows % S) * rps + rows // S
        blkp = np.zeros((self.KP, Rb), dtype=blk.dtype)
        blkp[prow, :R] = blk[:, :R]
        def scat(a, dtype=np.int32):
            out = np.zeros(self.KP, dtype=dtype)
            out[prow] = a[:K]
            return out
        args = (jax.device_put(blkp, self._sharding(self.axis, None)),
                jax.device_put(scat(offs), self._sharding(self.axis)),
                jax.device_put(scat(rcount), self._sharding(self.axis)),
                jax.device_put(scat(rstart0), self._sharding(self.axis)),
                jax.device_put(scat(rlen), self._sharding(self.axis)))
        self._ring, out = fn(self._ring_arr(), *args)
        getattr(out, "copy_to_host_async", lambda: None)()
        stats_add("dispatches")
        wr = np.asarray(wrows, dtype=np.int64)
        sel = ((wr % S) * rps + wr // S, np.asarray(widx))
        self._inflight.append((meta, sel, out, time.perf_counter()))
        while len(self._inflight) > self.depth:
            self._harvest_one()


def prewarm_regular_ladder(mults=(2, 4, 8, 16), devices=None,
                           max_cells=1 << 24) -> int:
    """Compile the coalesced-shape siblings of every step (regular,
    irregular, mesh) already compiled in this process.

    Deep launch coalescing dispatches merged shapes on the {2x, 4x, ...}
    buddy ladder — diagonal (Rb*m, B*m) siblings for irregular steps, the
    lower triangle {(Rb*m, C*b), b <= m} for regular steps (try_merge
    admits window-bucket growth at most proportional to row-bucket
    growth) — only under wire stall, exactly when a cold
    ~10 s mid-run compile hurts most (BASELINE.md: odd-shape recompiles
    measured mid-benchmark).  A benchmark calls this once after its warmup
    run: whatever regular buckets the warmup compiled, their ladder
    siblings compile now, deterministically, regardless of warmup-time
    wire weather.  ``devices`` should list every device the run's
    executors own (jit executables cache per placement; a farm worker on
    another chip would otherwise cold-compile its first merged shape) —
    default is device 0 only.  Returns the number of steps compiled."""
    devices = list(devices) if devices else [jax.devices()[0]]
    warmed = 0
    for key in list(_STEP_CACHE):
        if key in _PREWARMED:
            # a prewarmed sibling never seeds further ladders: the buddy
            # multiplicity caps at 16x of a NATURAL launch shape, so
            # ladders-of-ladders are undispatchable (and repeat calls
            # must be no-ops)
            continue
        tag = key[0] if isinstance(key, tuple) and key else None
        if tag == "reg":
            _t, op, cap, Rb, KP, C, blk_dt, acc_dt, slide = key
            mesh = axis = None
        elif tag == "mesh-reg":
            (_t, op, cap, Rb, KP, C, blk_dt, acc_dt, slide, mesh,
             axis) = key
        elif isinstance(tag, tuple) and len(key) == 8:
            # plain (irregular-descriptor) step: TB windows and non-sum
            # ops merge on explicit descriptors, so their ladder siblings
            # double both the rectangle AND the window-count bucket.
            # (multi-field keys are also tuple-tagged but 10-long — their
            # executor is Python-core only, which never coalesces)
            _ops, cap, Rb, Bb, KP, blk_dt, acc_dt, pad = key
            mesh = axis = None
        elif tag == "mesh":
            # mesh irregular step: the coalescer merges irregular launches
            # on the mesh-backed native path too (non-sum ops, TB windows),
            # so merged (Rb*m, Bs*m) diagonal siblings must be warm as well
            # (ADVICE r3).  The per-shard window bucket Bs tracks the total
            # window count's bucket in the common case (strided shard
            # assignment); the diagonal ladder covers exactly those.
            (_t, ops_m, cap, Rb, Bb, KP, blk_dt, acc_dt, pad, mesh,
             axis) = key
        else:
            continue
        for m in mults:
            # a real merge can never exceed the ring (try_merge's offset
            # guard bounds bucket(newR) by cap) ...
            if Rb * m > cap:
                continue
            # ... and its area guard counts LIVE keys (K2 * bucket(newR)
            # <= max_cells, wf_native.cpp:try_merge); the smallest live K
            # a KP-row launch can carry is KP//2 + 1 (bucket property), so
            # skip only shapes NO admissible merge could produce — a
            # padded-KP guard here would refuse shapes the coalescer then
            # builds and compiles cold mid-run
            if (KP // 2 + 1) * Rb * m > max_cells:
                continue
            if isinstance(tag, tuple):
                sks = [(tag, cap, Rb * m, Bb * m, KP, blk_dt, acc_dt, pad)]
            elif tag == "mesh":
                # the mesh dispatch key's window bucket Bs is PER-SHARD
                # (bucket of the fullest shard's window count,
                # MeshResidentExecutor.launch) while try_merge guards the
                # TOTAL window bucket — clamping decouples them (merged
                # per-shard counts can sit under the lo=8 clamp while rows
                # double), so merged mesh shapes live on the same lower
                # triangle as regular ones: warm {(Rb*m, Bs*b), b <= m}
                sks = []
                b = 1
                while b <= m:
                    sks.append(("mesh", ops_m, cap, Rb * m, Bb * b, KP,
                                blk_dt, acc_dt, pad, mesh, axis))
                    b *= 2
            else:
                # regular merges live on the LOWER TRIANGLE {(Rb*a, C*b),
                # b <= a}: small per-key window counts can clamp the C
                # bucket while rows double (try_merge admits rc <= rr), so
                # the diagonal sibling alone would leave e.g. (2*Rb, C)
                # cold exactly when the coalescer builds it mid-stall
                # (ADVICE r3)
                sks = []
                b = 1
                while b <= m:
                    if mesh is None:
                        sks.append(("reg", op, cap, Rb * m, KP, C * b,
                                    blk_dt, acc_dt, slide))
                    else:
                        sks.append(("mesh-reg", op, cap, Rb * m, KP, C * b,
                                    blk_dt, acc_dt, slide, mesh, axis))
                    b *= 2
            todo = [sk for sk in sks if sk not in _STEP_CACHE]
            if not todo:
                continue
            # the warm inputs depend only on (family, m), never on the
            # triangle's C value (it shapes the OUTPUT only) — allocate
            # them once per placement and reuse across siblings (a ring is
            # up to 128 MB; re-shipping it per sibling would stretch the
            # warmup window for nothing)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                s2 = NamedSharding(mesh, P(axis, None))
                s1 = NamedSharding(mesh, P(axis))
                placements = [(s2, s1)]
            else:
                placements = [(dev, dev) for dev in devices]
            bases = []
            for p2, p1 in placements:
                ring = jax.device_put(
                    jnp.zeros((KP, cap), dtype=np.dtype(acc_dt)), p2)
                blk = jax.device_put(
                    jnp.zeros((KP, Rb * m), dtype=np.dtype(blk_dt)), p2)
                zk = jax.device_put(jnp.zeros(KP, dtype=np.int32), p1)
                bases.append((p2, p1, ring, blk, zk))
            for sk in todo:
                # cache only AFTER the warm dispatch succeeds: a transient
                # wire error mid-warm must leave the key retryable, not
                # "warm" with a cold executable behind it
                if tag == "mesh":
                    fn = _make_mesh_step(sk)
                elif isinstance(tag, tuple):
                    fn = _make_step(sk)
                elif mesh is None:
                    fn = _make_regular_step(sk)
                else:
                    fn = _make_mesh_regular_step(sk)
                for p2, p1, ring, blk, zk in bases:
                    # the window-descriptor vectors are the one input whose
                    # shape varies across mesh/plain irregular siblings
                    # (sk[4] / sk[3] is that sibling's Bs); regular steps
                    # take per-key scalars only
                    if tag == "mesh":
                        S = int(mesh.shape[axis])
                        zb = jax.device_put(
                            jnp.zeros((S, sk[4]), dtype=np.int32), p2)
                        args = (ring, blk, zk, zb, zb, zb)
                    elif isinstance(tag, tuple):
                        zb = jax.device_put(
                            jnp.zeros(sk[3], dtype=np.int32), p1)
                        args = (ring, blk, zk, zb, zb, zb)
                    else:
                        args = (ring, blk, zk, zk, zk, zk)
                    _ring2, out = fn(*args)
                    jax.block_until_ready(out)
                _STEP_CACHE[sk] = fn
                _PREWARMED.add(sk)
                warmed += 1
    return warmed
