"""TPU device execution of window batches — the graft replacing the CUDA
micro-batch path (reference win_seq_gpu.hpp).

The reference fires windows into batch vectors and, at ``batch_len``, copies
``(Bin, start, end, gwids)`` to the GPU and launches one kernel with one
window per CUDA thread (win_seq_gpu.hpp:429-501), synchronising per batch
(:481).  The TPU design differs where it should:

* **Staging**: the window batch is described as a *flat* buffer of archive
  rows plus per-window (start, len) — the flat buffer is staged once even
  though consecutive sliding windows overlap (the device-side analog of the
  reference's refcounted host-side multicast, meta_utils.hpp:354).
* **Compute**: one XLA computation evaluates all windows: a gather expands
  ``flat[start_i + j]`` into a (B, pad) tile, a mask kills the padding, and
  the reduction runs on the VPU — or a Pallas kernel reduces each window
  directly from VMEM without materialising the (B, pad) tile (pallas.py).
* **Shapes**: XLA needs static shapes where CUDA took runtime sizes, so
  (B, pad, N) are bucketed to powers of two and jits are cached per bucket —
  the recompile-amortisation answer to win_seq_gpu.hpp:462-473's grow/shrink
  heuristic.
* **Overlap**: launches are asynchronous (JAX dispatch); up to ``depth``
  batches are in flight before the host blocks, replacing the reference's
  blocking ``cudaStreamSynchronize`` per batch — strictly more overlap.

User-function contract: a JAX function ``fn(keys, gwids, cols, mask) ->
result column(s)`` over the whole window batch (cols[field]: (B, pad)).
Built-in reductions provide it out of the box; arbitrary *host* Python
functions cannot be staged to the device (XLA cannot JIT host code — the
same restriction the reference's CUDA path has, where the functor must be a
__device__ lambda) and use the host path instead.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .monoid import identity as _monoid_identity
from .monoid import jnp_reducer

_INT32_MIN, _INT32_MAX = -(2 ** 31), 2 ** 31 - 1

#: process-wide compiled-function cache — executors come and go per pattern
#: instance, the executables they compile should not
_JIT_CACHE = {}

#: platforms where Mosaic rejected the pallas kernel — recorded so later
#: executors skip straight to the gather path instead of re-paying the
#: failing compile (jax does not cache failed compiles)
_PALLAS_BROKEN = set()


def _bucket(n: int, lo: int = 8) -> int:
    """Next power of two >= n (shape bucketing for jit reuse)."""
    b = lo
    while b < n:
        b *= 2
    return b


@functools.lru_cache(maxsize=None)
def builtin_batch_fn(op: str, field: str = "value"):
    """Batched window function for a built-in reduction, in JAX.  Cached so
    every executor evaluating the same (op, field) shares one function
    object — and therefore one compiled-executable cache entry."""

    def fn(keys, gwids, cols, mask):
        if op == "count":
            return jnp.sum(mask, axis=1)
        vals = cols[field]
        if op == "mean":
            s = jnp.sum(jnp.where(mask, vals, 0), axis=1)
            c = jnp.maximum(jnp.sum(mask, axis=1), 1)
            return s / c
        ident = _monoid_identity(op, vals.dtype)
        return jnp_reducer(op)(jnp.where(mask, vals, ident), axis=1)

    fn._windflow_shared = True  # safe to cache executables process-wide
    return fn


class DeviceWindowExecutor:
    """Compiles and launches batched window evaluations with bucketed
    shapes and bounded asynchronous depth."""

    def __init__(self, batch_fn, fields=("value",), out_fields=("value",),
                 device=None, depth: int = 4, use_pallas: bool = False,
                 op: str = None, compute_dtype=None, out_dtypes=None,
                 empty_fill=None):
        self.batch_fn = batch_fn
        self.fields = tuple(fields)
        self.out_fields = tuple(out_fields)
        self.device = device or jax.devices()[0]
        self.depth = depth
        self.use_pallas = use_pallas
        self.op = op
        self.compute_dtype = compute_dtype
        # result dtypes per out_field: harvest casts into them so that
        # empty-window fills (below) can hold full-width identities
        self.out_dtypes = {f: np.dtype(d) for f, d in (out_dtypes or {}).items()}
        # {field: value} written over empty windows at harvest — keeps the
        # device path's empty-window results identical to the host path's
        # even when compute happens in a narrower dtype (int32 vs int64)
        self.empty_fill = dict(empty_fill or {})
        # Executables compiled for process-lifetime functions (the lru-cached
        # builtins, or anything marked _windflow_shared) go in the process-
        # wide cache so new executor instances reuse them; ad-hoc user
        # functions keep a per-instance cache (a global entry keyed on a
        # short-lived lambda could never be reused but never dies either).
        shared = (getattr(batch_fn, "_windflow_shared", False)
                  or (use_pallas and op is not None and self.fields))
        self._jits = _JIT_CACHE if shared else {}
        self._inflight = []  # [(meta, B, empty_mask, device_results)]
        self._ready = []     # harvested result batches (host)
        self._warned_downcast = False
        self._warned_id_range = False

    # ----------------------------------------------------------- compilation

    def _pallas_key(self, pad, N):
        return ("pallas", self.op, self.fields[0] if self.fields else None,
                self.device.platform, pad, N)

    def _compiled(self, B, pad, N):
        # the jitted callable closes over (pad, N) only; B varies through the
        # argument shapes, which jax.jit re-specialises on by itself.  Keyed
        # process-wide on the user function object so a new executor (a new
        # pattern instance, a re-run pipeline) reuses executables already
        # compiled for the same function and bucket.
        if self.use_pallas and self.device.platform in _PALLAS_BROKEN:
            self.use_pallas = False
        if self.use_pallas and self.op is not None and self.fields:
            key = self._pallas_key(pad, N)
        else:
            key = (self.batch_fn, pad, N)
        fn = self._jits.get(key)
        if fn is not None:
            return fn
        if self.use_pallas and self.op is not None and self.fields:
            from .pallas_kernels import windowed_reduce_pallas
            op = self.op
            field = self.fields[0]
            interpret = self.device.platform != "tpu"

            def run(flat_cols, starts, lens, keys, gwids):
                out = windowed_reduce_pallas(flat_cols[field], starts, lens,
                                             pad, op, interpret=interpret)
                return (out,)
        else:
            batch_fn = self.batch_fn

            def run(flat_cols, starts, lens, keys, gwids):
                idx = starts[:, None] + jnp.arange(pad, dtype=jnp.int32)[None, :]
                idx = jnp.minimum(idx, N - 1)
                mask = jnp.arange(pad, dtype=jnp.int32)[None, :] < lens[:, None]
                cols = {f: jnp.where(mask, flat_cols[f][idx], 0)
                        for f in flat_cols}
                out = batch_fn(keys, gwids, cols, mask)
                return out if isinstance(out, tuple) else (out,)

        fn = jax.jit(run)
        self._jits[key] = fn
        return fn

    # ------------------------------------------------------------- execution

    def launch(self, meta, flat_cols: dict, starts: np.ndarray,
               lens: np.ndarray, keys: np.ndarray, gwids: np.ndarray):
        """Asynchronously evaluate one window batch.  `meta` is returned
        with the results at harvest time (host-side result headers)."""
        B = len(starts)
        Bb = _bucket(B)
        pad = _bucket(int(lens.max()) if len(lens) else 1)
        n = len(next(iter(flat_cols.values()))) if flat_cols else 1
        # flat is padded past n so any [start, start+pad) slice is in bounds
        # (required by the pallas path; harmless for the gather path)
        Nb = _bucket(max(n, 1) + pad)

        def pad1(a, size, dtype=None):
            a = np.asarray(a)
            out = np.zeros(size, dtype=dtype or a.dtype)
            out[:len(a)] = a
            return out

        dcols = {}
        for f, col in flat_cols.items():
            col = np.asarray(col)
            if self.compute_dtype is not None and col.dtype.kind in "iuf":
                col = col.astype(self.compute_dtype)
            elif col.dtype == np.int64:
                # TPU-native integer width; reductions exceeding int32 range
                # will wrap — pick compute_dtype explicitly for wide sums
                if not self._warned_downcast:
                    self._warned_downcast = True
                    import warnings
                    warnings.warn(
                        "device path downcasts int64 payloads to int32; "
                        "window reductions beyond ±2^31 will overflow — pass "
                        "compute_dtype (e.g. np.float32) for wide ranges",
                        stacklevel=3)
                col = col.astype(np.int32)
            dcols[f] = pad1(col, Nb)
        if not self._warned_id_range:
            for name, a in (("keys", keys), ("gwids", gwids)):
                fits = (a.dtype.kind == "i" and a.dtype.itemsize <= 4) or \
                       (a.dtype.kind == "u" and a.dtype.itemsize <= 2)
                if fits or not len(a):
                    continue  # provably within int32: skip the O(B) scan
                mx, mn = int(a.max()), int(a.min())
                if mx > _INT32_MAX or mn < _INT32_MIN:
                    self._warned_id_range = True
                    bad = mx if mx > _INT32_MAX else mn
                    import warnings
                    warnings.warn(
                        f"device path downcasts {name} to int32 and "
                        f"{bad} is out of range; a window function "
                        "reading them will see wrapped values", stacklevel=3)
        args = jax.device_put(
            (dcols,
             pad1(starts.astype(np.int32), Bb),
             pad1(lens.astype(np.int32), Bb),
             pad1(keys.astype(np.int32), Bb),
             pad1(gwids.astype(np.int32), Bb)),
            self.device)
        try:
            out = self._compiled(Bb, pad, Nb)(*args)
        except Exception:
            if not self.use_pallas:
                raise
            # Mosaic may reject the kernel (e.g. unaligned rank-1 dynamic
            # slices on some toolchains) — fall back to the XLA gather path,
            # which on a v5e measures >1e9 windows/s anyway.  Evict the
            # failing entry and mark the platform so later executors skip
            # straight to the gather path.
            _JIT_CACHE.pop(self._pallas_key(pad, Nb), None)
            _PALLAS_BROKEN.add(self.device.platform)
            self.use_pallas = False
            if not getattr(self.batch_fn, "_windflow_shared", False):
                # sharing was justified by the pallas key only; the gather
                # path would key on an ad-hoc fn — keep those per-instance
                self._jits = {}
            out = self._compiled(Bb, pad, Nb)(*args)
        for o in out:
            # start the D2H transfer now so harvest finds it on host —
            # on a tunneled device a blocking fetch costs a full round-trip
            getattr(o, "copy_to_host_async", lambda: None)()
        empty = lens == 0 if self.empty_fill and (lens == 0).any() else None
        self._inflight.append((meta, B, empty, out))
        while len(self._inflight) > self.depth:
            self._harvest_one()

    def _harvest_one(self):
        meta, B, empty, out = self._inflight.pop(0)
        host = [np.asarray(o)[:B] for o in out]  # blocks until ready
        cols = {}
        for f, v in zip(self.out_fields, host):
            dt = self.out_dtypes.get(f)
            if dt is not None and v.dtype != dt:
                v = v.astype(dt)
            if empty is not None and f in self.empty_fill:
                v = v.copy() if v.base is not None else v
                v[empty] = self.empty_fill[f]
            cols[f] = v
        self._ready.append((meta, cols))

    def poll(self):
        """Harvest any completed launches without blocking on new ones;
        returns [(meta, {field: values})]."""
        while self._inflight and self._is_ready(self._inflight[0][3]):
            self._harvest_one()
        ready, self._ready = self._ready, []
        return ready

    @staticmethod
    def _is_ready(out) -> bool:
        try:
            return all(o.is_ready() for o in out)
        except AttributeError:
            return True

    def drain(self):
        """Block until every in-flight batch is harvested."""
        while self._inflight:
            self._harvest_one()
        ready, self._ready = self._ready, []
        return ready
