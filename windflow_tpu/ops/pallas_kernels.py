"""Pallas TPU kernels for windowed reductions.

The XLA gather path (ops/device.py) materialises a (B, pad) tile in HBM
before reducing; for large windows that tile dominates memory traffic.
This kernel instead walks the *flat* staged buffer directly: each program
dynamic-slices its windows out of VMEM and reduces on the VPU, so HBM
traffic is O(flat + B) instead of O(B * pad) — the sliding-window overlap
between consecutive windows is read from VMEM, not re-fetched from HBM.

One program reduces a group of G windows (the analog of the reference's
one-window-per-CUDA-thread kernel, win_seq_gpu.hpp:54-67, re-tiled for the
8x128 VPU instead of 32-thread warps).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .monoid import identity as _identity
from .monoid import jnp_reducer

_GROUP = 8  # windows per program (one VPU sublane each)


def _kernel(starts_ref, lens_ref, flat_ref, out_ref, *, pad, op, dtype):
    i = pl.program_id(0)
    ident = _identity(op, dtype)
    lane = jax.lax.iota(jnp.int32, pad)
    rows = []
    for g in range(_GROUP):
        w = i * _GROUP + g
        s = starts_ref[w]
        l = lens_ref[w]
        vals = flat_ref[pl.ds(s, pad)]
        if op == "count":
            rows.append(l.astype(dtype))
        else:
            masked = jnp.where(lane < l, vals, ident)
            rows.append(jnp_reducer(op)(masked))
    out_ref[pl.ds(i * _GROUP, _GROUP)] = jnp.stack(rows)


@functools.partial(jax.jit, static_argnames=("pad", "op", "interpret"))
def windowed_reduce_pallas(flat, starts, lens, pad, op, interpret=False):
    """Reduce B windows (flat[starts[i] : starts[i]+lens[i]], lens <= pad)
    with the monoid `op`; flat must be padded so every slice of `pad`
    elements starting at any start is in bounds."""
    B = starts.shape[0]
    assert B % _GROUP == 0, "batch must be a multiple of the window group"
    kernel = functools.partial(_kernel, pad=pad, op=op, dtype=flat.dtype)
    return pl.pallas_call(
        kernel,
        grid=(B // _GROUP,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # starts
            pl.BlockSpec(memory_space=pltpu.SMEM),   # lens
            pl.BlockSpec(memory_space=pltpu.VMEM),   # flat buffer
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B,), flat.dtype),
        interpret=interpret,
    )(starts, lens, flat)
