"""One source of truth for the built-in monoid reductions: identities and
reducer tables shared by the host path (numpy, ops/functions.py), the XLA
device path (ops/device.py), the Pallas kernels (ops/pallas_kernels.py),
and the mesh layer (parallel/mesh.py).

Semantics of the identity (what an *empty* window produces, matching the
reference's behaviour of leaving the result default-initialised): sum and
count give 0, prod gives 1, min/max give the dtype extremes — ``±inf`` for
floats, ``iinfo`` bounds for integers.
"""

from __future__ import annotations

import numpy as np

OPS = ("sum", "count", "mean", "min", "max", "prod")


def identity(op: str, dtype):
    """Monoid identity of `op` in `dtype` (accepts numpy or jax dtypes)."""
    dt = np.dtype(dtype)
    if op in ("sum", "count", "mean"):
        return dt.type(0)
    if op == "prod":
        return dt.type(1)
    if op not in ("min", "max"):
        raise ValueError(f"unknown op {op!r}")
    if dt.kind == "f":
        return dt.type(np.inf if op == "min" else -np.inf)
    info = np.iinfo(dt)
    return dt.type(info.max if op == "min" else info.min)


#: numpy ufuncs for the host fold (count has no ufunc: it counts rows)
NP_UFUNCS = {
    "sum": np.add,
    "min": np.minimum,
    "max": np.maximum,
    "prod": np.multiply,
}


def jnp_ufunc(op: str):
    """The jax.numpy pairwise combiner for `op` (count combines like sum —
    partial counts add)."""
    import jax.numpy as jnp
    return {"sum": jnp.add, "count": jnp.add, "mean": jnp.add,
            "min": jnp.minimum, "max": jnp.maximum,
            "prod": jnp.multiply}[op]


def jnp_reducer(op: str):
    """The jax.numpy whole-axis reducer for `op` (mean/count handled by the
    callers from masks)."""
    import jax.numpy as jnp
    return {"sum": jnp.sum, "mean": jnp.sum, "min": jnp.min,
            "max": jnp.max, "prod": jnp.prod}[op]
