#!/usr/bin/env python3
"""wf-lint — stand-alone static analysis for windflow_tpu graphs
(docs/CHECKS.md).

Imports one or more app modules, collects their dataflow graphs, runs
the ``windflow_tpu/check`` validator, and prints each diagnostic with a
``file:line`` anchor when one is known:

    python scripts/wf_lint.py windflow_tpu.apps.ysb windflow_tpu.apps.pipe
    python scripts/wf_lint.py path/to/my_app.py --error
    python scripts/wf_lint.py --plane deploy/plane_spec.py --error
    python scripts/wf_lint.py my_app.py --json

Graph discovery, per module:

* a callable ``wf_check_pipelines()`` (the convention the bundled bench
  apps follow) — returns an iterable of ``MultiPipe``/``Dataflow``/
  ``WireConfig``/``PlanePolicy`` objects to validate;
* otherwise, module-level attributes that already ARE such objects
  (manual-graph scripts that build a bare ``Dataflow`` at module level
  are lintable without the hook).

``--plane <spec>`` lints a declared multi-host topology instead
(check/plane.py, WF22x): the spec module advertises its
``windflow_tpu.check.plane.PlaneSpec`` objects via a ``wf_plane_spec()``
callable or module-level instances.  ``--plane`` may repeat and may be
combined with positional app modules.

``--json`` replaces the human-readable report with one JSON document on
stdout for CI consumption::

    {"targets": 3, "diagnostics": [
        {"id": "WF205", "severity": "error", "module": "...",
         "target": "...", "file": "...", "line": 42,
         "message": "..."}, ...],
     "suppressed": [...]}       # only under --show-suppressed

Exit-code contract (stable, scriptable):

* **0** — every target validated; no diagnostic reported, or
  diagnostics were reported but ``--error`` was not given (lint is
  informational by default);
* **1** — ``--error`` was given and at least one non-suppressed
  diagnostic was reported (any severity: a warning-severity finding is
  still a finding);
* **2** — usage or import failure: a module failed to import, a
  ``--plane`` spec contained no PlaneSpec, or no lintable target was
  named.

``# wf-lint: disable=WF###`` on the anchored source line suppresses a
diagnostic (``--show-suppressed`` lists them anyway).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: module-level type names the fallback scan (no wf_check_pipelines()
#: hook) picks up as validation targets
_SCAN_TYPES = ("MultiPipe", "Dataflow", "WireConfig", "PlanePolicy")


def load_module(spec: str):
    """Import ``spec`` — a dotted module name or a path to a .py file."""
    if spec.endswith(".py") or os.path.sep in spec:
        path = os.path.abspath(spec)
        name = os.path.splitext(os.path.basename(path))[0]
        mspec = importlib.util.spec_from_file_location(name, path)
        if mspec is None:
            raise ImportError(f"cannot load {spec!r}")
        mod = importlib.util.module_from_spec(mspec)
        sys.modules.setdefault(name, mod)
        mspec.loader.exec_module(mod)
        return mod
    return importlib.import_module(spec)


def collect_targets(mod):
    """Validation targets of one module (see module docstring)."""
    hook = getattr(mod, "wf_check_pipelines", None)
    if callable(hook):
        targets = list(hook())
    else:
        targets = []
        for name in sorted(vars(mod)):
            obj = getattr(mod, name)
            if type(obj).__name__ in _SCAN_TYPES:
                targets.append(obj)
    return targets


def collect_plane_specs(mod):
    """PlaneSpec targets of one ``--plane`` spec module: a
    ``wf_plane_spec()`` hook, else module-level PlaneSpec objects."""
    hook = getattr(mod, "wf_plane_spec", None)
    if callable(hook):
        out = hook()
        return list(out) if isinstance(out, (list, tuple)) else [out]
    return [getattr(mod, name) for name in sorted(vars(mod))
            if type(getattr(mod, name)).__name__ == "PlaneSpec"]


def _diag_record(d, module: str, target: str) -> dict:
    rec = {"id": d.code, "severity": d.severity, "module": module,
           "target": target, "message": d.message}
    if d.anchor:
        rec["file"], rec["line"] = d.anchor[0], d.anchor[1]
    if d.node:
        rec["node"] = d.node
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_lint", description="static analysis for windflow_tpu "
        "graphs (docs/CHECKS.md)")
    ap.add_argument("modules", nargs="*",
                    help="dotted module names or .py paths to lint")
    ap.add_argument("--plane", action="append", default=[],
                    metavar="SPEC",
                    help="lint a declared multi-host topology: a module "
                    "exposing PlaneSpec objects (wf_plane_spec() hook "
                    "or module level); repeatable")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON document instead of the "
                    "human-readable report (see module docstring)")
    ap.add_argument("--error", action="store_true",
                    help="exit 1 when any diagnostic is reported")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print wf-lint:disable'd diagnostics")
    args = ap.parse_args(argv)
    if not args.modules and not args.plane:
        ap.print_usage(sys.stderr)
        print("wf_lint: name at least one module or --plane spec",
              file=sys.stderr)
        return 2

    from windflow_tpu.check import validate

    out = [] if args.as_json else None
    out_sup = [] if args.as_json else None
    n_diags = n_targets = 0
    failed = False

    def run_targets(spec, targets):
        nonlocal n_diags, n_targets
        for target in targets:
            n_targets += 1
            tname = getattr(target, "name", type(target).__name__)
            report = validate(target)
            for d in report:
                n_diags += 1
                if out is not None:
                    out.append(_diag_record(d, spec, tname))
                else:
                    print(f"{d.where()}: {d.code} {d.severity}: "
                          f"{d.message}")
            if args.show_suppressed:
                for d in report.suppressed:
                    if out_sup is not None:
                        out_sup.append(_diag_record(d, spec, tname))
                    else:
                        print(f"{d.where()}: {d.code} suppressed: "
                              f"{d.message}")
            if not len(report) and out is None:
                print(f"{spec}:{tname}: OK")

    for spec in args.modules:
        try:
            mod = load_module(spec)
        except Exception as e:
            print(f"{spec}: import failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        targets = collect_targets(mod)
        if not targets:
            print(f"{spec}: no dataflow graphs found (define "
                  f"wf_check_pipelines() or module-level MultiPipe/"
                  f"Dataflow/WireConfig/PlanePolicy objects)",
                  file=sys.stderr)
            failed = True
            continue
        run_targets(spec, targets)

    for spec in args.plane:
        try:
            mod = load_module(spec)
        except Exception as e:
            print(f"{spec}: import failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        specs = collect_plane_specs(mod)
        if not specs:
            print(f"{spec}: no PlaneSpec found (define wf_plane_spec() "
                  f"or module-level PlaneSpec objects)", file=sys.stderr)
            failed = True
            continue
        run_targets(spec, specs)

    if out is not None:
        doc = {"targets": n_targets, "diagnostics": out}
        if args.show_suppressed:
            doc["suppressed"] = out_sup
        json.dump(doc, sys.stdout, indent=2)
        print()
    else:
        print(f"wf-lint: {n_targets} graph(s), {n_diags} diagnostic(s)")
    if failed:
        return 2
    if args.error and n_diags:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
