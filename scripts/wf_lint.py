#!/usr/bin/env python3
"""wf-lint — stand-alone static analysis for windflow_tpu graphs
(docs/CHECKS.md).

Imports one or more app modules, collects their dataflow graphs, runs
the ``windflow_tpu/check`` validator, and prints each diagnostic with a
``file:line`` anchor when one is known:

    python scripts/wf_lint.py windflow_tpu.apps.ysb windflow_tpu.apps.pipe
    python scripts/wf_lint.py path/to/my_app.py --error

Graph discovery, per module:

* a callable ``wf_check_pipelines()`` (the convention the bundled bench
  apps follow) — returns an iterable of ``MultiPipe``/``Dataflow``/
  ``WireConfig`` objects to validate;
* otherwise, module-level attributes that already ARE such objects.

Exit status: 0 when clean (or diagnostics are informational), 1 under
``--error`` when any non-suppressed diagnostic was reported, 2 on usage
or import failure.  ``# wf-lint: disable=WF###`` on the anchored source
line suppresses a diagnostic (``--show-suppressed`` lists them anyway).
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_module(spec: str):
    """Import ``spec`` — a dotted module name or a path to a .py file."""
    if spec.endswith(".py") or os.path.sep in spec:
        path = os.path.abspath(spec)
        name = os.path.splitext(os.path.basename(path))[0]
        mspec = importlib.util.spec_from_file_location(name, path)
        if mspec is None:
            raise ImportError(f"cannot load {spec!r}")
        mod = importlib.util.module_from_spec(mspec)
        sys.modules.setdefault(name, mod)
        mspec.loader.exec_module(mod)
        return mod
    return importlib.import_module(spec)


def collect_targets(mod):
    """Validation targets of one module (see module docstring)."""
    hook = getattr(mod, "wf_check_pipelines", None)
    if callable(hook):
        targets = list(hook())
    else:
        targets = []
        for name in sorted(vars(mod)):
            obj = getattr(mod, name)
            cls = type(obj).__name__
            if cls in ("MultiPipe", "Dataflow", "WireConfig"):
                targets.append(obj)
    return targets


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="wf_lint", description="static analysis for windflow_tpu "
        "graphs (docs/CHECKS.md)")
    ap.add_argument("modules", nargs="+",
                    help="dotted module names or .py paths to lint")
    ap.add_argument("--error", action="store_true",
                    help="exit 1 when any diagnostic is reported")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print wf-lint:disable'd diagnostics")
    args = ap.parse_args(argv)

    from windflow_tpu.check import validate

    n_diags = n_targets = 0
    for spec in args.modules:
        try:
            mod = load_module(spec)
        except Exception as e:
            print(f"{spec}: import failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        targets = collect_targets(mod)
        if not targets:
            print(f"{spec}: no dataflow graphs found (define "
                  f"wf_check_pipelines() or module-level MultiPipe/"
                  f"Dataflow/WireConfig objects)", file=sys.stderr)
            continue
        for target in targets:
            n_targets += 1
            tname = getattr(target, "name", type(target).__name__)
            report = validate(target)
            for d in report:
                n_diags += 1
                print(f"{d.where()}: {d.code} {d.severity}: {d.message}")
            if args.show_suppressed:
                for d in report.suppressed:
                    print(f"{d.where()}: {d.code} suppressed: "
                          f"{d.message}")
            if not len(report):
                print(f"{spec}:{tname}: OK")
    print(f"wf-lint: {n_targets} graph(s), {n_diags} diagnostic(s)")
    if args.error and n_diags:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
