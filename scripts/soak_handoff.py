"""Seeded handoff-chaos soak: a supervised 2-worker row plane driven
through randomized :class:`~windflow_tpu.parallel.faults.HandoffChaos`
schedules (a worker killed at a sealed epoch -> its peer adopts via the
replicated portable checkpoint, or rolled -> the same member restarts
against its own store with ``resume_epoch=``), optionally compounded
with per-sender wire :class:`~windflow_tpu.parallel.faults.FaultPlan`
chaos (kill / torn / dup) on the feeder's journaling links.  Checked
*differentially*: the merged per-key outputs (sealed prefixes + adopted
or resumed tails) must be byte-identical to the uncrashed oracle —
no gaps, no duplicates (docs/ROBUSTNESS.md "Cross-host recovery").

Mirrors the soak_wire.py pattern: standalone, seeded, any failure is
reproducible in isolation:

    python scripts/soak_handoff.py --n 30 --seed 11       # the soak
    python scripts/soak_handoff.py --seed 11 --case 4     # one repro

The test suite runs a small slow-marked slice of this via
tests/test_portable.py (tier-1 excludes it with -m 'not slow').
"""

import argparse
import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _apply(rows, sums, sink):
    for r in rows:
        k, v = int(r["key"]), int(r["value"])
        sums[k] = sums.get(k, 0) + v
        sink.append([k, int(r["id"]), sums[k]])


class _Worker:
    """One in-process plane member: data receiver + per-epoch sealing
    store + portable spool + monitor endpoints, with the chaos hooks
    (hard death at a seal, or roll-in-place with ``resume_epoch=``)."""

    def __init__(self, pid, peer, root):
        from windflow_tpu.obs.federation import (FederationPolicy,
                                                 FederationShipper,
                                                 TelemetryAggregator)
        from windflow_tpu.parallel.channel import RowReceiver, WireResume
        from windflow_tpu.recovery.portable import PortableSpool
        from windflow_tpu.recovery.store import CheckpointStore
        self.pid, self.peer = pid, peer
        self.store = CheckpointStore(os.path.join(root, f"store{pid}"),
                                     retain=16)
        self.spool = PortableSpool(os.path.join(root, f"spool{pid}"))
        self.recv = RowReceiver(1, resume=WireResume(deadline=30.0),
                                ack_epochs=False, accept_timeout=30.0)
        self.port = self.recv.port
        # telemetry federation riding the same monitor links the
        # portable checkpoints use: each worker ships a per-seal
        # snapshot to its peer, whose aggregator spools the ring when
        # the plane declares this worker dead — the black box the soak
        # asserts after a kill (docs/OBSERVABILITY.md "Federation &
        # SLOs")
        fed_pol = FederationPolicy(host=str(pid), period=0.05)
        self.fed_spool = os.path.join(root, f"fedspool{pid}")
        self.agg = TelemetryAggregator(fed_pol, spool_dir=self.fed_spool)
        self.shipper = FederationShipper(fed_pol, host=str(pid),
                                         dataflow_name=f"w{pid}")
        # a short monitor-link resume deadline: after a peer death,
        # a replicate() that lost the mid-transmit race against the
        # ack reader's EOF detection stalls the survivor's seal loop
        # for at most this long (per-peer failures are swallowed and
        # the next seal re-ships — docs/ROBUSTNESS.md)
        self.mon_recv = RowReceiver(1, resume=WireResume(deadline=5.0),
                                    accept_timeout=30.0,
                                    ckpt_sink=self.spool,
                                    telemetry_sink=self.agg)
        self.mon_port = self.mon_recv.port
        self.mon_snd = None
        self.sup = None
        self.sealed_rows, self.adopted_rows = [], []
        self.fate, self.error = "clean", None
        self.adopt_done = threading.Event()
        self.adopt_done.set()   # cleared only when an adoption starts

    def supervise(self, workers, addresses):
        from windflow_tpu.parallel.channel import (RowSender, WireConfig,
                                                   WireResume)
        from windflow_tpu.parallel.plane import (PlanePolicy,
                                                 PlaneSupervisor)
        self.mon_snd = RowSender("127.0.0.1", workers[self.peer].mon_port,
                                 resume=WireResume(deadline=5.0),
                                 connect_deadline=10.0)
        policy = PlanePolicy(
            down_deadline=0.5, period=0.05, candidates={1, 2},
            wire=WireConfig(connect_deadline=10.0, heartbeat=2.0,
                            stall_timeout=30.0, resume=True,
                            recovery=False))
        self.shipper.bind({self.peer: self.mon_snd})
        self.sup = PlaneSupervisor(
            self.pid, addresses, {self.peer: self.mon_snd}, policy=policy,
            store=self.store, spool=self.spool, on_adopt=self._on_adopt,
            on_death=self.agg.on_death)
        self.sup.start()

    def _on_adopt(self, dead, epoch, st):
        from windflow_tpu.recovery.epoch import EpochMarker
        self.adopt_done.clear()

        def run():
            try:
                sums = st.load(int(epoch), "sums") if st else {}
                tr = self.sup.takeover_receiver(dead, epoch, n_senders=1)
                pend = []
                for item in tr.batches(epoch_markers=True):
                    if isinstance(item, EpochMarker):
                        self.adopted_rows.extend(pend)
                        pend = []
                        tr.ack_epoch(int(item.epoch))
                        continue
                    _apply(item, sums, pend)
                tr.close()
            except Exception as e:              # noqa: BLE001
                self.error = self.error or e
            finally:
                self.adopt_done.set()

        threading.Thread(target=run, daemon=True).start()

    def run(self, chaos):
        """The seal loop; returns when the stream EOSes or the chaos
        plan kills this member."""
        from windflow_tpu.parallel.channel import RowReceiver, WireResume
        from windflow_tpu.recovery.epoch import EpochMarker
        sums, pending = {}, []
        try:
            while True:
                rolled_to = None
                for item in self.recv.batches(epoch_markers=True):
                    if not isinstance(item, EpochMarker):
                        _apply(item, sums, pending)
                        continue
                    e = int(item.epoch)
                    n = self.store.save_blob(e, "sums", dict(sums))
                    self.store.commit(e, {"sums": {"bytes": n}})
                    self.sealed_rows.extend(pending)
                    pending = []
                    self.sup.replicate(e)
                    # force-ship one telemetry snapshot per seal (no
                    # sampler runs here): the kill epoch's snapshot is
                    # the last thing the victim says, and the
                    # survivor's black box must hold it
                    self.shipper.ship({"t": time.time(), "seq": e,
                                       "dataflow": f"w{self.pid}",
                                       "nodes": [],
                                       "counters": {"sealed": e}})
                    self.recv.ack_epoch(e)
                    ev = chaos.event_at(self.pid, e)
                    if ev == "kill":
                        self.fate = "killed"
                        self._die()
                        return
                    if ev == "roll":
                        self.fate = "rolled"
                        rolled_to = e
                        break
                if rolled_to is None:
                    return   # clean EOS
                # roll-in-place: drop the link without EOS, rebind the
                # SAME port with resume_epoch= and restore our own store
                self.recv.close()
                self.recv = RowReceiver(
                    1, port=self.port, resume=WireResume(deadline=30.0),
                    resume_epoch=rolled_to, ack_epochs=False,
                    accept_timeout=30.0)
                sums = self.store.load(rolled_to, "sums")
                pending = []
        except Exception as e:                  # noqa: BLE001
            self.error = self.error or e

    def _die(self):
        """kill -9 equivalent: every socket drops without EOS."""
        for obj in (self.recv, self.mon_recv):
            try:
                obj.close()
            except Exception:                   # noqa: BLE001
                pass
        try:
            self.mon_snd._sock.close()
        except Exception:                       # noqa: BLE001
            pass
        self.sup.close()

    def teardown(self):
        if self.fate == "killed":
            return
        self.sup.close()
        try:
            self.mon_snd.abort()
        except Exception:                       # noqa: BLE001
            pass
        for obj in (self.recv, self.mon_recv):
            try:
                obj.close()
            except Exception:                   # noqa: BLE001
                pass


def run_case(seed: int, case: int, verbose: bool = False) -> dict:
    """One randomized handoff-chaos case over a live 2-worker plane;
    raises AssertionError with the repro command on any divergence."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import RowSender, WireResume
    from windflow_tpu.parallel.faults import FaultPlan, HandoffChaos

    rng = random.Random(seed * 1_000_003 + case)
    n_epochs = rng.randint(4, 8)
    bpe = rng.randint(1, 3)           # batches per epoch
    n_keys = rng.randint(4, 8)
    chaos = HandoffChaos.seeded(rng.randrange(2**31), pids=(1, 2),
                                last_epoch=n_epochs)
    plans = {}
    if rng.random() < 0.5:
        horizon = n_epochs * (bpe + 1)
        plans = {w: FaultPlan.seeded(rng.randrange(2**31),
                                     horizon=horizon,
                                     n_faults=rng.randint(1, 2),
                                     kinds=("kill", "torn", "dup"))
                 for w in (1, 2)}
    params = dict(n_epochs=n_epochs, bpe=bpe, n_keys=n_keys,
                  chaos=repr(chaos),
                  plans={w: repr(p) for w, p in plans.items()})
    repro = f"python scripts/soak_handoff.py --seed {seed} --case {case}"
    if verbose:
        print(f"case {case}: {params}")

    schema = Schema(value=np.int64)
    with tempfile.TemporaryDirectory(prefix="soak_handoff_") as root:
        workers = {1: _Worker(1, 2, root), 2: _Worker(2, 1, root)}
        addresses = {w: ("127.0.0.1", workers[w].port) for w in (1, 2)}
        for w in workers.values():
            w.supervise(workers, addresses)
        threads = {w: threading.Thread(target=workers[w].run,
                                       args=(chaos,), daemon=True)
                   for w in (1, 2)}
        for t in threads.values():
            t.start()
        senders = {w: RowSender("127.0.0.1", workers[w].port,
                                resume=WireResume(deadline=30.0),
                                faults=plans.get(w),
                                connect_deadline=10.0)
                   for w in (1, 2)}
        bi = 0
        for e in range(1, n_epochs + 1):
            for _ in range(bpe):
                keys = np.arange(n_keys, dtype=np.int64)
                ids = np.full(n_keys, bi, dtype=np.int64)
                vals = 13 * ids + keys + 1
                for w, snd in senders.items():
                    m = (1 + keys % 2) == w
                    snd.send(batch_from_columns(
                        schema, key=keys[m], id=ids[m], ts=ids[m],
                        value=vals[m]))
                bi += 1
            for snd in senders.values():
                snd.send_epoch(e)
        # the feeder must outlive the chaos event: wait until it fired
        # and the journaling link to that worker noticed the drop, so
        # close() resume-cycles (reconnect + replay + EOS) instead of
        # writing EOS into a half-closed link nobody will ever read
        event_pid = next(iter({**chaos.kill, **chaos.roll}))
        t0 = time.monotonic()
        while workers[event_pid].fate == "clean":
            if time.monotonic() - t0 > 30.0:
                raise AssertionError(
                    f"{repro}: chaos event on worker {event_pid} "
                    f"never fired (params {params})")
            time.sleep(0.01)
        # a beat for EOF to reach the journaling link's ack reader;
        # close() then resume-cycles (reconnect + replay) if the link
        # is down, or EOSes cleanly if _deliver already resumed it
        time.sleep(0.3)
        try:
            for snd in senders.values():
                snd.close()
        except Exception as e:                  # noqa: BLE001
            states = {w.pid: dict(fate=w.fate, error=repr(w.error),
                                  dead=w.sup.dead())
                      for w in workers.values()}
            raise AssertionError(
                f"{repro}: feeder close failed: {e!r} (worker states "
                f"{states}, params {params})") from e

        for w, t in threads.items():
            t.join(timeout=60)
            assert not t.is_alive(), (
                f"{repro}: worker {w} hung (params {params})")
        for w in workers.values():
            assert w.adopt_done.wait(60), (
                f"{repro}: adoption on worker {w.pid} never finished "
                f"(params {params})")
        for w in workers.values():
            assert w.error is None, (
                f"{repro}: worker {w.pid} raised {w.error!r} "
                f"(params {params})")
        got = {}
        for w in workers.values():
            for k, rid, cum in (*w.sealed_rows, *w.adopted_rows):
                got.setdefault(k, []).append([rid, cum])
        for rows in got.values():
            rows.sort()
        # the black-box half of the handoff promise: after a kill, the
        # successor's federation spool must hold the victim's final
        # telemetry snapshots — including the seal the victim died at
        # (the aggregator's on_death spooled them when the plane
        # declared the death, before adoption)
        for victim, kill_epoch in chaos.kill.items():
            if workers[victim].fate != "killed":
                continue
            import glob as _glob
            survivor = workers[victim].peer
            files = _glob.glob(os.path.join(
                workers[survivor].fed_spool, f"blackbox-{victim}-*.json"))
            assert files, (
                f"{repro}: worker {victim} was killed at epoch "
                f"{kill_epoch} but the survivor's federation spool "
                f"holds no black box for it (params {params})")
            import json as _json
            with open(sorted(files)[-1]) as f:
                box = _json.load(f)
            seqs = [s.get("seq") for s in box.get("samples", ())]
            assert kill_epoch in seqs, (
                f"{repro}: the spooled black box for worker {victim} "
                f"misses its final snapshot (seal {kill_epoch}); got "
                f"seqs {seqs} (params {params})")
        for w in workers.values():
            w.teardown()

    want, sums = {}, {}
    for b in range(n_epochs * bpe):
        for k in range(n_keys):
            v = 13 * b + k + 1
            sums[k] = sums.get(k, 0) + v
            want.setdefault(k, []).append([b, sums[k]])
    assert got == want, (
        f"{repro}: merged outputs diverged from the uncrashed oracle "
        f"(params {params})")
    return params


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): the
    soak's supervised feeder + 2-worker row plane, declared as a
    check.plane.PlaneSpec (WF22x cross-host lint) plus the per-process
    wire bundle the workers run."""
    from windflow_tpu.check.plane import HostSpec, PlaneSpec
    from windflow_tpu.parallel.channel import WireConfig
    from windflow_tpu.parallel.plane import PlanePolicy

    wire = WireConfig(connect_deadline=30.0, heartbeat=2.0,
                      stall_timeout=10.0, resume=True, recovery=True)
    hosts = [
        # pid 0: the feeder — supervises the plane and federates its
        # telemetry; pids 1-2: the workers, each a portable-spool
        # replica target for its peer's takeover
        HostSpec(0, sends="row", resume=True,
                 plane=PlanePolicy(wire=wire), federate=True),
        HostSpec(1, sends="row", resume=True, ckpt_sink=True,
                 federate=True, aggregator=True),
        HostSpec(2, sends="row", resume=True, ckpt_sink=True,
                 federate=True),
    ]
    spec = PlaneSpec({0: ("127.0.0.1", 9100), 1: ("127.0.0.1", 9101),
                      2: ("127.0.0.1", 9102)}, hosts,
                     name="soak_handoff", wire=wire)
    return [spec, wire]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=30, help="number of cases")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--case", type=int, default=None,
                    help="run exactly one case (repro mode)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    if args.case is not None:
        run_case(args.seed, args.case, verbose=True)
        print("OK")
        return
    for case in range(args.n):
        run_case(args.seed, case, verbose=args.verbose)
        if (case + 1) % 10 == 0:
            print(f"{case + 1}/{args.n} cases OK")
    print(f"all {args.n} cases OK")


if __name__ == "__main__":
    main()
