"""Seeded crash-recovery soak: randomized windowed graphs with kill-point
injection into a stateful worker, recovered under a RecoveryPolicy and
checked *differentially* against the same graph's uncrashed run — the
recovered output must be byte-identical (docs/ROBUSTNESS.md "Recovery").

Mirrors the soak_overload.py pattern: standalone, seeded, and any failure
is reproducible in isolation:

    python scripts/soak_crash.py --n 200 --seed 11       # the soak
    python scripts/soak_crash.py --seed 11 --case 42     # one repro

The test suite runs a small slow-marked slice of this via
tests/test_recovery.py (tier-1 excludes it with -m 'not slow').

--native runs the same differential over the C++ resident core's state
ABI (docs/ROBUSTNESS.md "Native state ABI"): randomized WinSeqTPU
graphs that route to NativeResidentCore, killed mid-stream and
restored from the exported blob:

    python scripts/soak_crash.py --native --n 50 --seed 11
    python scripts/soak_crash.py --native --seed 11 --case 7
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _batches(schema, n_batches, rows, n_keys, seed):
    rng = np.random.default_rng((seed, 0xbeef))
    ctr = {}
    for _ in range(n_batches):
        b = np.zeros(rows, dtype=schema.dtype())
        keys = rng.integers(0, n_keys, rows)
        b["key"] = keys
        b["value"] = rng.integers(0, 1000, rows)
        for i, k in enumerate(keys.tolist()):
            b["id"][i] = ctr.get(k, 0)
            ctr[k] = ctr.get(k, 0) + 1
        b["ts"] = b["id"]
        yield b


def run_case(seed: int, case: int, verbose: bool = False) -> dict:
    """One randomized crash-recovery case; raises AssertionError (with
    the repro command in the message) on any divergence from the
    uncrashed differential oracle."""
    from windflow_tpu import (RecoveryPolicy, Reducer, Sink, Source,
                              WinFarm, WinSeq)
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.core.windows import WinType
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    rng = np.random.default_rng((seed, case))
    schema = Schema(value=np.int64)
    n_batches = int(rng.integers(10, 40))
    rows = int(rng.integers(16, 80))
    n_keys = int(rng.integers(1, 8))
    win = int(rng.integers(2, 16))
    slide = int(rng.integers(1, win + 1))
    win_type = WinType.CB if rng.random() < 0.7 else WinType.TB
    farm = bool(rng.random() < 0.4)
    pardegree = int(rng.integers(2, 4)) if farm else 1
    epoch_batches = int(rng.integers(2, 12))
    n_kills = int(rng.integers(1, 3))
    # farm workers share one svc-call counter across pardegree replicas
    # (the window-range multicast roughly multiplies calls), so late
    # kill points need the wider range
    kill_at = sorted(set(
        rng.integers(1, max(n_batches * (pardegree if farm else 1), 2),
                     size=n_kills).tolist()))
    use_nic = bool(rng.random() < 0.3) and not farm
    params = dict(n_batches=n_batches, rows=rows, n_keys=n_keys, win=win,
                  slide=slide, win_type=win_type.name, farm=farm,
                  pardegree=pardegree, epoch_batches=epoch_batches,
                  kill_at=kill_at, use_nic=use_nic)
    repro = f"python scripts/soak_crash.py --seed {seed} --case {case}"
    if verbose:
        print(f"case {case}: {params}")

    def pattern():
        if farm:
            return WinFarm(Reducer("sum", "value"), win, slide, win_type,
                           pardegree=pardegree, name="w")
        if use_nic:
            return WinSeq(
                lambda key, gwid, rows_: (int(rows_["value"].sum()),),
                win, slide, win_type, name="w",
                result_fields={"value": np.int64})
        return WinSeq(Reducer("sum", "value"), win, slide, win_type,
                      name="w")

    def run(recovery=None, kills=()):
        out = []
        df = Dataflow(f"soak{case}", capacity=8, recovery=recovery)
        build_pipeline(df, [
            Source(batches=lambda i: _batches(schema, n_batches, rows,
                                              n_keys, seed + case),
                   name="src"),
            pattern(),
            Sink(lambda r: out.append((int(r["key"]), int(r["id"]),
                                       int(r["value"])))
                 if r is not None else None, name="sink"),
        ])
        workers = [n for n in df.nodes
                   if n.name == "w" or n.name.startswith("w.")
                   or n.name.startswith("w_")]
        workers = [n for n in workers
                   if "emitter" not in n.name and "collector" not in n.name]
        state = {"n": 0, "todo": sorted(kills, reverse=True)}
        for node in workers:
            orig = node.svc

            def svc(batch, channel=0, _orig=orig):
                state["n"] += 1
                if state["todo"] and state["n"] >= state["todo"][-1]:
                    state["todo"].pop()
                    raise RuntimeError(f"{repro}: injected crash "
                                       f"@svc {state['n']}")
                return _orig(batch, channel)

            node.svc = svc
        df.run_and_wait_end(timeout=120)
        return out

    oracle = run()
    pol = RecoveryPolicy(epoch_batches=epoch_batches,
                         max_restarts=n_kills + 1,
                         restart_backoff=0.005)
    got = run(recovery=pol, kills=kill_at)
    if farm:
        oracle, got = sorted(oracle), sorted(got)
    assert got == oracle, (
        f"{repro}: recovered output diverged from the uncrashed oracle "
        f"({len(got)} vs {len(oracle)} rows; params {params})")
    return params


class NativeUnavailable(RuntimeError):
    """--native requested but no state-ABI native core on this host."""


def run_case_native(seed: int, case: int, verbose: bool = False) -> dict:
    """One randomized crash-recovery case over the C++ resident core:
    a WinSeqTPU graph routed to NativeResidentCore is killed mid-stream
    and its state restored through the blob ABI; output must match the
    uncrashed differential oracle byte-for-byte."""
    from windflow_tpu import RecoveryPolicy, Reducer, Sink, Source
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.core.windows import WinType
    from windflow_tpu.native import enabled
    from windflow_tpu.patterns.native_core import NativeResidentCore
    from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    lib = enabled()
    if lib is None or not getattr(lib, "wf_has_state_abi", False):
        raise NativeUnavailable(
            "native library with the state ABI unavailable (build with "
            "`make -C native`, unset WF_NO_NATIVE)")

    rng = np.random.default_rng((seed, case, 0x4e41))
    schema = Schema(value=np.int64)
    n_batches = int(rng.integers(10, 32))
    rows = int(rng.integers(16, 80))
    n_keys = int(rng.integers(1, 8))
    win = int(rng.integers(2, 16))
    slide = int(rng.integers(1, win + 1))
    win_type = WinType.CB if rng.random() < 0.7 else WinType.TB
    batch_len = int(rng.choice([16, 32, 64]))
    shards = int(rng.integers(1, 3))
    epoch_batches = int(rng.integers(2, 10))
    n_kills = int(rng.integers(1, 3))
    kill_at = sorted(set(
        rng.integers(1, max(n_batches, 2), size=n_kills).tolist()))
    params = dict(n_batches=n_batches, rows=rows, n_keys=n_keys, win=win,
                  slide=slide, win_type=win_type.name, batch_len=batch_len,
                  shards=shards, epoch_batches=epoch_batches,
                  kill_at=kill_at)
    repro = f"python scripts/soak_crash.py --native --seed {seed} " \
            f"--case {case}"
    if verbose:
        print(f"native case {case}: {params}")

    def run(recovery=None, kills=()):
        out = []
        df = Dataflow(f"nsoak{case}", capacity=8, recovery=recovery)
        build_pipeline(df, [
            Source(batches=lambda i: _batches(schema, n_batches, rows,
                                              n_keys, seed + case),
                   name="src"),
            WinSeqTPU(Reducer("sum", "value"), win, slide, win_type,
                      batch_len=batch_len, shards=shards, name="w"),
            Sink(lambda r: out.append((int(r["key"]), int(r["id"]),
                                       int(r["value"])))
                 if r is not None else None, name="sink"),
        ])
        node = next(n for n in df.nodes
                    if n.name == "w" or n.name.startswith("w."))
        if not isinstance(node.core, NativeResidentCore):
            raise NativeUnavailable(
                f"routing picked {type(node.core).__name__}, not the "
                f"native core, on this host")
        state = {"n": 0, "todo": sorted(kills, reverse=True)}
        orig = node.svc

        def svc(batch, channel=0):
            state["n"] += 1
            if state["todo"] and state["n"] >= state["todo"][-1]:
                state["todo"].pop()
                raise RuntimeError(f"{repro}: injected crash "
                                   f"@svc {state['n']}")
            return orig(batch, channel)

        node.svc = svc
        df.run_and_wait_end(timeout=300)
        return out

    pol = RecoveryPolicy(epoch_batches=epoch_batches,
                         max_restarts=n_kills + 1,
                         restart_backoff=0.005)
    # shards > 1 overlap ships completed launches in completion order,
    # so the plain run's cross-key interleave is wall-clock; recovery
    # mode pins overlap off (patterns/native_core.py) — judge the crash
    # against an uncrashed run under the SAME policy so both sides are
    # deterministic and the compare stays byte-exact
    oracle = run(recovery=pol if shards > 1 else None)
    got = run(recovery=pol, kills=kill_at)
    assert got == oracle, (
        f"{repro}: recovered native-core output diverged from the "
        f"uncrashed oracle ({len(got)} vs {len(oracle)} rows; "
        f"params {params})")
    return params


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the crash-recovery topology — source ->
    window farm -> sink under a RecoveryPolicy.  The sink opts into
    restart (its real body is an idempotent list append)."""
    from windflow_tpu import (RecoveryPolicy, Reducer, Sink, Source,
                              WinFarm)
    from windflow_tpu.core.windows import WinType
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    sink = Sink(lambda r: None, name="sink")
    sink.recoverable = True
    df = Dataflow("soak_crash_lint", capacity=8,
                  recovery=RecoveryPolicy(epoch_batches=4))
    build_pipeline(df, [
        Source(batches=lambda i: iter(()), name="src"),
        WinFarm(Reducer("sum", "value"), 8, 4, WinType.CB, pardegree=2,
                name="w"),
        sink])
    return [df]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=100, help="number of cases")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--case", type=int, default=None,
                    help="run exactly one case (repro mode)")
    ap.add_argument("--native", action="store_true",
                    help="soak the C++ resident core's state ABI")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    case_fn = run_case_native if args.native else run_case
    if args.case is not None:
        case_fn(args.seed, args.case, verbose=True)
        print("OK")
        return
    for case in range(args.n):
        case_fn(args.seed, case, verbose=args.verbose)
        if (case + 1) % 10 == 0:
            print(f"{case + 1}/{args.n} cases OK")
    print(f"all {args.n} cases OK")


if __name__ == "__main__":
    main()
