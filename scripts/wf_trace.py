"""wf_trace — latency attribution from sampled spans, and Perfetto export.

Reads the ``trace.jsonl`` the span tracer appends (``Dataflow(trace=
TracePolicy(...))``, docs/OBSERVABILITY.md §tracing) and answers "p95
tripled — WHICH stage?" two ways:

* the default text report: per-stage queue-wait / service p50/p95/p99
  over the sampled hops, the end-to-end distribution per trace, the
  device-launch phase breakdown (``device_put`` / ``dispatch`` /
  ``harvest_wait`` child spans), and the control-plane span counts;
* ``--chrome out.json``: Chrome trace-event JSON — open it in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``.  Every sampled
  batch renders as a queue slice + service slice on its node's track,
  device launches as child slices, and epoch/checkpoint/rescale as
  instant events (both the tracer's ``ctrl`` spans and, when an
  ``events.jsonl`` sits beside the trace, the engine's recovery/control
  events).

    WF_LOG_DIR=/tmp/wf python my_job.py        # with trace= set
    python scripts/wf_trace.py /tmp/wf                 # text report
    python scripts/wf_trace.py /tmp/wf --json          # machine-readable
    python scripts/wf_trace.py /tmp/wf --chrome t.json # Perfetto
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: event-log kinds worth a timeline instant (docs/OBSERVABILITY.md)
_INSTANT_EVENTS = ("epoch", "checkpoint", "rescale")


def read_records(path):
    """Parse trace.jsonl; returns a list of span dicts (torn tail lines,
    from a still-running writer, are skipped)."""
    records = []
    with open(path) as f:
        for line in f:
            if not line.endswith("\n"):
                break
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return records


def read_events(path):
    """epoch/checkpoint/rescale lines of an events.jsonl (empty list
    when the file is absent)."""
    if not path or not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if not line.endswith("\n"):
                break
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("event") in _INSTANT_EVENTS:
                out.append(rec)
    return out


# ---------------------------------------------------------------- summary

def _pcts(values):
    if not len(values):
        return {}
    a = np.asarray(values, dtype=np.float64)
    p50, p95, p99 = np.percentile(a, (50, 95, 99))
    return {"mean": float(a.mean()), "p50": float(p50),
            "p95": float(p95), "p99": float(p99)}


def summarize(records):
    """Aggregate span records into the report dict (pure: testable)."""
    hops = [r for r in records if r.get("kind") == "hop"]
    launches = [r for r in records if r.get("kind") == "launch"]
    ctrls = [r for r in records if r.get("kind") == "ctrl"]
    stages = {}
    traces = {}
    for s in hops:
        st = stages.setdefault(s["node"], {"q_us": [], "svc_us": [],
                                           "end_us": [], "rows": 0})
        st["q_us"].append(s["q_us"])
        st["svc_us"].append(s["svc_us"])
        st["end_us"].append(s["end_us"])
        st["rows"] += s.get("rows", 0)
        tr = traces.setdefault((s["dataflow"], s["trace"]),
                               {"end_us": 0.0, "hops": 0})
        tr["end_us"] = max(tr["end_us"], s["end_us"])
        tr["hops"] += 1
    # stage order: median completion offset approximates topology order
    order = sorted(stages,
                   key=lambda n: float(np.median(stages[n]["end_us"])))
    stage_rows = [{"node": name, "n": len(stages[name]["q_us"]),
                   "queue_us": _pcts(stages[name]["q_us"]),
                   "svc_us": _pcts(stages[name]["svc_us"])}
                  for name in order]
    phases = {}
    for rec in launches:
        phases.setdefault(rec.get("phase", "?"), []).append(rec["dur_us"])
    rep = {"n_spans": len(records), "n_hops": len(hops),
           "n_traces": len(traces), "stages": stage_rows,
           "end_to_end_us": _pcts([t["end_us"] for t in traces.values()]),
           "launch_phases": {p: dict(_pcts(v), n=len(v))
                             for p, v in sorted(phases.items())},
           "ctrl": {}}
    for rec in ctrls:
        key = rec.get("name", "?")
        cur = rep["ctrl"].setdefault(key, {"n": 0, "dur_us": 0.0})
        cur["n"] += 1
        cur["dur_us"] += rec.get("dur_us", 0.0)
    if stage_rows and rep["end_to_end_us"].get("mean"):
        worst = max(stage_rows, key=lambda s: (s["queue_us"]["mean"]
                                               + s["svc_us"]["mean"]))
        rep["critical_stage"] = worst["node"]
        q_mean = sum(s["queue_us"]["mean"] for s in stage_rows)
        c_mean = sum(s["svc_us"]["mean"] for s in stage_rows)
        total = max(rep["end_to_end_us"]["mean"], q_mean + c_mean)
        rep["shares"] = {"queue": round(q_mean / total, 4),
                         "compute": round(c_mean / total, 4),
                         "launch_async": round(
                             max(total - q_mean - c_mean, 0.0) / total, 4)}
    return rep


def _fmt_us(v):
    return f"{v / 1e3:8.2f}" if v is not None else "       -"


def render(rep):
    lines = [f"wf_trace  spans={rep['n_spans']}  hops={rep['n_hops']}  "
             f"traces={rep['n_traces']}"]
    if not rep["n_hops"]:
        lines.append("no hop spans recorded (was trace= set, with a "
                     "trace dir?)")
        return "\n".join(lines)
    lines.append("")
    lines.append(f"{'STAGE':<30} {'N':>6}  {'Q_P50':>8} {'Q_P95':>8} "
                 f"{'Q_P99':>8}  {'S_P50':>8} {'S_P95':>8} {'S_P99':>8}"
                 f"   (ms)")
    for s in rep["stages"]:
        q, v = s["queue_us"], s["svc_us"]
        lines.append(
            f"{s['node']:<30} {s['n']:>6}  {_fmt_us(q['p50'])} "
            f"{_fmt_us(q['p95'])} {_fmt_us(q['p99'])}  {_fmt_us(v['p50'])} "
            f"{_fmt_us(v['p95'])} {_fmt_us(v['p99'])}")
    e2e = rep["end_to_end_us"]
    lines.append("")
    lines.append(f"end-to-end (ms): p50={e2e['p50'] / 1e3:.2f}  "
                 f"p95={e2e['p95'] / 1e3:.2f}  p99={e2e['p99'] / 1e3:.2f}"
                 f"  over {rep['n_traces']} sampled batches")
    if "shares" in rep:
        sh = rep["shares"]
        lines.append(f"share: queue={100 * sh['queue']:.0f}%  "
                     f"compute={100 * sh['compute']:.0f}%  "
                     f"launch/async={100 * sh['launch_async']:.0f}%"
                     f"   critical stage: {rep['critical_stage']}")
    for phase, st in rep["launch_phases"].items():
        lines.append(f"launch {phase:<14} n={st['n']:<6} "
                     f"p50={st['p50'] / 1e3:.3f} ms  "
                     f"p95={st['p95'] / 1e3:.3f} ms")
    for name, st in sorted(rep["ctrl"].items()):
        lines.append(f"ctrl {name:<16} n={st['n']:<6} "
                     f"total={st['dur_us'] / 1e3:.2f} ms")
    return "\n".join(lines)


# ----------------------------------------------------------- Perfetto

def chrome_trace(records, events=()) -> dict:
    """Convert span records (+ optional events.jsonl instants) into
    Chrome trace-event JSON (the object form Perfetto and
    chrome://tracing load).  Timestamps are the records' wall-clock
    ``t`` in microseconds; a hop renders as a queue slice + service
    slice (ph ``X``) on its node's thread track, launches as child
    slices, ctrl spans and recovery/control events as process-scoped
    instants (ph ``i``), and each trace carries flow arrows (ph
    ``s``/``t``) from source to sink."""
    pids = {}          # dataflow -> pid
    tids = {}          # (dataflow, node) -> tid
    ev = []

    def _pid(df):
        p = pids.get(df)
        if p is None:
            p = pids[df] = len(pids) + 1
            ev.append({"ph": "M", "pid": p, "name": "process_name",
                       "args": {"name": df}})
        return p

    def _tid(df, node):
        key = (df, node)
        t = tids.get(key)
        if t is None:
            t = tids[key] = sum(1 for k in tids if k[0] == df) + 1
            ev.append({"ph": "M", "pid": _pid(df), "tid": t,
                       "name": "thread_name", "args": {"name": node}})
        return t

    for r in records:
        kind = r.get("kind")
        df = r.get("dataflow", "?")
        node = r.get("node") or "?"
        t_us = r["t"] * 1e6
        pid, tid = _pid(df), _tid(df, node)
        args = {k: r[k] for k in ("trace", "span", "parent", "rows",
                                  "end_us") if r.get(k) is not None}
        if kind == "hop":
            ts_svc = t_us - r["svc_us"]
            if r["q_us"] or r["svc_us"]:
                ev.append({"ph": "X", "pid": pid, "tid": tid,
                           "ts": ts_svc - r["q_us"], "dur": r["q_us"],
                           "name": "queue", "cat": "queue",
                           "args": args})
                ev.append({"ph": "X", "pid": pid, "tid": tid,
                           "ts": ts_svc, "dur": max(r["svc_us"], 1.0),
                           "name": "svc", "cat": "service",
                           "args": args})
            # flow arrows stitch the trace across tracks/processes:
            # a start at the root hop, steps at every later hop
            ev.append({"ph": "s" if r.get("parent") is None else "t",
                       "pid": pid, "tid": tid, "ts": ts_svc,
                       "id": r["trace"], "name": "trace",
                       "cat": "trace"})
        elif kind == "launch":
            args["phase"] = r.get("phase")
            ev.append({"ph": "X", "pid": pid, "tid": tid,
                       "ts": t_us - r["dur_us"], "dur": r["dur_us"],
                       "name": r.get("phase", "launch"), "cat": "launch",
                       "args": args})
        elif kind == "ctrl":
            ev.append({"ph": "i", "s": "p", "pid": pid, "tid": tid,
                       "ts": t_us,
                       "name": f"{r.get('name', 'ctrl')} "
                               f"e{r.get('epoch', '?')}",
                       "cat": "ctrl",
                       "args": {k: v for k, v in r.items()
                                if k not in ("t", "kind")}})
    for rec in events:
        df = rec.get("dataflow", "?")
        pid = _pid(df)
        tid = _tid(df, rec.get("node") or rec.get("farm") or "engine")
        name = rec["event"]
        if "epoch" in rec:
            name = f"{name} e{rec['epoch']}"
        ev.append({"ph": "i", "s": "p", "pid": pid, "tid": tid,
                   "ts": rec["t"] * 1e6, "name": name, "cat": "event",
                   "args": {k: v for k, v in rec.items()
                            if k not in ("t", "event")}})
    return {"traceEvents": ev, "displayTimeUnit": "ms"}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="trace dir (WF_LOG_DIR) or a "
                                 "trace.jsonl file")
    ap.add_argument("--json", action="store_true",
                    help="print the summary report as one JSON object")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write Chrome trace-event JSON for Perfetto "
                         "('-' = stdout)")
    a = ap.parse_args(argv)

    path = a.path
    if os.path.isdir(path):
        ev_path = os.path.join(path, "events.jsonl")
        path = os.path.join(path, "trace.jsonl")
    else:
        ev_path = os.path.join(os.path.dirname(path), "events.jsonl")
    if not os.path.exists(path):
        print(f"wf_trace: no spans at {path} (run with trace= and a "
              f"trace dir set — trace_dir= or WF_LOG_DIR)",
              file=sys.stderr)
        return 2
    records = read_records(path)
    if a.chrome:
        doc = chrome_trace(records, read_events(ev_path))
        if a.chrome == "-":
            json.dump(doc, sys.stdout)
            sys.stdout.write("\n")
        else:
            with open(a.chrome, "w") as f:
                json.dump(doc, f)
            print(f"wf_trace: wrote {len(doc['traceEvents'])} events to "
                  f"{a.chrome} (open in https://ui.perfetto.dev)")
        return 0
    rep = summarize(records)
    if a.json:
        print(json.dumps(rep))
    else:
        print(render(rep))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout went away (| head); not an error worth a traceback
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
