"""Interleaved YSB A/B: host kf vs device kf-tpu (and optionally wmr vs
wmr-tpu) alternating in ONE process so tunnel weather averages across
arms — judged on MEDIAN as well as best (VERDICT r3 item 6).

Usage: python scripts/ab_ysb.py [rounds] [duration_sec] [pardegree2]
       [variant_pair: kf|wmr]
"""

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from windflow_tpu.apps.ysb import run, warmup


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    dur = float(sys.argv[2]) if len(sys.argv) > 2 else 4.0
    par = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    pair = sys.argv[4] if len(sys.argv) > 4 else "kf"
    host_v, dev_v = (pair, pair + "-tpu")

    warmup(dev_v, 1, par, 10.0, 262144)
    arms = {host_v: [], dev_v: []}
    for r in range(rounds):
        for v in (dev_v, host_v):
            out = run(v, duration_sec=dur, pardegree1=1, pardegree2=par,
                      warm=False)
            arms[v].append(out)
            print(f"round {r} {v}: {json.dumps(out)}", flush=True)
    for v, rows in arms.items():
        eps = [x["events_per_sec"] for x in rows]
        gen = [x.get("gen_events_per_sec", 0) for x in rows]
        lat = [x["avg_latency_us"] / 1e3 for x in rows]
        print(f"{v:8s}: best {max(eps):,.0f}  median "
              f"{statistics.median(eps):,.0f} ev/s   "
              f"median ingest {statistics.median(gen):,.0f} ev/s   "
              f"median avg-latency {statistics.median(lat):,.0f} ms")


if __name__ == "__main__":
    main()
