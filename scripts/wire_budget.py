"""Wire-budget breakdown for the headline bench workload (VERDICT r1 #2):
runs the bench pipeline once with WF_PROFILE=1 and prints where the wall
time goes — native bookkeeping, launch staging, device_put, dispatch,
harvest blocking, backpressure — plus bytes/rows shipped.

Usage:  WF_PROFILE=1 python scripts/wire_budget.py [n_million_tuples]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("WF_PROFILE", "1")

import bench
from windflow_tpu.utils import profile


def main():
    if len(sys.argv) > 1:
        bench.N_TUPLES = int(float(sys.argv[1]) * 1e6)
    import jax
    print("devices:", jax.devices())
    from windflow_tpu.core.tuples import Schema
    import numpy as np
    schema = Schema(value=np.int64)
    batches = bench.make_stream(schema)
    # warmup (compiles)
    bench.run_once(batches, schema)
    profile.reset()
    t0 = time.perf_counter()
    dt, n_out, total = bench.run_once(batches, schema)
    wall = time.perf_counter() - t0
    print(f"\n{bench.N_TUPLES/1e6:.0f}M tuples in {dt:.3f}s "
          f"= {bench.N_TUPLES/dt/1e6:.2f}M tuples/sec "
          f"({n_out} windows)\n")
    print(profile.dump())
    print(f"\nwall (incl. graph teardown): {wall:.3f}s")
    rep = dict(profile.report())
    ship = sum(rep.get(k, (0, 0))[0] for k in
               ("launch_take", "device_put", "dispatch", "harvest_wait"))
    print(f"ship-thread busy total: {ship:.3f}s "
          f"({100 * ship / dt:.0f}% of run)")


if __name__ == "__main__":
    main()
