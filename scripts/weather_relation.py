"""Weather-normalized bench relation: total run time vs per-run launch service.

VERDICT r4 item 2 offers two done-criteria for making the 1.5x capture
durable: a replication table with >=2 session medians >= 1.5, or "the
weather-normalized tps-vs-launch-ms relation that shows where any session
lands".  This script derives the second from DATA: every per-run
(tps, mean_launch_ms, dispatches) record in the driver artifacts
(BENCH_r0*.json) plus any sessions appended to BENCH_SESSIONS.jsonl.

Model: a bench run streams N tuples while issuing D wire dispatches whose
service partially serializes with the host loop, so total wall time is

    T(L) = T_host + k * L        (L = mean per-launch service, seconds)

with T_host the wire-free host floor and k the effective number of
NON-OVERLAPPED launch services (k < D because depth-pipelining hides most
of each RTT; k is fitted, not assumed).  Ordinary least squares over every
recorded run gives (T_host, k), and the relation answers, for any weather:

    predicted_tps(L) = N / (T_host + k * L)

and inversely, the worst launch service at which the configured bar is
still reachable:  L_bar = (N / bar_tps - T_host) / k.

Prints one JSON object with the fit, per-session residuals (is any session
slower than its weather explains?), and the bar crossing.  Exits nonzero
if fewer than 8 runs are on disk (the fit would be decorative).
"""

import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import BASELINE_TUPLES_PER_SEC, N_TUPLES  # noqa: E402

BAR_TPS = 1.5 * BASELINE_TUPLES_PER_SEC


#: artifacts OLDER than this are a different framework generation (the
#: round-4 native rebuild + round-5 keyscan changed T_host itself); fitting
#: them together conflates framework speedups with weather.  r03 runs sit
#: +0.16 s above the current-stack fit at the same launch service —
#: exactly that conflation.  --all-stacks includes them anyway.
CURRENT_STACK_MIN = 4


def load_runs(repo, all_stacks=False):
    """Every per-run record on disk: driver artifacts + session log."""
    runs = []
    for p in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        base = os.path.basename(p)
        try:
            rnum = int(base[len("BENCH_r"):].split(".")[0])
        except ValueError:
            rnum = 0
        if not all_stacks and rnum < CURRENT_STACK_MIN:
            continue
        try:
            with open(p) as f:
                parsed = json.load(f).get("parsed") or {}
        except Exception:
            continue
        for r in parsed.get("runs", []):
            if r.get("tps") and r.get("mean_launch_ms"):
                runs.append({"session": os.path.basename(p), **r})
    sess_log = os.path.join(repo, "BENCH_SESSIONS.jsonl")
    if os.path.exists(sess_log):
        with open(sess_log) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    d = json.loads(line)
                except Exception:
                    continue
                name = d.get("session", f"session_{i}")
                for r in d.get("runs", []):
                    if r.get("tps") and r.get("mean_launch_ms"):
                        runs.append({"session": name, **r})
    return runs


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runs = load_runs(repo, all_stacks="--all-stacks" in sys.argv)
    if len(runs) < 8:
        print(f"only {len(runs)} runs on disk; need >=8 for a fit",
              file=sys.stderr)
        return 1
    L = np.array([r["mean_launch_ms"] for r in runs]) / 1e3   # seconds
    T = N_TUPLES / np.array([r["tps"] for r in runs])          # seconds
    # OLS  T = T_host + k * L
    A = np.stack([np.ones_like(L), L], axis=1)
    (t_host, k), res, _rk, _sv = np.linalg.lstsq(A, T, rcond=None)
    pred = A @ np.array([t_host, k])
    ss_res = float(np.sum((T - pred) ** 2))
    ss_tot = float(np.sum((T - T.mean()) ** 2))
    r2 = 1.0 - ss_res / ss_tot if ss_tot else 0.0
    # per-session residual: mean (measured - predicted) run time, seconds.
    # A session slower than its weather explains shows positive residual.
    sessions = {}
    for r, t_meas, t_pred in zip(runs, T, pred):
        s = sessions.setdefault(r["session"], [])
        s.append(t_meas - t_pred)
    resid = {s: round(float(np.mean(v)), 3) for s, v in sessions.items()}
    l_bar_s = (N_TUPLES / BAR_TPS - t_host) / k if k > 0 else None
    out = {
        "n_runs": len(runs),
        "fit": {"t_host_s": round(float(t_host), 3),
                "k_effective_launches": round(float(k), 2),
                "r2": round(r2, 3)},
        "predicted_tps_at_launch_ms": {
            str(ms): round(N_TUPLES / (t_host + k * ms / 1e3) / 1e6, 2)
            for ms in (60, 116, 150, 200, 300, 500)},
        "bar": {"bar_tps": BAR_TPS,
                "launch_ms_at_bar": (round(l_bar_s * 1e3, 1)
                                     if l_bar_s is not None else None),
                "note": "sessions with mean launch service at or under "
                        "this meet vs_baseline>=1.5 by the fitted "
                        "relation"},
        "session_residual_s": resid,
    }
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
