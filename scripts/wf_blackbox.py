"""wf_blackbox — post-mortem timeline of a crash black-box file.

Renders the ``blackbox-<node>-<ts>.json`` flight-recorder dumps the
federation tier writes (docs/OBSERVABILITY.md "Federation & SLOs"):
either a node's own dump (on node_error / recovery give-up / plane
death — event ring + recent spans + last K sampler snapshots) or the
aggregator's spool of a dead peer's final snapshots.  Everything is
merged onto one wall-clock timeline, newest last, so the sequence that
led to the crash reads top to bottom.

    python scripts/wf_blackbox.py /tmp/wf                 # newest dump
    python scripts/wf_blackbox.py /tmp/wf/blackbox-w1-... # specific file
    python scripts/wf_blackbox.py /tmp/wf --list          # inventory
"""

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def find_dumps(path):
    """All black-box files under ``path`` (a dir or one file), newest
    first."""
    if os.path.isfile(path):
        return [path]
    return sorted(glob.glob(os.path.join(path, "blackbox-*.json")),
                  key=os.path.getmtime, reverse=True)


def load(path):
    with open(path) as f:
        return json.load(f)


def timeline(doc):
    """Merge the dump's rings onto one (t, kind, text) list, oldest
    first.  Pure: testable without files."""
    rows = []
    for e in doc.get("events", ()):
        extra = " ".join(f"{k}={v}" for k, v in e.items()
                         if k not in ("t", "event"))
        rows.append((e.get("t", 0.0), "event",
                     f"{e.get('event', '?'):<18} {extra}"))
    for s in doc.get("spans", ()):
        # tracer ring rows (obs/trace.py): per-batch spans with queue
        # wait + service in microseconds
        if not isinstance(s, dict):
            rows.append((0.0, "span", str(s)))
            continue
        rows.append((s.get("t", s.get("t0", 0.0)), "span",
                     f"{s.get('node', '?'):<18} "
                     f"q={s.get('q_us', s.get('queue_us', 0)):.0f}us "
                     f"svc={s.get('svc_us', s.get('service_us', 0)):.0f}us"))
    for rec in doc.get("samples", ()):
        nodes = rec.get("nodes", [])
        depth = max((n.get("depth", 0) for n in nodes), default=0)
        shed = sum(n.get("shed", 0) for n in nodes)
        rows.append((rec.get("t", 0.0), "sample",
                     f"seq={rec.get('seq', 0)} nodes={len(nodes)} "
                     f"max_depth={depth} shed={shed} "
                     f"dead_letters={rec.get('dead_letters', 0)}"))
    rows.sort(key=lambda r: r[0])
    return rows


def render(doc, clock=time.localtime):
    """The full post-mortem report as a string."""
    who = doc.get("node", doc.get("host", "?"))
    head = (f"wf_blackbox  {who}  reason={doc.get('reason', '?')}  "
            f"dumped={time.strftime('%H:%M:%S', clock(doc.get('t', 0)))}")
    lines = [head]
    extra = {k: v for k, v in doc.items()
             if k not in ("v", "node", "host", "t", "reason", "events",
                          "spans", "samples")}
    if extra:
        lines.append("  " + "  ".join(f"{k}={v}"
                                      for k, v in sorted(extra.items())))
    lines.append("")
    rows = timeline(doc)
    if not rows:
        lines.append("  (empty rings: nothing was recorded before the "
                     "dump)")
    for t, kind, text in rows:
        lines.append(f"  {time.strftime('%H:%M:%S', clock(t))} "
                     f"[{kind:<6}] {text}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="trace/spool dir, or one "
                                 "blackbox-*.json file")
    ap.add_argument("--list", action="store_true",
                    help="inventory the dumps instead of rendering one")
    a = ap.parse_args(argv)

    dumps = find_dumps(a.path)
    if not dumps:
        print(f"wf_blackbox: no blackbox-*.json under {a.path}",
              file=sys.stderr)
        return 2
    if a.list:
        for p in dumps:
            try:
                doc = load(p)
            except (OSError, json.JSONDecodeError):
                print(f"{p}  (unreadable)")
                continue
            print(f"{p}  {doc.get('node', doc.get('host', '?'))}  "
                  f"reason={doc.get('reason', '?')}  "
                  f"events={len(doc.get('events', ()))} "
                  f"spans={len(doc.get('spans', ()))} "
                  f"samples={len(doc.get('samples', ()))}")
        return 0
    print(render(load(dumps[0])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
