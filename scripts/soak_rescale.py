"""Seeded live-rescale soak: a Zipf-keyed source whose rate ramps up and
down (the diurnal-swing shape) feeds a Key_Farm under a ControlPolicy —
scripted rescale requests at randomized times plus admission control —
and the output is checked *differentially* against the same graph's
fixed-width oracle run: a farm rescaled N→N±k (and back) mid-stream must
produce byte-identical results, per-key order preserved, no drops or
duplicates (docs/CONTROL.md).

Mirrors the soak_overload.py / soak_crash.py pattern: standalone,
seeded, and any failure is reproducible in isolation:

    python scripts/soak_rescale.py --n 100 --seed 23      # the soak
    python scripts/soak_rescale.py --seed 23 --case 42    # one repro

The test suite runs a small slow-marked slice of this via
tests/test_control.py (tier-1 excludes it with -m 'not slow').
"""

import argparse
import contextlib
import os
import sys
import threading
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _zipf_batches(schema, n_batches, rows, n_keys, a, seed):
    """Zipf-keyed batches with per-key dense ids and a rate ramp: batch
    sizes swell and shrink over the stream (the content, not the
    timing, is what the differential pins)."""
    rng = np.random.default_rng((seed, 0xcafe))
    ctr = {}
    for b in range(n_batches):
        # diurnal-ish ramp: 0.4x .. 1.6x of the nominal batch size
        scale = 1.0 + 0.6 * np.sin(2 * np.pi * b / max(n_batches - 1, 1))
        n = max(4, int(rows * scale))
        batch = np.zeros(n, dtype=schema.dtype())
        keys = (rng.zipf(a, size=n) - 1) % n_keys
        batch["key"] = keys
        batch["value"] = rng.integers(0, 1000, n)
        for i, k in enumerate(keys.tolist()):
            batch["id"][i] = ctr.get(k, 0)
            ctr[k] = ctr.get(k, 0) + 1
        batch["ts"] = batch["id"]
        yield batch


def run_case(seed: int, case: int, verbose: bool = False) -> dict:
    """One randomized rescale case; raises AssertionError (with the
    repro command in the message) on any divergence from the fixed-width
    oracle.  Returns the params dict incl. how many rescales landed."""
    from windflow_tpu import (KeyFarm, MultiPipe, RecoveryPolicy, Reducer,
                              Sink, Source)
    from windflow_tpu.control import Admission, ControlPolicy, Rescale
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.core.windows import WinType

    rng = np.random.default_rng((seed, case))
    schema = Schema(value=np.int64)
    n_batches = int(rng.integers(40, 120))
    rows = int(rng.integers(32, 96))
    n_keys = int(rng.integers(6, 48))
    zipf_a = float(rng.uniform(1.3, 2.5))
    win = int(rng.integers(2, 16))
    slide = int(rng.integers(1, win + 1))
    win_type = WinType.CB if rng.random() < 0.7 else WinType.TB
    max_w = int(rng.integers(3, 7))
    init_w = int(rng.integers(1, max_w))
    epoch_batches = int(rng.integers(2, 10))
    admission = bool(rng.random() < 0.5)
    # scripted width schedule: (delay_s, target) pairs — the driver
    # issues them while the pipe runs; any timing is a correct timing
    n_req = int(rng.integers(2, 5))
    schedule = [(float(rng.uniform(0.02, 0.25)),
                 int(rng.integers(1, max_w + 1)))
                for _ in range(n_req)]
    params = dict(n_batches=n_batches, rows=rows, n_keys=n_keys,
                  zipf_a=round(zipf_a, 2), win=win, slide=slide,
                  win_type=win_type.name, init_w=init_w, max_w=max_w,
                  epoch_batches=epoch_batches, admission=admission,
                  schedule=schedule)
    repro = f"python scripts/soak_rescale.py --seed {seed} --case {case}"
    if verbose:
        print(f"case {case}: {params}")

    def build(control=None, recovery=None, metrics=None):
        pipe = MultiPipe(f"soak{case}", capacity=8, recovery=recovery,
                         metrics=metrics, control=control)
        pipe.add_source(Source(
            batches=lambda i: _zipf_batches(schema, n_batches, rows,
                                            n_keys, zipf_a, seed + case),
            name="src"))
        pipe.add(KeyFarm(Reducer("sum", "value"), win, slide, win_type,
                         pardegree=init_w, name="kf"))
        out = []
        pipe.add_sink(Sink(
            lambda r: out.append((int(r["key"]), int(r["id"]),
                                  int(r["value"])))
            if r is not None else None, name="sink"))
        return pipe, out

    @contextlib.contextmanager
    def _quiet():
        # the soak runs metrics with no trace_dir on purpose (no file
        # I/O per case): the WF207 guidance warning is expected noise
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=r"\[WF207\]")
            yield

    # fixed-width oracle: the same logical stream, never rescaled
    oracle_pipe, oracle = build()
    oracle_pipe.run_and_wait_end(timeout=300)

    rules = [Rescale("kf", max_workers=max_w, min_workers=1,
                     up_depth=10 ** 9, down_depth=-1, cooldown=10 ** 9)]
    if admission:
        # throttling delays emission but never changes content, so it
        # runs INSIDE the differential
        rules.append(Admission(max_rate=5e5, min_rate=5e4, high_depth=6,
                               low_depth=1, hysteresis=1, cooldown=0.05))
    pipe, got = build(
        control=ControlPolicy(rules, period=0.02),
        recovery=RecoveryPolicy(epoch_batches=epoch_batches,
                                restart_backoff=0.01),
        metrics=True)
    with _quiet():
        pipe.run()
    ctl = pipe.controller
    done = threading.Event()

    def driver():
        for delay, width in schedule:
            if done.wait(delay):
                return
            try:
                ctl.request_rescale("kf", width)
            except Exception:
                pass  # e.g. a request while one is in flight

    t = threading.Thread(target=driver, daemon=True)
    t.start()
    try:
        pipe.wait(timeout=300)
    finally:
        done.set()
    t.join(timeout=5)
    n_rescales = sum(len(fc.history) for fc in ctl.farms)
    params["rescales"] = n_rescales

    def per_key(rows):
        # each key's result sequence in arrival order: checks per-key
        # ORDER as well as drops/dups (cross-key interleave is
        # scheduling-dependent in both runs)
        d = {}
        for k, i, v in rows:
            d.setdefault(k, []).append((i, v))
        return d

    assert per_key(got) == per_key(oracle), (
        f"{repro}: rescaled output diverged from the fixed-width oracle "
        f"({len(got)} vs {len(oracle)} rows; params {params})")
    return params


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the rescale topology — keyed farm under
    a ControlPolicy + RecoveryPolicy with metrics on.  Unlike the soak
    cases (which run metrics trace-less on purpose and filter WF207),
    the lint twin supplies a trace_dir so it validates clean."""
    import tempfile

    from windflow_tpu import (KeyFarm, MultiPipe, RecoveryPolicy,
                              Reducer, Sink, Source)
    from windflow_tpu.control import ControlPolicy, Rescale
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.core.windows import WinType

    schema = Schema(value=np.int64)
    pipe = MultiPipe("soak_rescale_lint", capacity=8,
                     recovery=RecoveryPolicy(epoch_batches=4),
                     metrics=True, trace_dir=tempfile.gettempdir(),
                     control=ControlPolicy(
                         [Rescale("kf", max_workers=4, min_workers=1)]))
    pipe.add_source(Source(batches=lambda i: iter(()), schema=schema,
                           name="src"))
    pipe.add(KeyFarm(Reducer("sum", "value"), 8, 4, WinType.CB,
                     pardegree=2, name="kf"))
    sink = Sink(lambda r: None, name="sink")
    sink.recoverable = True
    pipe.add_sink(sink)
    return [pipe]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=100, help="number of cases")
    ap.add_argument("--seed", type=int, default=23)
    ap.add_argument("--case", type=int, default=None,
                    help="run exactly one case (repro mode)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    if args.case is not None:
        p = run_case(args.seed, args.case, verbose=True)
        print(f"OK ({p['rescales']} rescales)")
        return
    total = 0
    for case in range(args.n):
        p = run_case(args.seed, case, verbose=args.verbose)
        total += p["rescales"]
        if (case + 1) % 10 == 0:
            print(f"{case + 1}/{args.n} cases OK ({total} rescales so far)")
    # the schedule timings are random: single cases may legitimately see
    # no barrier in time, but a soak whose rescales NEVER land is
    # vacuous — fail loudly
    assert total > 0, "no rescale completed across the whole soak"
    print(f"all {args.n} cases OK ({total} rescales)")


if __name__ == "__main__":
    main()
