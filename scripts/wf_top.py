"""wf_top — live terminal view of a running dataflow's telemetry.

Tails the ``metrics.jsonl`` the background sampler writes
(``Dataflow(sample_period=...)`` / ``WF_SAMPLE_PERIOD``, see
docs/OBSERVABILITY.md) and renders per-node throughput, inbox occupancy
and shed/quarantine counters, plus the tail of ``events.jsonl`` — the
`top(1)` of a WindFlow graph.  Rates are derived from consecutive
samples, so the view needs two samples to warm up.

    WF_LOG_DIR=/tmp/wf WF_SAMPLE_PERIOD=0.5 python my_job.py &
    python scripts/wf_top.py /tmp/wf              # follow, 1 s refresh
    python scripts/wf_top.py /tmp/wf --once       # one frame (CI/tests)
    python scripts/wf_top.py /tmp/wf --expo       # Prometheus text dump
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_COLS = ("NODE", "DEPTH", "HWM", "BATCH/S", "TUPLES/S", "EWMA_US",
         "Q95_US", "S95_US", "SHED", "QUAR")
_W = (22, 6, 6, 10, 12, 9, 9, 9, 8, 6)


def _parse_lines(f):
    samples = []
    offset = f.tell()
    while True:
        line = f.readline()
        if not line:
            break
        if not line.endswith("\n"):
            break   # torn tail: re-read next refresh
        offset = f.tell()
        try:
            samples.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return samples, offset


def read_samples(path, offset=0):
    """Parse sample lines appended since ``offset``; returns
    (new_samples, new_offset).  A torn final line (writer mid-append) is
    left for the next read.  A file SHORTER than ``offset`` means the
    sampler rotated it (obs/sampler.py size bound): the unread tail now
    lives in ``<path>.1`` — drain that from the old offset first, then
    restart at the new file's head, so following survives the roll."""
    samples = []
    try:
        if os.path.getsize(path) < offset:
            try:
                with open(path + ".1") as f:
                    f.seek(offset)
                    samples.extend(_parse_lines(f)[0])
            except OSError:
                pass    # double-rolled between polls: tail is lost
            offset = 0
    except OSError:
        return samples, offset
    with open(path) as f:
        f.seek(offset)
        new, offset = _parse_lines(f)
        samples.extend(new)
    return samples, offset


def _rates(cur, prev):
    """Per-node {(node id): (batches/s, tuples/s)} between two samples."""
    out = {}
    if prev is None:
        return out
    dt = cur["t"] - prev["t"]
    if dt <= 0:
        return out
    before = {n["id"]: n for n in prev["nodes"]}
    for n in cur["nodes"]:
        p = before.get(n["id"])
        if p is None or "rcv_batches" not in n or "rcv_batches" not in p:
            continue
        out[n["id"]] = ((n["rcv_batches"] - p["rcv_batches"]) / dt,
                        (n["rcv_tuples"] - p["rcv_tuples"]) / dt)
    return out


def _control_line(cur):
    """Controller state from the ctl_* metrics (docs/CONTROL.md): active
    farm widths, admission rate cap, adaptive soft limit, and the
    decision counters — empty string when no control plane runs."""
    gauges = cur.get("gauges", {})
    counters = cur.get("counters", {})
    parts = []
    for k in sorted(gauges):
        if k.startswith("ctl_width_"):
            parts.append(f"width[{k[len('ctl_width_'):]}]="
                         f"{int(gauges[k])}")
    for k in sorted(gauges):
        if k.startswith("ctl_admission_rate"):
            tgt = k[len("ctl_admission_rate"):].lstrip("_") or "*"
            parts.append(f"admit[{tgt}]={gauges[k]:.0f}/s")
    if gauges.get("ctl_soft_limit"):
        parts.append(f"soft_limit={int(gauges['ctl_soft_limit'])}")
    ctl_counts = {k[4:]: v for k, v in counters.items()
                  if k.startswith("ctl_") and v}
    if ctl_counts:
        parts.append("  ".join(f"{k}={v}"
                               for k, v in sorted(ctl_counts.items())))
    return "control: " + "  ".join(parts) if parts else ""


#: plane/portable-checkpoint counters folded onto the plane line, not
#: the generic counters line (docs/ROBUSTNESS.md "Cross-host recovery")
_PLANE_COUNTERS = ("plane_handoffs", "ckpt_shipped_bytes",
                   "ckpt_fetched_bytes", "ckpt_spooled", "ckpt_fallbacks")


def _plane_line(cur):
    """Plane supervisor state (docs/ROBUSTNESS.md "Cross-host
    recovery"): membership and down counts from the plane_* gauges plus
    the handoff / portable-checkpoint counters — empty string when no
    supervised plane runs."""
    gauges = cur.get("gauges", {})
    counters = cur.get("counters", {})
    parts = []
    if "plane_members" in gauges:
        parts.append(f"members={int(gauges['plane_members'])}")
        parts.append(f"down={int(gauges.get('plane_down', 0))}")
    for k in _PLANE_COUNTERS:
        if counters.get(k):
            parts.append(f"{k}={int(counters[k])}")
    return "plane: " + "  ".join(parts) if parts else ""


def render(cur, prev, events=(), clock=time.localtime):
    """One frame of the view as a string (pure: testable without a tty)."""
    rates = _rates(cur, prev)
    head = (f"wf_top  dataflow={cur['dataflow']}  seq={cur['seq']}  "
            f"t={time.strftime('%H:%M:%S', clock(cur['t']))}  "
            f"dead_letters={cur.get('dead_letters', 0)}")
    lines = [head, ""]
    lines.append("  ".join(c.ljust(w) if i == 0 else c.rjust(w)
                           for i, (c, w) in enumerate(zip(_COLS, _W))))
    for n in cur["nodes"]:
        br, tr = rates.get(n["id"], (None, None))
        row = (n["node"],
               str(n["depth"]), str(n["hwm"]),
               f"{br:.1f}" if br is not None else "-",
               f"{tr:.0f}" if tr is not None else "-",
               f"{n['ewma_service_us_per_batch']:.1f}"
               if "ewma_service_us_per_batch" in n else "-",
               # span-tracer latency fields (docs/OBSERVABILITY.md
               # §tracing); absent on untraced graphs and on pre-trace
               # metrics.jsonl lines — render "-" either way
               f"{n['q_p95_us']:.1f}" if "q_p95_us" in n else "-",
               f"{n['svc_p95_us']:.1f}" if "svc_p95_us" in n else "-",
               str(n["shed"]), str(n["quarantined"]))
        lines.append("  ".join(c.ljust(w) if i == 0 else c.rjust(w)
                               for i, (c, w) in enumerate(zip(row, _W))))
    ctl = _control_line(cur)
    if ctl:
        lines.append("")
        lines.append(ctl)
    plane = _plane_line(cur)
    if plane:
        lines.append("")
        lines.append(plane)
    counters = {k: v for k, v in cur.get("counters", {}).items()
                if v and not k.startswith("ctl_")
                and k not in _PLANE_COUNTERS}
    # wire resume telemetry (docs/ROBUSTNESS.md "Wire resume"): the
    # journal depth is a gauge, not a counter — fold it (and any other
    # wire_ gauges) onto the same line so one glance shows resumes,
    # replayed frames, and how much tail is still journaled
    counters.update({k: int(v) for k, v in cur.get("gauges", {}).items()
                     if k.startswith("wire_") and v})
    if counters:
        lines.append("")
        lines.append("counters: " + "  ".join(
            f"{k}={v}" for k, v in sorted(counters.items())))
    if events:
        lines.append("")
        lines.append("recent events:")
        for e in events:
            extra = " ".join(f"{k}={v}" for k, v in e.items()
                             if k not in ("t", "event"))
            lines.append(
                f"  {time.strftime('%H:%M:%S', clock(e['t']))} "
                f"{e['event']:<18} {extra}")
    return "\n".join(lines)


_PLANE_COLS = ("HOST", "STATE", "AGE_S", "SEQ", "DATAFLOW", "DEPTH",
               "TUPLES", "SHED", "Q95_US")
_PLANE_W = (14, 6, 7, 6, 14, 6, 10, 8, 9)


def render_plane(state, clock=time.localtime):
    """One frame of the cluster view (``--plane``) from the aggregator's
    state file (obs/federation.py ``TelemetryAggregator.write_state``):
    one row per federated host, the plane-scope SLO signal view, and
    which objectives are burning.  Pure: testable without a tty."""
    hosts = state.get("hosts", {})
    view = state.get("view", {})
    fresh = sum(1 for h in hosts.values() if h.get("fresh"))
    head = (f"wf_top --plane  hosts={len(hosts)} fresh={fresh}  "
            f"t={time.strftime('%H:%M:%S', clock(state.get('t', 0)))}")
    lines = [head, ""]
    lines.append("  ".join(c.ljust(w) if i == 0 else c.rjust(w)
                           for i, (c, w) in enumerate(zip(_PLANE_COLS,
                                                          _PLANE_W))))
    for host in sorted(hosts):
        meta = hosts[host]
        snap = (state.get("latest") or {}).get(host) or {}
        nodes = snap.get("nodes", [])
        row = (host,
               "ok" if meta.get("fresh") else "STALE",
               f"{meta.get('age', 0.0):.1f}",
               str(meta.get("seq", 0)),
               meta.get("dataflow", ""),
               str(max((n.get("depth", 0) for n in nodes), default=0)),
               str(sum(n.get("rcv_tuples", 0) for n in nodes)),
               str(sum(n.get("shed", 0) for n in nodes)),
               f"{max((n.get('q_p95_us', 0.0) for n in nodes), default=0.0):.1f}")
        lines.append("  ".join(c.ljust(w) if i == 0 else c.rjust(w)
                               for i, (c, w) in enumerate(zip(row,
                                                              _PLANE_W))))
    parts = [f"availability={view.get('availability', 1.0):.2f}"]
    if view.get("q95_us"):
        parts.append(f"q95_us={view['q95_us']:.1f}")
    if view.get("shed_rate"):
        parts.append(f"shed_rate={view['shed_rate']:.1f}/s")
    if view.get("stale_seconds"):
        parts.append(f"stale_s={view['stale_seconds']:.1f}")
    burning = state.get("slo_burning", [])
    parts.append("slo=BURN[" + ",".join(burning) + "]" if burning
                 else "slo=ok")
    lines.append("")
    lines.append("plane: " + "  ".join(parts))
    return "\n".join(lines)


def tail_events(path, n=6):
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            if line.endswith("\n"):
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    return out[-n:]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="trace dir (WF_LOG_DIR) or a "
                                 "metrics.jsonl file")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in follow mode (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="render the latest frame and exit")
    ap.add_argument("--expo", action="store_true",
                    help="print the latest sample as Prometheus text "
                         "exposition and exit")
    ap.add_argument("--events", type=int, default=6,
                    help="event-log tail length (0 disables)")
    ap.add_argument("--plane", action="store_true",
                    help="cluster view: render the federation "
                         "aggregator's state file (federation.json in "
                         "the given dir) instead of one process's "
                         "metrics")
    a = ap.parse_args(argv)

    if a.plane:
        path = a.path
        if os.path.isdir(path):
            path = os.path.join(path, "federation.json")
        if not os.path.exists(path):
            print(f"wf_top: no federation state at {path} (is a "
                  f"TelemetryAggregator running with state_path= "
                  f"set?)", file=sys.stderr)
            return 2
        while True:
            with open(path) as f:
                try:
                    state = json.load(f)
                except json.JSONDecodeError:
                    state = None    # mid-replace race: retry next tick
            if state is not None:
                frame = render_plane(state)
                if a.once:
                    print(frame)
                    return 0
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
                sys.stdout.flush()
            elif a.once:
                print("wf_top: federation state file is unreadable",
                      file=sys.stderr)
                return 2
            time.sleep(a.interval)

    path = a.path
    if os.path.isdir(path):
        ev_path = os.path.join(path, "events.jsonl")
        path = os.path.join(path, "metrics.jsonl")
    else:
        ev_path = os.path.join(os.path.dirname(path), "events.jsonl")
    if not os.path.exists(path):
        print(f"wf_top: no metrics at {path} (is the job running with "
              f"sample_period / WF_SAMPLE_PERIOD set?)", file=sys.stderr)
        return 2

    if a.expo:
        from windflow_tpu.obs import expo
        samples, _ = read_samples(path)
        if not samples:
            print("wf_top: metrics file has no complete samples yet",
                  file=sys.stderr)
            return 2
        sys.stdout.write(expo.render_sample(samples[-1]))
        return 0

    offset = 0
    prev = cur = None
    while True:
        new, offset = read_samples(path, offset)
        for s in new:
            prev, cur = cur, s
        if cur is not None:
            events = tail_events(ev_path, a.events) if a.events else []
            frame = render(cur, prev, events)
            if a.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
        elif a.once:
            print("wf_top: metrics file has no complete samples yet",
                  file=sys.stderr)
            return 2
        time.sleep(a.interval)


if __name__ == "__main__":
    sys.exit(main())
