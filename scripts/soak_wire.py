"""Seeded wire-chaos soak: a resumable two-sender row plane driven
through randomized :class:`~windflow_tpu.parallel.faults.FaultPlan`
schedules (kill / torn frame / duplicated delivery / stalled socket),
checked *differentially* — the receiver's per-key arrival order must be
byte-identical to the unfaulted oracle (docs/ROBUSTNESS.md "Wire
resume").

Mirrors the soak_crash.py pattern: standalone, seeded, and any failure
is reproducible in isolation:

    python scripts/soak_wire.py --n 50 --seed 7        # the soak
    python scripts/soak_wire.py --seed 7 --case 12     # one repro

The test suite runs a small slow-marked slice of this via
tests/test_channel_faults.py (tier-1 excludes it with -m 'not slow').
"""

import argparse
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_case(seed: int, case: int, verbose: bool = False) -> dict:
    """One randomized wire-chaos case: two resumable senders partition a
    keyed stream to one receiver (the partition_and_ship shape), each
    sender under its own seeded FaultPlan; per-key arrival order must
    equal the generation-order oracle.  Raises AssertionError with the
    repro command on any divergence."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import (RowReceiver, RowSender,
                                               WireResume,
                                               partition_and_ship)
    from windflow_tpu.parallel.faults import FaultPlan

    rng = np.random.default_rng((seed, case))
    schema = Schema(value=np.int64)
    n_batches = int(rng.integers(8, 24))
    rows = int(rng.integers(4, 16))
    n_keys = int(rng.integers(2, 8))
    epoch_batches = int(rng.integers(2, 8))
    kinds = ["kill", "torn", "dup"]
    if rng.random() < 0.25:
        kinds.append("stall")
    n_faults = int(rng.integers(1, 4))
    # ~records per sender: its share of the batches + epoch frames
    horizon = max(4, n_batches + n_batches // epoch_batches + 2)
    plans = [FaultPlan.seeded(int(rng.integers(0, 2**31)),
                              horizon=horizon, n_faults=n_faults,
                              kinds=tuple(kinds), stall_for=0.3)
             for _ in range(2)]
    params = dict(n_batches=n_batches, rows=rows, n_keys=n_keys,
                  epoch_batches=epoch_batches,
                  plans=[repr(p) for p in plans])
    repro = f"python scripts/soak_wire.py --seed {seed} --case {case}"
    if verbose:
        print(f"case {case}: {params}")

    # the keyed stream (generation order IS the per-key oracle order)
    batches, oracle = [], {}
    ctr = 0
    for _ in range(n_batches):
        ks = rng.integers(0, n_keys, rows)
        vals = np.arange(ctr, ctr + rows)
        ctr += rows
        batches.append(batch_from_columns(
            schema, key=ks, id=vals, ts=vals, value=vals))
        for k, v in zip(ks.tolist(), vals.tolist()):
            oracle.setdefault(k, []).append(v)

    rs = WireResume(deadline=15.0)
    recv = RowReceiver(n_senders=2, resume=rs, ack_epochs=True)
    got, errs = {}, []

    def consume():
        try:
            for b in recv.batches(epoch_markers=True):
                if not isinstance(b, np.ndarray):
                    continue   # EpochMarker (completed barrier => ack)
                for r in b:
                    got.setdefault(int(r["key"]), []).append(
                        int(r["value"]))
        except Exception as e:   # surfaced in the assert below
            errs.append(e)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    senders = {s: RowSender("127.0.0.1", recv.port, resume=rs,
                            faults=plans[s], connect_deadline=10.0)
               for s in range(2)}
    # key % 2 owns the sender; my_pid=2 owns nothing, so every row ships
    epoch = 0
    for i, b in enumerate(batches):
        partition_and_ship(b, np.asarray(b["key"]) % 2, 2, senders)
        if (i + 1) % epoch_batches == 0:
            epoch += 1
            for snd in senders.values():
                snd.send_epoch(epoch)
    for snd in senders.values():
        snd.close()
    t.join(timeout=60)
    assert not t.is_alive(), f"{repro}: receiver hung (params {params})"
    assert not errs, f"{repro}: receiver raised {errs[0]!r} ({params})"
    recv.close()
    assert got == {k: v for k, v in oracle.items() if v}, (
        f"{repro}: per-key arrival order diverged from the oracle "
        f"(params {params})")
    return params


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): the
    wire bundle the chaos cases run — heartbeat paired with a stall
    timeout, resume journaling paired with receiver epoch tracking."""
    from windflow_tpu.parallel.channel import WireConfig
    return [WireConfig(connect_deadline=10.0, heartbeat=2.0,
                       stall_timeout=10.0, resume=True, recovery=True)]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=50, help="number of cases")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--case", type=int, default=None,
                    help="run exactly one case (repro mode)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    if args.case is not None:
        run_case(args.seed, args.case, verbose=True)
        print("OK")
        return
    for case in range(args.n):
        run_case(args.seed, case, verbose=args.verbose)
        if (case + 1) % 10 == 0:
            print(f"{case + 1}/{args.n} cases OK")
    print(f"all {args.n} cases OK")


if __name__ == "__main__":
    main()
