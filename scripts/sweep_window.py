"""Interleaved sweep of the in-flight dispatch window (the hold threshold
that gates reactive coalescing) and queue depth — the VERDICT r3 item-1
sweep, judged on the same per-run wire diagnostics as the bench.

Usage: python scripts/sweep_window.py [n_million] [rounds]
"""

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import numpy as np

CONFIGS = [
    {"dw": 8, "depth": 48},                        # r4 default (anchor)
    {"dw": 8, "depth": 48, "flush": 1 << 18},
    {"dw": 16, "depth": 96},
    {"dw": 8, "depth": 48, "no_overlap": True},
]


def main():
    n_m = float(sys.argv[1]) if len(sys.argv) > 1 else 16
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    bench.N_TUPLES = int(n_m * 1e6)
    from windflow_tpu.core.tuples import Schema
    schema = Schema(value=np.int64)
    batches = bench.make_stream(schema)
    want = bench.expected_total(batches)

    bench.run_once(batches, schema)
    from windflow_tpu.ops.resident import prewarm_regular_ladder
    prewarm_regular_ladder()

    results = {i: [] for i in range(len(CONFIGS))}
    for r in range(rounds):
        for i, cfg in enumerate(CONFIGS):
            os.environ["WF_DISPATCH_WINDOW"] = str(cfg["dw"])
            if cfg.get("no_overlap"):
                os.environ["WF_NO_OVERLAP"] = "1"
            else:
                os.environ.pop("WF_NO_OVERLAP", None)
            dt, _n, total, diag = _run(batches, schema, cfg["depth"],
                                       cfg.get("flush", bench.FLUSH_ROWS))
            assert total == want, (cfg, total, want)
            row = {"tps": round(bench.N_TUPLES / dt, 1), **diag}
            results[i].append(row)
            print(f"round {r} {cfg}: {json.dumps(row)}", flush=True)
    os.environ.pop("WF_DISPATCH_WINDOW", None)
    os.environ.pop("WF_NO_OVERLAP", None)
    for i, cfg in enumerate(CONFIGS):
        tps = [x["tps"] for x in results[i]]
        print(f"{cfg}: best {max(tps):,.0f} "
              f"median {statistics.median(tps):,.0f} "
              f"dispatches {[x['dispatches'] for x in results[i]]}")


def _run(batches, schema, depth, flush_rows=None):
    import time

    from windflow_tpu.core.windows import WinType
    from windflow_tpu.ops import resident
    from windflow_tpu.ops.functions import Reducer
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    n_out = [0]
    total = [0]

    def consume(rows):
        if rows is not None and len(rows):
            n_out[0] += len(rows)
            total[0] += int(rows["value"].sum())

    stage = WinSeqTPU(Reducer("sum", value_range=(0, 100)), bench.WIN,
                      bench.SLIDE, WinType.CB, batch_len=bench.BATCH_LEN,
                      flush_rows=flush_rows or bench.FLUSH_ROWS,
                      depth=depth, shards=1)
    df = Dataflow()
    build_pipeline(df, [Source(batches=batches, schema=schema),
                        stage, Sink(consume, vectorized=True)])
    resident.stats_snapshot(reset=True)
    t0 = time.perf_counter()
    df.run_and_wait_end()
    dt = time.perf_counter() - t0
    diag = resident.stats_snapshot(reset=True)
    return dt, n_out[0], total[0], diag


if __name__ == "__main__":
    main()
