"""Zero-downtime rolling restart sequencer for a supervised row plane
(docs/ROBUSTNESS.md "Cross-host recovery"): cycle every stateful worker
process through drain -> seal -> hand-off -> restart while the source
keeps emitting, then verify the merged outputs are byte-identical to the
uncrashed oracle (zero record loss, zero duplication).

The four phases, per rolled worker:

  drain     the feeding MultiPipe's control-plane ``Drain`` actuator
            gates the sources and settles every inbox (quiesce), so no
            new rows are in flight anywhere in the graph
  seal      an epoch barrier is shipped on every plane edge; the worker
            checkpoints its state (CheckpointStore) and acks the sealed
            epoch, trimming the feeder's resume journal to the barrier
  hand-off  the worker exits at the seal WITHOUT an EOS — the feeder's
            journaling senders mark the link down and hold the unsealed
            tail for replay (parallel/channel.py wire resume)
  restart   a fresh process restores the sealed checkpoint and rebinds
            the same plane address with ``resume_epoch=``; the senders
            reconnect and replay exactly the records past the barrier;
            ``release_drain()`` resumes emission

Run the built-in differential (a feeder MultiPipe + 2 worker processes,
each rolled once mid-stream):

    python scripts/wf_roll.py --epochs 8 -v

The same sequence is exercised in-suite by
tests/test_multihost_2proc.py::test_rolling_restart_zero_loss.
"""

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

#: the rolled worker: seal per-epoch running sums, exit at a seal when
#: the roll flag is present (phase A) or resume from a sealed epoch
#: (phase B) — the wf_roll sequencer drives both phases
_WORKER = r"""
import json, os, sys
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker
from windflow_tpu.recovery.store import CheckpointStore

w = int(sys.argv[1])
port, root, flag = int(sys.argv[2]), sys.argv[3], sys.argv[4]
resume_from = int(sys.argv[5])

store = CheckpointStore(os.path.join(root, f"store{w}"), retain=8)
sums = {}
if resume_from:
    latest = store.latest_complete()
    assert latest is not None and latest[0] == resume_from, latest
    sums = store.load(resume_from, "sums")

recv = RowReceiver(1, port=port, resume=WireResume(deadline=120.0),
                   resume_epoch=(resume_from or None), ack_epochs=False,
                   accept_timeout=60.0)
pending = []
out_f = open(os.path.join(root, f"out{w}.jsonl"), "a")
for item in recv.batches(epoch_markers=True):
    if isinstance(item, EpochMarker):
        e = int(item.epoch)
        n = store.save_blob(e, "sums", dict(sums))
        store.commit(e, {"sums": {"bytes": n}})
        for row in pending:
            out_f.write(json.dumps(row) + "\n")
        out_f.flush()
        os.fsync(out_f.fileno())
        pending = []
        recv.ack_epoch(e)
        if os.path.exists(flag):
            os._exit(0)   # hand-off: exit at the seal, no EOS — the
            #               feeder's journal bridges the restart gap
        continue
    for r in item:
        k, v = int(r["key"]), int(r["value"])
        sums[k] = sums.get(k, 0) + v
        pending.append([k, int(r["id"]), sums[k]])
recv.close()
"""


def _spawn_worker(w, port, root, flag, resume_from, script, env):
    return subprocess.Popen(
        [sys.executable, script, str(w), str(port), root, flag,
         str(resume_from)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def roll_worker(pipe, w, port, workers, senders, state, root, flag,
                script, env, verbose=False):
    """One drain -> seal -> hand-off -> restart cycle for worker ``w``;
    returns the epoch the restarted process resumed from."""
    from windflow_tpu.recovery.store import CheckpointStore

    if not pipe.request_drain(timeout=60.0):
        raise RuntimeError(f"drain for worker {w} never quiesced")
    # the flag goes down only AFTER quiesce: from here the one marker
    # the worker will see is the sequencer's own seal below, so it
    # exits exactly at the drained barrier
    with open(flag, "w"):
        pass
    # seal: every plane edge gets a barrier at the drained point (the
    # current epoch may be mid-stream — an extra marker is just a finer
    # seal, the per-key stream content is unchanged)
    state["epoch"] += 1
    for snd in senders.values():
        snd.send_epoch(state["epoch"])
    _out, err = workers[w].communicate(timeout=120)
    if workers[w].returncode != 0:
        raise RuntimeError(f"worker {w} failed at hand-off: "
                           f"{err.decode()[-2000:]}")
    sealed = CheckpointStore(os.path.join(root, f"store{w}"),
                             retain=8).latest_complete()
    if sealed is None:
        raise RuntimeError(f"worker {w} left no complete checkpoint")
    os.unlink(flag)
    workers[w] = _spawn_worker(w, port, root, flag, sealed[0], script, env)
    pipe.release_drain()
    if verbose:
        print(f"rolled worker {w}: sealed epoch {sealed[0]}, "
              f"restarted with resume_epoch={sealed[0]}")
    return sealed[0]


def run_roll(root, n_epochs=8, verbose=False):
    """The built-in differential: a Drain-controlled feeder MultiPipe
    ships a deterministic keyed stream to 2 worker processes; each is
    rolled once mid-stream; merged outputs must equal the uncrashed
    oracle."""
    from windflow_tpu.api import MultiPipe
    from windflow_tpu.control import ControlPolicy, Drain
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import RowSender, WireResume
    from windflow_tpu.patterns.basic import Sink, Source

    script = os.path.join(root, "roll_worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    env.setdefault("JAX_PLATFORMS", "cpu")

    import socket
    ports = {}
    for w in (1, 2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports[w] = s.getsockname()[1]
        s.close()
    flags = {w: os.path.join(root, f"roll{w}.flag") for w in (1, 2)}
    workers = {w: _spawn_worker(w, ports[w], root, flags[w], 0, script,
                                env)
               for w in (1, 2)}
    senders = {w: RowSender("127.0.0.1", ports[w],
                            resume=WireResume(deadline=120.0),
                            connect_deadline=60.0)
               for w in (1, 2)}

    schema = Schema(value=np.int64)
    state = {"bi": 0, "epoch": 0}

    def gen():
        for bi in range(2 * n_epochs):
            keys = np.arange(8, dtype=np.int64)
            ids = np.full(8, bi, dtype=np.int64)
            yield batch_from_columns(schema, key=keys, id=ids, ts=ids,
                                     value=7 * ids + keys + 1)
            time.sleep(0.02)   # the source keeps emitting through rolls

    def ship(rows):
        if rows is None:
            return
        keys = np.asarray(rows["key"])
        for w, snd in senders.items():
            m = (1 + keys % 2) == w
            if m.any():
                snd.send(rows[m])
        state["bi"] += 1
        if state["bi"] % 2 == 0:
            state["epoch"] += 1
            for snd in senders.values():
                snd.send_epoch(state["epoch"])

    pipe = (MultiPipe("wf_roll_feeder", capacity=8, metrics=True,
                      control=ControlPolicy([Drain(deadline=60.0,
                                                   poll=0.01)],
                                            period=0.05)))
    pipe.add_source(Source(batches=gen(), schema=schema, name="src"))
    pipe.add_sink(Sink(ship, vectorized=True, name="ship"))
    pipe.run()
    time.sleep(0.3)   # rows flowing before the first roll
    for w in sorted(workers):
        roll_worker(pipe, w, ports[w], workers, senders, state, root,
                    flags[w], script, env, verbose=verbose)
        time.sleep(0.2)
    pipe.wait(timeout=120)
    for snd in senders.values():
        snd.close()
    for w, p in workers.items():
        _out, err = p.communicate(timeout=120)
        if p.returncode != 0:
            raise RuntimeError(f"worker {w} failed after roll: "
                               f"{err.decode()[-2000:]}")

    # uncrashed oracle: per-key running sums over the generated stream
    want, sums = {}, {}
    for bi in range(2 * n_epochs):
        for k in range(8):
            v = 7 * bi + k + 1
            sums[k] = sums.get(k, 0) + v
            want.setdefault(k, []).append([bi, sums[k]])
    got = {}
    for w in (1, 2):
        with open(os.path.join(root, f"out{w}.jsonl")) as f:
            for line in f:
                k, rid, cum = json.loads(line)
                got.setdefault(int(k), []).append([int(rid), int(cum)])
    for rows in got.values():
        rows.sort()
    assert got == want, "rolled outputs diverged from the oracle"
    snap = pipe.metrics.snapshot()
    return {"rolled": sorted(workers),
            "drains": snap["counters"].get("ctl_drains", 0),
            "epochs_sealed": state["epoch"]}


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the feeder MultiPipe the roll sequencer
    drives (Drain-controlled source -> shipping sink), with a trace_dir
    so the metrics knob validates clean."""
    import tempfile

    from windflow_tpu.api import MultiPipe
    from windflow_tpu.control import ControlPolicy, Drain
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.patterns.basic import Sink, Source

    schema = Schema(value=np.int64)
    pipe = MultiPipe("wf_roll_feeder", capacity=8, metrics=True,
                     trace_dir=tempfile.gettempdir(),
                     control=ControlPolicy([Drain(deadline=60.0,
                                                  poll=0.01)],
                                           period=0.05))
    pipe.add_source(Source(batches=[], schema=schema, name="src"))
    pipe.add_sink(Sink(lambda rows: None, vectorized=True, name="ship"))
    return [pipe]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()
    import tempfile
    with tempfile.TemporaryDirectory(prefix="wf_roll_") as root:
        out = run_roll(root, n_epochs=args.epochs, verbose=args.verbose)
    print(f"rolling restart OK: workers {out['rolled']} cycled with "
          f"{out['drains']} drains over {out['epochs_sealed']} sealed "
          f"epochs, outputs byte-identical to the uncrashed oracle")


if __name__ == "__main__":
    main()
