"""Native sanitizer lane (docs/CHECKS.md "Native sanitizer lane"):
build the seeded stress driver under ThreadSanitizer / AddressSanitizer
and run the corpus; any sanitizer report or stress assertion fails the
lane.

TSan cannot be injected into an uninstrumented CPython via dlopen, so
the lane does NOT load libwfnative.so — ``native/Makefile``'s ``tsan`` /
``asan`` targets link ``wf_native.cpp`` straight into the standalone
``native/wf_stress.cpp`` driver (queue MPMC conservation, the parked-
producer close/free race, and concurrent state-ABI round trips; see the
driver's header comment for the phase list).

    python scripts/wf_sanitize.py                      # tsan, 4 cases
    python scripts/wf_sanitize.py --san both --n 8
    python scripts/wf_sanitize.py --san asan --seed 7

Exit 0 when every requested lane builds and runs clean; 1 otherwise.
The same lanes run in-suite (slow-marked) via tests/test_sanitize.py.
"""

import argparse
import os
import shutil
import subprocess
import sys

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")

#: substrings whose presence in the stress output fails the lane even if
#: the binary somehow exited 0 (sanitizers can be configured not to halt)
_REPORT_MARKS = ("WARNING: ThreadSanitizer", "ERROR: ThreadSanitizer",
                 "ERROR: AddressSanitizer", "ERROR: LeakSanitizer",
                 "runtime error:", "wf_stress FAILED")

_LANES = {"tsan": "wf_stress_tsan", "asan": "wf_stress_asan"}


def run_lane(san, seed, n, verbose=False):
    """Build one sanitizer target and run the seeded corpus; returns
    (ok, detail)."""
    binary = _LANES[san]
    mk = subprocess.run(["make", "-C", NATIVE_DIR, san],
                        capture_output=True, text=True)
    if mk.returncode != 0:
        return False, f"build failed:\n{mk.stdout}{mk.stderr}"
    env = dict(os.environ)
    # halt_on_error=0: collect EVERY report in one pass instead of dying
    # at the first — _REPORT_MARKS scanning catches them regardless
    env.setdefault("TSAN_OPTIONS", "halt_on_error=0 history_size=7")
    env.setdefault("ASAN_OPTIONS", "detect_leaks=1")
    proc = subprocess.run(
        [os.path.join(NATIVE_DIR, binary), "--seed", str(seed),
         "--n", str(n)],
        capture_output=True, text=True, env=env, timeout=900)
    out = proc.stdout + proc.stderr
    if verbose:
        sys.stderr.write(out)
    hits = [m for m in _REPORT_MARKS if m in out]
    if proc.returncode != 0 or hits:
        tail = "\n".join(out.splitlines()[-40:])
        return False, (f"rc={proc.returncode} reports={hits or 'none'}\n"
                       f"{tail}")
    return True, f"clean ({n} cases, seed={seed})"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="build + run the native sanitizer stress lane")
    ap.add_argument("--san", choices=("tsan", "asan", "both"),
                    default="tsan")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--n", type=int, default=4,
                    help="seeded stress cases per lane")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="echo the stress driver's output")
    args = ap.parse_args(argv)

    if shutil.which("make") is None or shutil.which("g++") is None:
        print("wf_sanitize: no native toolchain (make/g++); nothing run",
              file=sys.stderr)
        return 1

    lanes = ("tsan", "asan") if args.san == "both" else (args.san,)
    failed = False
    for san in lanes:
        ok, detail = run_lane(san, args.seed, args.n,
                              verbose=args.verbose)
        print(f"wf_sanitize [{san}]: {'OK' if ok else 'FAILED'} "
              f"— {detail.splitlines()[0]}")
        if not ok:
            failed = True
            sys.stderr.write(detail + "\n")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
