"""Seeded overload/poison soak: randomized fast-source -> map -> slow-sink
graphs under every backpressure policy (block / shed_oldest / shed_newest,
with and without put deadlines), with poison batches thrown at a
configurable error budget — asserting, per case, that the graph *degrades*
instead of dying or hanging and that the shed/quarantine accounting is
conserved (docs/ROBUSTNESS.md).

Mirrors the sweep-script pattern: standalone, seeded, and any failure is
reproducible in isolation:

    python scripts/soak_overload.py --n 500 --seed 7        # the soak
    python scripts/soak_overload.py --seed 7 --case 173     # one repro

The test suite runs a small slow-marked slice of this via
tests/test_overload.py (tier-1 excludes it with -m 'not slow').
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def run_case(seed: int, case: int, verbose: bool = False,
             trace_dir: str = None, sample_period: float = None) -> dict:
    """One randomized soak case; raises AssertionError (with the repro
    command in the message) on any invariant violation.

    ``trace_dir``/``sample_period`` opt the case's Dataflow into the
    observability layer (docs/OBSERVABILITY.md): the live sampler
    appends to ``<trace_dir>/metrics.jsonl`` while the case runs, which
    is how a soak under ``wf_top`` demonstrates in-flight occupancy and
    shedding.  Both also default from WF_LOG_DIR / WF_SAMPLE_PERIOD."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.patterns.basic import Map, Sink, Source
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline
    from windflow_tpu.runtime.overload import OverloadPolicy

    rng = np.random.default_rng((seed, case))
    shed = str(rng.choice(["block", "block", "shed_oldest", "shed_newest"]))
    put_deadline = (float(rng.uniform(2.0, 5.0))
                    if shed == "block" and rng.random() < 0.3 else None)
    capacity = int(rng.integers(2, 8))
    n_batches = int(rng.integers(5, 40))
    rows = int(rng.integers(8, 64))
    sink_delay = float(rng.choice([0.0, 0.0005, 0.002]))
    n_poison = int(rng.integers(0, 4))
    budget = int(rng.integers(0, 5))
    poison_at = set(rng.choice(n_batches, size=min(n_poison, n_batches),
                               replace=False).tolist())
    params = dict(shed=shed, put_deadline=put_deadline, capacity=capacity,
                  n_batches=n_batches, rows=rows, sink_delay=sink_delay,
                  poison_at=sorted(poison_at), budget=budget)
    repro = f"python scripts/soak_overload.py --seed {seed} --case {case}"

    schema = Schema(value=np.int64)
    batches = []
    for i in range(n_batches):
        vals = np.full(rows, i, dtype=np.int64)
        if i in poison_at:
            vals = vals.copy()
            vals[0] = -1    # the poison marker the map trips on
        batches.append(batch_from_columns(
            schema, key=np.zeros(rows), id=np.arange(rows),
            ts=np.arange(rows), value=vals))

    map_seen = [0]
    sink_seen = [0]

    def poison_map(b):
        map_seen[0] += 1
        if (b["value"] < 0).any():
            raise ValueError(f"poison batch (case {case})")

    def consume(rowsb):
        if rowsb is not None and len(rowsb):
            sink_seen[0] += 1
            if sink_delay:
                time.sleep(sink_delay)

    df = Dataflow(f"soak{case}", capacity=capacity,
                  overload=OverloadPolicy(shed=shed,
                                          put_deadline=put_deadline,
                                          error_budget=budget),
                  trace_dir=trace_dir, sample_period=sample_period)
    build_pipeline(df, [
        Source(batches=batches, schema=schema),
        Map(poison_map, name="poison_map", vectorized=True),
        Sink(consume, vectorized=True)])

    t0 = time.monotonic()
    err = None
    try:
        df.run_and_wait_end()
    except Exception as e:  # noqa: BLE001 — classified below
        err = e
    wall = time.monotonic() - t0

    # ---- invariants -------------------------------------------------------
    ctx = f"{params} [{repro}]"
    assert wall < 60, f"case hung ({wall:.1f}s): {ctx}"
    shed_counts = df.shed_counts()
    quarantined = len(df.dead_letters)
    map_name = "poison_map.0"
    map_emitted = map_seen[0] - quarantined
    if shed == "block":
        # blocking policy never sheds; errors only from budget exhaustion
        # (or a genuinely expired deadline, which these sizes never hit)
        assert not shed_counts, f"block policy shed: {shed_counts} {ctx}"
        if len(poison_at) <= budget:
            assert err is None, f"in-budget poison raised {err!r}: {ctx}"
            assert quarantined == len(poison_at), \
                f"dead letters {quarantined} != poison {len(poison_at)}: {ctx}"
            assert sink_seen[0] == n_batches - quarantined, \
                f"sink saw {sink_seen[0]}: {ctx}"
        else:
            assert isinstance(err, ValueError), \
                f"budget exhausted but raised {err!r}: {ctx}"
    else:
        # shedding: conservation per inbox — every batch is delivered or
        # counted shed; poison that reaches the map is quarantined within
        # budget (an over-budget arrival fails the graph, also valid —
        # then the source stops early and conservation no longer applies)
        if err is None:
            assert map_seen[0] + shed_counts.get(map_name, 0) \
                == n_batches, \
                f"map conservation broke: {map_seen[0]} + " \
                f"{shed_counts.get(map_name, 0)} != {n_batches}: {ctx}"
            assert sink_seen[0] + shed_counts.get("sink.0", 0) \
                == map_emitted, \
                f"sink conservation broke: {sink_seen[0]} + " \
                f"{shed_counts.get('sink.0', 0)} != {map_emitted}: {ctx}"
        else:
            assert isinstance(err, ValueError) and quarantined >= budget, \
                f"unexpected failure {err!r}: {ctx}"
    assert quarantined <= max(budget, 0) + 1, \
        f"quarantined {quarantined} over budget {budget}: {ctx}"
    if verbose:
        print(f"case {case}: ok  sink={sink_seen[0]} shed={shed_counts} "
              f"dead={quarantined} err={type(err).__name__ if err else None}"
              f" {params}")
    return dict(params=params, sink=sink_seen[0], shed=shed_counts,
                dead=quarantined, error=repr(err) if err else None)


def run_soak(n: int, seed: int, verbose: bool = False,
             trace_dir: str = None, sample_period: float = None) -> dict:
    stats = {"cases": 0, "shed_cases": 0, "poison_cases": 0, "errors": 0}
    for case in range(n):
        r = run_case(seed, case, verbose=verbose, trace_dir=trace_dir,
                     sample_period=sample_period)
        stats["cases"] += 1
        stats["shed_cases"] += bool(r["shed"])
        stats["poison_cases"] += bool(r["dead"])
        stats["errors"] += bool(r["error"])
    return stats


def wf_check_pipelines():
    """Static-analysis entry (scripts/wf_lint.py, docs/CHECKS.md): a
    tiny never-run instance of the soak topology — fast source ->
    poison map -> slow sink under a shedding OverloadPolicy."""
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.patterns.basic import Map, Sink, Source
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline
    from windflow_tpu.runtime.overload import OverloadPolicy

    schema = Schema(value=np.int64)
    df = Dataflow("soak_overload_lint", capacity=4,
                  overload=OverloadPolicy(shed="shed_oldest",
                                          error_budget=2))
    build_pipeline(df, [
        Source(batches=[], schema=schema),
        Map(lambda b: None, name="poison_map", vectorized=True),
        Sink(lambda rows: None, vectorized=True)])
    return [df]


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--n", type=int, default=200, help="number of cases")
    ap.add_argument("--seed", type=int, default=0, help="soak seed")
    ap.add_argument("--case", type=int, default=None,
                    help="run ONE case standalone (failure repro)")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--trace-dir", default=None,
                    help="observability output dir (metrics.jsonl / "
                         "events.jsonl / per-node logs; also WF_LOG_DIR)")
    ap.add_argument("--sample-period", type=float, default=None,
                    help="live sampler period in seconds (also "
                         "WF_SAMPLE_PERIOD); watch with scripts/wf_top.py")
    args = ap.parse_args()
    if args.case is not None:
        r = run_case(args.seed, args.case, verbose=True,
                     trace_dir=args.trace_dir,
                     sample_period=args.sample_period)
        print(r)
        return
    t0 = time.monotonic()
    stats = run_soak(args.n, args.seed, verbose=args.verbose,
                     trace_dir=args.trace_dir,
                     sample_period=args.sample_period)
    print(f"soak clean: {stats} in {time.monotonic() - t0:.1f}s")


if __name__ == "__main__":
    main()
