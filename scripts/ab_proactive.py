"""Interleaved A/B for proactive dispatch sizing (VERDICT r3 item 1).

Alternates the headline bench workload with proactive flush sizing ON and
OFF in ONE process, so tunnel weather averages across arms.  Proactive
sizing is opt-in: arm "on" sets WF_PROACTIVE=1, arm "off" unsets it
(native_core.py treats unset/"0"/"" as off) — the only comparison shape the wire's ±2x swings permit
(BASELINE.md).  Prints per-run tps + wire diagnostics and per-arm
best/median.

Usage: python scripts/ab_proactive.py [n_million] [rounds]
"""

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import numpy as np


def main():
    n_m = float(sys.argv[1]) if len(sys.argv) > 1 else 16
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    bench.N_TUPLES = int(n_m * 1e6)
    from windflow_tpu.core.tuples import Schema
    schema = Schema(value=np.int64)
    batches = bench.make_stream(schema)
    want = bench.expected_total(batches)

    bench.run_once(batches, schema)          # compile warmup
    from windflow_tpu.ops.resident import prewarm_regular_ladder
    prewarm_regular_ladder()

    arms = {"on": [], "off": []}
    for r in range(rounds):
        for arm in ("on", "off"):
            # proactive sizing is opt-in since the 2026-07-31 A/B showed
            # it losing on this wire (native_core.py): arm "on" opts in
            if arm == "on":
                os.environ["WF_PROACTIVE"] = "1"
            else:
                os.environ.pop("WF_PROACTIVE", None)
            dt, _n, total, diag = bench.run_once(batches, schema)
            assert total == want, (arm, total, want)
            row = {"tps": round(bench.N_TUPLES / dt, 1), **diag}
            arms[arm].append(row)
            print(f"round {r} {arm:3s}: {json.dumps(row)}", flush=True)
    os.environ.pop("WF_PROACTIVE", None)
    for arm, rows in arms.items():
        tps = [x["tps"] for x in rows]
        print(f"{arm:3s}: best {max(tps):,.0f}  median "
              f"{statistics.median(tps):,.0f}  "
              f"dispatches {[x['dispatches'] for x in rows]}")


if __name__ == "__main__":
    main()
