"""Sweep bench configs on the real chip (shards / flush_rows / depth),
interleaved round-robin so tunnel weather averages out across configs.

Usage: python scripts/sweep.py [n_million] [rounds]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench
import numpy as np


def main():
    n_m = float(sys.argv[1]) if len(sys.argv) > 1 else 8
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    bench.N_TUPLES = int(n_m * 1e6)
    from windflow_tpu.core.tuples import Schema
    schema = Schema(value=np.int64)
    batches = bench.make_stream(schema)

    configs = []
    for shards in (1, 2, 4):
        for flush in (1 << 19, 1 << 20):
            configs.append(dict(shards=shards, flush=flush, depth=24))

    best = {i: None for i in range(len(configs))}
    for r in range(rounds):
        for i, cfg in enumerate(configs):
            bench.FLUSH_ROWS = cfg["flush"]
            orig = bench.run_once

            def run_with(cfg=cfg):
                from windflow_tpu.core.windows import WinType
                from windflow_tpu.ops.functions import Reducer
                from windflow_tpu.patterns.basic import Sink, Source
                from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
                from windflow_tpu.runtime.engine import Dataflow
                from windflow_tpu.runtime.farm import build_pipeline
                n_out = [0]
                total = [0]

                def consume(rows):
                    if rows is not None and len(rows):
                        n_out[0] += len(rows)
                        total[0] += int(rows["value"].sum())

                df = Dataflow()
                build_pipeline(df, [
                    Source(batches=batches, schema=schema),
                    WinSeqTPU(Reducer("sum"), bench.WIN, bench.SLIDE,
                              batch_len=bench.BATCH_LEN,
                              flush_rows=cfg["flush"], depth=cfg["depth"],
                              shards=cfg["shards"]),
                    Sink(consume, vectorized=True)])
                t0 = time.perf_counter()
                df.run_and_wait_end()
                return time.perf_counter() - t0

            dt = run_with()
            tps = bench.N_TUPLES / dt
            if best[i] is None or tps > best[i]:
                best[i] = tps
            print(f"round {r} cfg{i} shards={cfg['shards']} "
                  f"flush=2^{cfg['flush'].bit_length()-1} "
                  f"depth={cfg['depth']}: {tps/1e6:.2f}M tps", flush=True)
    print("\nbest-of per config:")
    for i, cfg in enumerate(configs):
        print(f"  shards={cfg['shards']} flush=2^{cfg['flush'].bit_length()-1}"
              f" depth={cfg['depth']}: {best[i]/1e6:.2f}M tps")


if __name__ == "__main__":
    main()
